"""Task 2 — collective-communication data-parallel training.

Capability parity with the reference entrypoints (codes/task2/model.py,
codes/task2/model-mp.py): LeNet CNN on MNIST trained data-parallel with
per-step gradient aggregation, selectable collective strategy
(AllReduce / AllGather / ReduceScatter — the spec requires ≥2,
sections/task2.tex:18), wall-clock + communication-time accounting
(model-mp.py:48-79) and the bottleneck-node experiment (model-mp.py:47,
64-65; sections/checking.tex:22). Reference hyperparameters: 2 replicas,
batch 32/replica, SGD lr=0.01 momentum=0.9, 2 epochs (model.py:124-133).

TPU-first design: instead of one OS process per rank with per-tensor NCCL
calls, ONE jitted SPMD program is sharded over a mesh ``data`` axis; ranks
become mesh positions. The reference's launch story (manual --rank
processes / mp.spawn / docker-compose, SURVEY.md §4) maps to:
single-host multi-device (default), simulated devices
(``tpudml.launch`` CPU mode), or multi-host via TPUDML_COORDINATOR env
(jax.distributed).

Run: ``python -m tasks.task2 [--aggregation allgather] [--measure_comm]
[--zero1] [--sentinel] [--bottleneck_rank 1] [--n_devices 2]``
"""

from __future__ import annotations


from tasks.common import (
    final_checkpoint,
    init_distributed,
    load_splits,
    select_devices,
    setup_checkpointing,
)
from tpudml.metrics.profiler import trace
from tpudml.core.config import MeshConfig, TrainConfig, build_parser, config_from_args
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data import DataLoader, ShardedDataLoader
from tpudml.data.sampler import make_sampler
from tpudml.metrics import MetricsWriter
from tpudml.models import LeNet
from tpudml.obs.tracer import Tracer, set_tracer
from tpudml.optim import make_optimizer
from tpudml.parallel.dp import DataParallel
from tpudml.train import evaluate, train_loop


def reference_defaults() -> TrainConfig:
    cfg = TrainConfig()
    cfg.epochs = 2
    cfg.optimizer = "sgd"
    cfg.lr = 0.01  # reference: model.py:131
    cfg.momentum = 0.9
    cfg.data.batch_size = 32  # per-replica, reference: model.py:126
    return cfg


def run(cfg: TrainConfig) -> dict:
    init_distributed(cfg)
    devices = select_devices(cfg)
    mesh = make_mesh(MeshConfig({"data": len(devices)}), devices)
    world = mesh.shape["data"]

    train_set, test_set = load_splits(cfg)

    # DistributedSampler parity (reference model.py:124): random partition,
    # one sampler per mesh replica, per-epoch reshuffle via set_epoch.
    samplers = [
        make_sampler(
            cfg.data.division, len(train_set), world, r,
            shuffle=cfg.data.shuffle, seed=cfg.data.seed,
        )
        for r in range(world)
    ]
    train_loader = ShardedDataLoader(
        train_set, cfg.data.batch_size, samplers,
        drop_remainder=cfg.data.drop_remainder,
    )
    test_loader = DataLoader(test_set, cfg.data.batch_size, drop_remainder=False)

    model = LeNet(in_channels=train_set.images.shape[-1])
    optimizer = make_optimizer(cfg.optimizer, cfg.lr, cfg.momentum)
    # Flight recorder (--obs, docs/OBSERVABILITY.md): one Tracer feeds the
    # engine's step spans, the split-step comm spans, and — via the
    # ambient hook — checkpoint/sentinel/launcher events; exported as
    # run_dir/trace.json at the end of the run.
    tracer = Tracer() if cfg.obs else None
    dp = DataParallel(
        model,
        optimizer,
        mesh,
        aggregation=cfg.aggregation,
        zero1=cfg.zero1,
        sentinel=cfg.sentinel,
        obs=tracer if tracer is not None else False,
        measure_comm=cfg.measure_comm or cfg.bottleneck_rank is not None,
        bottleneck_rank=cfg.bottleneck_rank,
        bottleneck_delay_s=cfg.bottleneck_delay_s,
        accum_steps=cfg.accum_steps,
        stacked_batches=True,  # ShardedDataLoader yields [world, B, ...]
    )
    # Ambient tracer install (restored on exit): checkpoint save/verify
    # and sentinel-trip events emitted by the cross-cutting layers land on
    # the same timeline as the engine's spans.
    prev_tracer = set_tracer(tracer) if tracer is not None else None
    try:
        ts = dp.create_state(seed_key(cfg.seed))
        ts, hooks, ckpt_mgr = setup_checkpointing(cfg, ts)
        if dp.sentinel is not None:
            # Escalate past the consecutive-skip budget with a diagnostic
            # naming the poisoned leaf/microbatch (docs/RESILIENCE.md).
            from tpudml.resilience import sentinel_hook

            hooks.append(sentinel_hook(dp.sentinel, ts.params))
        step = dp.make_train_step()

        writer = MetricsWriter(
            cfg.log_dir, run_name=f"task2-{cfg.aggregation}-w{world}"
        )
        with trace(writer.run_dir / "profile", enabled=cfg.profile):
            ts, metrics = train_loop(
                model,
                optimizer,
                train_loader,
                cfg.epochs,
                seed_key(cfg.seed),
                writer=writer,
                log_every=cfg.log_every,
                step_fn=step,
                state=ts,
                hooks=hooks,
            )
        final_checkpoint(ckpt_mgr, ts)
    finally:
        if tracer is not None:
            set_tracer(prev_tracer)
    if dp.comm_stats.calls:
        print(dp.comm_stats.report())  # reference print parity: model-mp.py:79
        writer.add_scalar("Comm Time", dp.comm_stats.comm_time_s, int(ts.step))
        metrics["comm_time_s"] = dp.comm_stats.comm_time_s
    if tracer is not None:
        trace_path = tracer.export(writer.run_dir / "trace.json")
        print(f"[obs] trace: {trace_path}")
        metrics["trace_path"] = str(trace_path)

    acc = evaluate(model, ts, test_loader)
    print(f"Test accuracy: {acc * 100:.2f}%")
    writer.add_scalar("Test Accuracy", acc, int(ts.step))
    writer.close()
    metrics["test_accuracy"] = acc
    metrics["world"] = world
    # Exact artifact location for tooling (tools/plot_runs.py --regen):
    # guessing the run dir by newest-mtime races with concurrent writers.
    metrics["run_dir"] = str(writer.run_dir)
    return metrics


def main(argv=None):
    args = build_parser(reference_defaults()).parse_args(argv)
    return run(config_from_args(args))


if __name__ == "__main__":
    main()
