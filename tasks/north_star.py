"""North-star workload — CIFAR-10 ResNet-18 data-parallel training.

BASELINE.json's headline metric: "CIFAR-10 ResNet-18 DDP: imgs/sec/chip +
val-acc parity vs 2xGPU NCCL". The reference repo itself contains no ResNet
code (SURVEY.md §6) — the workload is driver-defined; this entrypoint is the
measurement vehicle.

TPU-first: bfloat16 compute (MXU), NHWC, one fused SPMD step over the mesh
``data`` axis, synchronous gradient psum-mean (same engine as tasks/task2).

Run: ``python -m tasks.north_star [--epochs 10] [--batch_size 128]
[--n_devices N] [--f32]``
"""

from __future__ import annotations

import time

import jax.numpy as jnp

from tasks.common import init_distributed, load_splits, select_devices
from tpudml.core.config import MeshConfig, TrainConfig, build_parser, config_from_args
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data import DataLoader, ShardedDataLoader
from tpudml.data.sampler import make_sampler
from tpudml.metrics import MetricsWriter
from tpudml.models import ResNet18, ResNet34, ResNet50
from tpudml.optim import make_optimizer
from tpudml.parallel.dp import DataParallel
from tpudml.train import evaluate, train_loop


def reference_defaults() -> TrainConfig:
    cfg = TrainConfig()
    cfg.epochs = 10
    cfg.optimizer = "sgd"
    cfg.lr = 0.1
    cfg.momentum = 0.9
    cfg.data.dataset = "cifar10"
    cfg.data.batch_size = 128  # per-replica
    return cfg


def run(cfg: TrainConfig, compute_dtype=jnp.bfloat16, model_name="resnet18") -> dict:
    init_distributed(cfg)
    devices = select_devices(cfg)
    mesh = make_mesh(MeshConfig({"data": len(devices)}), devices)
    world = mesh.shape["data"]

    train_set, test_set = load_splits(cfg)

    samplers = [
        make_sampler(
            cfg.data.division, len(train_set), world, r,
            shuffle=cfg.data.shuffle, seed=cfg.data.seed,
        )
        for r in range(world)
    ]
    train_loader = ShardedDataLoader(
        train_set, cfg.data.batch_size, samplers,
        drop_remainder=cfg.data.drop_remainder,
    )
    test_loader = DataLoader(test_set, cfg.data.batch_size, drop_remainder=False)

    ctors = {"resnet18": ResNet18, "resnet34": ResNet34, "resnet50": ResNet50}
    if model_name not in ctors:
        raise ValueError(f"unknown model {model_name!r}; options: {sorted(ctors)}")
    model = ctors[model_name](
        compute_dtype=compute_dtype, in_channels=train_set.images.shape[-1]
    )
    optimizer = make_optimizer(cfg.optimizer, cfg.lr, cfg.momentum)
    dp = DataParallel(
        model, optimizer, mesh, accum_steps=cfg.accum_steps,
        stacked_batches=True,  # ShardedDataLoader yields [world, B, ...]
    )
    ts = dp.create_state(seed_key(cfg.seed))
    step = dp.make_train_step()

    writer = MetricsWriter(cfg.log_dir, run_name=f"north-star-w{world}")
    t0 = time.time()
    ts, metrics = train_loop(
        model,
        optimizer,
        train_loader,
        cfg.epochs,
        seed_key(cfg.seed),
        writer=writer,
        log_every=cfg.log_every,
        step_fn=step,
        state=ts,
    )
    train_time = time.time() - t0
    global_batch = cfg.data.batch_size * world
    imgs_per_sec = global_batch * metrics["steps"] / train_time
    metrics["imgs_per_sec_per_chip"] = imgs_per_sec / world

    acc = evaluate(model, ts, test_loader)
    print(
        f"Test accuracy: {acc * 100:.2f}% | "
        f"{metrics['imgs_per_sec_per_chip']:.1f} imgs/sec/chip"
    )
    writer.add_scalar("Test Accuracy", acc, int(ts.step))
    writer.add_scalar("Imgs/sec/chip", metrics["imgs_per_sec_per_chip"], int(ts.step))
    writer.close()
    metrics["test_accuracy"] = acc
    metrics["world"] = world
    return metrics


def main(argv=None):
    parser = build_parser(reference_defaults())
    parser.add_argument(
        "--f32", action="store_true", help="disable bf16 compute (numerics A/B)"
    )
    parser.add_argument(
        "--model", choices=["resnet18", "resnet34", "resnet50"],
        default="resnet18",
        help="resnet50 = the BASELINE.json MindSpore auto-parallel parity "
        "config (bottleneck blocks)",
    )
    args = parser.parse_args(argv)
    cfg = config_from_args(args)
    return run(
        cfg,
        compute_dtype=jnp.float32 if args.f32 else jnp.bfloat16,
        model_name=args.model,
    )


if __name__ == "__main__":
    main()
