"""Task 3 — data parallelism with a custom sampler (division strategies).

Capability parity with the reference entrypoint (codes/task3/model.py +
codes/task3/sampler.py): data-parallel training where the dataset-division
strategy is a first-class choice — **random partition** (shared-seed
shuffle, disjoint per-rank shards) vs **random sampling** (per-rank
independent shuffles, the reference's ``seed=rank`` discipline; examples
may repeat across ranks) — per sections/task3.tex:19-24 and
sections/checking.tex:13. Reference hyperparameters: batch 32/replica,
SGD lr=0.001, 2 epochs (model.py:111-120).

The spec's analysis requirements (task3.tex:23) are runnable directly:
DP-vs-single-machine speedup via ``--n_devices 1`` vs the full mesh, and
division-strategy effects via ``--division partition|sampling`` (alias
``--mode`` for reference-flag parity).

Run: ``python -m tasks.task3 [--division sampling] [--n_devices N]``
"""

from __future__ import annotations

from tpudml.core.config import TrainConfig, build_parser, config_from_args

import tasks.task2 as task2


def reference_defaults() -> TrainConfig:
    cfg = TrainConfig()
    cfg.epochs = 2
    cfg.optimizer = "sgd"
    cfg.lr = 0.001  # reference: codes/task3/model.py:118
    cfg.momentum = 0.0
    cfg.data.batch_size = 32  # per-replica
    cfg.data.division = "partition"
    return cfg


def run(cfg: TrainConfig) -> dict:
    # Same DP engine as task2; what task3 adds is the sampler framework,
    # which the config's ``division`` field selects (SURVEY.md §3.3: the
    # reference's task3 differs from task2 only in sampler + lr).
    return task2.run(cfg)


def main(argv=None):
    args = build_parser(reference_defaults()).parse_args(argv)
    return run(config_from_args(args))


if __name__ == "__main__":
    main()
