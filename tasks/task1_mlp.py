"""Task 1 (second-framework track) — MLP via the high-level Model API.

Capability parity with the reference's MindSpore notebook path
(codes/task1/mindspore/model.ipynb): MNIST through a batched/shuffled
dataset pipeline (cell 2), the ForwardNN 784→512→…→32→10 MLP (cell 4),
``Model(net, loss, opt, {"Accuracy"})`` with ``LossMonitor`` callbacks and
sink-mode training (cells 5-7), then ``model.eval``. Sink mode maps to the
jitted XLA step — the notebook's graph-compiled data-sinking execution is
exactly this framework's native model (SURVEY.md §3.5).

Run: ``python -m tasks.task1_mlp [--epochs 10] [--optimizer sgd] ...``
"""

from __future__ import annotations

from tpudml.api import LossMonitor, Model
from tpudml.core.config import TrainConfig, build_parser, config_from_args
from tpudml.data import DataLoader, load_dataset
from tpudml.metrics import MetricsWriter
from tpudml.models import ForwardMLP
from tpudml.optim import make_optimizer


def reference_defaults() -> TrainConfig:
    cfg = TrainConfig()
    cfg.epochs = 10  # notebook: model.train(10, ...)
    cfg.optimizer = "sgd"
    cfg.lr = 0.01
    cfg.data.batch_size = 32
    return cfg


def run(cfg: TrainConfig) -> dict:
    train_set = load_dataset(
        cfg.data.dataset, cfg.data.data_dir, "train",
        synthetic_fallback=cfg.data.synthetic_fallback,
    )
    test_set = load_dataset(
        cfg.data.dataset, cfg.data.data_dir, "test",
        synthetic_fallback=cfg.data.synthetic_fallback,
    )
    train_loader = DataLoader(train_set, cfg.data.batch_size)
    test_loader = DataLoader(test_set, cfg.data.batch_size, drop_remainder=False)

    model = Model(
        ForwardMLP(),
        optimizer=make_optimizer(cfg.optimizer, cfg.lr, cfg.momentum),
        metrics={"accuracy"},
        seed=cfg.seed,
    )
    callbacks = [LossMonitor(cfg.log_every)] if cfg.log_every else []
    model.train(cfg.epochs, train_loader, callbacks=callbacks)
    print(f"Training time: {model.train_time_s:.3f}s")
    results = model.eval(test_loader)
    print(results)

    writer = MetricsWriter(cfg.log_dir, run_name="task1-mlp")
    writer.add_scalar("Test Accuracy", results["Accuracy"], int(model.state.step))
    writer.close()
    return {"test_accuracy": results["Accuracy"], "train_time_s": model.train_time_s}


def main(argv=None):
    args = build_parser(reference_defaults()).parse_args(argv)
    return run(config_from_args(args))


if __name__ == "__main__":
    main()
