"""Task 4 — model-parallel training (the RPC lab, GSPMD re-design).

Capability parity with the reference entrypoint (codes/task4/model.py):
LeNet split across devices — SubNetConv on worker1 / SubNetFC on worker2
driven by rank-0 RPC in the reference (model.py:49-66,104-139) — trained
with gradients computed and optimizer updates applied where each parameter
lives (dist_autograd + DistributedOptimizer over RRefs, model.py:75-84,126).
Reference hyperparameters: batch 32, SGD lr=0.01, CPU/gloo (task4.tex:26).

TPU-first design: no RPC exists. The staged model's parameters carry
GSPMD shardings over a mesh ``stage`` axis; ONE jitted program computes
forward/backward/update, and XLA schedules the inter-device activation
transfers the reference did with two blocking rpc_sync round-trips per
batch (SURVEY.md §3.4). Optimizer state inherits each parameter's sharding
— the DistributedOptimizer semantic by construction. Parity contract:
loss-curve equivalence to single-device training (SURVEY.md §7), asserted
in tests/test_mp.py.

Run: ``python -m tasks.task4 [--n_devices 2] [--mode division]``
(CPU-only like the reference? Not anymore — same code runs on CPU devices,
simulated meshes, or TPU slices.)

The reference's *other* defining property — each stage is its own
process running its own program, coupled only by activation/gradient
messages — is deliberately NOT reproduced here (GSPMD puts every stage
in one program). That multi-program shape lives in ``tpudml/mpmd``:
one process group per stage, host-TCP boundary transfers with the RPC
round-trips replaced by deterministic framed p2p, and membership-aware
re-mesh instead of whole-world restart (``python -m tpudml.mpmd
--drill``).
"""

from __future__ import annotations


from tasks.common import init_distributed, load_splits, select_devices
from tpudml.core.config import MeshConfig, TrainConfig, build_parser, config_from_args
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data import DataLoader
from tpudml.data.sampler import make_sampler
from tpudml.metrics import MetricsWriter
from tpudml.models import lenet_stages
from tpudml.optim import make_optimizer
from tpudml.parallel.mp import GSPMDParallel
from tpudml.train import train_loop


def reference_defaults() -> TrainConfig:
    cfg = TrainConfig()
    cfg.epochs = 1
    cfg.optimizer = "sgd"
    cfg.lr = 0.01  # reference: codes/task4/model.py:126
    cfg.momentum = 0.0
    cfg.data.batch_size = 32
    return cfg


def run(cfg: TrainConfig, schedule: str = "gspmd", microbatches: int = 4) -> dict:
    init_distributed(cfg)
    devices = select_devices(cfg)
    if schedule in ("gpipe", "1f1b"):
        return run_gpipe(cfg, devices, microbatches, schedule)
    mesh = make_mesh(MeshConfig({"stage": len(devices)}), devices)
    world = mesh.shape["stage"]

    train_set, test_set = load_splits(cfg)
    # Data enters on the host like the reference's rank-0-only loading
    # (model.py:117-124); batches are replicated across stage devices.
    sampler = make_sampler(
        cfg.data.division, len(train_set), 1, 0,
        shuffle=cfg.data.shuffle, seed=cfg.data.seed,
    )
    train_loader = DataLoader(
        train_set, cfg.data.batch_size, sampler, drop_remainder=cfg.data.drop_remainder
    )
    test_loader = DataLoader(test_set, cfg.data.batch_size, drop_remainder=False)

    model = lenet_stages(in_channels=train_set.images.shape[-1])
    optimizer = make_optimizer(cfg.optimizer, cfg.lr, cfg.momentum)
    mp = GSPMDParallel(model, optimizer, mesh, accum_steps=cfg.accum_steps)
    ts = mp.create_state(seed_key(cfg.seed))
    step = mp.make_train_step()

    writer = MetricsWriter(cfg.log_dir, run_name=f"task4-stage{world}")
    ts, metrics = train_loop(
        model, optimizer, train_loader, cfg.epochs, seed_key(cfg.seed),
        writer=writer, log_every=cfg.log_every, step_fn=step, state=ts,
    )

    eval_step = mp.make_eval_step()
    correct, total = 0, 0
    for images, labels in test_loader:
        correct += int(eval_step(ts.params, ts.model_state, images, labels))
        total += len(labels)
    acc = correct / max(total, 1)
    print(f"Test accuracy: {acc * 100:.2f}%")
    writer.add_scalar("Test Accuracy", acc, int(ts.step))
    writer.close()
    metrics["test_accuracy"] = acc
    metrics["world"] = world
    return metrics


def run_gpipe(cfg: TrainConfig, devices, microbatches: int,
              schedule: str = "gpipe") -> dict:
    """Micro-batched pipelined task4: the reference's conv/fc split
    (codes/task4/model.py:18-47) as TRUE pipeline stages — activations
    ppermute between the conv and fc devices per micro-batch tick instead
    of one blocking round-trip per batch (model.py:49-66), and extra
    devices become data-parallel pipeline replicas on a 2-D mesh."""
    from tpudml.parallel.pp import HeteroOneFOneB, HeteroPipeline

    if cfg.accum_steps > 1:
        # Micro-batching IS the accumulation axis of this engine; honoring
        # a second silent accumulation would fake a memory win (the guard
        # train_loop raises for step_fn engines, made explicit here).
        raise ValueError(
            f"--schedule {schedule} does not support --accum_steps; raise "
            "--microbatches instead"
        )
    staged = lenet_stages()  # synthetic/MNIST are single-channel
    stages = [m for _, m in staged.stages]
    n_stage = len(stages)
    if len(devices) % n_stage:
        raise ValueError(
            f"--schedule gpipe needs a multiple of {n_stage} devices, "
            f"got {len(devices)}"
        )
    n_data = len(devices) // n_stage
    divisor = n_data * microbatches
    if cfg.data.batch_size % divisor:
        raise ValueError(
            f"--batch_size {cfg.data.batch_size} must be divisible by "
            f"data replicas × microbatches = {n_data} × {microbatches}"
        )
    if n_data > 1:
        mesh = make_mesh(MeshConfig({"data": n_data, "stage": n_stage}), devices)
    else:
        mesh = make_mesh(MeshConfig({"stage": n_stage}), devices)

    train_set, test_set = load_splits(cfg)
    sampler = make_sampler(
        cfg.data.division, len(train_set), 1, 0,
        shuffle=cfg.data.shuffle, seed=cfg.data.seed,
    )
    train_loader = DataLoader(
        train_set, cfg.data.batch_size, sampler, drop_remainder=cfg.data.drop_remainder
    )
    test_loader = DataLoader(test_set, cfg.data.batch_size, drop_remainder=False)

    optimizer = make_optimizer(cfg.optimizer, cfg.lr, cfg.momentum)
    # 1f1b: same stages, the memory-bounded schedule (S activation slots
    # instead of all M in flight) with dropout support via rng_root.
    engine = HeteroOneFOneB if schedule == "1f1b" else HeteroPipeline
    pipe = engine(
        stages,
        n_microbatches=microbatches,
        mesh=mesh,
        optimizer=optimizer,
        batch_axis="data" if n_data > 1 else None,
    )
    ts = pipe.create_state(seed_key(cfg.seed))
    step = pipe.make_train_step()

    writer = MetricsWriter(
        cfg.log_dir, run_name=f"task4-{schedule}{n_stage}x{n_data}"
    )
    ts, metrics = train_loop(
        staged, optimizer, train_loader, cfg.epochs, seed_key(cfg.seed),
        writer=writer, log_every=cfg.log_every, step_fn=step, state=ts,
    )

    import numpy as np
    import jax.numpy as jnp

    forward = pipe.make_forward()
    correct, total = 0, 0
    for images, labels in test_loader:
        n = len(labels)
        if n % divisor:
            # Pad the final partial batch up to the data×microbatch
            # multiple the pipeline requires; padded rows are sliced off
            # the predictions below.
            pad = divisor - n % divisor
            images = np.concatenate(
                [images, np.zeros((pad,) + images.shape[1:], images.dtype)]
            )
        logits = forward(ts.params, jnp.asarray(images))[:n]
        correct += int(jnp.sum(jnp.argmax(logits, -1) == jnp.asarray(labels)))
        total += n
    acc = correct / max(total, 1)
    print(f"Test accuracy: {acc * 100:.2f}%")
    writer.add_scalar("Test Accuracy", acc, int(ts.step))
    writer.close()
    metrics["test_accuracy"] = acc
    metrics["world"] = len(devices)
    metrics["schedule"] = schedule
    return metrics


def main(argv=None):
    p = build_parser(reference_defaults())
    p.add_argument(
        "--schedule", choices=["gspmd", "gpipe", "1f1b"], default="gspmd",
        help="gspmd: sharded one-program split (default); gpipe: "
        "micro-batched heterogeneous pipeline (conv stage -> fc stage); "
        "1f1b: the same pipeline on the memory-bounded 1F1B schedule",
    )
    p.add_argument("--microbatches", type=int, default=4)
    args = p.parse_args(argv)
    return run(config_from_args(args), schedule=args.schedule,
               microbatches=args.microbatches)


if __name__ == "__main__":
    main()
