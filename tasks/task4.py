"""Task 4 — model-parallel training (the RPC lab, GSPMD re-design).

Capability parity with the reference entrypoint (codes/task4/model.py):
LeNet split across devices — SubNetConv on worker1 / SubNetFC on worker2
driven by rank-0 RPC in the reference (model.py:49-66,104-139) — trained
with gradients computed and optimizer updates applied where each parameter
lives (dist_autograd + DistributedOptimizer over RRefs, model.py:75-84,126).
Reference hyperparameters: batch 32, SGD lr=0.01, CPU/gloo (task4.tex:26).

TPU-first design: no RPC exists. The staged model's parameters carry
GSPMD shardings over a mesh ``stage`` axis; ONE jitted program computes
forward/backward/update, and XLA schedules the inter-device activation
transfers the reference did with two blocking rpc_sync round-trips per
batch (SURVEY.md §3.4). Optimizer state inherits each parameter's sharding
— the DistributedOptimizer semantic by construction. Parity contract:
loss-curve equivalence to single-device training (SURVEY.md §7), asserted
in tests/test_mp.py.

Run: ``python -m tasks.task4 [--n_devices 2] [--mode division]``
(CPU-only like the reference? Not anymore — same code runs on CPU devices,
simulated meshes, or TPU slices.)
"""

from __future__ import annotations


from tasks.common import init_distributed, load_splits, select_devices
from tpudml.core.config import MeshConfig, TrainConfig, build_parser, config_from_args
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data import DataLoader
from tpudml.data.sampler import make_sampler
from tpudml.metrics import MetricsWriter
from tpudml.models import lenet_stages
from tpudml.optim import make_optimizer
from tpudml.parallel.mp import GSPMDParallel
from tpudml.train import train_loop


def reference_defaults() -> TrainConfig:
    cfg = TrainConfig()
    cfg.epochs = 1
    cfg.optimizer = "sgd"
    cfg.lr = 0.01  # reference: codes/task4/model.py:126
    cfg.momentum = 0.0
    cfg.data.batch_size = 32
    return cfg


def run(cfg: TrainConfig) -> dict:
    init_distributed(cfg)
    devices = select_devices(cfg)
    mesh = make_mesh(MeshConfig({"stage": len(devices)}), devices)
    world = mesh.shape["stage"]

    train_set, test_set = load_splits(cfg)
    # Data enters on the host like the reference's rank-0-only loading
    # (model.py:117-124); batches are replicated across stage devices.
    sampler = make_sampler(
        cfg.data.division, len(train_set), 1, 0,
        shuffle=cfg.data.shuffle, seed=cfg.data.seed,
    )
    train_loader = DataLoader(
        train_set, cfg.data.batch_size, sampler, drop_remainder=cfg.data.drop_remainder
    )
    test_loader = DataLoader(test_set, cfg.data.batch_size, drop_remainder=False)

    model = lenet_stages(in_channels=train_set.images.shape[-1])
    optimizer = make_optimizer(cfg.optimizer, cfg.lr, cfg.momentum)
    mp = GSPMDParallel(model, optimizer, mesh, accum_steps=cfg.accum_steps)
    ts = mp.create_state(seed_key(cfg.seed))
    step = mp.make_train_step()

    writer = MetricsWriter(cfg.log_dir, run_name=f"task4-stage{world}")
    ts, metrics = train_loop(
        model, optimizer, train_loader, cfg.epochs, seed_key(cfg.seed),
        writer=writer, log_every=cfg.log_every, step_fn=step, state=ts,
    )

    eval_step = mp.make_eval_step()
    correct, total = 0, 0
    for images, labels in test_loader:
        correct += int(eval_step(ts.params, ts.model_state, images, labels))
        total += len(labels)
    acc = correct / max(total, 1)
    print(f"Test accuracy: {acc * 100:.2f}%")
    writer.add_scalar("Test Accuracy", acc, int(ts.step))
    writer.close()
    metrics["test_accuracy"] = acc
    metrics["world"] = world
    return metrics


def main(argv=None):
    args = build_parser(reference_defaults()).parse_args(argv)
    return run(config_from_args(args))


if __name__ == "__main__":
    main()
