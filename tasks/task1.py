"""Task 1 — single-device optimizer lab.

Capability parity with the reference entrypoint (codes/task1/pytorch/
model.py:83-111): LeNet-style CNN on MNIST, hand-written GD/SGD/Adam
optimizers, TensorBoard-style loss logging every 20 iters, test-set top-1
accuracy. Reference hyperparameters: batch 200, 1 epoch, custom Adam with
lr = 5e-4·√batch (model.py:96-98) and no bias correction
(MyOptimizer.py:26-43).

TPU-first design: the whole per-batch body (forward, loss, backward,
optimizer update) is one jitted XLA program; device pinning
(``CUDA_VISIBLE_DEVICES``, model.py:110) is unnecessary — XLA owns the chip.

Run: ``python -m tasks.task1 [--optimizer adam_ref] [--epochs 1] ...``
"""

from __future__ import annotations

import math

from tasks.common import final_checkpoint, setup_checkpointing
from tpudml.core.config import TrainConfig, build_parser, config_from_args
from tpudml.core.prng import seed_key
from tpudml.data import DataLoader, load_dataset
from tpudml.metrics import MetricsWriter
from tpudml.metrics.profiler import trace
from tpudml.models import LeNet
from tpudml.optim import make_optimizer
from tpudml.train import TrainState, evaluate, train_loop


def reference_defaults() -> TrainConfig:
    cfg = TrainConfig()
    cfg.epochs = 1
    cfg.optimizer = "adam_ref"
    cfg.lr = 5e-4 * math.sqrt(200)  # reference lr rule (task1 model.py:96-98)
    cfg.data.batch_size = 200
    return cfg


def run(cfg: TrainConfig) -> dict:
    train_set = load_dataset(
        cfg.data.dataset, cfg.data.data_dir, "train",
        synthetic_fallback=cfg.data.synthetic_fallback,
    )
    test_set = load_dataset(
        cfg.data.dataset, cfg.data.data_dir, "test",
        synthetic_fallback=cfg.data.synthetic_fallback,
    )
    from tpudml.data.sampler import make_sampler

    sampler = make_sampler(
        cfg.data.division if cfg.data.shuffle else "sequential",
        len(train_set),
        1,
        0,
        shuffle=cfg.data.shuffle,
        seed=cfg.data.seed,
    )
    train_loader = DataLoader(
        train_set, cfg.data.batch_size, sampler, drop_remainder=cfg.data.drop_remainder
    )
    test_loader = DataLoader(test_set, cfg.data.batch_size, drop_remainder=False)

    model = LeNet(in_channels=train_set.images.shape[-1])
    optimizer = make_optimizer(cfg.optimizer, cfg.lr, cfg.momentum)
    writer = MetricsWriter(cfg.log_dir, run_name=f"task1-epoch{cfg.epochs}")
    ts = TrainState.create(model, optimizer, seed_key(cfg.seed))
    ts, hooks, ckpt_mgr = setup_checkpointing(cfg, ts)
    with trace(writer.run_dir / "profile", enabled=cfg.profile):
        ts, metrics = train_loop(
            model,
            optimizer,
            train_loader,
            cfg.epochs,
            seed_key(cfg.seed),
            writer=writer,
            log_every=cfg.log_every,
            state=ts,
            hooks=hooks,
            accum_steps=cfg.accum_steps,
        )
    final_checkpoint(ckpt_mgr, ts)
    acc = evaluate(model, ts, test_loader)
    print(f"Test accuracy: {acc * 100:.2f}%")
    writer.add_scalar("Test Accuracy", acc, int(ts.step))
    writer.close()
    metrics["test_accuracy"] = acc
    return metrics


def main(argv=None):
    args = build_parser(reference_defaults()).parse_args(argv)
    return run(config_from_args(args))


if __name__ == "__main__":
    main()
