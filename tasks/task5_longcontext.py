"""Task 5 — long-context transformer training (beyond reference parity).

The reference has no sequence models (SURVEY.md §5.7), but long-context
and distributed execution are first-class in this framework. This
entrypoint trains a decoder-only TransformerLM on deterministic synthetic
next-token data with a selectable parallelism/attention strategy:

- ``--parallel single``  one chip, full or flash (Pallas) attention;
- ``--parallel dp``      data parallel over a {"data": N} mesh;
- ``--parallel fsdp``    ZeRO-3 fully-sharded DP — params/grads/opt-state
  sharded over the same {"data": N} axis (all_gather on use,
  reduce_scatter gradients, shard-local updates);
- ``--parallel cp``      ring-attention context parallelism — the sequence
                         axis sharded over a {"seq": N} mesh, K/V blocks
                         rotating on ICI (``--attn ulysses`` for the
                         all-to-all variant);
- ``--parallel tp``      Megatron-style tensor parallelism via GSPMD rules
                         over a {"model": N} mesh;
- ``--parallel pp``      micro-batched pipeline — one decoder block per
                         stage over a {"stage": N} mesh (depth = N;
                         ``--num_layers`` is ignored in this mode);
                         ``--schedule gpipe`` (scan+AD), ``1f1b``
                         (S-bounded activation memory, dropout-capable),
                         or ``interleaved`` (virtual stages — v_chunks
                         blocks per device, ~v_chunks× smaller bubble);
- ``--parallel ep``      expert parallelism — requires ``--moe_experts N``;
                         the Switch-MoE FFN's experts shard over an
                         {"expert": N} mesh with all_to_all dispatch.

Model knobs on any strategy: ``--rope`` (rotary positions),
``--num_kv_heads`` (GQA/MQA), ``--remat`` (ring-tick remat),
``--moe_experts``/``--moe_top_k`` (Switch k=1 / GShard k=2 FFN,
dense unless --parallel ep).

Reports steady-state tokens/sec and final loss.

Run: ``python -m tasks.task5_longcontext --parallel cp --seq_len 512``
"""

from __future__ import annotations

import argparse
import math
import time

import jax
import numpy as np

from tpudml.capabilities import reject
from tpudml.core.config import MeshConfig
from tpudml.core.dist import assert_same_program, distributed_init, make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_lm
from tpudml.metrics import MetricsWriter
from tpudml.models import TransformerLM
from tpudml.optim import make_optimizer
from tpudml.parallel.cp import ContextParallel
from tpudml.parallel.dp import DataParallel
from tpudml.parallel.mp import GSPMDParallel, tensor_parallel_rules
from tpudml.train import TrainState, make_train_step


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser()
    p.add_argument(
        "--parallel",
        choices=["single", "dp", "fsdp", "cp", "tp", "pp", "ep"],
        default="single",
    )
    p.add_argument("--microbatches", type=int, default=4, help="pp micro-batches")
    p.add_argument(
        "--fused_ln", action="store_true",
        help="fused residual-add+LayerNorm junction kernels (TPU; "
        "reference math elsewhere) — the round-4 flagship trunk",
    )
    p.add_argument(
        "--fused_xent_scores", action="store_true",
        help="fused-xent SPEED mode: FORCE the f32 score residual "
        "(O(B*T*V) memory, 2 fewer backward matmuls); default is AUTO — "
        "speed mode while the residual fits the 2 GiB budget, the O(B*T) "
        "lean mode beyond (xent_kernel.SAVE_S_AUTO_MAX_BYTES)",
    )
    p.add_argument(
        "--fused_xent_lean", action="store_true",
        help="FORCE the fused-xent O(B*T) lean backward (recompute "
        "matmuls) regardless of the auto threshold",
    )
    p.add_argument(
        "--fused_xent", action="store_true",
        help="fused linear-cross-entropy head (Pallas) — the [B*T, V] "
        "logits are never materialized, trading ~2 ms/step of score "
        "recompute for O(B*T) head residual memory (very long T / large "
        "vocab regimes); loss-only metrics. Composes with every "
        "--parallel strategy except pp: single/dp/cp run the kernel "
        "token-parallel, tp/fsdp run the vocab-sharded form (per-shard "
        "partial stats merged by the online lse rule; docs/API.md)",
    )
    p.add_argument(
        "--target_loss", type=float, default=None,
        help="stop when train loss reaches this value (checked on "
        "--log_every steps, where the loss is already fetched; every 10 "
        "steps when --log_every 0); the run reports steps/time-to-target",
    )
    p.add_argument(
        "--v_chunks", type=int, default=2,
        help="--schedule interleaved: model chunks per device (virtual "
        "stages; pipeline depth becomes v_chunks * n_stages — like the "
        "other pp schedules, --num_layers is ignored)",
    )
    p.add_argument(
        "--pp_data", type=int, default=1,
        help="pp only: data-parallel replicas composed with the pipeline "
        "(2-D {data, stage} mesh; n_devices/pp_data stages per replica)",
    )
    p.add_argument(
        "--schedule", choices=["gpipe", "1f1b", "interleaved"], default="gpipe",
        help="pp schedule: gpipe (scan+AD), 1f1b (S-bounded activation "
        "memory, dropout-capable), interleaved (virtual stages: v_chunks "
        "blocks per device -> depth v_chunks*N, ~v_chunks x smaller bubble)",
    )
    p.add_argument("--attn", choices=["full", "flash", "ring", "ulysses"], default=None,
                   help="attention impl; defaults: single/dp/tp=full, cp=ring")
    p.add_argument("--cp_layout", choices=["contiguous", "striped"],
                   default="contiguous",
                   help="ring-CP token layout; striped balances causal work "
                   "across the ring (~2x causal wall-clock on TPU)")
    p.add_argument("--n_devices", type=int, default=None)
    p.add_argument("--seq_len", type=int, default=256)
    p.add_argument("--batch_size", type=int, default=8, help="global batch (sequences)")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--embed_dim", type=int, default=128)
    p.add_argument("--num_heads", type=int, default=8)
    p.add_argument("--num_layers", type=int, default=2)
    p.add_argument("--num_kv_heads", type=int, default=None, help="GQA/MQA")
    p.add_argument("--rope", action="store_true", help="rotary positions")
    p.add_argument(
        "--remat", action="store_true",
        help="accepted for compatibility (ring backward always recomputes)",
    )
    p.add_argument("--moe_experts", type=int, default=0, help="MoE FFN experts")
    p.add_argument("--moe_top_k", type=int, default=1,
                   help="experts per token (1=Switch, 2=GShard)")
    p.add_argument("--moe_dispatch", choices=("gather", "einsum", "ragged"),
                   default="gather",
                   help="expert dispatch: gather (speed default), einsum "
                        "(GShard one-hot oracle), ragged (DROPLESS "
                        "lax.ragged_dot grouped matmuls — single-shard only, "
                        "rejects --parallel ep)")
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--log_every", type=int, default=20)
    p.add_argument("--log_dir", type=str, default="./logs")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--sentinel", action="store_true",
        help="in-graph step sentinel (tpudml.resilience): skip non-finite "
        "updates on-device and escalate past the consecutive-skip budget "
        "with a leaf-naming diagnostic; composes with dp/fsdp/tp/pp "
        "(cp/ep engines don't carry a sentinel yet)",
    )
    p.add_argument(
        "--ckpt_dir", type=str, default=None,
        help="checkpoint directory (enables --ckpt_every/--resume)",
    )
    p.add_argument(
        "--ckpt_every", type=int, default=0,
        help="save a rolling checkpoint every N optimizer steps "
        "(keyed by the TrainState's monotonic step counter)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="restore the latest VALID checkpoint from --ckpt_dir and "
        "continue to --steps (step-granular: a run killed at step k "
        "restarts from the last verified save, not from scratch)",
    )
    args = p.parse_args(argv)
    if (args.resume or args.ckpt_every) and not args.ckpt_dir:
        p.error("--resume/--ckpt_every need --ckpt_dir")
    return args


def build_engine(args, devices):
    """(train_state, step_fn) for the selected strategy."""
    n = len(devices)
    if getattr(args, "fused_xent", False) and args.parallel == "pp":
        # The one remaining exclusion: pipeline stages ship LOGITS
        # between stages, so there is no pre-head feature tensor for the
        # fused kernel to consume. Every other strategy composes:
        # single/dp/cp run the token-parallel kernel per shard; tp/fsdp
        # run the vocab-sharded form (per-shard partial statistics
        # merged by the online log-sum-exp rule; see docs/API.md).
        reject("pp_fused_xent")
    scores = getattr(args, "fused_xent_scores", False)
    lean = getattr(args, "fused_xent_lean", False)
    if (scores or lean) and not args.fused_xent:
        # Silently no-opping would mislabel A/B numbers (the flags only
        # configure the fused head's backward).
        raise ValueError(
            "--fused_xent_scores/--fused_xent_lean require --fused_xent"
        )
    if scores and lean:
        raise ValueError(
            "--fused_xent_scores and --fused_xent_lean are exclusive"
        )
    # Tristate: force-on / force-lean / None = auto by residual size.
    args._save_scores = True if scores else (False if lean else None)
    sentinel = getattr(args, "sentinel", False)
    args._sentinel = None  # engine's GradSentinel, for the escalation hook
    if sentinel and args.parallel not in ("dp", "fsdp", "tp", "pp"):
        # single's make_train_step and the cp/ep engines have no sentinel
        # slot in their optimizer chain; silently dropping the flag would
        # fake resilience coverage.
        raise ValueError(
            f"--sentinel composes with --parallel dp/fsdp/tp/pp, not "
            f"{args.parallel!r}"
        )
    base = dict(
        vocab_size=args.vocab,
        embed_dim=args.embed_dim,
        num_heads=args.num_heads,
        num_layers=args.num_layers,
        max_len=args.seq_len,
        num_kv_heads=args.num_kv_heads,
        rope=args.rope,
        remat=args.remat,
        moe_experts=args.moe_experts,
        moe_top_k=args.moe_top_k,
        moe_dispatch=args.moe_dispatch,
        dropout=args.dropout,
        fused_ln=args.fused_ln,
    )
    opt = make_optimizer("adam", args.lr)
    rng_root = jax.random.key(args.seed ^ 0xD0) if args.dropout else None
    if args.parallel not in ("cp",) and args.attn in ("ring", "ulysses"):
        raise ValueError(f"--attn {args.attn} requires --parallel cp")
    if args.cp_layout != "contiguous" and args.parallel != "cp":
        raise ValueError("--cp_layout striped requires --parallel cp")
    if args.parallel == "ep":
        # MoE decoder trained expert-parallel: tokens + experts share the
        # expert axis, capacity buffers move by all_to_all.
        if not args.moe_experts:
            raise ValueError("--parallel ep needs --moe_experts N")
        if args.moe_experts % n:
            raise ValueError(
                f"--moe_experts {args.moe_experts} must divide over {n} devices"
            )
        if args.dropout:
            reject("ep_dropout")
        from tpudml.parallel.ep import ExpertParallel

        mesh = make_mesh(MeshConfig({"expert": n}), devices)
        model = TransformerLM(**dict(base, moe_axis="expert"), impl=args.attn or "full")
        engine = ExpertParallel(model, opt, mesh)
        return engine.create_state(seed_key(args.seed)), engine.make_train_step()
    if args.parallel == "cp":
        impl = args.attn or "ring"
        if impl not in ("ring", "ulysses"):
            raise ValueError("cp needs --attn ring|ulysses")
        if args.cp_layout == "striped" and impl != "ring":
            raise ValueError("--cp_layout striped requires --attn ring")
        mesh = make_mesh(MeshConfig({"seq": n}), devices)
        model = TransformerLM(
            **base, impl=impl, seq_sharded=True, seq_layout=args.cp_layout
        )
        engine = ContextParallel(
            model, opt, mesh, rng_root=rng_root, layout=args.cp_layout,
            fused_xent=args.fused_xent, save_scores=args._save_scores,
        )
        return engine.create_state(seed_key(args.seed)), engine.make_train_step()
    impl = args.attn or "full"
    model = TransformerLM(**base, impl=impl)
    if args.parallel == "single":
        ts = TrainState.create(model, opt, seed_key(args.seed))
        if args.fused_xent:
            from tpudml.train import make_lm_fused_train_step

            return ts, make_lm_fused_train_step(
                model, opt, rng_root=rng_root,
                save_scores=args._save_scores,
            )
        return ts, make_train_step(model, opt, rng_root=rng_root)
    if args.parallel == "dp":
        mesh = make_mesh(MeshConfig({"data": n}), devices)
        # [B, T] token batches are never the stacked-loader form.
        engine = DataParallel(
            model, opt, mesh, rng_root=rng_root, stacked_batches=False,
            fused_xent=args.fused_xent, save_scores=args._save_scores,
            sentinel=sentinel,
        )
        args._sentinel = engine.sentinel
        return engine.create_state(seed_key(args.seed)), engine.make_train_step()
    if args.parallel == "fsdp":
        # ZeRO-3: params/grads/opt-state sharded over the data axis too.
        from tpudml.parallel.fsdp import FSDP

        mesh = make_mesh(MeshConfig({"data": n}), devices)
        engine = FSDP(
            model, opt, mesh, rng_root=rng_root,
            fused_xent=args.fused_xent, save_scores=args._save_scores,
            sentinel=sentinel,
        )
        args._sentinel = engine.sentinel
        return engine.create_state(seed_key(args.seed)), engine.make_train_step()
    if args.parallel == "pp":
        # One decoder block per pipeline stage; embed/head replicated.
        # Model knobs carry over; MoE blocks are stateful (aux-loss slot)
        # and the pipeline requires stateless blocks. --schedule gpipe is
        # the all-forward-then-AD-backward scan; --schedule 1f1b
        # interleaves backwards (S in-flight activations instead of M)
        # and supports --dropout via per-(stage, micro) rng keys.
        if args.moe_experts:
            reject("pp_moe")
        if args.dropout and args.schedule not in ("1f1b", "interleaved"):
            raise ValueError(
                "--dropout pipelines need --schedule 1f1b or interleaved"
            )
        from tpudml.models import TransformerBlock, TransformerEmbed, TransformerHead
        from tpudml.parallel.pp import GPipe, OneFOneB

        # --pp_data D composes the pipeline with data parallelism on a
        # 2-D {data, stage} mesh: D replicas each pipeline n/D stages.
        d = args.pp_data
        if d < 1 or n % d:
            raise ValueError(f"--pp_data {d} must be >= 1 and divide n_devices {n}")
        if d > 1:
            mesh = make_mesh(MeshConfig({"data": d, "stage": n // d}), devices)
        else:
            mesh = make_mesh(MeshConfig({"stage": n}), devices)
        common = dict(
            n_microbatches=args.microbatches,
            mesh=mesh,
            optimizer=opt,
            prologue=TransformerEmbed(
                args.vocab, args.embed_dim, args.seq_len,
                use_pos_embed=not args.rope,
            ),
            epilogue=TransformerHead(args.embed_dim, args.vocab),
            batch_axis="data" if d > 1 else None,
            sentinel=sentinel,
        )
        block = TransformerBlock(
            args.embed_dim, args.num_heads, causal=True, impl=impl,
            num_kv_heads=args.num_kv_heads, rope=args.rope,
            dropout=args.dropout, fused_ln=args.fused_ln,
        )
        if args.schedule == "interleaved":
            from tpudml.parallel.pp import Interleaved1F1B

            pipe = Interleaved1F1B(
                block, rng_root=rng_root, v_chunks=args.v_chunks, **common
            )
        elif args.schedule == "1f1b":
            pipe = OneFOneB(block, rng_root=rng_root, **common)
        else:
            pipe = GPipe(block, **common)
        args._sentinel = pipe.sentinel
        return pipe.create_state(seed_key(args.seed)), pipe.make_train_step()
    # tp
    mesh = make_mesh(MeshConfig({"model": n}), devices)
    engine = GSPMDParallel(
        model, opt, mesh, rule=tensor_parallel_rules("model"),
        axis_name="model", rng_root=rng_root,
        fused_xent=args.fused_xent, save_scores=args._save_scores,
        sentinel=sentinel,
    )
    args._sentinel = engine.sentinel
    return engine.create_state(seed_key(args.seed)), engine.make_train_step()


def run(args) -> dict:
    if args.steps < 1:
        raise ValueError("--steps must be >= 1")
    distributed_init()
    # Same-program guard (SURVEY.md §5.2): all ranks must agree on argv
    # (minus host-local paths, which may be rank-templated).
    rank_invariant = {k: v for k, v in vars(args).items()
                      if k not in ("log_dir", "ckpt_dir")}
    assert_same_program(repr(sorted(rank_invariant.items())), "task5 args")
    devices = jax.devices()
    if args.n_devices and args.parallel != "single":
        devices = devices[: args.n_devices]
    if args.parallel == "single":
        devices = devices[:1]

    seqs = synthetic_lm(args.batch_size * 4, args.seq_len, args.vocab, seed=args.seed)
    ts, step = build_engine(args, devices)

    mgr = None
    start = 0
    if args.ckpt_dir:
        from tpudml.checkpoint import CheckpointManager

        mgr = CheckpointManager(args.ckpt_dir)
        if args.resume:
            # Latest VALID checkpoint: restores verify per-leaf checksums
            # and walk past corrupt/partial step dirs (docs/RESILIENCE.md).
            ts = mgr.restore_latest(ts)
            start = int(ts.step)
            if start >= args.steps:
                raise ValueError(
                    f"--resume: latest checkpoint is already at step "
                    f"{start} >= --steps {args.steps}; nothing left to run"
                )
            if start:
                print(f"resumed from step {start} ({args.ckpt_dir})")
    guard = None
    if args._sentinel is not None:
        # Escalate past the consecutive-skip budget with a diagnostic
        # naming the poisoned leaf (same hook task2 installs).
        from tpudml.resilience import sentinel_hook

        guard = sentinel_hook(args._sentinel, ts.params)

    writer = MetricsWriter(args.log_dir, run_name=f"task5-{args.parallel}")
    rng = np.random.default_rng(args.seed)
    t0 = None
    loss = float("nan")
    hit_target = None
    time_to_target = None
    final_step = args.steps
    steady_from = start + 1  # may break out before the steady-state marker
    # Steady state: past the compile on the first step of THIS run, capped
    # at 5 so even a run that hits its target at the earliest check
    # (step 10) still has a throughput window.
    steady_mark = start + min(max((args.steps - start) // 5, 1), 5)
    for i in range(start + 1, args.steps + 1):
        # The loop counter IS the global step: resume starts past the
        # restored ts.step, so the data stream, checkpoint keys, and
        # logging all continue where the killed run stopped.
        rows = rng.integers(0, len(seqs), size=args.batch_size)
        batch = seqs[rows]
        ts, metrics = step(ts, batch[:, :-1], batch[:, 1:])
        if guard is not None:
            guard(step=i, train_state=ts, metrics=metrics)
        if mgr is not None and args.ckpt_every and i % args.ckpt_every == 0:
            mgr.save(ts, i, metadata={"parallel": args.parallel})
        if i == steady_mark:
            jax.block_until_ready(metrics["loss"])
            t0, steady_from = time.time(), i
        logged = args.log_every and i % args.log_every == 0
        if logged:
            loss = float(metrics["loss"])
            writer.add_scalar("Train Loss", loss, i)
            print(f"step {i}: loss {loss:.4f}")
        if args.target_loss is not None and t0 is not None and (logged or (
            not args.log_every and i % 10 == 0
        )):
            # Convergence-target mode (the reference pins quality targets,
            # not step counts — checking.tex:5-9): stop when reached, so
            # the recording is "steps/time TO a loss", not "loss at N".
            # Checked on log steps (the loss is already fetched there) so
            # target mode adds no extra host syncs to the timed window;
            # with --log_every 0 it falls back to a fetch every 10 steps.
            # Gated on t0 (the steady-state marker) so an instantly-met
            # target cannot break out before the throughput clock starts.
            checked = loss if logged else float(metrics["loss"])
            if checked <= args.target_loss:
                hit_target, final_step = i, i
                time_to_target = time.time() - t0
                print(
                    f"target loss {args.target_loss} reached at step {i} "
                    f"({time_to_target:.1f}s after steady-state step "
                    f"{steady_from})"
                )
                break
    jax.block_until_ready(ts.params)
    if mgr is not None:
        from tasks.common import final_checkpoint

        final_checkpoint(mgr, ts)
    loss = float(metrics["loss"])
    elapsed = time.time() - t0 if t0 else float("nan")
    tokens = (final_step - steady_from) * args.batch_size * args.seq_len
    tok_per_s = (
        tokens / elapsed if tokens > 0 and elapsed and elapsed > 0 else float("nan")
    )
    # Clamp only at the float64 exp ceiling — a diverged run should report
    # its true (huge) perplexity, not a fabricated smaller one.
    ppl = math.exp(min(loss, 700.0))
    print(
        f"[{args.parallel}/{args.attn or 'default'}] {len(devices)} device(s), "
        f"T={args.seq_len}: {tok_per_s:,.0f} tokens/sec, final loss {loss:.4f} "
        f"(ppl {ppl:.2f})"
    )
    writer.add_scalar("Tokens Per Sec", tok_per_s, final_step)
    writer.add_scalar("Perplexity", ppl, final_step)
    writer.close()
    return {
        "tokens_per_sec": tok_per_s,
        "final_loss": loss,
        "perplexity": ppl,
        "devices": len(devices),
        "steps_run": final_step,
        "target_reached_at": hit_target,
        "time_to_target_s": time_to_target,
    }


def main(argv=None):
    return run(parse_args(argv))


if __name__ == "__main__":
    main()
