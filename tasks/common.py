"""Scaffolding shared by the task entrypoints (device selection, dataset
splits) so launch semantics can't silently diverge between tasks."""

from __future__ import annotations

import jax

from tpudml.core.config import TrainConfig
from tpudml.data import load_dataset


def select_devices(cfg: TrainConfig) -> list:
    """Visible devices, honoring --n_devices on a single host.

    ``--n_devices N`` on one host uses the first N chips (``--n_devices 1``
    is the single-machine baseline of sections/task3.tex:23); in multi-process
    runs the world size is fixed by the launcher, so the flag is ignored.
    """
    devices = jax.devices()
    n = cfg.dist.num_processes if cfg.dist.explicit_world else None
    if n is not None and n <= len(devices) and jax.process_count() == 1:
        devices = devices[:n]
    return devices


def init_distributed(cfg: TrainConfig) -> None:
    """Multi-process init + same-program guard, in one place so no
    entrypoint can forget the guard: after the rendezvous, every process
    allgathers a hash of its rank-invariant config and fails fast on
    divergence (SURVEY.md §5.2 — a mismatched rank would otherwise
    deadlock in the first collective)."""
    from tpudml.core.dist import assert_same_program, distributed_init

    distributed_init(cfg.dist)
    assert_same_program(cfg.fingerprint(), "task config")


def setup_checkpointing(cfg: TrainConfig, ts):
    """(train_state, hooks, manager) per the config's checkpoint fields.

    With ``--ckpt_dir`` set: ``--resume`` restores the LATEST VALID
    checkpoint into ``ts`` — restores verify per-leaf checksums and walk
    past corrupt/partial ``step_*`` dirs (every host reads the same files
    — the persistent form of the reference's rank-0 parameter broadcast,
    SURVEY.md §5.4; integrity semantics in docs/RESILIENCE.md) — and
    ``--ckpt_every N`` installs a rolling-save train_loop hook. The
    caller does the final save via the returned manager.
    """
    if not cfg.ckpt_dir:
        return ts, [], None
    from tpudml.checkpoint import CheckpointHook, CheckpointManager

    mgr = CheckpointManager(cfg.ckpt_dir)
    if cfg.resume:
        ts = mgr.restore_latest(ts)
    hooks = [CheckpointHook(mgr, every_n_steps=cfg.ckpt_every)] if cfg.ckpt_every else []
    return ts, hooks, mgr


def final_checkpoint(mgr, ts) -> None:
    """End-of-run save, skipped when the rolling hook already wrote this
    exact step (avoids re-gathering + rewriting identical bytes)."""
    if mgr is not None and mgr.latest_step() != int(ts.step):
        mgr.save(ts, int(ts.step))


def load_splits(cfg: TrainConfig):
    """(train, test) ArrayDatasets per the config's dataset selection."""
    train_set = load_dataset(
        cfg.data.dataset, cfg.data.data_dir, "train",
        synthetic_fallback=cfg.data.synthetic_fallback,
    )
    test_set = load_dataset(
        cfg.data.dataset, cfg.data.data_dir, "test",
        synthetic_fallback=cfg.data.synthetic_fallback,
    )
    return train_set, test_set
