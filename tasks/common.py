"""Scaffolding shared by the task entrypoints (device selection, dataset
splits) so launch semantics can't silently diverge between tasks."""

from __future__ import annotations

import jax

from tpudml.core.config import TrainConfig
from tpudml.data import load_dataset


def select_devices(cfg: TrainConfig) -> list:
    """Visible devices, honoring --n_devices on a single host.

    ``--n_devices N`` on one host uses the first N chips (``--n_devices 1``
    is the single-machine baseline of sections/task3.tex:23); in multi-process
    runs the world size is fixed by the launcher, so the flag is ignored.
    """
    devices = jax.devices()
    n = cfg.dist.num_processes if cfg.dist.explicit_world else None
    if n is not None and n <= len(devices) and jax.process_count() == 1:
        devices = devices[:n]
    return devices


def load_splits(cfg: TrainConfig):
    """(train, test) ArrayDatasets per the config's dataset selection."""
    train_set = load_dataset(
        cfg.data.dataset, cfg.data.data_dir, "train",
        synthetic_fallback=cfg.data.synthetic_fallback,
    )
    test_set = load_dataset(
        cfg.data.dataset, cfg.data.data_dir, "test",
        synthetic_fallback=cfg.data.synthetic_fallback,
    )
    return train_set, test_set
