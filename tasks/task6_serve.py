"""Task 6 — prefill/decode LM serving with continuous batching.

Drives ``tpudml.serve.ServingEngine`` over a decoder-only TransformerLM
with a seeded Poisson arrival stream (open-loop: arrival times are fixed
before the run, so queueing delay shows up in the latencies instead of
back-pressuring the generator). The engine runs ONE jitted decode step
for a fixed batch of ``--slots`` cache rows, prefills prompts in
``--prefill_chunk``-token chunks, and refills freed slots mid-flight.

Knobs: ``--cache_kind int8`` for the quantized KV cache, ``--tp N`` to
shard params + cache heads + the decode step over an N-way
tensor-parallel mesh (reuses the training TP rules — a TP checkpoint
serves unmodified), ``--qps inf`` for the saturation (closed-queue)
regime. Multi-tenant levers: ``--paged`` (+ ``--page_size``,
``--prefix_sharing``) for the page-pool cache layout, ``--spec_k K``
(+ ``--draft_layers``) for trunk-draft speculative decoding,
``--slo_tpot_ms`` for cost-model-priced admission, and
``--weight_quant int8`` for per-channel int8 decode weights
(serve.fleet.quant). TP composes with dense f32 weights only —
paged/spec/weight_quant under ``--tp`` raise ServeCompositionError by
contract.

Reports generated tokens/sec and p50/p99 per-token, time-to-first-token,
and end-to-end request latency, then cross-checks the workload ledger's
per-request TTFT/TPOT annotations against the raw timing ledger (exact
accounting).

Run: ``python -m tasks.task6_serve --n_requests 16 --qps 4 --paged``
"""

from __future__ import annotations

import argparse

import jax

from tpudml.core.dist import assert_same_program, distributed_init
from tpudml.metrics import MetricsWriter
from tpudml.models import TransformerLM
from tpudml.serve import ServeConfig, ServingEngine, poisson_workload


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser()
    # model
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--embed_dim", type=int, default=128)
    p.add_argument("--num_heads", type=int, default=8)
    p.add_argument("--num_layers", type=int, default=2)
    p.add_argument("--num_kv_heads", type=int, default=None, help="GQA/MQA")
    p.add_argument("--no_rope", action="store_true",
                   help="learned position table instead of rotary")
    # serving
    p.add_argument("--slots", type=int, default=4,
                   help="fixed decode batch: concurrent in-flight sequences")
    p.add_argument("--max_len", type=int, default=256,
                   help="cache rows per slot (prompt + generation bound)")
    p.add_argument("--prefill_chunk", type=int, default=32)
    p.add_argument("--cache_kind", choices=("f32", "bf16", "int8"),
                   default="f32")
    p.add_argument("--tp", type=int, default=0,
                   help="tensor-parallel ways (0 = single device)")
    # multi-tenant levers
    p.add_argument("--paged", action="store_true",
                   help="page-pool KV cache layout (serve.paged)")
    p.add_argument("--page_size", type=int, default=16)
    p.add_argument("--num_pages", type=int, default=None,
                   help="pool size (default: dense-equivalent capacity)")
    p.add_argument("--prefix_sharing", action="store_true",
                   help="reuse pages across equal prompt heads (paged only)")
    p.add_argument("--spec_k", type=int, default=0,
                   help="draft tokens per target step (0 = off)")
    p.add_argument("--draft_layers", type=int, default=None,
                   help="trunk-draft depth (default: num_layers // 2)")
    p.add_argument("--slo_tpot_ms", type=float, default=None,
                   help="per-token budget for SLO-priced admission")
    p.add_argument("--weight_quant", choices=("int8", "int8_sim"),
                   default=None,
                   help="int8 per-channel weight quantization "
                        "(serve.fleet.quant); int8_sim = f32-storage "
                        "oracle. Dense only — raises under --tp.")
    # workload
    p.add_argument("--n_requests", type=int, default=16)
    p.add_argument("--qps", type=str, default="4",
                   help="Poisson arrival rate; 'inf' = all at t=0")
    p.add_argument("--prompt_len", type=int, nargs=2, default=(8, 48),
                   metavar=("MIN", "MAX"))
    p.add_argument("--new_tokens", type=int, nargs=2, default=(8, 32),
                   metavar=("MIN", "MAX"))
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log_dir", type=str, default="./logs")
    # observability
    p.add_argument("--obs", action="store_true",
                   help="write run_dir/trace.json from the event log "
                        "(pure conversion, Perfetto-openable)")
    p.add_argument("--step_time_s", type=float, default=None,
                   help="virtual decode-step clock (deterministic runs + "
                        "real-time trace timestamps)")
    return p.parse_args(argv)


def build_engine(args) -> ServingEngine:
    model = TransformerLM(
        vocab_size=args.vocab,
        embed_dim=args.embed_dim,
        num_heads=args.num_heads,
        num_layers=args.num_layers,
        num_kv_heads=args.num_kv_heads,
        max_len=args.max_len,
        rope=not args.no_rope,
    )
    params, _ = model.init(jax.random.key(args.seed))
    slo = None
    if args.slo_tpot_ms is not None:
        from tpudml.serve import SLOConfig

        slo = SLOConfig(tpot_budget_s=args.slo_tpot_ms / 1e3)
    cfg = ServeConfig(
        slots=args.slots, max_len=args.max_len,
        prefill_chunk=args.prefill_chunk, cache_kind=args.cache_kind,
        cache_layout="paged" if args.paged else "dense",
        page_size=args.page_size, num_pages=args.num_pages,
        prefix_sharing=args.prefix_sharing, spec_k=args.spec_k, slo=slo,
        step_time_s=args.step_time_s, weight_quant=args.weight_quant,
    )
    if args.tp:
        from tpudml.core.config import MeshConfig
        from tpudml.core.dist import make_mesh

        if len(jax.devices()) < args.tp:
            raise RuntimeError(
                f"--tp {args.tp} needs {args.tp} devices, have "
                f"{len(jax.devices())}")
        mesh = make_mesh(MeshConfig({"model": args.tp}),
                         jax.devices()[: args.tp])
        return ServingEngine(model, params, cfg, mesh=mesh,
                             axis_name="model")
    return ServingEngine(model, params, cfg,
                         draft_layers=args.draft_layers)


def run(args) -> dict:
    distributed_init()
    rank_invariant = {k: v for k, v in vars(args).items() if k != "log_dir"}
    assert_same_program(repr(sorted(rank_invariant.items())), "task6 args")

    qps = float(args.qps)
    engine = build_engine(args)
    requests, ledger = poisson_workload(
        args.n_requests, qps, args.seed, vocab_size=args.vocab,
        prompt_len=tuple(args.prompt_len),
        new_tokens=tuple(args.new_tokens),
    )
    report = engine.run(requests)

    owed = sum(o["max_new_tokens"] for o in ledger.values())
    assert report.generated_tokens == owed, (
        f"token accounting mismatch: generated {report.generated_tokens}, "
        f"ledger owes {owed}")
    # Exact accounting: the ledger's per-request TTFT/TPOT annotations
    # must replay bit-for-bit from the raw timing ledger.
    report.annotate_ledger(ledger)
    for rid, row in ledger.items():
        st = report.requests[rid]
        assert row["ttft_s"] == st.first_token - st.arrival, rid
        if len(st.token_times) >= 2:
            span = st.token_times[-1] - st.token_times[0]
            assert row["tpot_s"] == span / (len(st.token_times) - 1), rid
        else:
            assert row["tpot_s"] is None, rid
    lat = report.latency_summary()
    writer = MetricsWriter(args.log_dir, run_name="task6-serve")
    writer.add_scalar("Serve Tokens Per Sec", report.tokens_per_sec, 0)
    writer.add_scalar("Per-Token p50 (ms)", lat["per_token_p50_s"] * 1e3, 0)
    writer.add_scalar("Per-Token p99 (ms)", lat["per_token_p99_s"] * 1e3, 0)
    writer.add_scalar("E2E p99 (s)", lat["e2e_p99_s"], 0)
    writer.close()
    trace_path = None
    if args.obs:
        from tpudml.obs import write_serve_trace

        trace_path = write_serve_trace(
            report, writer.run_dir / "trace.json",
            step_time_s=args.step_time_s,
        )
        print(f"[obs] trace: {trace_path}")

    refills = sum(1 for e in report.events if e[0] == "admit" and e[3] > 0)
    mode = "".join([
        "/tp" + str(args.tp) if args.tp else "",
        "/paged" if args.paged else "",
        f"/spec{args.spec_k}" if args.spec_k else "",
        f"/w{args.weight_quant}" if args.weight_quant else "",
    ])
    print(
        f"[serve{mode}/{args.cache_kind}] {args.n_requests} requests @ "
        f"qps={args.qps}, {args.slots} slots: "
        f"{report.generated_tokens} tokens in {report.wall_time:.2f}s "
        f"({report.tokens_per_sec:,.1f} tok/s, {report.decode_steps} decode "
        f"steps, {refills} mid-flight refills)"
    )
    if args.spec_k:
        print(f"  spec: mean accepted_len "
              f"{report.mean_accepted_len:.2f} of {args.spec_k} "
              f"({1 + report.mean_accepted_len:.2f} tokens/target step)")
    if report.pool_stats is not None:
        print(f"  pages: {report.pool_stats['prefix_hits']} prefix hits, "
              f"{report.pool_stats['pages_reused']} pages reused, "
              f"{report.pool_stats['retained_evictions']} retained evicted")
    print(
        f"  per-token p50/p99: {lat['per_token_p50_s'] * 1e3:.2f}/"
        f"{lat['per_token_p99_s'] * 1e3:.2f} ms | ttft p50/p99: "
        f"{lat['ttft_p50_s'] * 1e3:.1f}/{lat['ttft_p99_s'] * 1e3:.1f} ms | "
        f"e2e p50/p99: {lat['e2e_p50_s']:.3f}/{lat['e2e_p99_s']:.3f} s"
    )
    return {
        "tokens_per_sec": report.tokens_per_sec,
        "decode_steps": report.decode_steps,
        "generated_tokens": report.generated_tokens,
        "mid_flight_refills": refills,
        "mean_accepted_len": report.mean_accepted_len,
        "pool_stats": report.pool_stats,
        "trace_path": str(trace_path) if trace_path else None,
        **lat,
    }


def main(argv=None):
    return run(parse_args(argv))


if __name__ == "__main__":
    main()
