"""Task 6 — prefill/decode LM serving with continuous batching.

Drives ``tpudml.serve.ServingEngine`` over a decoder-only TransformerLM
with a seeded Poisson arrival stream (open-loop: arrival times are fixed
before the run, so queueing delay shows up in the latencies instead of
back-pressuring the generator). The engine runs ONE jitted decode step
for a fixed batch of ``--slots`` cache rows, prefills prompts in
``--prefill_chunk``-token chunks, and refills freed slots mid-flight.

Knobs: ``--cache_kind int8`` for the quantized KV cache, ``--tp N`` to
shard params + cache heads + the decode step over an N-way
tensor-parallel mesh (reuses the training TP rules — a TP checkpoint
serves unmodified), ``--qps inf`` for the saturation (closed-queue)
regime.

Reports generated tokens/sec and p50/p99 per-token, time-to-first-token,
and end-to-end request latency.

Run: ``python -m tasks.task6_serve --n_requests 16 --qps 4``
"""

from __future__ import annotations

import argparse

import jax

from tpudml.core.dist import assert_same_program, distributed_init
from tpudml.metrics import MetricsWriter
from tpudml.models import TransformerLM
from tpudml.serve import ServeConfig, ServingEngine, poisson_workload


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser()
    # model
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--embed_dim", type=int, default=128)
    p.add_argument("--num_heads", type=int, default=8)
    p.add_argument("--num_layers", type=int, default=2)
    p.add_argument("--num_kv_heads", type=int, default=None, help="GQA/MQA")
    p.add_argument("--no_rope", action="store_true",
                   help="learned position table instead of rotary")
    # serving
    p.add_argument("--slots", type=int, default=4,
                   help="fixed decode batch: concurrent in-flight sequences")
    p.add_argument("--max_len", type=int, default=256,
                   help="cache rows per slot (prompt + generation bound)")
    p.add_argument("--prefill_chunk", type=int, default=32)
    p.add_argument("--cache_kind", choices=("f32", "bf16", "int8"),
                   default="f32")
    p.add_argument("--tp", type=int, default=0,
                   help="tensor-parallel ways (0 = single device)")
    # workload
    p.add_argument("--n_requests", type=int, default=16)
    p.add_argument("--qps", type=str, default="4",
                   help="Poisson arrival rate; 'inf' = all at t=0")
    p.add_argument("--prompt_len", type=int, nargs=2, default=(8, 48),
                   metavar=("MIN", "MAX"))
    p.add_argument("--new_tokens", type=int, nargs=2, default=(8, 32),
                   metavar=("MIN", "MAX"))
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log_dir", type=str, default="./logs")
    return p.parse_args(argv)


def build_engine(args) -> ServingEngine:
    model = TransformerLM(
        vocab_size=args.vocab,
        embed_dim=args.embed_dim,
        num_heads=args.num_heads,
        num_layers=args.num_layers,
        num_kv_heads=args.num_kv_heads,
        max_len=args.max_len,
        rope=not args.no_rope,
    )
    params, _ = model.init(jax.random.key(args.seed))
    cfg = ServeConfig(
        slots=args.slots, max_len=args.max_len,
        prefill_chunk=args.prefill_chunk, cache_kind=args.cache_kind,
    )
    if args.tp:
        from tpudml.core.config import MeshConfig
        from tpudml.core.dist import make_mesh

        if len(jax.devices()) < args.tp:
            raise RuntimeError(
                f"--tp {args.tp} needs {args.tp} devices, have "
                f"{len(jax.devices())}")
        mesh = make_mesh(MeshConfig({"model": args.tp}),
                         jax.devices()[: args.tp])
        return ServingEngine(model, params, cfg, mesh=mesh,
                             axis_name="model")
    return ServingEngine(model, params, cfg)


def run(args) -> dict:
    distributed_init()
    rank_invariant = {k: v for k, v in vars(args).items() if k != "log_dir"}
    assert_same_program(repr(sorted(rank_invariant.items())), "task6 args")

    qps = float(args.qps)
    engine = build_engine(args)
    requests, ledger = poisson_workload(
        args.n_requests, qps, args.seed, vocab_size=args.vocab,
        prompt_len=tuple(args.prompt_len),
        new_tokens=tuple(args.new_tokens),
    )
    report = engine.run(requests)

    owed = sum(o["max_new_tokens"] for o in ledger.values())
    assert report.generated_tokens == owed, (
        f"token accounting mismatch: generated {report.generated_tokens}, "
        f"ledger owes {owed}")
    lat = report.latency_summary()
    writer = MetricsWriter(args.log_dir, run_name="task6-serve")
    writer.add_scalar("Serve Tokens Per Sec", report.tokens_per_sec, 0)
    writer.add_scalar("Per-Token p50 (ms)", lat["per_token_p50_s"] * 1e3, 0)
    writer.add_scalar("Per-Token p99 (ms)", lat["per_token_p99_s"] * 1e3, 0)
    writer.add_scalar("E2E p99 (s)", lat["e2e_p99_s"], 0)
    writer.close()

    refills = sum(1 for e in report.events if e[0] == "admit" and e[3] > 0)
    print(
        f"[serve{'/tp' + str(args.tp) if args.tp else ''}/"
        f"{args.cache_kind}] {args.n_requests} requests @ "
        f"qps={args.qps}, {args.slots} slots: "
        f"{report.generated_tokens} tokens in {report.wall_time:.2f}s "
        f"({report.tokens_per_sec:,.1f} tok/s, {report.decode_steps} decode "
        f"steps, {refills} mid-flight refills)"
    )
    print(
        f"  per-token p50/p99: {lat['per_token_p50_s'] * 1e3:.2f}/"
        f"{lat['per_token_p99_s'] * 1e3:.2f} ms | ttft p50/p99: "
        f"{lat['ttft_p50_s'] * 1e3:.1f}/{lat['ttft_p99_s'] * 1e3:.1f} ms | "
        f"e2e p50/p99: {lat['e2e_p50_s']:.3f}/{lat['e2e_p99_s']:.3f} s"
    )
    return {
        "tokens_per_sec": report.tokens_per_sec,
        "decode_steps": report.decode_steps,
        "generated_tokens": report.generated_tokens,
        "mid_flight_refills": refills,
        **lat,
    }


def main(argv=None):
    return run(parse_args(argv))


if __name__ == "__main__":
    main()
