"""CLI: ``python -m tpudml.elastic`` — elastic supervision + the drills.

Drill modes (the acceptance gates — exit 0 iff the verdict holds):

- restart drill (kill→re-form→resume, bit-exact vs uninterrupted)::

    JAX_PLATFORMS=cpu python -m tpudml.elastic --drill

- shrink-re-plan drill (kill→shrink→planner consulted at the new world→
  resume under a DIFFERENT engine chain, bit-exact vs a reference run of
  that chain from the same checkpoint)::

    JAX_PLATFORMS=cpu python -m tpudml.elastic --drill --shrink

- fixture replay (meshless CI mode: no processes spawned, no mesh —
  replays a pre-recorded membership/drift event stream through the
  Replanner and prints the re-plan/receipt/calibration report)::

    python -m tpudml.elastic --drill --fixture tests/elastic_fixtures/shrink_drift.json

Supervision mode (the elastic counterpart of ``python -m tpudml.launch``:
re-forms on failure instead of plain relaunch)::

    python -m tpudml.elastic -n 4 --policy shrink --min_world 2 \
        --max_reforms 3 --backoff_s 1.0 -- \
        python -m tasks.task2 --ckpt_dir ckpts --resume
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile

from tpudml.elastic.controller import ElasticController
from tpudml.launch.cluster import ClusterSpec


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        argv, cmd = argv[:split], argv[split + 1 :]
    else:
        cmd = []
    p = argparse.ArgumentParser(prog="tpudml.elastic")
    p.add_argument("--drill", action="store_true",
                   help="run the scripted failure drill; exit 0 iff the "
                        "resumed run is bit-identical to an uninterrupted one")
    p.add_argument("--shrink", action="store_true",
                   help="with --drill: the shrink-re-plan drill (planner "
                        "consulted at the new world, chain switch required)")
    p.add_argument("--fixture", type=str, default=None,
                   help="with --drill: replay a recorded membership/drift "
                        "event fixture through the Replanner — no processes, "
                        "no mesh (the CI-friendly mode)")
    p.add_argument("--naive", action="store_true",
                   help="with --drill --shrink: also run the A/B arm that "
                        "forces the OLD chain at the shrunken world")
    p.add_argument("--dir", type=str, default=None,
                   help="drill working dir (default: a fresh temp dir)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--ckpt_every", type=int, default=5)
    p.add_argument("--kill_step", type=int, default=13)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-n", "--num_processes", type=int, default=2)
    p.add_argument("--policy", choices=("restart", "shrink"), default="restart")
    p.add_argument("--min_world", type=int, default=1)
    p.add_argument("--max_reforms", type=int, default=2)
    p.add_argument("--timeout_s", type=float, default=None)
    p.add_argument("--backoff_s", type=float, default=0.0)
    args = p.parse_args(argv)

    if args.drill and args.fixture:
        from tpudml.elastic.replan import replay_fixture

        with open(args.fixture) as f:
            fixture = json.load(f)
        report = replay_fixture(fixture, sink=sys.stderr)
        print(json.dumps(report, sort_keys=True))
        return 0 if report["ok"] else 1

    if args.drill:
        base = args.dir or tempfile.mkdtemp(prefix="tpudml_drill_")
        if args.shrink:
            from tpudml.elastic.drill import run_shrink_drill

            report = run_shrink_drill(
                base,
                world=args.num_processes,
                steps=args.steps,
                ckpt_every=args.ckpt_every,
                kill_step=args.kill_step,
                seed=args.seed,
                backoff_s=args.backoff_s or 0.25,
                include_naive=args.naive,
            )
        else:
            from tpudml.elastic.drill import run_drill

            report = run_drill(
                base,
                world=args.num_processes,
                steps=args.steps,
                ckpt_every=args.ckpt_every,
                kill_step=args.kill_step,
                seed=args.seed,
                backoff_s=args.backoff_s or 0.25,
            )
        print(json.dumps(report, sort_keys=True))
        return 0 if report["ok"] else 1

    if not cmd:
        p.error("no command given; usage: python -m tpudml.elastic [opts] -- cmd ...")
    spec = ClusterSpec(
        num_processes=args.num_processes,
        timeout_s=args.timeout_s,
        restart_backoff_s=args.backoff_s,
        restart_backoff_seed=args.seed,
    )
    ctrl = ElasticController(
        cmd,
        spec,
        policy=args.policy,
        min_world=args.min_world,
        max_reforms=args.max_reforms,
    )
    res = ctrl.run()
    print(
        f"[elastic] {res.stop_reason}: {len(res.records)} round(s), "
        f"final world {res.final_world}, {res.total_elapsed_s:.1f}s",
        file=sys.stderr,
    )
    return 0 if res.success else 1


if __name__ == "__main__":
    sys.exit(main())
