"""Adaptive re-planning: the static planner as a runtime controller.

PR 13 made ``tpudml/plan`` decide configs once, offline; PR 14 made
failure a membership event. This module closes the loop between them:

- **membership trigger** — on a shrink (or grow-back) the
  :class:`ElasticController` hands the new world size to
  :meth:`Replanner.replan` *before* re-forming. The planner re-runs
  enumerate → prune → score at the new world and may pick a different
  engine chain entirely (world 2 ZeRO-1+accum → world 1 plain DP); the
  sharded checkpoint's any-world-restores-any-world property makes the
  switch a restore, not a retrain. Every re-plan stamps the plan's v2
  ``replan`` block with the old winner and machine-readable
  **receipts** for why the old config lost at the new world;
- **drift trigger** — :meth:`Replanner.on_drift` feeds measured
  static-vs-measured records (``obs/drift.py`` — the same 10% threshold
  rule J118 holds plans to) through
  :class:`~tpudml.plan.score.Calibration` and re-scores the lattice
  with the measured constants folded into the roofline: the cost model
  calibrates itself instead of ranking with a constant it has been
  shown to be wrong by. A fresh (in-threshold) report produces **no**
  re-plan — no false positives;
- **fixture replay** — :func:`replay_fixture` runs the whole loop over
  a pre-recorded membership/drift event stream (mirroring
  ``python -m tpudml.obs --check-drift --fixture``), so controller +
  planner logic is exercised in tier-1 CI without spawning a process
  group or touching a device mesh (``verify=False`` plans never build
  an engine).

Receipt verdicts (machine-readable, one per re-plan, for the old
winner re-instantiated at the new world):

- ``infeasible_at_world`` — the old engine chain has no mesh at the
  new world (e.g. ZeRO-1 on one chip shards nothing);
- ``pruned`` — the shared capability/divisibility/HBM rules dropped it
  (the receipt carries the prune rule + reason verbatim);
- ``outranked`` — feasible, but a different candidate scores better
  (the receipt carries the rank and the slowdown ratio);
- ``retained`` — the old config is still the winner (no switch).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from pathlib import Path

from tpudml.plan.emit import load_plan, make_plan, plan_to_json
from tpudml.plan.score import Calibration
from tpudml.plan.space import ModelSpec, _engine_meshes, flagship_lm

#: Candidate knobs that identify "the same config" across world sizes
#: (everything except the mesh, which necessarily changes with world).
_CONFIG_KNOBS = (
    "engine", "zero1", "zero1_overlap", "accum_steps", "fused_xent",
    "sentinel", "obs",
)


@dataclass
class ReplanRecord:
    """One re-plan decision — the telemetry row the drill/bench report."""

    trigger: str  # "membership" | "drift"
    why: str
    old_world: int
    new_world: int
    old_key: str | None
    new_key: str | None
    switched: bool
    latency_s: float
    receipts: list = field(default_factory=list)
    calibration: dict | None = None
    error: str | None = None

    def to_dict(self) -> dict:
        return {
            "trigger": self.trigger,
            "why": self.why,
            "old_world": self.old_world,
            "new_world": self.new_world,
            "old_key": self.old_key,
            "new_key": self.new_key,
            "switched": self.switched,
            "latency_s": self.latency_s,
            "receipts": list(self.receipts),
            "calibration": self.calibration,
            "error": self.error,
        }


def _knobs(cand: dict) -> tuple:
    return tuple(cand[k] for k in _CONFIG_KNOBS)


def _receipts(old_plan: dict, new_plan: dict) -> list:
    """Why the old plan's winner is not the new plan's winner (or is).

    The old winner is matched *by knobs* (engine chain + flags, mesh
    excluded) against the new plan's ranking and prune records — the
    honest question is "what happened to this config re-instantiated at
    the new world", not string equality of mesh-bearing keys.
    """
    old = old_plan["winner"]["candidate"]
    target = _knobs(old)
    new_world = new_plan["world"]

    if not _engine_meshes(old["engine"], new_world):
        return [{
            "candidate": old["key"],
            "verdict": "infeasible_at_world",
            "reason": (
                f"engine {old['engine']!r} has no mesh at world "
                f"{new_world}: nothing left to shard"
            ),
        }]

    out = []
    for rank, entry in enumerate(new_plan["ranking"]):
        if _knobs(entry["candidate"]) != target:
            continue
        if rank == 0:
            out.append({
                "candidate": entry["candidate"]["key"],
                "verdict": "retained",
                "reason": "old config still ranks first at the new world",
            })
        else:
            winner = new_plan["ranking"][0]
            ratio = (
                entry["score"]["per_token_s"]
                / winner["score"]["per_token_s"]
            )
            out.append({
                "candidate": entry["candidate"]["key"],
                "verdict": "outranked",
                "rank": rank,
                "slowdown_vs_winner": ratio,
                "reason": (
                    f"ranked #{rank + 1} at world {new_world}: "
                    f"{ratio:.3f}x the winner's per-token time"
                ),
            })
        break
    for rec in new_plan["pruned"]:
        if _knobs(rec["candidate"]) == target:
            out.append({
                "candidate": rec["candidate"]["key"],
                "verdict": "pruned",
                "rule": rec["rule"],
                "reason": rec["reason"],
            })
    if not out:
        out.append({
            "candidate": old["key"],
            "verdict": "infeasible_at_world",
            "reason": (
                f"config not enumerable at world {new_world} "
                f"(no candidate with matching knobs)"
            ),
        })
    return out


class Replanner:
    """Holds the current plan and re-runs the planner on triggers.

    ``verify=False`` (the default) keeps every plan meshless — scores
    come from the analytic roofline, no engine is built and no jax
    backend is touched, which is what lets the controller consult the
    planner from inside a supervision loop (and the fixture replay run
    in tier-1). ``plan_path`` (optional) is kept up to date with the
    current plan after every (re-)plan — the file ``--plan``-consuming
    child commands read, so the next incarnation picks the new config
    up through the existing explicit-CLI-wins merge.

    Re-planning **fails open**: a planner error (no feasible candidate
    at the new world, unwritable plan file) is caught and recorded on
    the returned :class:`ReplanRecord` — the controller proceeds with
    the old plan rather than dying inside recovery.
    """

    def __init__(
        self,
        spec: ModelSpec | None = None,
        *,
        engines=None,
        hbm_budget_bytes: int | None = None,
        verify: bool = False,
        plan_path: str | Path | None = None,
    ):
        self.spec = spec if spec is not None else flagship_lm()
        self.engines = list(engines) if engines is not None else None
        self.hbm_budget_bytes = hbm_budget_bytes
        self.verify = verify
        self.plan_path = Path(plan_path) if plan_path is not None else None
        self.plan: dict | None = None
        self.calibration: Calibration | None = None

    # ------------------------------------------------------------- plumbing

    def _emit(self, plan: dict) -> None:
        self.plan = plan
        if self.plan_path is not None:
            self.plan_path.parent.mkdir(parents=True, exist_ok=True)
            self.plan_path.write_text(plan_to_json(plan))

    def _make(self, world: int, replan: dict | None) -> dict:
        return make_plan(
            self.spec,
            world,
            hbm_budget_bytes=self.hbm_budget_bytes,
            engines=self.engines,
            verify=self.verify,
            calibration=self.calibration,
            replan=replan,
        )

    @property
    def winner_key(self) -> str | None:
        if self.plan is None:
            return None
        return self.plan["winner"]["candidate"]["key"]

    # ------------------------------------------------------------- triggers

    def initial_plan(self, world: int) -> dict:
        """Plan the first incarnation (no trigger, no receipts)."""
        self._emit(self._make(world, None))
        return self.plan

    def load_existing(self, path: str | Path) -> dict | None:
        """Adopt an existing plan.json as the current plan — *tolerant*:
        a vandalized / truncated / wrong-version file returns None (and
        leaves the current plan unchanged) instead of raising, so a
        corrupted plan file degrades to re-planning from scratch."""
        try:
            plan = load_plan(str(path))
            # A plan must at least name a winner + world to be usable.
            plan["winner"]["candidate"]["key"]
            int(plan["world"])
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError):
            return None
        self.plan = plan
        return plan

    def replan(
        self,
        world: int,
        *,
        why: str = "membership change",
        trigger: str = "membership",
    ) -> ReplanRecord:
        """Re-run the planner at ``world`` and record the decision."""
        old_plan = self.plan
        old_key = self.winner_key
        old_world = old_plan["world"] if old_plan else 0
        t0 = time.perf_counter()
        try:
            provenance = None
            if old_plan is not None:
                # Receipts need the new plan; plan twice-cheaply is
                # avoided by stamping provenance after the fact on the
                # same dict (make_plan records it verbatim).
                provenance = {
                    "trigger": trigger,
                    "why": why,
                    "old_world": old_world,
                    "old_winner": dict(old_plan["winner"]["candidate"]),
                    "receipts": [],
                }
            new_plan = self._make(world, provenance)
            if provenance is not None:
                provenance["receipts"] = _receipts(old_plan, new_plan)
            self._emit(new_plan)
        except Exception as e:  # fail open: recovery must not die here
            return ReplanRecord(
                trigger=trigger,
                why=why,
                old_world=old_world,
                new_world=world,
                old_key=old_key,
                new_key=old_key,
                switched=False,
                latency_s=time.perf_counter() - t0,
                receipts=[],
                calibration=None,
                error=f"{type(e).__name__}: {e}",
            )
        return ReplanRecord(
            trigger=trigger,
            why=why,
            old_world=old_world,
            new_world=world,
            old_key=old_key,
            new_key=self.winner_key,
            switched=old_key is not None and old_key != self.winner_key,
            latency_s=time.perf_counter() - t0,
            receipts=list(
                (self.plan.get("replan") or {}).get("receipts", ())
            ),
            calibration=self.plan.get("calibration"),
        )

    def on_drift(
        self,
        pairs: list[dict],
        threshold: float | None = None,
    ) -> ReplanRecord | None:
        """Drift-triggered re-score at the *current* world.

        ``pairs`` are drift fixture pairs (``entrypoint`` +
        ``static_wire_bytes`` + ``measured_wire_bytes``, the
        ``obs --check-drift --fixture`` schema). In-threshold reports
        return None — the plan stands, no false-positive re-plan. Past
        the threshold, the measured constants become a
        :class:`Calibration` and the lattice is re-scored with them.
        """
        from tpudml.obs.drift import (
            DEFAULT_THRESHOLD,
            build_drift_report,
            drift_from_pairs,
        )

        if self.plan is None:
            raise RuntimeError("on_drift needs a current plan")
        thr = DEFAULT_THRESHOLD if threshold is None else threshold
        report = build_drift_report(drift_from_pairs(pairs), thr)
        if report["ok"]:
            return None
        worst = max(
            report["records"], key=lambda r: r["rel_err"]
        )
        self.calibration = Calibration.from_drift_records(
            report["records"], source="obs/drift"
        )
        return self.replan(
            self.plan["world"],
            why=(
                f"measured drift {worst['rel_err']:.1%} > "
                f"{thr:.0%} at {worst['entrypoint']}"
            ),
            trigger="drift",
        )


# ------------------------------------------------------------ fixture replay

FIXTURE_VERSION = 1


def replay_fixture(
    fixture: dict | str | Path,
    *,
    plan_path: str | Path | None = None,
    sink=None,
) -> dict:
    """Replay a pre-recorded membership/drift event stream — the
    meshless tier-1 mode of ``python -m tpudml.elastic --drill
    --fixture``.

    Fixture schema (``tests/elastic_fixtures/*.json``)::

        {
          "version": 1,
          "engines": ["dp", "zero1"] | null,   # null → all engines
          "spec": ModelSpec.to_dict() | null,  # null → flagship_lm()
          "initial_world": int,
          "events": [
            {"type": "membership", "world": int, "why": str},
            {"type": "drift", "pairs": [  # obs drift fixture pairs
                {"entrypoint", "static_wire_bytes",
                 "measured_wire_bytes"}, ...]},
            ...
          ]
        }

    Returns the replay report: every re-plan record, the switch/firing
    counts, and the final plan summary. ``ok`` is False iff any
    re-plan errored out.
    """
    if not isinstance(fixture, dict):
        fixture = json.loads(Path(fixture).read_text())
    ver = fixture.get("version")
    if ver != FIXTURE_VERSION:
        raise ValueError(
            f"fixture version {ver!r} != supported {FIXTURE_VERSION}"
        )
    spec = (
        ModelSpec.from_dict(fixture["spec"])
        if fixture.get("spec")
        else flagship_lm()
    )
    rp = Replanner(
        spec,
        engines=fixture.get("engines"),
        verify=False,
        plan_path=plan_path,
    )
    rp.initial_plan(int(fixture["initial_world"]))
    initial_key = rp.winner_key

    def emit(msg: str) -> None:
        if sink is not None:
            sink.write(msg + "\n")
            sink.flush()

    emit(f"[replay] initial world {fixture['initial_world']}: {initial_key}")
    replans: list[ReplanRecord] = []
    drift_checks = 0
    drift_firings = 0
    for ev in fixture.get("events", ()):
        kind = ev.get("type")
        if kind == "membership":
            rec = rp.replan(
                int(ev["world"]), why=ev.get("why", "membership change")
            )
            replans.append(rec)
            emit(
                f"[replay] membership → world {rec.new_world}: "
                f"{rec.old_key} → {rec.new_key}"
                + (" (switched)" if rec.switched else "")
                + (f" ERROR {rec.error}" if rec.error else "")
            )
        elif kind == "drift":
            drift_checks += 1
            rec = rp.on_drift(ev["pairs"], ev.get("threshold"))
            if rec is None:
                emit("[replay] drift check: in threshold, no re-plan")
                continue
            drift_firings += 1
            replans.append(rec)
            emit(
                f"[replay] drift fired: {rec.why} → {rec.new_key} "
                f"(comm_scale "
                f"{(rec.calibration or {}).get('comm_scale', 1.0):.3f})"
            )
        else:
            raise ValueError(f"unknown fixture event type {kind!r}")
    return {
        "initial": {
            "world": int(fixture["initial_world"]),
            "winner": initial_key,
        },
        "events": len(fixture.get("events", ())),
        "replans": [r.to_dict() for r in replans],
        "plan_switches": sum(
            1 for r in replans if r.switched and not r.error
        ),
        "drift_checks": drift_checks,
        "drift_firings": drift_firings,
        "final": {
            "world": rp.plan["world"],
            "winner": rp.winner_key,
            "engine_config": dict(rp.plan["engine_config"]),
            "calibration": rp.plan["calibration"],
        },
        "ok": not any(r.error for r in replans),
    }
