"""Elastic run control: supervise a multi-process job across rank death.

``tpudml.launch`` contains failures (one dead rank tears down the whole
job instead of deadlocking the survivors) and can relaunch the job whole.
This package closes the remaining gap to "multi-host reality": a
controller that treats each relaunch as a *membership event* — fresh
rendezvous (new coordinator port, so no half-dead coordinator or zombie
rank can poison the re-form), an optional shrink policy that drops the
failed rank and re-meshes the survivors, and resume from the newest
CRC-valid sharded checkpoint so the restarted job continues the same
training trajectory bit-exactly.

The sharded checkpoint format is what makes shrink possible at all:
restore reassembles full host arrays from *all* processes' shard files,
so any post-failure topology can restore any pre-failure topology's
checkpoint (``tpudml/checkpoint/sharded.py``).

``drill.py`` is the proof: a scripted failure drill (SIGKILL-grade rank
death mid-training → backoff → re-form → resume) whose final parameters
must be bit-identical to an uninterrupted run. Run it as a library
(:func:`run_drill`), via ``python -m tpudml.elastic --drill``, or as the
MTTR benchmark row (``python bench.py --drill``).
"""

from tpudml.elastic.controller import (
    ElasticController,
    ElasticResult,
    ReformRecord,
)


def __getattr__(name):
    # Lazy: ``python -m tpudml.elastic.drill`` (the per-rank child) must
    # not find the drill module pre-imported by its own package (runpy
    # warns, and the child only needs the controller-free half anyway).
    if name == "run_drill":
        from tpudml.elastic.drill import run_drill

        return run_drill
    raise AttributeError(name)


__all__ = [
    "ElasticController",
    "ElasticResult",
    "ReformRecord",
    "run_drill",
]
