"""Elastic controller: membership-aware restart on top of the launcher.

Where ``launch(max_restarts=...)`` relaunches the *same* job shape on a
failure, :class:`ElasticController` treats each failure as a membership
event and re-forms the job:

- **fresh rendezvous** — every re-form gets a coordinator port never used
  by an earlier round of this job, so a zombie rank still blocked in the
  old rendezvous (or a half-dead coordinator holding the socket) can
  never join — or deadlock — the new incarnation. Ports are *reserved by
  binding* (the socket is held until the instant the round spawns), not
  picked-and-released, so two concurrent controllers on one host cannot
  race each other onto the same port; a pinned ``coordinator_port`` is
  probed for bindability first and falls back to a fresh port (with a
  warning) when something else is squatting on it — a collision degrades
  to a port change, never to a rendezvous deadlock;
- **adaptive re-plan** — with a ``replanner``
  (:class:`tpudml.elastic.replan.Replanner`) attached, every membership
  *change* consults the planner at the new world size before re-forming:
  the next incarnation may run a different engine chain entirely, picked
  up by ``--plan``-consuming children from the refreshed plan file. The
  re-plan decision (old/new winner, receipts, latency) is recorded on
  the result and its latency is charged against the whole-job budget;
  a replanner failure is recorded and the old plan is kept — recovery
  never dies inside the recovery path;
- **membership policy** — ``"restart"`` re-forms at the same world size
  (the failed rank's slot is refilled); ``"shrink"`` drops one rank per
  failure and re-forms the survivors at ``world-1`` (never below
  ``min_world``), the preemption story where the capacity is *gone*;
- **budget + backoff** — one whole-job wall-clock budget
  (``spec.timeout_s``) is charged across every round *and* every backoff
  sleep, and the backoff schedule is the launcher's seeded exponential
  (:func:`tpudml.launch.launcher.restart_backoff`) so drills are
  reproducible per (spec, seed).

Resume is the command's job, by design: pair the supervised command with
a sharded checkpoint dir (``restore_latest_valid_sharded``) and each
incarnation continues from the newest CRC-valid step — any world size
can restore any other world size's checkpoint, which is what makes
``"shrink"`` a *training* policy and not just a process policy.
"""

from __future__ import annotations

import dataclasses
import random
import socket
import sys
import time
from dataclasses import dataclass, field

from tpudml.launch.cluster import ClusterSpec
from tpudml.launch.launcher import LaunchResult, _launch_once, restart_backoff

#: Env var telling each child which incarnation of the job it belongs to
#: (0 = first form, k = after k re-forms). Children use it to tag
#: per-round artifacts (traces, logs); training code can ignore it.
ROUND_ENV = "TPUDML_ELASTIC_ROUND"


@dataclass
class ReformRecord:
    """One incarnation of the job (round 0 = the initial form)."""

    round: int
    world: int
    coordinator_port: int
    returncodes: list[int]
    failed_rank: int | None
    timed_out: bool
    elapsed_s: float
    backoff_s: float  # slept BEFORE this round formed (0.0 for round 0)
    t_start: float  # wall clock (time.time()) at spawn / end of round —
    t_end: float  # the MTTR measurement anchors for drill evidence

    @property
    def success(self) -> bool:
        return not self.timed_out and all(rc == 0 for rc in self.returncodes)


@dataclass
class ElasticResult:
    records: list[ReformRecord] = field(default_factory=list)
    #: One dict per planner consultation (ReplanRecord.to_dict() plus a
    #: "round" key naming the incarnation the new plan formed), in order.
    replans: list[dict] = field(default_factory=list)
    success: bool = False
    total_elapsed_s: float = 0.0
    #: Why the controller stopped: "success" | "max_reforms" |
    #: "budget_exhausted" | "below_min_world".
    stop_reason: str = ""

    @property
    def reforms(self) -> int:
        return max(0, len(self.records) - 1)

    @property
    def final_world(self) -> int:
        return self.records[-1].world if self.records else 0

    def to_dict(self) -> dict:
        """The telemetry record drills persist (``elastic.json``) and
        ``tools/obs_report.py``'s reform/replan section reads."""
        return {
            "records": [dataclasses.asdict(r) for r in self.records],
            "replans": [dict(r) for r in self.replans],
            "success": self.success,
            "total_elapsed_s": self.total_elapsed_s,
            "stop_reason": self.stop_reason,
            "reforms": self.reforms,
            "final_world": self.final_world,
        }


class ElasticController:
    """Supervise ``cmd`` across rank death with membership re-forms.

    ``cmd`` and ``spec`` mean exactly what they mean for
    :func:`tpudml.launch.launch`; ``spec.max_restarts`` is ignored here —
    re-forming is this controller's job (``max_reforms``), and each round
    runs exactly once via the launcher's single-attempt core (which
    already contains failures: first non-zero rank ⇒ SIGTERM→SIGKILL of
    the whole round, so no zombie survives into the next rendezvous).

    ``replanner`` (optional) is consulted on every membership *change*
    (``replanner.replan(new_world, why=...)``) before the re-form — any
    object with that method works; the real one is
    :class:`tpudml.elastic.replan.Replanner`, which this module never
    imports (controller semantics stay importable and testable without
    the planner's jax dependency).
    """

    def __init__(
        self,
        cmd: list[str],
        spec: ClusterSpec | None = None,
        *,
        policy: str = "restart",
        min_world: int = 1,
        max_reforms: int = 2,
        replanner=None,
        sink=None,
    ):
        if policy not in ("restart", "shrink"):
            raise ValueError(f"unknown membership policy {policy!r}")
        if min_world < 1:
            raise ValueError(f"min_world must be >= 1, got {min_world}")
        self.cmd = list(cmd)
        self.spec = dataclasses.replace(spec) if spec is not None else ClusterSpec()
        self.policy = policy
        self.min_world = min_world
        self.max_reforms = max_reforms
        self.replanner = replanner
        self.sink = sink

    def _reserve_fresh_port(self, used: set[int]):
        """Reserve a never-used port by *binding* it and holding the
        socket: ``(sock, port)``. The caller closes ``sock`` at the last
        instant before spawning the round, so a concurrent controller
        (or any fault-injected squatter) probing ports in the meantime
        cannot grab it — the pick-without-binding race this replaces
        left a window from pick to rendezvous."""
        for _ in range(64):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.bind((self.spec.coordinator_host, 0))
            except OSError:
                s.close()
                continue
            port = s.getsockname()[1]
            if port in used:
                s.close()
                continue
            return s, port
        raise RuntimeError("could not reserve a fresh coordinator port")

    def _pinned_port_usable(self, port: int) -> bool:
        """Bindability probe for an explicitly pinned round-0 port."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.bind((self.spec.coordinator_host, port))
            return True
        except OSError:
            return False
        finally:
            s.close()

    def run(self) -> ElasticResult:
        from tpudml.obs.tracer import get_tracer

        out = self.sink or sys.stdout
        spec = self.spec
        budget = spec.timeout_s
        world = spec.num_processes
        rng = random.Random(spec.restart_backoff_seed)
        used_ports: set[int] = set()
        res = ElasticResult()
        backoff = 0.0
        for rnd in range(self.max_reforms + 1):
            # Fresh rendezvous per incarnation: an explicitly pinned port is
            # honored for the first form only — re-forms must never reuse a
            # port a (possibly zombie) earlier round rendezvoused on. Fresh
            # ports stay *bound* (reservation held) until the round spawns.
            reservation = None
            if rnd == 0 and spec.coordinator_port != 0:
                port = spec.coordinator_port
                if not self._pinned_port_usable(port):
                    out.write(
                        f"[elastic] pinned coordinator port {port} is not "
                        f"bindable (already in use) — falling back to a "
                        f"fresh port\n"
                    )
                    out.flush()
                    reservation, port = self._reserve_fresh_port(used_ports)
            else:
                reservation, port = self._reserve_fresh_port(used_ports)
            used_ports.add(port)
            remaining = None if budget is None else budget - res.total_elapsed_s
            round_spec = dataclasses.replace(
                spec,
                num_processes=world,
                coordinator_port=port,
                timeout_s=remaining,
                max_restarts=0,
                env={**spec.env, ROUND_ENV: str(rnd)},
            )
            t_start = time.time()
            if reservation is not None:
                # Release at the last instant — the round's coordinator
                # binds this port next.
                reservation.close()
            launched: LaunchResult = _launch_once(self.cmd, round_spec, out)
            t_end = time.time()
            res.total_elapsed_s += launched.elapsed_s
            rec = ReformRecord(
                round=rnd,
                world=world,
                coordinator_port=port,
                returncodes=launched.returncodes,
                failed_rank=launched.failed_rank,
                timed_out=launched.timed_out,
                elapsed_s=launched.elapsed_s,
                backoff_s=backoff,
                t_start=t_start,
                t_end=t_end,
            )
            res.records.append(rec)
            if rec.success:
                res.success = True
                res.stop_reason = "success"
                break
            if rnd == self.max_reforms:
                res.stop_reason = "max_reforms"
                break
            why = (
                "timeout"
                if rec.timed_out
                else f"rank {rec.failed_rank} failed"
                f" (rc={rec.returncodes[rec.failed_rank]})"
                if rec.failed_rank is not None
                else "job failed"
            )
            next_world = world
            if self.policy == "shrink" and not rec.timed_out:
                next_world = world - 1
                if next_world < self.min_world:
                    out.write(
                        f"[elastic] {why}; world {world}-1 < min_world "
                        f"{self.min_world} — cannot re-form\n"
                    )
                    out.flush()
                    res.stop_reason = "below_min_world"
                    break
            backoff = restart_backoff(spec, rng, rnd + 1)
            if budget is not None and res.total_elapsed_s + backoff >= budget:
                res.stop_reason = "budget_exhausted"
                break
            if self.replanner is not None and next_world != world:
                # Membership changed: consult the planner at the new world
                # before re-forming. Latency is real recovery time, so it
                # is charged against the whole-job budget like everything
                # else; a replanner failure keeps the old plan.
                t0 = time.time()
                try:
                    rep = self.replanner.replan(next_world, why=why)
                    rep_d = (
                        rep.to_dict() if hasattr(rep, "to_dict") else dict(rep)
                    )
                except Exception as e:
                    rep_d = {
                        "trigger": "membership",
                        "why": why,
                        "old_world": world,
                        "new_world": next_world,
                        "old_key": None,
                        "new_key": None,
                        "switched": False,
                        "latency_s": 0.0,
                        "receipts": [],
                        "calibration": None,
                        "error": f"{type(e).__name__}: {e}",
                    }
                latency = time.time() - t0
                res.total_elapsed_s += latency
                rep_d["round"] = rnd + 1
                res.replans.append(rep_d)
                if rep_d.get("error"):
                    out.write(
                        f"[elastic] re-plan at world {next_world} failed "
                        f"({rep_d['error']}); keeping the old plan\n"
                    )
                else:
                    out.write(
                        f"[elastic] re-plan at world {next_world}: "
                        f"{rep_d.get('old_key')} → {rep_d.get('new_key')}"
                        + (" (engine chain switched)"
                           if rep_d.get("switched") else " (retained)")
                        + f" in {rep_d.get('latency_s', 0.0):.3f}s\n"
                    )
                out.flush()
                get_tracer().instant(
                    "elastic_replan",
                    cat="elastic",
                    args={
                        "round": rnd + 1,
                        "world": next_world,
                        "old_key": rep_d.get("old_key"),
                        "new_key": rep_d.get("new_key"),
                        "switched": bool(rep_d.get("switched")),
                        "latency_s": rep_d.get("latency_s", 0.0),
                        "error": rep_d.get("error"),
                    },
                )
            out.write(
                f"[elastic] {why}; re-form {rnd + 1}/{self.max_reforms}: "
                f"world {world}→{next_world}, fresh port"
                + (f", {backoff:.2f}s backoff" if backoff > 0 else "")
                + "\n"
            )
            out.flush()
            get_tracer().instant(
                "elastic_reform",
                cat="elastic",
                args={
                    "round": rnd + 1,
                    "why": why,
                    "world": next_world,
                    "backoff_s": backoff,
                },
            )
            if backoff > 0:
                time.sleep(backoff)
                res.total_elapsed_s += backoff
            world = next_world
        return res
