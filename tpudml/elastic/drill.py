"""The elastic failure drill: kill → backoff → re-form → bit-exact resume.

Two halves:

- :func:`child_main` — the per-rank training program the drill supervises
  (``python -m tpudml.elastic.drill``). A deliberately small but *real*
  multi-process job: gloo-backed cross-process psum DP on a
  ``('data',)`` mesh, batches that are a pure function of the step index
  (so any incarnation replays the same trajectory), sharded CRC-verified
  checkpoints every k steps, and resume from the newest valid step. A
  seeded :func:`~tpudml.resilience.faults.rank_kill_hook` plays the
  adversary: ``os._exit`` mid-training, at most once per drill (marker
  file). Each rank prints its final parameter CRC and exports its own
  flight-recorder track (one Chrome-trace pid per process).

- :func:`run_drill` — the drill driver and the MTTR evidence source: run
  the job once uninterrupted, once under :class:`ElasticController` with
  the adversary armed, then require the two final parameter CRCs to be
  **bit-identical** and report recovery stats (steps lost to the kill,
  restart latency including backoff, wall-clock overhead vs the
  uninterrupted run).
"""

from __future__ import annotations

import argparse
import dataclasses
import io
import json
import re
import sys
import time
import zlib
from pathlib import Path

import numpy as np


# --------------------------------------------------------------- child


def _params_crc(tree) -> int:
    """CRC-32 over the concatenated little-endian bytes of every leaf, in
    ``jax.tree.leaves`` order — the drill's bit-exactness witness."""
    import jax

    crc = 0
    for leaf in jax.tree.leaves(tree):
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(leaf)).tobytes(), crc)
    return crc


def child_main(argv: list[str] | None = None) -> int:
    """One rank of the drill job (rank/world/coordinator via the
    launcher's TPUDML_* env contract)."""
    ap = argparse.ArgumentParser(prog="tpudml.elastic.drill")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--ckpt_dir", type=str, required=True)
    ap.add_argument("--ckpt_every", type=int, default=5)
    ap.add_argument("--global_batch", type=int, default=16)
    ap.add_argument("--feature_dim", type=int, default=16)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kill_step", type=int, default=-1)
    ap.add_argument("--kill_rank", type=int, default=1)
    ap.add_argument("--kill_marker", type=str, default=None)
    ap.add_argument("--obs_dir", type=str, default=None)
    args = ap.parse_args(argv)

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpudml.checkpoint.sharded import (
        restore_latest_valid_sharded,
        save_sharded_checkpoint,
    )
    from tpudml.core.config import DistributedConfig, MeshConfig
    from tpudml.core.dist import distributed_init, make_mesh, process_index
    from tpudml.core.prng import seed_key
    from tpudml.models.mlp import ForwardMLP
    from tpudml.nn.losses import softmax_cross_entropy
    from tpudml.obs.tracer import Tracer, set_tracer
    from tpudml.optim.optimizers import make_optimizer
    from tpudml.parallel.sharding import shard_map_fn
    from tpudml.resilience.faults import rank_kill_hook

    distributed_init(DistributedConfig.from_env())
    rank = process_index()
    tracer = Tracer()
    set_tracer(tracer)
    mesh = make_mesh(MeshConfig({"data": -1}))
    world = int(np.prod(mesh.devices.shape))
    if args.global_batch % world:
        raise SystemExit(f"global_batch {args.global_batch} % world {world} != 0")

    model = ForwardMLP(
        in_features=args.feature_dim, hidden=(32, 16), num_classes=args.classes
    )
    params, _ = model.init(seed_key(args.seed))
    opt = make_optimizer("sgd", args.lr, momentum=args.momentum)
    opt_state = opt.init(params)

    # Batches are a pure function of the step index (same on every rank and
    # every incarnation): a resumed run replays steps c..N-1 bit-exactly.
    teacher = (
        np.random.default_rng(args.seed + 777)
        .standard_normal((args.feature_dim, args.classes))
        .astype(np.float32)
    )

    def batch_for(step: int) -> tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(args.seed * 1_000_003 + step)
        x = rng.standard_normal((args.global_batch, args.feature_dim)).astype(
            np.float32
        )
        y = np.argmax(x @ teacher, axis=1).astype(np.int32)
        return x, y

    rep = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P("data"))

    def to_global(host: np.ndarray, sharding) -> jax.Array:
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx, a=host: a[idx]
        )

    def replicate(tree):
        return jax.tree.map(
            lambda a: to_global(np.asarray(a), rep), tree
        )

    # Resume from the newest CRC-valid sharded checkpoint, if any. The
    # restore reassembles full host arrays from ALL processes' shards, so
    # this works even when the writing incarnation had a different world
    # size (the controller's "shrink" policy).
    target = {
        "opt": jax.tree.map(np.asarray, opt_state),
        "params": jax.tree.map(np.asarray, params),
        "step": np.zeros((), np.int64),
    }
    restored = restore_latest_valid_sharded(args.ckpt_dir, target)
    start_step = int(restored["step"])
    if start_step:
        print(
            f"[drill] rank {rank} resumed step {start_step} "
            f"wall {time.time():.3f}",
            flush=True,
        )
        tracer.instant("drill_resume", cat="elastic", args={"step": start_step})
    params = replicate(restored["params"])
    opt_state = replicate(restored["opt"])

    def step_body(params, opt_state, x, y):
        def loss_fn(p):
            logits, _ = model.apply(p, {}, x, train=True)
            return softmax_cross_entropy(logits, y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "data"), grads)
        loss = jax.lax.pmean(loss, "data")
        new_params, new_opt = opt.update(grads, opt_state, params)
        return new_params, new_opt, loss

    step_fn = jax.jit(
        shard_map_fn(
            step_body,
            mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P()),
        )
    )

    kill = None
    if args.kill_step >= 0:
        kill = rank_kill_hook(
            args.kill_step, marker=args.kill_marker, rank=args.kill_rank
        )

    loss = None
    for step in range(start_step, args.steps):
        if kill is not None:
            kill(step=step)
        x, y = batch_for(step)
        with tracer.span("drill_step", cat="step", args={"step": step}):
            params, opt_state, loss = step_fn(
                params, opt_state, to_global(x, row_sharded), to_global(y, row_sharded)
            )
            jax.block_until_ready(loss)
        if args.ckpt_every and (step + 1) % args.ckpt_every == 0:
            with tracer.span("drill_checkpoint", cat="ckpt", args={"step": step + 1}):
                save_sharded_checkpoint(
                    args.ckpt_dir,
                    {
                        "opt": opt_state,
                        "params": params,
                        "step": np.int64(step + 1),
                    },
                    step + 1,
                )

    crc = _params_crc(params)
    print(
        f"[drill] rank {rank} world {world} final_step {args.steps} "
        f"loss {float(np.asarray(loss)):.6f} params_crc {crc:08x}",
        flush=True,
    )
    if args.obs_dir:
        # One Chrome-trace pid track per process (pid = process_index()).
        tracer.export(Path(args.obs_dir) / f"trace_p{rank}.json")
    return 0


# --------------------------------------------------------------- driver

_CRC_RE = re.compile(
    r"\[drill\] rank (\d+) world (\d+) final_step (\d+) "
    r"loss [-0-9.einfa]+ params_crc ([0-9a-f]{8})"
)
_RESUME_RE = re.compile(r"\[drill\] rank (\d+) resumed step (\d+) wall ([0-9.]+)")


class _Tee(io.TextIOBase):
    def __init__(self, *sinks):
        self.sinks = [s for s in sinks if s is not None]

    def write(self, s):
        for k in self.sinks:
            k.write(s)
        return len(s)

    def flush(self):
        for k in self.sinks:
            k.flush()


def _parse_crcs(log: str) -> dict[int, str]:
    return {int(m.group(1)): m.group(4) for m in _CRC_RE.finditer(log)}


def _parse_resumes(log: str) -> list[tuple[int, int, float]]:
    return [
        (int(m.group(1)), int(m.group(2)), float(m.group(3)))
        for m in _RESUME_RE.finditer(log)
    ]


def run_drill(
    base_dir: str,
    *,
    world: int = 2,
    steps: int = 20,
    ckpt_every: int = 5,
    kill_step: int = 13,
    kill_rank: int = 1,
    backoff_s: float = 0.25,
    timeout_s: float = 600.0,
    seed: int = 0,
    sink=None,
) -> dict:
    """Run the full drill; returns the MTTR/bit-exactness evidence dict.

    Sequence: (1) uninterrupted ``world``-process run → reference CRC;
    (2) same job with rank ``kill_rank`` hard-killed at ``kill_step``,
    supervised by :class:`ElasticController` (restart policy, seeded
    backoff, fresh coordinator port) → must resume from the newest valid
    checkpoint and finish with the *same* CRC; (3) merge the per-rank
    traces into one document and check one pid track per process.
    ``ok`` in the result is the drill verdict the CLI / tests gate on.
    """
    from tpudml.elastic.controller import ElasticController
    from tpudml.launch.cluster import ClusterSpec
    from tpudml.launch.launcher import launch
    from tpudml.obs.tracer import merge_chrome_traces, validate_chrome_trace

    base = Path(base_dir)
    base.mkdir(parents=True, exist_ok=True)
    obs_dir = base / "obs"
    child = [
        sys.executable, "-u", "-m", "tpudml.elastic.drill",
        "--steps", str(steps),
        "--ckpt_every", str(ckpt_every),
        "--seed", str(seed),
        "--obs_dir", str(obs_dir),
    ]
    spec = ClusterSpec(num_processes=world, timeout_s=timeout_s, grace_s=3.0)

    # (1) the uninterrupted reference run.
    clean_log = io.StringIO()
    clean = launch(
        child + ["--ckpt_dir", str(base / "clean_ckpt")],
        spec,
        sink=_Tee(clean_log, sink),
    )
    clean_crcs = _parse_crcs(clean_log.getvalue())

    # (2) the drill run: adversary armed, controller supervising.
    marker = base / "kill.marker"
    drill_cmd = child + [
        "--ckpt_dir", str(base / "drill_ckpt"),
        "--kill_step", str(kill_step),
        "--kill_rank", str(kill_rank),
        "--kill_marker", str(marker),
    ]
    drill_log = io.StringIO()
    ctrl = ElasticController(
        drill_cmd,
        dataclasses.replace(
            spec,
            restart_backoff_s=backoff_s,
            restart_backoff_jitter=0.5,
            restart_backoff_seed=seed,
        ),
        policy="restart",
        max_reforms=2,
        sink=_Tee(drill_log, sink),
    )
    eres = ctrl.run()
    drill_crcs = _parse_crcs(drill_log.getvalue())
    resumes = _parse_resumes(drill_log.getvalue())

    # (3) per-process trace evidence: the final (successful) incarnation's
    # ranks each exported their own pid track.
    pids: list[int] = []
    trace_ok = False
    trace_files = sorted(obs_dir.glob("trace_p*.json"))
    if trace_files:
        try:
            merged = merge_chrome_traces(
                [json.loads(p.read_text()) for p in trace_files]
            )
            validate_chrome_trace(merged)
            (obs_dir / "trace.json").write_text(
                json.dumps(merged, sort_keys=True, separators=(",", ":")) + "\n"
            )
            pids = sorted(
                {e["pid"] for e in merged["traceEvents"] if e["ph"] != "M"}
            )
            trace_ok = pids == list(range(world))
        except ValueError:
            trace_ok = False

    # MTTR accounting, anchored on wall clocks: the failed round's end
    # (containment complete) → the last rank's resume print.
    steps_lost = None
    restart_latency_s = None
    resume_step = None
    if resumes and len(eres.records) >= 2:
        resume_step = min(s for _, s, _ in resumes)
        steps_lost = kill_step - resume_step
        restart_latency_s = max(w for _, _, w in resumes) - eres.records[0].t_end
    ports = [r.coordinator_port for r in eres.records]
    bit_exact = (
        len(clean_crcs) == world
        and len(drill_crcs) == world
        and len({*clean_crcs.values(), *drill_crcs.values()}) == 1
    )
    ok = (
        clean.success
        and eres.success
        and eres.reforms == 1
        and bit_exact
        and steps_lost is not None
        and steps_lost >= 0
        and len(set(ports)) == len(ports)
        and trace_ok
    )
    return {
        "ok": ok,
        "bit_exact": bit_exact,
        "world": world,
        "steps": steps,
        "kill_step": kill_step,
        "kill_rank": kill_rank,
        "killed_rank_observed": eres.records[0].failed_rank
        if eres.records
        else None,
        "resume_step": resume_step,
        "steps_lost": steps_lost,
        "reforms": eres.reforms,
        "coordinator_ports": ports,
        "fresh_port": len(set(ports)) == len(ports),
        "backoff_s": eres.records[-1].backoff_s if eres.reforms else 0.0,
        "restart_latency_s": restart_latency_s,
        "clean_wall_s": clean.elapsed_s,
        "drill_wall_s": eres.total_elapsed_s,
        "overhead_vs_clean_frac": (
            (eres.total_elapsed_s - clean.elapsed_s) / clean.elapsed_s
            if clean.elapsed_s
            else None
        ),
        "params_crc": next(iter(clean_crcs.values()), None),
        "trace_pids": pids,
    }


if __name__ == "__main__":
    sys.exit(child_main())
