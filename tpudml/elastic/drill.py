"""The elastic failure drills: kill → backoff → re-form → bit-exact resume.

Three pieces:

- :func:`child_main` — the per-rank training program the drills supervise
  (``python -m tpudml.elastic.drill``). A deliberately small but *real*
  multi-process job: gloo-backed cross-process collectives on a
  ``('data',)`` mesh, batches that are a pure function of the step index
  (so any incarnation replays the same trajectory), sharded CRC-verified
  checkpoints every k steps, and resume from the newest valid step. The
  child speaks the planner's language: ``--plan plan.json`` picks the
  engine chain (plain DP, or ZeRO-1 via the real
  :class:`~tpudml.optim.zero1.ZeRO1` wrapper) and accumulation with the
  same explicit-CLI-wins precedence the tasks use, and its checkpoints
  are **chain-agnostic**: always the canonical ``{params, mom, step}``
  full-parameter layout (ZeRO-1's flat optimizer shards are gathered to
  parameter shape at save and re-sharded at restore), so any chain at
  any world restores any other chain's checkpoint. A seeded
  :func:`~tpudml.resilience.faults.rank_kill_hook` plays the adversary.
  Each rank prints its final parameter CRC, its executed-loss-history
  CRC, and its measured steps/s, and exports its own flight-recorder
  track.

- :func:`run_drill` — the PR 14 restart drill: run the job once
  uninterrupted, once under :class:`ElasticController` (restart policy)
  with the adversary armed, then require the two final parameter CRCs to
  be **bit-identical** and report the MTTR evidence.

- :func:`run_shrink_drill` — the adaptive-recovery drill (PR 16): SIGKILL
  a rank under the ``shrink`` policy with a
  :class:`~tpudml.elastic.replan.Replanner` attached. The controller
  consults the planner at the new world, the planner picks a *different*
  engine chain (world 2 ZeRO-1+accum → world 1 plain DP — ZeRO-1 shards
  nothing on one chip), and the next incarnation resumes from the
  CRC-valid sharded checkpoint under the new chain. The verdict requires
  the continued run to be bit-exact (params CRC *and* loss-history CRC)
  against an uninterrupted run of the new chain started from the same
  checkpoint, and the re-plan receipts to say *why* the old chain lost.
  Optionally an A/B "naive" arm re-runs the old chain at the shrunken
  world (explicit ``--engine``/``--accum_steps`` flags overriding the
  plan — the precedence demo) so "re-planned beats naive" is a measured
  row.
"""

from __future__ import annotations

import argparse
import dataclasses
import io
import json
import re
import shutil
import sys
import time
import zlib
from pathlib import Path

import numpy as np


# --------------------------------------------------------------- child


def _params_crc(tree) -> int:
    """CRC-32 over the concatenated little-endian bytes of every leaf, in
    ``jax.tree.leaves`` order — the drill's bit-exactness witness."""
    import jax

    crc = 0
    for leaf in jax.tree.leaves(tree):
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(leaf)).tobytes(), crc)
    return crc


def _flat_pad_np(a: np.ndarray, world: int) -> np.ndarray:
    """Host-side mirror of ZeRO1's flatten-and-pad leaf layout."""
    flat = np.ascontiguousarray(a).reshape(-1)
    c = -(-flat.size // world)
    out = np.zeros((world * c,), flat.dtype)
    out[: flat.size] = flat
    return out


def child_main(argv: list[str] | None = None) -> int:
    """One rank of the drill job (rank/world/coordinator via the
    launcher's TPUDML_* env contract)."""
    ap = argparse.ArgumentParser(prog="tpudml.elastic.drill")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--ckpt_dir", type=str, required=True)
    ap.add_argument("--ckpt_every", type=int, default=5)
    ap.add_argument("--global_batch", type=int, default=16)
    ap.add_argument("--feature_dim", type=int, default=16)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kill_step", type=int, default=-1)
    ap.add_argument("--kill_rank", type=int, default=1)
    ap.add_argument("--kill_marker", type=str, default=None)
    ap.add_argument("--obs_dir", type=str, default=None)
    # Engine-chain knobs: the plan fills whatever the CLI leaves unset —
    # the same explicit-flags-win precedence core/config.py applies for
    # the tasks' --plan wiring.
    ap.add_argument("--plan", type=str, default=None,
                    help="planner plan.json; its engine_config fills "
                         "engine/accum_steps unless given explicitly")
    ap.add_argument("--engine", type=str, default=None,
                    choices=("dp", "zero1"))
    ap.add_argument("--accum_steps", type=int, default=None)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpudml.checkpoint.sharded import (
        restore_latest_valid_sharded,
        save_sharded_checkpoint,
    )
    from tpudml.core.config import DistributedConfig, MeshConfig
    from tpudml.core.dist import distributed_init, make_mesh, process_index
    from tpudml.core.prng import seed_key
    from tpudml.models.mlp import ForwardMLP
    from tpudml.nn.losses import softmax_cross_entropy
    from tpudml.obs.tracer import Tracer, set_tracer
    from tpudml.optim.optimizers import make_optimizer
    from tpudml.optim.zero1 import ZeRO1
    from tpudml.parallel.sharding import shard_map_fn
    from tpudml.resilience.faults import rank_kill_hook

    # Plan merge, explicit CLI wins: flags left at their (None) defaults
    # are filled from the plan's engine_config; anything given explicitly
    # overrides the plan.
    engine = args.engine
    accum = args.accum_steps
    if args.plan:
        from tpudml.plan.emit import load_plan

        ec = load_plan(args.plan)["engine_config"]
        if engine is None:
            engine = ec.get("engine")
        if accum is None:
            accum = int(ec.get("accum_steps", 1))
    engine = engine or "dp"
    accum = accum or 1
    if engine not in ("dp", "zero1"):
        raise SystemExit(
            f"drill child implements dp/zero1 chains, got {engine!r}"
        )

    distributed_init(DistributedConfig.from_env())
    rank = process_index()
    tracer = Tracer()
    set_tracer(tracer)
    mesh = make_mesh(MeshConfig({"data": -1}))
    world = int(np.prod(mesh.devices.shape))
    if args.global_batch % world:
        raise SystemExit(f"global_batch {args.global_batch} % world {world} != 0")
    if (args.global_batch // world) % accum:
        raise SystemExit(
            f"local batch {args.global_batch // world} % accum {accum} != 0"
        )

    model = ForwardMLP(
        in_features=args.feature_dim, hidden=(32, 16), num_classes=args.classes
    )
    params, _ = model.init(seed_key(args.seed))
    opt = make_optimizer("sgd", args.lr, momentum=args.momentum)
    zopt = (
        ZeRO1(base=opt, axis_name="data", world=world)
        if engine == "zero1"
        else None
    )

    # Batches are a pure function of the step index (same on every rank and
    # every incarnation): a resumed run replays steps c..N-1 bit-exactly.
    teacher = (
        np.random.default_rng(args.seed + 777)
        .standard_normal((args.feature_dim, args.classes))
        .astype(np.float32)
    )

    def batch_for(step: int) -> tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(args.seed * 1_000_003 + step)
        x = rng.standard_normal((args.global_batch, args.feature_dim)).astype(
            np.float32
        )
        y = np.argmax(x @ teacher, axis=1).astype(np.int32)
        return x, y

    rep = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P("data"))
    flat_sharded = NamedSharding(mesh, P("data"))

    def to_global(host: np.ndarray, sharding) -> jax.Array:
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx, a=host: a[idx]
        )

    def replicate(tree):
        return jax.tree.map(
            lambda a: to_global(np.asarray(a), rep), tree
        )

    # Resume from the newest CRC-valid sharded checkpoint, if any. The
    # checkpoint layout is CANONICAL — full-shaped params + full-shaped
    # momentum ("mom") + step — regardless of the chain that wrote it, so
    # any chain at any world restores any other's checkpoint (the
    # property that makes shrink-with-chain-switch a restore, not a
    # retrain).
    params_host = jax.tree.map(np.asarray, params)
    mom_host = (
        jax.tree.map(np.zeros_like, params_host) if args.momentum else ()
    )
    target = {
        "mom": mom_host,
        "params": params_host,
        "step": np.zeros((), np.int64),
    }
    restored = restore_latest_valid_sharded(args.ckpt_dir, target)
    start_step = int(restored["step"])
    if start_step:
        print(
            f"[drill] rank {rank} resumed step {start_step} "
            f"wall {time.time():.3f}",
            flush=True,
        )
        tracer.instant("drill_resume", cat="elastic", args={"step": start_step})
    params = replicate(restored["params"])
    if engine == "zero1":
        # Chain-specific device layout: ZeRO-1 moments live flat-padded
        # [N·c] and row-sharded over the data axis — the exact
        # ZeRO1.flatten_params layout, zero-padding exact for SGD.
        opt_state = jax.tree.map(
            lambda a: to_global(_flat_pad_np(np.asarray(a), world), flat_sharded),
            restored["mom"],
        )
    else:
        opt_state = replicate(restored["mom"])

    def loss_fn(p, xm, ym):
        logits, _ = model.apply(p, {}, xm, train=True)
        return softmax_cross_entropy(logits, ym)

    def local_loss_grads(p, x, y):
        """Gradient accumulation over ``accum`` micro-batches of the
        local rows (mean loss, mean grads) — unrolled, deterministic."""
        if accum == 1:
            return jax.value_and_grad(loss_fn)(p, x, y)
        xs = x.reshape(accum, -1, x.shape[-1])
        ys = y.reshape(accum, -1)
        loss, grads = jax.value_and_grad(loss_fn)(p, xs[0], ys[0])
        for i in range(1, accum):
            li, gi = jax.value_and_grad(loss_fn)(p, xs[i], ys[i])
            loss = loss + li
            grads = jax.tree.map(jnp.add, grads, gi)
        return loss / accum, jax.tree.map(lambda g: g / accum, grads)

    if engine == "zero1":
        state_spec = P("data")

        def step_body(params, opt_state, x, y):
            loss, grads = local_loss_grads(params, x, y)
            loss = jax.lax.pmean(loss, "data")
            # No gradient pmean: ZeRO1.update's reduce-scatter IS the
            # mean over the data axis (zero1_handles contract).
            new_params, new_opt = zopt.update(grads, opt_state, params)
            return new_params, new_opt, loss
    else:
        state_spec = P()

        def step_body(params, opt_state, x, y):
            loss, grads = local_loss_grads(params, x, y)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "data"), grads)
            loss = jax.lax.pmean(loss, "data")
            new_params, new_opt = opt.update(grads, opt_state, params)
            return new_params, new_opt, loss

    step_fn = jax.jit(
        shard_map_fn(
            step_body,
            mesh,
            in_specs=(P(), state_spec, P("data"), P("data")),
            out_specs=(P(), state_spec, P()),
        )
    )

    if engine == "zero1":
        tmpl = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params_host
        )
        gather_mom = jax.jit(
            shard_map_fn(
                lambda s: zopt.gather_params(s, tmpl),
                mesh,
                in_specs=(state_spec,),
                out_specs=P(),
            )
        )

    def canonical_mom(state):
        """The checkpointed momentum: always full parameter-shaped."""
        if engine == "zero1" and jax.tree.leaves(state):
            return gather_mom(state)
        return state

    kill = None
    if args.kill_step >= 0:
        kill = rank_kill_hook(
            args.kill_step, marker=args.kill_marker, rank=args.kill_rank
        )

    loss = None
    losses: list[np.float32] = []
    t_loop = time.perf_counter()
    for step in range(start_step, args.steps):
        if kill is not None:
            kill(step=step)
        x, y = batch_for(step)
        with tracer.span("drill_step", cat="step", args={"step": step}):
            params, opt_state, loss = step_fn(
                params, opt_state, to_global(x, row_sharded), to_global(y, row_sharded)
            )
            jax.block_until_ready(loss)
        losses.append(np.float32(np.asarray(loss)))
        if args.ckpt_every and (step + 1) % args.ckpt_every == 0:
            with tracer.span("drill_checkpoint", cat="ckpt", args={"step": step + 1}):
                save_sharded_checkpoint(
                    args.ckpt_dir,
                    {
                        "mom": canonical_mom(opt_state),
                        "params": params,
                        "step": np.int64(step + 1),
                    },
                    step + 1,
                )
    wall = time.perf_counter() - t_loop
    executed = args.steps - start_step
    sps = executed / wall if wall > 0 else 0.0

    crc = _params_crc(params)
    loss_crc = zlib.crc32(np.asarray(losses, np.float32).tobytes())
    print(
        f"[drill] rank {rank} world {world} engine {engine} accum {accum} "
        f"final_step {args.steps} loss {float(np.asarray(loss)):.6f} "
        f"params_crc {crc:08x} loss_crc {loss_crc:08x} "
        f"steps_per_s {sps:.3f}",
        flush=True,
    )
    if args.obs_dir:
        # One Chrome-trace pid track per process (pid = process_index()).
        tracer.export(Path(args.obs_dir) / f"trace_p{rank}.json")
    return 0


# --------------------------------------------------------------- driver

_FINAL_RE = re.compile(
    r"\[drill\] rank (\d+) world (\d+) engine (\w+) accum (\d+) "
    r"final_step (\d+) loss [-0-9.einfa]+ params_crc ([0-9a-f]{8}) "
    r"loss_crc ([0-9a-f]{8}) steps_per_s ([0-9.]+)"
)
_RESUME_RE = re.compile(r"\[drill\] rank (\d+) resumed step (\d+) wall ([0-9.]+)")


class _Tee(io.TextIOBase):
    def __init__(self, *sinks):
        self.sinks = [s for s in sinks if s is not None]

    def write(self, s):
        for k in self.sinks:
            k.write(s)
        return len(s)

    def flush(self):
        for k in self.sinks:
            k.flush()


def _parse_finals(log: str) -> dict[int, dict]:
    """rank → the final-line evidence record."""
    out = {}
    for m in _FINAL_RE.finditer(log):
        out[int(m.group(1))] = {
            "world": int(m.group(2)),
            "engine": m.group(3),
            "accum_steps": int(m.group(4)),
            "final_step": int(m.group(5)),
            "params_crc": m.group(6),
            "loss_crc": m.group(7),
            "steps_per_s": float(m.group(8)),
        }
    return out


def _parse_crcs(log: str) -> dict[int, str]:
    return {r: f["params_crc"] for r, f in _parse_finals(log).items()}


def _parse_resumes(log: str) -> list[tuple[int, int, float]]:
    return [
        (int(m.group(1)), int(m.group(2)), float(m.group(3)))
        for m in _RESUME_RE.finditer(log)
    ]


def run_drill(
    base_dir: str,
    *,
    world: int = 2,
    steps: int = 20,
    ckpt_every: int = 5,
    kill_step: int = 13,
    kill_rank: int = 1,
    backoff_s: float = 0.25,
    timeout_s: float = 600.0,
    seed: int = 0,
    sink=None,
) -> dict:
    """Run the full restart drill; returns the MTTR/bit-exactness evidence.

    Sequence: (1) uninterrupted ``world``-process run → reference CRC;
    (2) same job with rank ``kill_rank`` hard-killed at ``kill_step``,
    supervised by :class:`ElasticController` (restart policy, seeded
    backoff, fresh coordinator port) → must resume from the newest valid
    checkpoint and finish with the *same* CRC; (3) merge the per-rank
    traces into one document and check one pid track per process.
    ``ok`` in the result is the drill verdict the CLI / tests gate on.
    """
    from tpudml.elastic.controller import ElasticController
    from tpudml.launch.cluster import ClusterSpec
    from tpudml.launch.launcher import launch
    from tpudml.obs.tracer import merge_chrome_traces, validate_chrome_trace

    base = Path(base_dir)
    base.mkdir(parents=True, exist_ok=True)
    obs_dir = base / "obs"
    child = [
        sys.executable, "-u", "-m", "tpudml.elastic.drill",
        "--steps", str(steps),
        "--ckpt_every", str(ckpt_every),
        "--seed", str(seed),
        "--obs_dir", str(obs_dir),
    ]
    spec = ClusterSpec(num_processes=world, timeout_s=timeout_s, grace_s=3.0)

    # (1) the uninterrupted reference run.
    clean_log = io.StringIO()
    clean = launch(
        child + ["--ckpt_dir", str(base / "clean_ckpt")],
        spec,
        sink=_Tee(clean_log, sink),
    )
    clean_crcs = _parse_crcs(clean_log.getvalue())

    # (2) the drill run: adversary armed, controller supervising.
    marker = base / "kill.marker"
    drill_cmd = child + [
        "--ckpt_dir", str(base / "drill_ckpt"),
        "--kill_step", str(kill_step),
        "--kill_rank", str(kill_rank),
        "--kill_marker", str(marker),
    ]
    drill_log = io.StringIO()
    ctrl = ElasticController(
        drill_cmd,
        dataclasses.replace(
            spec,
            restart_backoff_s=backoff_s,
            restart_backoff_jitter=0.5,
            restart_backoff_seed=seed,
        ),
        policy="restart",
        max_reforms=2,
        sink=_Tee(drill_log, sink),
    )
    eres = ctrl.run()
    drill_crcs = _parse_crcs(drill_log.getvalue())
    resumes = _parse_resumes(drill_log.getvalue())
    obs_dir.mkdir(parents=True, exist_ok=True)
    (obs_dir / "elastic.json").write_text(
        json.dumps(eres.to_dict(), indent=2, sort_keys=True) + "\n"
    )

    # (3) per-process trace evidence: the final (successful) incarnation's
    # ranks each exported their own pid track.
    pids: list[int] = []
    trace_ok = False
    trace_files = sorted(obs_dir.glob("trace_p*.json"))
    if trace_files:
        try:
            merged = merge_chrome_traces(
                [json.loads(p.read_text()) for p in trace_files]
            )
            validate_chrome_trace(merged)
            (obs_dir / "trace.json").write_text(
                json.dumps(merged, sort_keys=True, separators=(",", ":")) + "\n"
            )
            pids = sorted(
                {e["pid"] for e in merged["traceEvents"] if e["ph"] != "M"}
            )
            trace_ok = pids == list(range(world))
        except ValueError:
            trace_ok = False

    # MTTR accounting, anchored on wall clocks: the failed round's end
    # (containment complete) → the last rank's resume print.
    steps_lost = None
    restart_latency_s = None
    resume_step = None
    if resumes and len(eres.records) >= 2:
        resume_step = min(s for _, s, _ in resumes)
        steps_lost = kill_step - resume_step
        restart_latency_s = max(w for _, _, w in resumes) - eres.records[0].t_end
    ports = [r.coordinator_port for r in eres.records]
    bit_exact = (
        len(clean_crcs) == world
        and len(drill_crcs) == world
        and len({*clean_crcs.values(), *drill_crcs.values()}) == 1
    )
    ok = (
        clean.success
        and eres.success
        and eres.reforms == 1
        and bit_exact
        and steps_lost is not None
        and steps_lost >= 0
        and len(set(ports)) == len(ports)
        and trace_ok
    )
    return {
        "ok": ok,
        "bit_exact": bit_exact,
        "world": world,
        "steps": steps,
        "kill_step": kill_step,
        "kill_rank": kill_rank,
        "killed_rank_observed": eres.records[0].failed_rank
        if eres.records
        else None,
        "resume_step": resume_step,
        "steps_lost": steps_lost,
        "reforms": eres.reforms,
        "coordinator_ports": ports,
        "fresh_port": len(set(ports)) == len(ports),
        "backoff_s": eres.records[-1].backoff_s if eres.reforms else 0.0,
        "restart_latency_s": restart_latency_s,
        "clean_wall_s": clean.elapsed_s,
        "drill_wall_s": eres.total_elapsed_s,
        "overhead_vs_clean_frac": (
            (eres.total_elapsed_s - clean.elapsed_s) / clean.elapsed_s
            if clean.elapsed_s
            else None
        ),
        "params_crc": next(iter(clean_crcs.values()), None),
        "trace_pids": pids,
    }


def _copy_step(src_ckpt: Path, step: int, dst_ckpt: Path) -> None:
    """Copy one ``step_{k}`` checkpoint dir — the pristine restore point
    the reference arms start from (the drill's own dir keeps growing past
    it as the continuation writes newer steps)."""
    src = src_ckpt / f"step_{step}"
    dst = dst_ckpt / f"step_{step}"
    dst_ckpt.mkdir(parents=True, exist_ok=True)
    shutil.copytree(src, dst)


def run_shrink_drill(
    base_dir: str,
    *,
    world: int = 2,
    steps: int = 20,
    ckpt_every: int = 5,
    kill_step: int = 13,
    kill_rank: int = 1,
    backoff_s: float = 0.25,
    timeout_s: float = 600.0,
    seed: int = 0,
    include_naive: bool = False,
    sink=None,
) -> dict:
    """The shrink-re-plan drill: SIGKILL → planner consulted at the new
    world → resume under a *different* engine chain → bit-exact.

    Sequence:

    1. Plan the launch config: :class:`Replanner` over the dp/zero1
       lattice at ``world`` (winner: ZeRO-1 + accumulation) writes
       ``plan.json``; the child picks the chain up via ``--plan``.
    2. Drill run under :class:`ElasticController` (``shrink`` policy,
       replanner attached): rank ``kill_rank`` is hard-killed at
       ``kill_step``; the controller shrinks to ``world-1``, consults
       the planner (at world 1 ZeRO-1 is infeasible — receipts say so —
       and plain DP wins), rewrites ``plan.json``, and re-forms; the
       new incarnation restores the canonical checkpoint under the new
       chain and finishes.
    3. Reference arm: an uninterrupted ``world-1`` run of the *new*
       chain started from a pristine copy of the same checkpoint — the
       continued run must match it bit-exactly (params CRC and
       loss-history CRC).
    4. Optional naive arm (``include_naive``): the *old* chain forced at
       ``world-1`` via explicit ``--engine``/``--accum_steps`` flags
       (which override the plan — the precedence contract), so
       re-planned-vs-naive throughput is measured, not claimed.
    """
    from tpudml.elastic.controller import ElasticController
    from tpudml.elastic.replan import Replanner
    from tpudml.launch.cluster import ClusterSpec
    from tpudml.launch.launcher import launch
    from tpudml.obs.tracer import (
        Tracer,
        merge_chrome_traces,
        set_tracer,
        validate_chrome_trace,
    )
    from tpudml.plan.space import flagship_lm

    base = Path(base_dir)
    base.mkdir(parents=True, exist_ok=True)
    obs_dir = base / "obs"
    obs_dir.mkdir(parents=True, exist_ok=True)
    plan_path = base / "plan.json"
    ckpt_dir = base / "drill_ckpt"

    # Controller-side tracer: the reform/replan instants land in their
    # own exported track (the children export theirs per-rank).
    tracer = Tracer()
    prev_tracer = set_tracer(tracer)
    try:
        # (1) plan the launch. dp/zero1 lattice: at world>=2 the planner
        # picks ZeRO-1 (+accum, overlap hidden); at world 1 ZeRO-1 has no
        # mesh, so a shrink forces a genuine chain switch.
        rp = Replanner(
            flagship_lm(),
            engines=["dp", "zero1"],
            verify=False,
            plan_path=plan_path,
        )
        old_plan = rp.initial_plan(world)
        old_key = old_plan["winner"]["candidate"]["key"]
        old_engine = old_plan["engine_config"]["engine"]
        old_accum = old_plan["engine_config"]["accum_steps"]

        child = [
            sys.executable, "-u", "-m", "tpudml.elastic.drill",
            "--steps", str(steps),
            "--ckpt_every", str(ckpt_every),
            "--seed", str(seed),
            "--plan", str(plan_path),
        ]
        spec = ClusterSpec(num_processes=world, timeout_s=timeout_s, grace_s=3.0)

        # (2) the drill: shrink policy + replanner.
        marker = base / "kill.marker"
        drill_cmd = child + [
            "--ckpt_dir", str(ckpt_dir),
            "--obs_dir", str(obs_dir),
            "--kill_step", str(kill_step),
            "--kill_rank", str(kill_rank),
            "--kill_marker", str(marker),
        ]
        drill_log = io.StringIO()
        ctrl = ElasticController(
            drill_cmd,
            dataclasses.replace(
                spec,
                restart_backoff_s=backoff_s,
                restart_backoff_jitter=0.5,
                restart_backoff_seed=seed,
            ),
            policy="shrink",
            min_world=1,
            max_reforms=2,
            replanner=rp,
            sink=_Tee(drill_log, sink),
        )
        eres = ctrl.run()
        finals = _parse_finals(drill_log.getvalue())
        resumes = _parse_resumes(drill_log.getvalue())
        new_plan = rp.plan
        new_key = new_plan["winner"]["candidate"]["key"]
        new_engine = new_plan["engine_config"]["engine"]
        new_accum = new_plan["engine_config"]["accum_steps"]
        replan = eres.replans[0] if eres.replans else None
        (obs_dir / "elastic.json").write_text(
            json.dumps(eres.to_dict(), indent=2, sort_keys=True) + "\n"
        )
        tracer.export(obs_dir / "trace_controller.json")

        resume_step = min((s for _, s, _ in resumes), default=None)
        steps_lost = kill_step - resume_step if resume_step is not None else None
        restart_latency_s = (
            max(w for _, _, w in resumes) - eres.records[0].t_end
            if resumes and len(eres.records) >= 2
            else None
        )
        final = finals.get(0)

        # (3) the reference arm: new chain, same checkpoint, uninterrupted.
        bit_exact = False
        ref_final = None
        if resume_step is not None and final is not None:
            _copy_step(ckpt_dir, resume_step, base / "ref_ckpt")
            ref_log = io.StringIO()
            ref = launch(
                child + ["--ckpt_dir", str(base / "ref_ckpt")],
                dataclasses.replace(spec, num_processes=world - 1),
                sink=_Tee(ref_log, sink),
            )
            ref_final = _parse_finals(ref_log.getvalue()).get(0)
            bit_exact = (
                ref.success
                and ref_final is not None
                and ref_final["params_crc"] == final["params_crc"]
                and ref_final["loss_crc"] == final["loss_crc"]
            )

        # (4) the naive A/B arm: old chain forced at the shrunken world by
        # explicit flags (explicit CLI beats the plan file).
        naive = None
        replan_beats_naive = None
        if include_naive and resume_step is not None and final is not None:
            _copy_step(ckpt_dir, resume_step, base / "naive_ckpt")
            naive_log = io.StringIO()
            naive_res = launch(
                child + [
                    "--ckpt_dir", str(base / "naive_ckpt"),
                    "--engine", str(old_engine),
                    "--accum_steps", str(old_accum),
                ],
                dataclasses.replace(spec, num_processes=world - 1),
                sink=_Tee(naive_log, sink),
            )
            naive_final = _parse_finals(naive_log.getvalue()).get(0)
            if naive_res.success and naive_final is not None:
                naive = {
                    "engine": naive_final["engine"],
                    "accum_steps": naive_final["accum_steps"],
                    "steps_per_s": naive_final["steps_per_s"],
                    "params_crc": naive_final["params_crc"],
                }
                replan_beats_naive = (
                    final["steps_per_s"] > naive_final["steps_per_s"]
                )

        # Trace evidence: the surviving incarnation's rank 0 track merges.
        pids: list[int] = []
        trace_files = sorted(obs_dir.glob("trace_p*.json"))
        if trace_files:
            try:
                merged = merge_chrome_traces(
                    [json.loads(p.read_text()) for p in trace_files]
                )
                validate_chrome_trace(merged)
                (obs_dir / "trace.json").write_text(
                    json.dumps(merged, sort_keys=True, separators=(",", ":")) + "\n"
                )
                pids = sorted(
                    {e["pid"] for e in merged["traceEvents"] if e["ph"] != "M"}
                )
            except ValueError:
                pids = []

        ports = [r.coordinator_port for r in eres.records]
        receipts = list(replan["receipts"]) if replan else []
        plan_switched = bool(replan and replan.get("switched") and not replan.get("error"))
        chain_switched = (
            final is not None
            and final["engine"] == new_engine
            and new_engine != old_engine
        )
        ok = (
            eres.success
            and eres.reforms == 1
            and eres.final_world == world - 1
            and plan_switched
            and chain_switched
            and bool(receipts)
            and resume_step is not None
            and steps_lost is not None
            and steps_lost >= 0
            and bit_exact
            and len(set(ports)) == len(ports)
        )
        return {
            "ok": ok,
            "mode": "shrink_replan",
            "bit_exact": bit_exact,
            "world": world,
            "final_world": eres.final_world,
            "steps": steps,
            "kill_step": kill_step,
            "kill_rank": kill_rank,
            "killed_rank_observed": eres.records[0].failed_rank
            if eres.records
            else None,
            "resume_step": resume_step,
            "steps_lost": steps_lost,
            "reforms": eres.reforms,
            "coordinator_ports": ports,
            "fresh_port": len(set(ports)) == len(ports),
            "backoff_s": eres.records[-1].backoff_s if eres.reforms else 0.0,
            "restart_latency_s": restart_latency_s,
            "drill_wall_s": eres.total_elapsed_s,
            "old_plan": {
                "key": old_key, "engine": old_engine, "accum_steps": old_accum,
            },
            "new_plan": {
                "key": new_key, "engine": new_engine, "accum_steps": new_accum,
            },
            "plan_switched": plan_switched,
            "chain_switched": chain_switched,
            "replan_latency_s": replan["latency_s"] if replan else None,
            "replan_receipts": receipts,
            "params_crc": final["params_crc"] if final else None,
            "loss_crc": final["loss_crc"] if final else None,
            "post_shrink_steps_per_s": final["steps_per_s"] if final else None,
            "naive": naive,
            "replan_beats_naive": replan_beats_naive,
            "trace_pids": pids,
        }
    finally:
        set_tracer(prev_tracer)


if __name__ == "__main__":
    sys.exit(child_main())
