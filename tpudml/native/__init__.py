"""Native (C++) host data-plane, bound via ctypes.

Lazy-builds ``dataplane.cpp`` with g++ into a cached shared library on
first use and exposes thin numpy wrappers. Every entry point has a pure
numpy fallback, so the framework runs unchanged where no toolchain exists
(``TPUDML_NO_NATIVE=1`` forces the fallback; ``available()`` reports which
path is active).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "dataplane.cpp"
_BUILD_DIR = _HERE / "_build"
_LIB_PATH = _BUILD_DIR / "libtpudml_dataplane.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def _build() -> ctypes.CDLL | None:
    if os.environ.get("TPUDML_NO_NATIVE"):
        return None
    try:
        if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime:
            _BUILD_DIR.mkdir(exist_ok=True)
            tmp = _LIB_PATH.with_suffix(f".tmp{os.getpid()}.so")
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", str(tmp), str(_SRC)],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, _LIB_PATH)  # atomic: concurrent builders race safely
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.tpudml_gather_rows_f32.argtypes = [
            _f32p, _i64p, ctypes.c_int64, ctypes.c_int64, _f32p,
        ]
        lib.tpudml_gather_rows_u8.argtypes = [
            _u8p, _i64p, ctypes.c_int64, ctypes.c_int64, _u8p,
        ]
        lib.tpudml_gather_normalize_u8.argtypes = [
            _u8p, _i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, _f32p,
        ]
        lib.tpudml_gather_i32.argtypes = [_i32p, _i64p, ctypes.c_int64, _i32p]
        lib.tpudml_byteswap.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
        ]
        lib.tpudml_byteswap.restype = ctypes.c_int
        return lib
    except (OSError, subprocess.CalledProcessError):
        return None


def _get() -> ctypes.CDLL | None:
    global _lib, _tried
    if not _tried:
        with _lock:
            if not _tried:
                _lib = _build()
                _tried = True
    return _lib


def available() -> bool:
    """True when the C++ data-plane is built and loaded."""
    return _get() is not None


def _prep_idx(idx: np.ndarray, n: int) -> np.ndarray:
    """Validate + canonicalize gather indices. The C++ kernels do raw
    pointer arithmetic, so out-of-range indices must be caught HERE (the
    numpy fallback would raise; the native path would read out of bounds).
    Negative indices follow numpy semantics (count from the end)."""
    idx = np.ascontiguousarray(idx, np.int64)
    if idx.size:
        lo, hi = int(idx.min()), int(idx.max())
        if lo < -n or hi >= n:
            raise IndexError(
                f"gather index out of range: [{lo}, {hi}] vs {n} rows"
            )
        if lo < 0:
            idx = np.ascontiguousarray(np.where(idx < 0, idx + n, idx))
    return idx


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """out[i] = src[idx[i]] for row-major [N, ...] float32/uint8 arrays."""
    idx = _prep_idx(idx, len(src))
    lib = _get()
    if lib is None or not src.flags.c_contiguous or src.dtype not in (
        np.float32,
        np.uint8,
    ):
        return src[idx]
    row = int(np.prod(src.shape[1:], dtype=np.int64))
    out = np.empty((len(idx), *src.shape[1:]), src.dtype)
    flat_src = src.reshape(len(src), row) if src.ndim != 2 else src
    flat_out = out.reshape(len(idx), row)
    if src.dtype == np.float32:
        lib.tpudml_gather_rows_f32(flat_src, idx, len(idx), row, flat_out)
    else:
        lib.tpudml_gather_rows_u8(flat_src, idx, len(idx), row, flat_out)
    return out


def gather_normalize(
    src: np.ndarray, idx: np.ndarray, scale: float, bias: float = 0.0
) -> np.ndarray:
    """out[i] = src[idx[i]] * scale + bias for uint8 [N, ...] → float32."""
    idx = _prep_idx(idx, len(src))
    lib = _get()
    if lib is None or not src.flags.c_contiguous or src.dtype != np.uint8:
        return src[idx].astype(np.float32) * scale + bias
    row = int(np.prod(src.shape[1:], dtype=np.int64))
    out = np.empty((len(idx), *src.shape[1:]), np.float32)
    lib.tpudml_gather_normalize_u8(
        src.reshape(len(src), row), idx, len(idx), row, scale, bias,
        out.reshape(len(idx), row),
    )
    return out


def gather_labels(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    idx = _prep_idx(idx, len(src))
    lib = _get()
    if lib is None or not src.flags.c_contiguous or src.dtype != np.int32:
        return src[idx]
    out = np.empty(len(idx), np.int32)
    lib.tpudml_gather_i32(src, idx, len(idx), out)
    return out


def byteswap_inplace(arr: np.ndarray) -> np.ndarray:
    """In-place endian swap (IDX big-endian payloads); returns ``arr``."""
    width = arr.dtype.itemsize
    lib = _get()
    if width == 1:
        return arr
    if not arr.flags.writeable:
        # The C++ path writes through the raw pointer; mirror numpy's
        # in-place semantics instead of corrupting a read-only buffer.
        raise ValueError("byteswap_inplace requires a writeable array")
    if lib is None or not arr.flags.c_contiguous:
        arr[...] = arr.byteswap()
        return arr
    rc = lib.tpudml_byteswap(
        arr.ctypes.data_as(ctypes.c_void_p), arr.size, width
    )
    if rc != 0:  # unsupported width — numpy handles it
        arr[...] = arr.byteswap()
    return arr
