// Host data-plane kernels for tpudml.
//
// The reference's host data path lives inside torchvision/DataLoader C++
// internals (SURVEY.md §2.4: its only native code is vendored library
// internals). This is our equivalent: the per-step batch materialization —
// row gather + dequantize-normalize — done in one pass in C++, invoked via
// ctypes (no pybind11 in the image). The fused u8 path lets datasets stay
// resident in memory at 1/4 the bytes of float32 and turns per-batch
// normalization into a single streaming loop.
//
// Build: g++ -O3 -shared -fPIC (see tpudml/native/__init__.py; rebuilt
// automatically when this source is newer than the cached .so).

#include <cstdint>
#include <cstring>

extern "C" {

// out[i, :] = src[idx[i], :]  (row-major, rows of `row` float32 elements)
void tpudml_gather_rows_f32(const float* src, const int64_t* idx, int64_t n,
                            int64_t row, float* out) {
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(out + i * row, src + idx[i] * row,
                static_cast<size_t>(row) * sizeof(float));
  }
}

// out[i, :] = src[idx[i], :]  (uint8 rows, no conversion)
void tpudml_gather_rows_u8(const uint8_t* src, const int64_t* idx, int64_t n,
                           int64_t row, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(out + i * row, src + idx[i] * row, static_cast<size_t>(row));
  }
}

// out[i, j] = src[idx[i], j] * scale + bias  — fused gather + dequantize
// (the ToTensor /255 normalization of the reference pipeline,
// codes/task1/pytorch/model.py:93-95, done at batch time instead of load
// time so the resident dataset stays uint8).
void tpudml_gather_normalize_u8(const uint8_t* src, const int64_t* idx,
                                int64_t n, int64_t row, float scale,
                                float bias, float* out) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* s = src + idx[i] * row;
    float* o = out + i * row;
    for (int64_t j = 0; j < row; ++j) {
      o[j] = static_cast<float>(s[j]) * scale + bias;
    }
  }
}

// out[i] = src[idx[i]]  (label gather)
void tpudml_gather_i32(const int32_t* src, const int64_t* idx, int64_t n,
                       int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = src[idx[i]];
}

// In-place endian swap of n elements of `width` bytes (IDX files are
// big-endian; payloads wider than 1 byte need swapping on little-endian
// hosts). width ∈ {2, 4, 8}. Returns 0 on success, -1 on bad width.
int tpudml_byteswap(void* data, int64_t n, int32_t width) {
  if (width == 2) {
    uint16_t* p = static_cast<uint16_t*>(data);
    for (int64_t i = 0; i < n; ++i) p[i] = __builtin_bswap16(p[i]);
  } else if (width == 4) {
    uint32_t* p = static_cast<uint32_t*>(data);
    for (int64_t i = 0; i < n; ++i) p[i] = __builtin_bswap32(p[i]);
  } else if (width == 8) {
    uint64_t* p = static_cast<uint64_t*>(data);
    for (int64_t i = 0; i < n; ++i) p[i] = __builtin_bswap64(p[i]);
  } else {
    return -1;
  }
  return 0;
}

}  // extern "C"
