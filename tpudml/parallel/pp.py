"""Pipeline parallelism with micro-batching (GPipe-style schedule).

The reference's model parallelism is a 2-stage layer split whose forward is
two *blocking* RPC round-trips per batch — worker1 idles while worker2
computes and vice versa (codes/task4/model.py:49-66; SURVEY.md §3.4 calls
it the degenerate pipeline: PP with 1 micro-batch). SURVEY.md §2.3 lists
true micro-batched pipelining as the stretch goal on top of that port.

TPU-native design: the schedule is a ``lax.scan`` over pipeline ticks
inside ONE ``shard_map``-ed XLA program over a ``stage`` mesh axis.
Activations move between neighbouring stages with ``lax.ppermute`` — a
point-to-point ICI transfer, not host RPC — and every stage computes every
tick, so with M micro-batches the bubble shrinks from (S-1)/S of the step
(the reference's sequential RPC chain) to (S-1)/(M+S-1). The backward pass
needs no hand scheduling: AD transposes the scan and the ppermutes, which
XLA schedules as the reverse ring.

Scope: ``GPipe``/``OneFOneB`` run homogeneous stages — one ``block``
Module repeated S times with its parameters stacked on a leading stage
axis (the idiomatic JAX/GSPMD layout; transformer decoders fit directly).
``HeteroPipeline`` (below) pipelines HETEROGENEOUS stages — the task4
conv/fc split with different block structures and activation shapes —
via padded stage-param ravel + ``lax.switch`` dispatch; the GSPMD engine
in ``tpudml.parallel.mp`` remains the non-micro-batched alternative.
Optimizer state lives sharded over the stage axis, so updates happen where
the parameters live — the DistributedOptimizer contract
(codes/task4/model.py:126) by construction.

Everything here is SPMD: every process runs the same scan, so stages
must agree on program, precision, and microbatch count, and a membership
event restarts the whole world. ``tpudml/mpmd`` is the multi-program
counterpart — one gloo world per stage, host-TCP boundary transfers, a
1F1B *host* loop, and re-mesh-in-place — for pipelines whose stages
differ in code, dtype, or chunking (arXiv 2412.14374).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudml.capabilities import reject
from tpudml.comm.collectives import pmean_tree, ppermute_ring, psum_tree
from tpudml.nn.layers import Module
from tpudml.nn.losses import accuracy, softmax_cross_entropy
from tpudml.optim import (
    Optimizer,
    ZeRO1,
    shard_aware_clip,
    stages_stacked,
    with_stacked,
    zero1_handles,
)
from tpudml.parallel.sharding import DispatchThrottle, shard_map_fn
from tpudml.train import TrainState

PyTree = Any


@jax.custom_vjp
def _grad_scale(x: jax.Array, c: float) -> jax.Array:
    """Identity forward; cotangent scaled by ``c`` on the way back.

    Needed because the pipeline's final mask+psum broadcast runs with
    replication checking off (see ``shard_map_fn``), where ``psum``
    transposes to ``psum``: every one of the S devices differentiates its
    own (identical) copy of the loss, so cotangents crossing the broadcast
    arrive summed — exactly S× the true gradient. Scaling the broadcast
    output's cotangent by 1/S restores the mathematical gradient; the
    parity tests against the sequential reference pin this down.
    """
    return x


def _grad_scale_fwd(x, c):
    return x, c


def _grad_scale_bwd(c, g):
    return g * c, None


_grad_scale.defvjp(_grad_scale_fwd, _grad_scale_bwd)


def _has_dropout(module) -> bool:
    """Detect active dropout anywhere in a Module tree (a ``dropout``
    field or a nested ``Dropout`` layer — rate-0 Dropout is the identity,
    not "active"). Traversal delegated to the shared walker."""
    from tpudml.nn.layers import Dropout, iter_module_tree

    for obj in iter_module_tree(module):
        if isinstance(obj, Dropout):
            if getattr(obj, "rate", 0.0):
                return True
        elif getattr(obj, "dropout", 0.0):
            return True
    return False


def _spec_shardings(spec_tree: PyTree, mesh: Mesh) -> PyTree:
    """Map a (prefix) tree of PartitionSpecs to NamedShardings."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


class GPipe:
    """Micro-batched pipeline engine over a mesh ``stage`` axis.

    Usage::

        pipe = GPipe(block, n_microbatches=8, mesh=mesh, optimizer=opt,
                     prologue=embed, epilogue=head)
        ts = pipe.create_state(key)
        step = pipe.make_train_step()      # (ts, x, labels) -> (ts, metrics)

    ``block`` is applied once per stage with per-stage parameters (stacked
    leading axis, sharded over ``stage``); ``prologue``/``epilogue`` are
    replicated modules run before/after the pipelined trunk (their redundant
    compute is the standard trade for keeping them out of the schedule).
    Blocks must be shape-preserving and stateless (no BatchNorm).

    PP×DP composition: on a 2-D ``{"data": D, "stage": S}`` mesh, pass
    ``batch_axis="data"`` — the global batch shards over ``data`` (each
    data-replica pipelines its own shard through the same per-stage
    params, which are replicated over ``data`` by construction), and
    gradients/metrics are ``pmean``-ed over ``data`` before the optimizer
    so replicas stay bitwise in sync. Same composition contract as
    CP×DP (``parallel/cp.py``) and the GSPMD engine's ``batch_axis``.
    """

    def __init__(
        self,
        block: Module,
        n_microbatches: int,
        mesh: Mesh,
        optimizer: Optimizer | None = None,
        axis_name: str = "stage",
        prologue: Module | None = None,
        epilogue: Module | None = None,
        loss: Callable = softmax_cross_entropy,
        remat: bool = False,
        batch_axis: str | None = None,
        sentinel: bool | dict = False,
        obs=False,
    ):
        self.block = block
        self.remat = remat
        self.n_microbatches = n_microbatches
        self.mesh = mesh
        # The update runs inside shard_map on the local [1, ...] stage
        # slice: a global-norm clip must psum its norm over the stage axis
        # (stage leaves local, prologue/epilogue replicated) or each stage
        # would clip by a different scale and de-sync the replicated parts.
        self.optimizer = (
            shard_aware_clip(
                optimizer,
                (axis_name,),
                lambda path: bool(path)
                and getattr(path[0], "key", None) == "stages",
            )
            if optimizer is not None
            else None
        )
        self.axis_name = axis_name
        self.n_stages = mesh.shape[axis_name]
        self.batch_axis = batch_axis
        if batch_axis is not None and batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} is not an axis of the mesh "
                f"{dict(mesh.shape)}"
            )
        if isinstance(self.optimizer, ZeRO1):
            # PP×DP with ZeRO-1 weight-update sharding: the optimizer
            # state chunks over the DATA axis on top of the stage layout.
            if batch_axis is None:
                reject("pp_zero1_needs_batch_axis")
            z = self.optimizer
            if z.axis_name != batch_axis or z.world != mesh.shape[batch_axis]:
                raise ValueError(
                    f"ZeRO1(axis_name={z.axis_name!r}, world={z.world}) "
                    f"does not match batch_axis {batch_axis!r} of size "
                    f"{mesh.shape[batch_axis]}"
                )
            # Stage leaves carry a leading stage-stacked dim the chunking
            # must preserve (state specs become P(stage, data)).
            self.optimizer = with_stacked(self.optimizer, stages_stacked)
        # In-graph step sentinel (tpudml.resilience): the update runs
        # inside shard_map on stage-LOCAL grads (prologue/epilogue
        # replicated over stage), so the anomaly predicate psums over the
        # stage axis; attach_sentinel appends the data axis when a ZeRO1
        # chunks the grads over it too.
        self.sentinel = None
        if sentinel:
            if self.optimizer is None:
                raise ValueError("sentinel needs an optimizer")
            from tpudml.resilience.sentinel import attach_sentinel, find_sentinel

            kw = dict(sentinel) if isinstance(sentinel, dict) else {}
            self.optimizer = attach_sentinel(
                self.optimizer, (axis_name,), **kw
            )
            self.sentinel = find_sentinel(self.optimizer)
        self.prologue = prologue
        self.epilogue = epilogue
        self.loss = loss
        self._throttle = DispatchThrottle(mesh)
        # Observability (tpudml.obs, same knob as the DP/GSPMD engines):
        # one "step" span per dispatch plus the in-graph StepStats pytree
        # under metrics["step_stats"]. comm_bytes stays 0 — the schedule's
        # ppermute traffic is a schedule property, not a per-step ring-
        # model constant (the static analyzer prices it; see --cost).
        from tpudml.obs.tracer import Tracer as _Tracer

        self.tracer = None
        self._obs_stats = False
        if obs:
            self.tracer = obs if isinstance(obs, _Tracer) else _Tracer()
            self._obs_stats = True

    def _batch_spec(self) -> P:
        """Spec for batch-shaped arrays: sharded over the data axis when
        composing with DP, replicated otherwise."""
        return P(self.batch_axis) if self.batch_axis else P()

    def _obs_span(self, name: str):
        """Per-dispatch tracer span; a shared no-op object when obs is
        off (the hot path must not allocate per step)."""
        if self.tracer is None:
            from tpudml.obs.tracer import NULL_SPAN

            return NULL_SPAN
        return self.tracer.span(name, cat="step")

    def _obs_step_stats(self, metrics: dict, grads, new_opt, step):
        """Append the in-graph StepStats pytree to the step's metrics
        (obs mode only; shared by all three schedule bodies). Stage grads
        are stage-local shards and prologue/epilogue grads replicated
        over the stage axis, so the stage norm² psums once and the
        replicated parts add once — the exact global grad norm. Under
        ZeRO-1 PP×DP the optimizer-boundary grads are per-data-replica;
        the pmean makes the report the RMS of per-replica norms (the DP
        engine's zero1 convention)."""
        if not self._obs_stats:
            return metrics
        from tpudml.obs.stepstats import grad_normsq, make_step_stats

        normsq = lax.psum(grad_normsq(grads["stages"]), self.axis_name)
        normsq = normsq + grad_normsq(
            {"prologue": grads["prologue"], "epilogue": grads["epilogue"]}
        )
        if self.batch_axis and zero1_handles(self.optimizer, self.batch_axis):
            normsq = lax.pmean(normsq, self.batch_axis)
        metrics = dict(metrics)
        metrics["step_stats"] = make_step_stats(
            metrics["loss"], normsq, new_opt, 0.0, step
        )
        return metrics

    # ---------------------------------------------------------------- params

    def _validate_block(self, states) -> None:
        if jax.tree.leaves(states):
            raise ValueError("pipeline blocks must be stateless (no BatchNorm)")
        if _has_dropout(self.block):
            # The GPipe schedule runs blocks in inference mode (no
            # train/rng threading through the scan); silent no-op dropout
            # would fake regularization, so reject it loudly. The 1F1B
            # engine threads per-(stage, micro) rng keys and lifts this.
            reject("gpipe_dropout")

    def init_params(self, key: jax.Array) -> PyTree:
        kp, kb, ke = jax.random.split(key, 3)
        stage_keys = jax.random.split(kb, self.n_stages)
        stacked, states = jax.vmap(self.block.init)(stage_keys)
        self._validate_block(states)
        pro = self.prologue.init(kp)[0] if self.prologue is not None else {}
        epi = self.epilogue.init(ke)[0] if self.epilogue is not None else {}
        return {"prologue": pro, "stages": stacked, "epilogue": epi}

    def param_specs(self) -> PyTree:
        """Prefix spec tree: stage params sharded over the stage axis,
        prologue/epilogue replicated."""
        return {"prologue": P(), "stages": P(self.axis_name), "epilogue": P()}

    def create_state(self, key: jax.Array) -> TrainState:
        if self.optimizer is None:
            raise ValueError("create_state needs an optimizer")
        params = self.init_params(key)
        ts = TrainState(
            params=params,
            model_state={},
            opt_state=self.optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )
        shardings = TrainState(
            params=_spec_shardings(self.param_specs(), self.mesh),
            model_state=NamedSharding(self.mesh, P()),
            opt_state=_spec_shardings(
                self.optimizer.init_spec(self.param_specs()), self.mesh
            ),
            step=NamedSharding(self.mesh, P()),
        )
        return jax.device_put(ts, shardings)

    # --------------------------------------------------------------- forward

    # Schedule hooks — overridden by HeteroPipeline (stage-dependent
    # apply over padded flat buffers); GPipe runs the homogeneous block.
    # ``ctx`` is whatever static plan ``_prep`` derives from the input
    # (None for homogeneous stages; the IO plan for hetero) — threaded
    # explicitly through the hooks so no mutable trace state is stashed
    # on the engine (ADVICE r3).

    def _prep(self, params: PyTree, x: jax.Array):
        """Full-local-batch input -> (pipeline input, static ctx)."""
        if self.prologue is not None:
            return self.prologue(params["prologue"], x), None
        return x, None

    def _tick_apply(self, local: PyTree, inp: jax.Array, stage, ctx) -> jax.Array:
        """One stage application at a tick (``stage`` = this device's
        stage index, a traced scalar; homogeneous blocks ignore it)."""
        return self.block(local, inp)

    def _post(self, params: PyTree, y: jax.Array, ctx) -> jax.Array:
        """Pipeline output -> logits."""
        if self.epilogue is not None:
            return self.epilogue(params["epilogue"], y)
        return y

    def _pipe_body(self, params: PyTree, x: jax.Array) -> jax.Array:
        """Per-device pipeline forward (runs under shard_map; x replicated)."""
        axis, S, M = self.axis_name, self.n_stages, self.n_microbatches
        stage = lax.axis_index(axis)
        # Local stage's parameters: shard_map hands each device its [1, ...]
        # slice of the stacked stage axis.
        local = jax.tree.map(lambda p: p[0], params["stages"])

        h, ctx = self._prep(params, x)
        batch = h.shape[0]
        if batch % M:
            raise ValueError(f"batch {batch} not divisible by {M} microbatches")
        mb = h.reshape(M, batch // M, *h.shape[1:])

        buf = jnp.zeros_like(mb[0])
        outbuf = jnp.zeros_like(mb)
        perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            buf, outbuf = carry
            # Stage 0 feeds micro-batch t (clamped: ticks past M re-run the
            # last micro-batch; those ghost outputs never reach outbuf, so
            # they contribute nothing — forward or backward).
            inp = jnp.where(
                stage == 0,
                lax.dynamic_index_in_dim(mb, jnp.clip(t, 0, M - 1), keepdims=False),
                buf,
            )
            # Stage s holds real data only for ticks s..s+M-1; fill/drain
            # ghost ticks skip the block compute entirely (the cond leaves
            # the bubble out of the runtime — its zeros never influence
            # outbuf, so gradients are unchanged).
            live = (t >= stage) & (t - stage < M)
            out = lax.cond(
                live,
                lambda: self._tick_apply(local, inp, stage, ctx),
                lambda: jnp.zeros_like(inp),
            )
            # Last stage banks micro-batch t-(S-1) once the fill completes.
            valid = jnp.logical_and(stage == S - 1, t >= S - 1)
            banked = lax.dynamic_update_index_in_dim(
                outbuf, out, jnp.clip(t - (S - 1), 0, M - 1), 0
            )
            outbuf = jnp.where(valid, banked, outbuf)
            if perm:
                buf = lax.ppermute(out, axis, perm)
            return (buf, outbuf), None

        if self.remat:
            # Rematerialize each pipeline tick in the backward pass: the
            # block's activations are recomputed instead of stored — the
            # residual memory drops from (M+S-1) tick activations to the
            # scan carries, the standard deep-pipeline trade.
            tick = jax.checkpoint(tick)
        (_, outbuf), _ = lax.scan(tick, (buf, outbuf), jnp.arange(M + S - 1))
        # Replicate the last stage's banked outputs to every device (mask +
        # psum lowers to a one-to-all on ICI).
        y = lax.psum(jnp.where(stage == S - 1, outbuf, jnp.zeros_like(outbuf)), axis)
        y = _grad_scale(y, 1.0 / S)
        y = y.reshape(batch, *y.shape[2:])
        return self._post(params, y, ctx)

    def make_forward(self) -> Callable:
        """Jitted full-batch pipeline forward: (params, x) -> logits."""
        fwd = shard_map_fn(
            self._pipe_body,
            self.mesh,
            in_specs=(self.param_specs(), self._batch_spec()),
            out_specs=self._batch_spec(),
        )
        return jax.jit(fwd)

    # ------------------------------------------------------------ train step

    def _spmd_step(self, ts: TrainState, x, labels):
        """Per-device train-step body (under shard_map); the 1F1B subclass
        replaces this with its interleaved schedule."""
        axis = self.axis_name

        def loss_fn(params):
            logits = self._pipe_body(params, x)
            return self.loss(logits, labels), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            ts.params
        )
        # Prologue cotangents exist only on stage 0 (only its prologue
        # output feeds the pipeline); psum replicates the true gradient.
        # Epilogue gradients are computed identically on every device
        # (replicated input, replicated params) — no collective needed.
        grads = dict(grads, prologue=psum_tree(grads["prologue"], axis))
        metrics = {"loss": loss, "accuracy": accuracy(logits, labels)}
        if self.batch_axis:
            # DP composition: every data-replica pipelined a different
            # batch shard; averaging grads = grad of the global-batch mean
            # loss (each replica's loss is already its shard mean). A
            # ZeRO1 optimizer skips the grads pmean — the reduce-scatter
            # inside its update performs the data-axis mean.
            if not zero1_handles(self.optimizer, self.batch_axis):
                grads = pmean_tree(grads, self.batch_axis)
            metrics = {
                k: lax.pmean(v, self.batch_axis) for k, v in metrics.items()
            }
        new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
        metrics = self._obs_step_stats(metrics, grads, new_opt, ts.step)
        new_ts = TrainState(
            params=new_params,
            model_state=ts.model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return new_ts, metrics

    def make_train_step(self) -> Callable:
        if self.optimizer is None:
            raise ValueError("make_train_step needs an optimizer")
        specs = TrainState(
            params=self.param_specs(),
            model_state=P(),
            opt_state=self.optimizer.init_spec(self.param_specs()),
            step=P(),
        )
        # Donate the TrainState: per-stage params/opt-state rewrite in place.
        # Input state is CONSUMED; callers must rebind ts every step.
        jitted = jax.jit(
            shard_map_fn(
                self._spmd_step,
                self.mesh,
                in_specs=(specs, self._batch_spec(), self._batch_spec()),
                out_specs=(specs, P()),
            ),
            donate_argnums=(0,),
        )

        def step(ts: TrainState, x, labels):
            with self._obs_span("train_step"):
                out = jitted(ts, jnp.asarray(x), jnp.asarray(labels))
                self._throttle.after_step(out[1]["loss"])
            return out

        # Raw program for tpudml.analysis (wrapper does host-side work);
        # in_specs/mesh_axes seed the dataflow interpreter and --cost.
        step.jitted = jitted
        step.in_specs = (specs, self._batch_spec(), self._batch_spec())
        step.mesh_axes = dict(self.mesh.shape)
        return step

    # ------------------------------------------------------------- reference

    def sequential_forward(self, params: PyTree, x: jax.Array) -> jax.Array:
        """Single-device reference semantics: prologue → S blocks in order →
        epilogue. The pipeline forward must match this exactly (the parity
        oracle, mirroring SURVEY.md §7's 'loss-curve equivalence' criterion
        for model-parallel ports)."""
        h = x
        if self.prologue is not None:
            h = self.prologue(params["prologue"], h)
        for s in range(self.n_stages):
            h = self.block(jax.tree.map(lambda p, s=s: p[s], params["stages"]), h)
        if self.epilogue is not None:
            h = self.epilogue(params["epilogue"], h)
        return h


class OneFOneB(GPipe):
    """1F1B (one-forward-one-backward) pipeline schedule.

    GPipe's scan schedule holds ALL M micro-batch activations in flight
    (the scan's AD residuals); 1F1B interleaves each stage's backward
    between forwards so at most S activations are ever live per stage —
    the standard deep-pipeline memory schedule (Megatron/DeepSpeed
    lineage), here as one lockstep SPMD program:

    - tick t, stage s: forward of micro m at t = s + 2m, backward of
      micro m at t = 2S − s − 1 + 2m. The two never collide on a stage,
      every dependency arrives exactly one ppermute hop earlier, and slot
      reuse m mod S is safe because bwd(s, m) always completes before
      fwd(s, m+S).
    - backwards are hand-rolled per-stage ``jax.vjp`` calls that
      RECOMPUTE the stage forward from the saved input (flash-style
      remat): the only live state is the S-slot input buffer + carried
      gradient accumulators, so scan-AD residual growth with M is gone.
    - the last stage fuses its forward with loss + epilogue inside its
      backward tick (cotangent seeded 1/M), so its forward tick only
      banks the input.
    - dropout IS supported (GPipe's restriction lifted): per-(stage,
      micro) keys fold ``rng_root``/step/stage/micro, and the backward's
      recompute folds the SAME key, so gradients are exact for the
      dropout-applied function. Stateless blocks only, as in GPipe.

    Lockstep trade: each tick runs either a forward (1×) or a backward
    (~2× + recompute) unit, so tick latency is the slowest stage's unit;
    utilization matches GPipe's bubble fraction while peak activation
    memory drops from M to S slots — the property asserted by the
    compiled memory-analysis test.
    """

    def _validate_block(self, states) -> None:
        if jax.tree.leaves(states):
            raise ValueError("pipeline blocks must be stateless (no BatchNorm)")
        if _has_dropout(self.block) and self.rng_root is None:
            raise ValueError("dropout pipeline stages need rng_root")

    def __init__(self, *args, rng_root: jax.Array | None = None, **kwargs):
        self.rng_root = rng_root  # before super(): _validate_block reads it
        super().__init__(*args, **kwargs)

    # -------------------------------------------------------- schedule hooks
    # The 1F1B schedule below runs unchanged for heterogeneous stages
    # (HeteroOneFOneB) through these four hooks; defaults implement the
    # homogeneous block + prologue/epilogue contract. ``ctx`` is the
    # static per-input plan from ``_sched_ctx`` (None here; the hetero IO
    # plan there).

    def _sched_ctx(self, x):
        return None

    def _sched_prep(self, p_pro, xm, ctx):
        """Raw micro-batch -> stage-0 pipeline input (differentiated
        w.r.t. ``p_pro`` on stage 0's backward ticks)."""
        return self.prologue(p_pro, xm) if self.prologue is not None else xm

    def _sched_apply(self, local, xin, key, stage, ctx):
        """One stage forward (differentiated w.r.t. ``local`` and ``xin``
        in the hand-rolled per-(stage, micro) backward)."""
        return self.block.apply(
            local, {}, xin, train=self.rng_root is not None, rng=key
        )[0]

    def _sched_post(self, p_epi, h, ctx):
        """Last stage's pipeline output -> logits (differentiated w.r.t.
        ``p_epi`` inside the fused last-stage backward)."""
        return self.epilogue(p_epi, h) if self.epilogue is not None else h

    # ------------------------------------------------------------- schedule

    def _spmd_step(self, ts: TrainState, x, labels):
        axis, S, M = self.axis_name, self.n_stages, self.n_microbatches
        stage = lax.axis_index(axis)
        train = self.rng_root is not None
        step_key = (
            jax.random.fold_in(self.rng_root, ts.step) if train else None
        )

        local = jax.tree.map(lambda p: p[0], ts.params["stages"])
        p_pro, p_epi = ts.params["prologue"], ts.params["epilogue"]

        batch = x.shape[0]
        if batch % M:
            raise ValueError(f"batch {batch} not divisible by {M} microbatches")
        mb = x.reshape(M, batch // M, *x.shape[1:])
        mb_labels = labels.reshape(M, batch // M, *labels.shape[1:])
        ctx = self._sched_ctx(x)

        def run_pro(xm):
            return self._sched_prep(p_pro, xm, ctx)

        def key_for(m):
            if step_key is None:
                return None
            key = jax.random.fold_in(jax.random.fold_in(step_key, stage), m)
            if self.batch_axis:
                # Decorrelate dropout masks across data replicas (each
                # sees a different batch shard) — DataParallel's contract.
                key = jax.random.fold_in(key, lax.axis_index(self.batch_axis))
            return key

        def run_block(p, xin, key):
            return self._sched_apply(p, xin, key, stage, ctx)

        act_template = jax.eval_shape(run_pro, jax.ShapeDtypeStruct(
            mb.shape[1:], mb.dtype
        ))
        zeros_act = jnp.zeros(act_template.shape, act_template.dtype)
        zeros_stage = jax.tree.map(jnp.zeros_like, local)
        zeros_pro = jax.tree.map(jnp.zeros_like, p_pro)
        zeros_epi = jax.tree.map(jnp.zeros_like, p_epi)

        def tick(carry, t):
            act_buf, fwd_recv, bwd_recv, g_st, g_pro, g_epi, loss_sum, acc_sum = carry

            # ---------------------------------------------- forward unit
            tf = t - stage
            valid_f = (tf >= 0) & (tf % 2 == 0) & (tf < 2 * M)
            m_f = jnp.clip(tf // 2, 0, M - 1)
            xm_f = lax.dynamic_index_in_dim(mb, m_f, keepdims=False)
            x_in = jnp.where(stage == 0, run_pro(xm_f), fwd_recv)
            act_buf = lax.cond(
                valid_f,
                lambda b: lax.dynamic_update_index_in_dim(b, x_in, m_f % S, 0),
                lambda b: b,
                act_buf,
            )
            # Last stage's forward fuses into its backward tick — its
            # forward unit only banks the input above.
            y = lax.cond(
                valid_f & (stage < S - 1),
                lambda: run_block(local, x_in, key_for(m_f)),
                lambda: zeros_act,
            )
            fwd_send = ppermute_ring(y, axis, 1)

            # --------------------------------------------- backward unit
            tb = t - (2 * S - stage - 1)
            valid_b = (tb >= 0) & (tb % 2 == 0) & (tb < 2 * M)
            m_b = jnp.clip(tb // 2, 0, M - 1)
            x_saved = lax.dynamic_index_in_dim(act_buf, m_b % S, keepdims=False)
            ym_b = lax.dynamic_index_in_dim(mb_labels, m_b, keepdims=False)
            xm_b = lax.dynamic_index_in_dim(mb, m_b, keepdims=False)
            key_b = key_for(m_b)

            def last_bwd():
                def f(p_st, p_ep, xin):
                    h = run_block(p_st, xin, key_b)
                    logits = self._sched_post(p_ep, h, ctx)
                    return self.loss(logits, ym_b), logits

                loss_m, pull, logits = jax.vjp(f, local, p_epi, x_saved,
                                               has_aux=True)
                d_st, d_ep, dx = pull(jnp.asarray(1.0 / M, loss_m.dtype))
                return d_st, d_ep, dx, loss_m, accuracy(logits, ym_b)

            def mid_bwd():
                _, pull = jax.vjp(
                    lambda p_st, xin: run_block(p_st, xin, key_b), local, x_saved
                )
                d_st, dx = pull(bwd_recv)
                return d_st, zeros_epi, dx, jnp.zeros(()), jnp.zeros(())

            def bwd_unit():
                d_st, d_ep, dx, loss_m, acc_m = lax.cond(
                    stage == S - 1, last_bwd, mid_bwd
                )
                # Stage 0 consumes its own dx through the prologue.
                def pro_bwd():
                    _, pull = jax.vjp(
                        lambda p: self._sched_prep(p, xm_b, ctx), p_pro
                    )
                    return pull(dx)[0]

                d_pro = lax.cond(stage == 0, pro_bwd, lambda: zeros_pro)
                return d_st, d_pro, d_ep, dx, loss_m, acc_m

            d_st, d_pro, d_ep, dx, loss_m, acc_m = lax.cond(
                valid_b,
                bwd_unit,
                lambda: (zeros_stage, zeros_pro, zeros_epi, zeros_act,
                         jnp.zeros(()), jnp.zeros(())),
            )
            bwd_send = ppermute_ring(dx, axis, -1)

            g_st = jax.tree.map(jnp.add, g_st, d_st)
            g_pro = jax.tree.map(jnp.add, g_pro, d_pro)
            g_epi = jax.tree.map(jnp.add, g_epi, d_ep)
            new_carry = (
                act_buf, fwd_send, bwd_send, g_st, g_pro, g_epi,
                loss_sum + loss_m, acc_sum + acc_m,
            )
            return new_carry, None

        n_ticks = 2 * (M + S - 1)
        init = (
            jnp.zeros((S,) + zeros_act.shape, zeros_act.dtype),
            zeros_act,
            zeros_act,
            zeros_stage,
            zeros_pro,
            zeros_epi,
            jnp.zeros(()),
            jnp.zeros(()),
        )
        (_, _, _, g_st, g_pro, g_epi, loss_sum, acc_sum), _ = lax.scan(
            tick, init, jnp.arange(n_ticks)
        )

        grads = {
            # Only stage 0 / stage S-1 hold nonzero prologue / epilogue
            # grads; psum replicates them (and the loss) to every stage.
            "prologue": psum_tree(g_pro, axis),
            "stages": jax.tree.map(lambda g: g[None], g_st),
            "epilogue": psum_tree(g_epi, axis),
        }
        metrics = {
            "loss": lax.psum(loss_sum, axis) / M,
            "accuracy": lax.psum(acc_sum, axis) / M,
        }
        if self.batch_axis:
            # PP×DP: average the per-data-replica pipeline grads/metrics
            # (see GPipe._spmd_step; ZeRO1 owns the grad mean itself).
            if not zero1_handles(self.optimizer, self.batch_axis):
                grads = pmean_tree(grads, self.batch_axis)
            metrics = {
                k: lax.pmean(v, self.batch_axis) for k, v in metrics.items()
            }
        new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
        metrics = self._obs_step_stats(metrics, grads, new_opt, ts.step)
        new_ts = TrainState(
            params=new_params,
            model_state=ts.model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return new_ts, metrics


class HeteroPipeline(GPipe):
    """Micro-batched pipeline over HETEROGENEOUS stages — the reference's
    actual model-parallel workload: a conv stage feeding an fc stage with
    different block structures and different activation shapes
    (codes/task4/model.py:18-47), pipelined with micro-batching instead of
    the reference's blocking per-batch RPC round-trips.

    SPMD needs every device to run one program on same-shaped buffers, so
    heterogeneity is encoded as data, not control flow:

    - **params**: each stage's param tree is raveled to a flat f32 vector,
      zero-padded to the longest stage, and stacked [S, L] — sharded over
      ``stage`` like GPipe's stacked homogeneous blocks. Elementwise
      optimizers (SGD/momentum/Adam/AdamW — everything in tpudml.optim)
      act identically on the raveled layout, and the padding lanes carry
      zero gradients forever. Each device unravels only ITS stage's slice.
    - **activations**: micro-batches travel as [B_micro, A] buffers with
      A = max per-sample activation width over all stage boundaries; each
      stage slices its input width, reshapes to its real input shape,
      applies, and re-pads its output.
    - **apply**: ``lax.switch`` over per-stage branches (each branch is
      traced with its own static unravel/reshape structure); the device's
      stage index picks the branch at run time. All S branches compile
      per device — the price of SPMD heterogeneity, fine for the 2-4
      stage splits this models.

    Grad-exactness: the schedule, masking, psum broadcast, and 1/S grad
    scale are inherited from GPipe unchanged, so the pipeline remains
    mathematically the sequential chain of stages — pinned by parity
    tests against ``sequential_forward`` and the single-device update.
    Composes with data parallelism via ``batch_axis`` exactly like GPipe.
    Stateless stages only; dropout needs the 1F1B engine (not offered for
    hetero stages yet).
    """

    def __init__(
        self,
        stages: Sequence[Module],
        n_microbatches: int,
        mesh: Mesh,
        optimizer: Optimizer | None = None,
        axis_name: str = "stage",
        loss: Callable = softmax_cross_entropy,
        remat: bool = False,
        batch_axis: str | None = None,
        **schedule_kw,
    ):
        if mesh.shape[axis_name] != len(stages):
            raise ValueError(
                f"{len(stages)} stages need a {len(stages)}-wide "
                f"{axis_name!r} mesh axis, got {mesh.shape[axis_name]}"
            )
        # The hetero schedule has no prologue/epilogue (stage 0 consumes
        # raw input; the last stage's output is the logits); accepting the
        # GPipe kwargs here would silently drop the user's layers. Only
        # the 1F1B subclass's rng_root may pass through.
        bad = set(schedule_kw) - {"rng_root"}
        if bad:
            raise TypeError(
                f"hetero pipelines do not take {sorted(bad)} (stage 0 is "
                "the prologue, the last stage is the epilogue)"
            )
        super().__init__(
            block=None,
            n_microbatches=n_microbatches,
            mesh=mesh,
            optimizer=optimizer,
            axis_name=axis_name,
            loss=loss,
            remat=remat,
            batch_axis=batch_axis,
            **schedule_kw,  # e.g. rng_root when the MRO includes OneFOneB
        )
        self.stages = tuple(stages)
        for i, st in enumerate(self.stages):
            # The GPipe schedule runs stages without rng; the 1F1B
            # subclass (HeteroOneFOneB) threads per-(stage, micro) keys
            # and lifts the restriction when rng_root is provided.
            if _has_dropout(st) and getattr(self, "rng_root", None) is None:
                raise ValueError(
                    f"stage {i} has dropout; use HeteroOneFOneB with "
                    "rng_root (the GPipe hetero schedule runs without rng)"
                )
        # Static per-stage param layout from abstract init: shapes via
        # eval_shape (no device compute), ravel/unravel closures via
        # ravel_pytree on host-side numpy zeros of those shapes.
        from jax.flatten_util import ravel_pytree

        self._param_shapes = []  # per-stage abstract param trees
        self._unravels = []
        self._stage_width = []
        key = jax.random.PRNGKey(0)
        for i, st in enumerate(self.stages):
            p_shapes, s_shapes = jax.eval_shape(st.init, key)
            if jax.tree.leaves(s_shapes):
                raise ValueError(
                    f"stage {i} is stateful (no BatchNorm in pipelines)"
                )
            zeros = jax.tree.map(
                lambda l: np.zeros(l.shape, l.dtype), p_shapes
            )
            flat, unravel = ravel_pytree(zeros)
            if flat.size and flat.dtype != jnp.float32:
                raise ValueError(
                    "hetero pipeline ravels stage params into one f32 "
                    f"buffer; stage {i} ravels to {flat.dtype}"
                )
            self._param_shapes.append(p_shapes)
            self._unravels.append(unravel)
            self._stage_width.append(int(flat.size))
        self._param_width = max(self._stage_width) if self._stage_width else 1

    # ------------------------------------------------------------- params

    def _unravel(self, s: int, flat: jax.Array) -> PyTree:
        return self._unravels[s](flat[: self._stage_width[s]])

    def init_params(self, key: jax.Array) -> PyTree:
        from jax.flatten_util import ravel_pytree

        rows = []
        for st, k in zip(self.stages, jax.random.split(key, len(self.stages))):
            flat, _ = ravel_pytree(st.init(k)[0])
            flat = flat.astype(jnp.float32) if flat.size else jnp.zeros((0,), jnp.float32)
            rows.append(jnp.pad(flat, (0, self._param_width - flat.shape[0])))
        return {
            "prologue": {},
            "stages": jnp.stack(rows),
            "epilogue": {},
        }

    # -------------------------------------------------------- activations

    def _io_plan(self, sample_shape, dtype):
        """Static chain of per-SAMPLE IO shapes through the stages:
        returns (sample shapes [input, out_0, ..., out_{S-1}], per-sample
        widths, buffer width A = max width). Derived abstractly with
        ``eval_shape`` — batch-size independent because stages are
        per-sample maps (checked)."""
        probe_b = 2  # avoid batch-1 broadcast ambiguities in the probe
        shapes = [tuple(sample_shape)]
        for i, st in enumerate(self.stages):
            out = jax.eval_shape(
                lambda p, xx, st=st: st(p, xx),
                self._param_shapes[i],
                jax.ShapeDtypeStruct((probe_b,) + shapes[-1], dtype),
            )
            if out.shape[0] != probe_b:
                raise ValueError(
                    f"stage {i} changed the batch dim "
                    f"({probe_b} -> {out.shape[0]}); stages must be "
                    "per-sample maps"
                )
            if out.dtype != dtype:
                raise ValueError(
                    f"stage {i} changed the activation dtype "
                    f"({dtype} -> {out.dtype}); hetero buffers are "
                    "single-dtype"
                )
            shapes.append(tuple(out.shape[1:]))
        widths = [int(np.prod(s)) for s in shapes]
        return shapes, widths, max(widths)

    def _prep(self, params: PyTree, x: jax.Array):
        # Raw input flattened per-sample and padded to the buffer width;
        # the static IO plan rides along as the hook ctx (threaded through
        # the schedule explicitly — no mutable trace state on the engine).
        plan = self._io_plan(x.shape[1:], x.dtype)
        _, _, a = plan
        flat = x.reshape(x.shape[0], -1)
        return jnp.pad(flat, ((0, 0), (0, a - flat.shape[1]))), plan

    def _tick_apply(self, local: jax.Array, inp: jax.Array, stage, ctx,
                    *, train: bool = False, rng=None) -> jax.Array:
        bm = inp.shape[0]
        shapes, widths, a = ctx

        def branch(s):
            def f(flat_in):
                p = self._unravel(s, local)
                xx = flat_in[:, : widths[s]].reshape((bm,) + shapes[s])
                y = self.stages[s].apply(p, {}, xx, train=train, rng=rng)[0]
                yf = y.reshape(bm, -1)
                return jnp.pad(yf, ((0, 0), (0, a - widths[s + 1])))

            return f

        return lax.switch(stage, [branch(s) for s in range(len(self.stages))], inp)

    def _post(self, params: PyTree, y: jax.Array, ctx) -> jax.Array:
        shapes, widths, _ = ctx
        return y[:, : widths[-1]].reshape((y.shape[0],) + shapes[-1])

    def sequential_forward(self, params: PyTree, x: jax.Array) -> jax.Array:
        h = x
        for s, st in enumerate(self.stages):
            h = st(self._unravel(s, params["stages"][s]), h)
        return h


class HeteroOneFOneB(HeteroPipeline, OneFOneB):
    """1F1B schedule over HETEROGENEOUS stages — the reference's conv→fc
    split (codes/task4/model.py:18-47) with S-bounded activation memory
    AND dropout support, lifting HeteroPipeline's two GPipe-inherited
    restrictions (VERDICT r3 item 4).

    Composition by MRO: HeteroPipeline contributes the padded-ravel
    params, the IO plan, and the ``lax.switch`` stage dispatch;
    OneFOneB contributes the 1F1B tick schedule with hand-rolled
    per-(stage, micro) VJPs and rng keys. The four ``_sched_*`` hooks
    bridge them — activations travel as the padded flat [B_micro, A]
    buffers, the last stage's loss is taken on the sliced/reshaped
    logits, and the backward's recompute folds the SAME per-(stage,
    micro) key, so gradients are exact for the dropout-applied function
    (OneFOneB's contract, pinned by parity tests).

    Usage matches HeteroPipeline plus ``rng_root`` for dropout stages::

        pipe = HeteroOneFOneB(stages, n_microbatches=M, mesh=mesh,
                              optimizer=opt, rng_root=seed_key(1))
    """

    def _sched_ctx(self, x):
        return self._io_plan(x.shape[1:], x.dtype)

    def _sched_prep(self, p_pro, xm, ctx):
        _, _, a = ctx
        flat = xm.reshape(xm.shape[0], -1)
        return jnp.pad(flat, ((0, 0), (0, a - flat.shape[1])))

    def _sched_apply(self, local, xin, key, stage, ctx):
        return self._tick_apply(
            local, xin, stage, ctx,
            train=self.rng_root is not None, rng=key,
        )

    def _sched_post(self, p_epi, h, ctx):
        shapes, widths, _ = ctx
        return h[:, : widths[-1]].reshape((h.shape[0],) + shapes[-1])


class Interleaved1F1B(GPipe):
    """Interleaved (virtual-stage) 1F1B: each device hosts ``v_chunks``
    NON-adjacent model chunks (Megatron's interleaved schedule lineage) —
    virtual stage σ = v·S + s runs chunk v on device s, so the model is
    L = V·S blocks deep while the per-tick unit shrinks to ONE block.

    Why: the plain 1F1B/GPipe bubble is (S-1) *stage* units of ramp-up
    and ramp-down, where a stage unit is all V blocks a device holds.
    Interleaving keeps the ramp at the same number of ticks but makes
    each tick 1/V of the work: total ticks 2(M + V·S - 1) of one-block
    units vs 2(M + S - 1) of V-block units — faster whenever V > 1 and
    M > 1, approaching a V× smaller bubble for M >> S.

    Schedule (lockstep SPMD scan, one program):
    - fwd(σ, m) at tick t = σ + 2m; bwd(σ, m) at t = 2·V·S - σ - 1 + 2m
      (the OneFOneB timing over VIRTUAL stages). On even S two chunks of
      one device can land on the same tick; the per-tick chunk loop
      simply runs both (the tick costs two units then — the schedule
      stays correct, just locally denser).
    - activations ppermute device s → s+1 every tick in a [V, ...]
      buffer slotted by the SENDER's chunk; the ring wrap S-1 → 0 is the
      chunk boundary, so device 0 reads slot v-1 for its chunk-v input
      while everyone else reads slot v. Cotangents mirror this on the
      reverse ring (device S-1 reads slot v+1).
    - backwards are hand-rolled per-(chunk, micro) ``jax.vjp`` calls that
      recompute the chunk forward from a saved input (OneFOneB's
      flash-style remat); the input buffer holds V·S slots per chunk
      (slot m mod V·S — fwd(σ, m') reuses bwd(σ, m)'s slot only after
      m' ≥ m + V·S - σ, so V·S slots are always safe). The memory trade
      vs OneFOneB: V·S·V in-flight micro-activations instead of S.
    - ring traffic (round 4): per device per tick the forward and
      backward phases are exactly COMPLEMENTARY when S is even — fwd
      units live iff (t − stage) is even (v·S is even for every chunk),
      bwd units live iff odd — so the fwd and bwd send buffers are never
      simultaneously nonzero and merge into ONE [V, ...] ppermute per
      tick whose permutation alternates by tick parity: even ticks
      {even s → s+1 (fwd), odd s → s−1 (bwd)}, odd ticks the mirror —
      each a bijection, delivered exactly where the next tick's
      complementary phase consumes it. That halves the schedule's ring
      transfer volume (2 → 1 act-buffer per tick), the static-shape
      floor: on live ticks ALL in-window chunks of a device fire
      together (the windows overlap whenever 2M > S), so the live slot
      count on a firing device is V, not 1-2, and no static [<V] buffer
      can carry it. Odd S (round 5) reaches the same BYTE floor a
      different way: its phases are complementary per CHUNK PARITY
      (fwd lives on v ≡ t+s, bwd on the complement — σ = vS + s has
      parity v + s when S is odd), so each direction ships only its
      [⌈V/2⌉] parity class, reconstructed at the receiver with the
      actual sender's parity (the wrap edge of an odd ring flips it).
      2·⌈V/2⌉ slots per tick vs even-S's V; the residual odd-S cost is
      message COUNT (2 ppermutes — opposite directions cannot share a
      permutation). Accounted by the transfer-bytes test (jaxpr
      ppermute operand totals).
    - dropout: per-(virtual stage, micro) keys, refolded identically in
      the backward recompute — grads stay exact for the dropout-applied
      function (the OneFOneB contract).

    Parity oracle: ``sequential_forward`` applies the V·S blocks in σ
    order on one device; the schedule must match its loss and update
    exactly. ``v_chunks=1`` degenerates to OneFOneB's schedule.
    Stateless shape-preserving blocks; composes with DP via
    ``batch_axis`` like the other pipeline engines.
    """

    def __init__(self, *args, v_chunks: int = 2,
                 rng_root: jax.Array | None = None, **kwargs):
        self.v_chunks = v_chunks
        self.rng_root = rng_root
        super().__init__(*args, **kwargs)
        if v_chunks < 1:
            raise ValueError(f"v_chunks {v_chunks} must be >= 1")

    def _validate_block(self, states) -> None:
        if jax.tree.leaves(states):
            raise ValueError("pipeline blocks must be stateless (no BatchNorm)")
        if _has_dropout(self.block) and self.rng_root is None:
            raise ValueError("dropout pipeline stages need rng_root")

    # ---------------------------------------------------------------- params

    def init_params(self, key: jax.Array) -> PyTree:
        """Stacked [S, V, ...] per-block params, sharded over ``stage`` on
        the leading axis; block σ = v·S + s lives at [s, v]."""
        kp, kb, ke = jax.random.split(key, 3)
        S, V = self.n_stages, self.v_chunks
        keys = jax.random.split(kb, S * V).reshape(S, V)
        # vmap over devices and chunks: [S, V] leading axes.
        stacked, states = jax.vmap(jax.vmap(lambda k: self.block.init(k)))(
            keys
        )
        self._validate_block(states)
        pro = self.prologue.init(kp)[0] if self.prologue is not None else {}
        epi = self.epilogue.init(ke)[0] if self.epilogue is not None else {}
        return {"prologue": pro, "stages": stacked, "epilogue": epi}

    def sequential_forward(self, params: PyTree, x: jax.Array) -> jax.Array:
        S, V = self.n_stages, self.v_chunks
        h = x
        if self.prologue is not None:
            h = self.prologue(params["prologue"], h)
        for sigma in range(V * S):
            s, v = sigma % S, sigma // S
            h = self.block(
                jax.tree.map(lambda p, s=s, v=v: p[s, v], params["stages"]), h
            )
        if self.epilogue is not None:
            h = self.epilogue(params["epilogue"], h)
        return h

    # -------------------------------------------------------------- schedule

    def _spmd_step(self, ts: TrainState, x, labels):
        axis, S, M, V = self.axis_name, self.n_stages, self.n_microbatches, \
            self.v_chunks
        VS = V * S
        stage = lax.axis_index(axis)
        train = self.rng_root is not None
        step_key = (
            jax.random.fold_in(self.rng_root, ts.step) if train else None
        )

        # Local chunk params: [1, V, ...] slice -> [V, ...].
        local = jax.tree.map(lambda p: p[0], ts.params["stages"])
        p_pro, p_epi = ts.params["prologue"], ts.params["epilogue"]

        batch = x.shape[0]
        if batch % M:
            raise ValueError(f"batch {batch} not divisible by {M} microbatches")
        mb = x.reshape(M, batch // M, *x.shape[1:])
        mb_labels = labels.reshape(M, batch // M, *labels.shape[1:])

        def run_pro(xm):
            return self.prologue(p_pro, xm) if self.prologue is not None else xm

        def key_for(v, m):
            if step_key is None:
                return None
            sigma = v * S + stage
            key = jax.random.fold_in(jax.random.fold_in(step_key, sigma), m)
            if self.batch_axis:
                key = jax.random.fold_in(key, lax.axis_index(self.batch_axis))
            return key

        def chunk_params(v):
            return jax.tree.map(
                lambda p: lax.dynamic_index_in_dim(p, v, keepdims=False), local
            )

        def run_block(p, xin, key):
            return self.block.apply(p, {}, xin, train=train, rng=key)[0]

        act_template = jax.eval_shape(run_pro, jax.ShapeDtypeStruct(
            mb.shape[1:], mb.dtype
        ))
        zeros_act = jnp.zeros(act_template.shape, act_template.dtype)
        zeros_chunks = jax.tree.map(jnp.zeros_like, local)  # [V, ...]
        zeros_pro = jax.tree.map(jnp.zeros_like, p_pro)
        zeros_epi = jax.tree.map(jnp.zeros_like, p_epi)

        def tick_core(carry, t):
            """One tick's compute; returns the carry (recv slots untouched)
            plus the fwd/bwd send buffers — the caller routes them through
            the ring (combined single ppermute for even S, classic pair
            for odd S; see the class docstring's ring-traffic note)."""
            (act_buf, fwd_recv, bwd_recv, g_ch, g_pro, g_epi,
             loss_sum, acc_sum) = carry
            # act_buf: [V, VS, ...] saved chunk inputs.
            # fwd_recv/bwd_recv: [V, ...] slotted by SENDER chunk.
            fwd_send = jnp.zeros((V,) + zeros_act.shape, zeros_act.dtype)
            bwd_send = jnp.zeros((V,) + zeros_act.shape, zeros_act.dtype)

            for v in range(V):  # static unroll: per-chunk units this tick
                sigma = v * S + stage

                # ------------------------------------------ forward unit
                tf = t - sigma
                valid_f = (tf >= 0) & (tf % 2 == 0) & (tf < 2 * M)
                m_f = jnp.clip(tf // 2, 0, M - 1)
                xm_f = lax.dynamic_index_in_dim(mb, m_f, keepdims=False)
                # Chunk-v input: stage 0 feeds micro m (v=0) or reads the
                # wrap slot v-1; other stages read slot v.
                recv_slot = jnp.where(stage == 0, max(v - 1, 0), v)
                x_in = lax.dynamic_index_in_dim(
                    fwd_recv, recv_slot, keepdims=False
                )
                if v == 0:
                    x_in = jnp.where(stage == 0, run_pro(xm_f), x_in)
                act_buf = lax.cond(
                    valid_f,
                    lambda b: jax.tree.map(
                        lambda bb, xx: lax.dynamic_update_index_in_dim(
                            bb, lax.dynamic_update_index_in_dim(
                                lax.dynamic_index_in_dim(bb, v, keepdims=False),
                                xx, m_f % VS, 0,
                            ), v, 0,
                        ),
                        b, x_in,
                    ),
                    lambda b: b,
                    act_buf,
                )
                # Last virtual stage fuses its fwd into the bwd tick.
                is_last = (stage == S - 1) & (v == V - 1)
                y = lax.cond(
                    valid_f & jnp.logical_not(is_last),
                    lambda: run_block(chunk_params(v), x_in, key_for(v, m_f)),
                    lambda: zeros_act,
                )
                fwd_send = lax.dynamic_update_index_in_dim(fwd_send, y, v, 0)

                # ----------------------------------------- backward unit
                tb = t - (2 * VS - sigma - 1)
                valid_b = (tb >= 0) & (tb % 2 == 0) & (tb < 2 * M)
                m_b = jnp.clip(tb // 2, 0, M - 1)
                x_saved = lax.dynamic_index_in_dim(
                    lax.dynamic_index_in_dim(act_buf, v, keepdims=False),
                    m_b % VS, keepdims=False,
                )
                ym_b = lax.dynamic_index_in_dim(mb_labels, m_b, keepdims=False)
                xm_b = lax.dynamic_index_in_dim(mb, m_b, keepdims=False)
                key_b = key_for(v, m_b)
                # Cotangent arriving for chunk v: device S-1 reads the
                # wrap slot v+1, others read slot v.
                bslot = jnp.where(stage == S - 1, min(v + 1, V - 1), v)
                cot_in = lax.dynamic_index_in_dim(
                    bwd_recv, bslot, keepdims=False
                )

                def last_bwd():
                    def f(p_ch, p_ep, xin):
                        h = run_block(p_ch, xin, key_b)
                        logits = (
                            self.epilogue(p_ep, h)
                            if self.epilogue is not None else h
                        )
                        return self.loss(logits, ym_b), logits

                    loss_m, pull, logits = jax.vjp(
                        f, chunk_params(v), p_epi, x_saved, has_aux=True
                    )
                    d_ch, d_ep, dx = pull(jnp.asarray(1.0 / M, loss_m.dtype))
                    return d_ch, d_ep, dx, loss_m, accuracy(logits, ym_b)

                def mid_bwd():
                    _, pull = jax.vjp(
                        lambda p_ch, xin: run_block(p_ch, xin, key_b),
                        chunk_params(v), x_saved,
                    )
                    d_ch, dx = pull(cot_in)
                    return d_ch, zeros_epi, dx, jnp.zeros(()), jnp.zeros(())

                def bwd_unit():
                    d_ch, d_ep, dx, loss_m, acc_m = lax.cond(
                        is_last, last_bwd, mid_bwd
                    )

                    def run_pro_p(p, xm):
                        return (
                            self.prologue(p, xm)
                            if self.prologue is not None else xm
                        )

                    def pro_bwd():
                        _, pull = jax.vjp(lambda p: run_pro_p(p, xm_b), p_pro)
                        return pull(dx)[0]

                    # The model input is virtual stage 0 = device 0 chunk 0.
                    d_pro = lax.cond(
                        (stage == 0) & (v == 0), pro_bwd, lambda: zeros_pro
                    )
                    return d_ch, d_pro, d_ep, dx, loss_m, acc_m

                d_ch, d_pro, d_ep, dx, loss_m, acc_m = lax.cond(
                    valid_b,
                    bwd_unit,
                    lambda: (
                        jax.tree.map(
                            lambda z: lax.dynamic_index_in_dim(
                                z, v, keepdims=False
                            ),
                            zeros_chunks,
                        ),
                        zeros_pro, zeros_epi, zeros_act,
                        jnp.zeros(()), jnp.zeros(()),
                    ),
                )
                bwd_send = lax.dynamic_update_index_in_dim(bwd_send, dx, v, 0)
                g_ch = jax.tree.map(
                    lambda g, d, v=v: lax.dynamic_update_index_in_dim(
                        g, lax.dynamic_index_in_dim(g, v, keepdims=False) + d,
                        v, 0,
                    ),
                    g_ch, d_ch,
                )
                g_pro = jax.tree.map(jnp.add, g_pro, d_pro)
                g_epi = jax.tree.map(jnp.add, g_epi, d_ep)
                loss_sum = loss_sum + loss_m
                acc_sum = acc_sum + acc_m

            return (
                act_buf, fwd_recv, bwd_recv, g_ch, g_pro, g_epi,
                loss_sum, acc_sum,
            ), fwd_send, bwd_send

        def set_recv(carry, fwd_recv, bwd_recv):
            act_buf, _, _, g_ch, g_pro, g_epi, loss_sum, acc_sum = carry
            return (act_buf, fwd_recv, bwd_recv, g_ch, g_pro, g_epi,
                    loss_sum, acc_sum)

        n_ticks = 2 * (M + VS - 1)
        init = (
            jnp.zeros((V, VS) + zeros_act.shape, zeros_act.dtype),
            jnp.zeros((V,) + zeros_act.shape, zeros_act.dtype),
            jnp.zeros((V,) + zeros_act.shape, zeros_act.dtype),
            zeros_chunks,
            zeros_pro,
            zeros_epi,
            jnp.zeros(()),
            jnp.zeros(()),
        )
        if S % 2 == 0:
            # Even S: phases are complementary per device (docstring note)
            # — ONE combined ppermute per tick. fwd_send + bwd_send is
            # exact because at most one is nonzero on any device; the
            # permutation pairs fwd hops (s → s+1 for in-phase senders)
            # with bwd hops (s → s−1 mod S for the others), alternating
            # by tick parity, and the receiver reads the same buffer as
            # whichever kind its next-tick phase consumes.
            perm_even = [(s, s + 1) for s in range(0, S - 1, 2)] + [
                (s, (s - 1) % S) for s in range(1, S, 2)
            ]
            perm_odd = [(s, (s + 1) % S) for s in range(1, S, 2)] + [
                (s, (s - 1) % S) for s in range(0, S, 2)
            ]

            def pair_body(carry, u):
                t0 = 2 * u
                carry, fs, bs = tick_core(carry, t0)
                recv = lax.ppermute(fs + bs, axis, perm_even)
                carry = set_recv(carry, recv, recv)
                carry, fs, bs = tick_core(carry, t0 + 1)
                recv = lax.ppermute(fs + bs, axis, perm_odd)
                carry = set_recv(carry, recv, recv)
                return carry, None

            # n_ticks = 2(M + VS - 1) is always even.
            (_, _, _, g_ch, g_pro, g_epi, loss_sum, acc_sum), _ = lax.scan(
                pair_body, init, jnp.arange(n_ticks // 2)
            )
        else:
            # Odd S (round 5): the phases are not complementary per DEVICE
            # (σ = vS + s parity is v + s when S is odd), but they ARE
            # complementary per CHUNK PARITY — on tick t, device s's fwd
            # units live exactly on chunks v ≡ t + s (mod 2) and its bwd
            # units on the complement. So each direction only needs its
            # parity class: pack the live half of each [V, ...] buffer
            # into a [⌈V/2⌉, ...] buffer and ppermute that — 2·⌈V/2⌉
            # act-slots per tick, the same byte floor as the even-S
            # combined buffer (2 messages instead of 1 is the remaining
            # odd-S cost: the two directions have different destinations,
            # so they cannot share one permutation).
            #
            # Wrap subtlety: around an odd ring, sender parity t + s is
            # NOT consistent across the S-1 → 0 edge ((s − 1) mod S flips
            # parity there), so the receiver reconstructs physical slot
            # ids with the ACTUAL sender's parity — (t + (s−1) mod S) for
            # fwd, (t + (s+1) mod S + 1) for bwd — and scatters the half
            # buffer back into a zeros [V, ...] at those slots. Receivers
            # only ever read slots their own valid units consume, which
            # are exactly the reconstructed ones (docstring invariants),
            # so the zero filler is never observed. V odd pads the last
            # slot (index V clips on pack, drops on scatter).
            Vh = (V + 1) // 2
            lane = jnp.arange(Vh)

            def pack(buf, parity):
                idx = jnp.minimum(parity + 2 * lane, V - 1)
                return jnp.take(buf, idx, axis=0)

            def unpack(half, parity):
                full = jnp.zeros((V,) + half.shape[1:], half.dtype)
                return full.at[parity + 2 * lane].set(half, mode="drop")

            def tick(carry, t):
                carry, fs, bs = tick_core(carry, t)
                pf = (t + stage) % 2          # fwd-live chunk parity here
                fs_h = ppermute_ring(pack(fs, pf), axis, 1)
                bs_h = ppermute_ring(pack(bs, 1 - pf), axis, -1)
                pf_r = (t + (stage - 1) % S) % 2      # fwd sender's parity
                pb_r = (t + (stage + 1) % S + 1) % 2  # bwd sender's parity
                return set_recv(
                    carry, unpack(fs_h, pf_r), unpack(bs_h, pb_r)
                ), None

            (_, _, _, g_ch, g_pro, g_epi, loss_sum, acc_sum), _ = lax.scan(
                tick, init, jnp.arange(n_ticks)
            )

        grads = {
            "prologue": psum_tree(g_pro, axis),
            "stages": jax.tree.map(lambda g: g[None], g_ch),
            "epilogue": psum_tree(g_epi, axis),
        }
        metrics = {
            "loss": lax.psum(loss_sum, axis) / M,
            "accuracy": lax.psum(acc_sum, axis) / M,
        }
        if self.batch_axis:
            # PP×DP (ZeRO1 owns the grad mean itself; see GPipe._spmd_step).
            if not zero1_handles(self.optimizer, self.batch_axis):
                grads = pmean_tree(grads, self.batch_axis)
            metrics = {
                k: lax.pmean(v, self.batch_axis) for k, v in metrics.items()
            }
        new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
        metrics = self._obs_step_stats(metrics, grads, new_opt, ts.step)
        new_ts = TrainState(
            params=new_params,
            model_state=ts.model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return new_ts, metrics
