"""Pipeline parallelism with micro-batching (GPipe-style schedule).

The reference's model parallelism is a 2-stage layer split whose forward is
two *blocking* RPC round-trips per batch — worker1 idles while worker2
computes and vice versa (codes/task4/model.py:49-66; SURVEY.md §3.4 calls
it the degenerate pipeline: PP with 1 micro-batch). SURVEY.md §2.3 lists
true micro-batched pipelining as the stretch goal on top of that port.

TPU-native design: the schedule is a ``lax.scan`` over pipeline ticks
inside ONE ``shard_map``-ed XLA program over a ``stage`` mesh axis.
Activations move between neighbouring stages with ``lax.ppermute`` — a
point-to-point ICI transfer, not host RPC — and every stage computes every
tick, so with M micro-batches the bubble shrinks from (S-1)/S of the step
(the reference's sequential RPC chain) to (S-1)/(M+S-1). The backward pass
needs no hand scheduling: AD transposes the scan and the ppermutes, which
XLA schedules as the reverse ring.

Scope: homogeneous stages — one ``block`` Module repeated S times with its
parameters stacked on a leading stage axis (the idiomatic JAX/GSPMD layout;
transformer decoders fit directly). Heterogeneous splits (the task4
conv/fc split) stay on the GSPMD engine in ``tpudml.parallel.mp``.
Optimizer state lives sharded over the stage axis, so updates happen where
the parameters live — the DistributedOptimizer contract
(codes/task4/model.py:126) by construction.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudml.comm.collectives import psum_tree
from tpudml.nn.layers import Module
from tpudml.nn.losses import accuracy, softmax_cross_entropy
from tpudml.optim import Optimizer, shard_aware_clip
from tpudml.parallel.sharding import DispatchThrottle, shard_map_fn
from tpudml.train import TrainState

PyTree = Any


@jax.custom_vjp
def _grad_scale(x: jax.Array, c: float) -> jax.Array:
    """Identity forward; cotangent scaled by ``c`` on the way back.

    Needed because the pipeline's final mask+psum broadcast runs with
    replication checking off (see ``shard_map_fn``), where ``psum``
    transposes to ``psum``: every one of the S devices differentiates its
    own (identical) copy of the loss, so cotangents crossing the broadcast
    arrive summed — exactly S× the true gradient. Scaling the broadcast
    output's cotangent by 1/S restores the mathematical gradient; the
    parity tests against the sequential reference pin this down.
    """
    return x


def _grad_scale_fwd(x, c):
    return x, c


def _grad_scale_bwd(c, g):
    return g * c, None


_grad_scale.defvjp(_grad_scale_fwd, _grad_scale_bwd)


def _spec_shardings(spec_tree: PyTree, mesh: Mesh) -> PyTree:
    """Map a (prefix) tree of PartitionSpecs to NamedShardings."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


class GPipe:
    """Micro-batched pipeline engine over a mesh ``stage`` axis.

    Usage::

        pipe = GPipe(block, n_microbatches=8, mesh=mesh, optimizer=opt,
                     prologue=embed, epilogue=head)
        ts = pipe.create_state(key)
        step = pipe.make_train_step()      # (ts, x, labels) -> (ts, metrics)

    ``block`` is applied once per stage with per-stage parameters (stacked
    leading axis, sharded over ``stage``); ``prologue``/``epilogue`` are
    replicated modules run before/after the pipelined trunk (their redundant
    compute is the standard trade for keeping them out of the schedule).
    Blocks must be shape-preserving and stateless (no BatchNorm).
    """

    def __init__(
        self,
        block: Module,
        n_microbatches: int,
        mesh: Mesh,
        optimizer: Optimizer | None = None,
        axis_name: str = "stage",
        prologue: Module | None = None,
        epilogue: Module | None = None,
        loss: Callable = softmax_cross_entropy,
        remat: bool = False,
    ):
        self.block = block
        self.remat = remat
        self.n_microbatches = n_microbatches
        self.mesh = mesh
        # The update runs inside shard_map on the local [1, ...] stage
        # slice: a global-norm clip must psum its norm over the stage axis
        # (stage leaves local, prologue/epilogue replicated) or each stage
        # would clip by a different scale and de-sync the replicated parts.
        self.optimizer = (
            shard_aware_clip(
                optimizer,
                (axis_name,),
                lambda path: bool(path)
                and getattr(path[0], "key", None) == "stages",
            )
            if optimizer is not None
            else None
        )
        self.axis_name = axis_name
        self.n_stages = mesh.shape[axis_name]
        self.prologue = prologue
        self.epilogue = epilogue
        self.loss = loss
        self._throttle = DispatchThrottle(mesh)

    # ---------------------------------------------------------------- params

    def init_params(self, key: jax.Array) -> PyTree:
        kp, kb, ke = jax.random.split(key, 3)
        stage_keys = jax.random.split(kb, self.n_stages)
        stacked, states = jax.vmap(self.block.init)(stage_keys)
        if jax.tree.leaves(states):
            raise ValueError("pipeline blocks must be stateless (no BatchNorm)")
        if getattr(self.block, "dropout", 0.0):
            # The schedule runs blocks in inference mode (no train/rng
            # threading through the scan); silent no-op dropout would fake
            # regularization, so reject it loudly.
            raise ValueError("pipeline stages do not support dropout")
        pro = self.prologue.init(kp)[0] if self.prologue is not None else {}
        epi = self.epilogue.init(ke)[0] if self.epilogue is not None else {}
        return {"prologue": pro, "stages": stacked, "epilogue": epi}

    def param_specs(self) -> PyTree:
        """Prefix spec tree: stage params sharded over the stage axis,
        prologue/epilogue replicated."""
        return {"prologue": P(), "stages": P(self.axis_name), "epilogue": P()}

    def create_state(self, key: jax.Array) -> TrainState:
        if self.optimizer is None:
            raise ValueError("create_state needs an optimizer")
        params = self.init_params(key)
        ts = TrainState(
            params=params,
            model_state={},
            opt_state=self.optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )
        shardings = TrainState(
            params=_spec_shardings(self.param_specs(), self.mesh),
            model_state=NamedSharding(self.mesh, P()),
            opt_state=_spec_shardings(
                self.optimizer.init_spec(self.param_specs()), self.mesh
            ),
            step=NamedSharding(self.mesh, P()),
        )
        return jax.device_put(ts, shardings)

    # --------------------------------------------------------------- forward

    def _pipe_body(self, params: PyTree, x: jax.Array) -> jax.Array:
        """Per-device pipeline forward (runs under shard_map; x replicated)."""
        axis, S, M = self.axis_name, self.n_stages, self.n_microbatches
        stage = lax.axis_index(axis)
        # Local stage's parameters: shard_map hands each device its [1, ...]
        # slice of the stacked stage axis.
        local = jax.tree.map(lambda p: p[0], params["stages"])

        h = x
        if self.prologue is not None:
            h = self.prologue(params["prologue"], h)
        batch = h.shape[0]
        if batch % M:
            raise ValueError(f"batch {batch} not divisible by {M} microbatches")
        mb = h.reshape(M, batch // M, *h.shape[1:])

        buf = jnp.zeros_like(mb[0])
        outbuf = jnp.zeros_like(mb)
        perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            buf, outbuf = carry
            # Stage 0 feeds micro-batch t (clamped: ticks past M re-run the
            # last micro-batch; those ghost outputs never reach outbuf, so
            # they contribute nothing — forward or backward).
            inp = jnp.where(
                stage == 0,
                lax.dynamic_index_in_dim(mb, jnp.clip(t, 0, M - 1), keepdims=False),
                buf,
            )
            out = self.block(local, inp)
            # Last stage banks micro-batch t-(S-1) once the fill completes.
            valid = jnp.logical_and(stage == S - 1, t >= S - 1)
            banked = lax.dynamic_update_index_in_dim(
                outbuf, out, jnp.clip(t - (S - 1), 0, M - 1), 0
            )
            outbuf = jnp.where(valid, banked, outbuf)
            if perm:
                buf = lax.ppermute(out, axis, perm)
            return (buf, outbuf), None

        if self.remat:
            # Rematerialize each pipeline tick in the backward pass: the
            # block's activations are recomputed instead of stored — the
            # residual memory drops from (M+S-1) tick activations to the
            # scan carries, the standard deep-pipeline trade.
            tick = jax.checkpoint(tick)
        (_, outbuf), _ = lax.scan(tick, (buf, outbuf), jnp.arange(M + S - 1))
        # Replicate the last stage's banked outputs to every device (mask +
        # psum lowers to a one-to-all on ICI).
        y = lax.psum(jnp.where(stage == S - 1, outbuf, jnp.zeros_like(outbuf)), axis)
        y = _grad_scale(y, 1.0 / S)
        y = y.reshape(batch, *y.shape[2:])
        if self.epilogue is not None:
            y = self.epilogue(params["epilogue"], y)
        return y

    def make_forward(self) -> Callable:
        """Jitted full-batch pipeline forward: (params, x) -> logits."""
        fwd = shard_map_fn(
            self._pipe_body,
            self.mesh,
            in_specs=(self.param_specs(), P()),
            out_specs=P(),
        )
        return jax.jit(fwd)

    # ------------------------------------------------------------ train step

    def make_train_step(self) -> Callable:
        if self.optimizer is None:
            raise ValueError("make_train_step needs an optimizer")
        axis = self.axis_name

        def spmd(ts: TrainState, x, labels):
            def loss_fn(params):
                logits = self._pipe_body(params, x)
                return self.loss(logits, labels), logits

            (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                ts.params
            )
            # Prologue cotangents exist only on stage 0 (only its prologue
            # output feeds the pipeline); psum replicates the true gradient.
            # Epilogue gradients are computed identically on every device
            # (replicated input, replicated params) — no collective needed.
            grads = dict(grads, prologue=psum_tree(grads["prologue"], axis))
            new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
            metrics = {"loss": loss, "accuracy": accuracy(logits, labels)}
            new_ts = TrainState(
                params=new_params,
                model_state=ts.model_state,
                opt_state=new_opt,
                step=ts.step + 1,
            )
            return new_ts, metrics

        specs = TrainState(
            params=self.param_specs(),
            model_state=P(),
            opt_state=self.optimizer.init_spec(self.param_specs()),
            step=P(),
        )
        # Donate the TrainState: per-stage params/opt-state rewrite in place.
        # Input state is CONSUMED; callers must rebind ts every step.
        jitted = jax.jit(
            shard_map_fn(
                spmd,
                self.mesh,
                in_specs=(specs, P(), P()),
                out_specs=(specs, P()),
            ),
            donate_argnums=(0,),
        )

        def step(ts: TrainState, x, labels):
            out = jitted(ts, jnp.asarray(x), jnp.asarray(labels))
            self._throttle.after_step(out[1]["loss"])
            return out

        return step

    # ------------------------------------------------------------- reference

    def sequential_forward(self, params: PyTree, x: jax.Array) -> jax.Array:
        """Single-device reference semantics: prologue → S blocks in order →
        epilogue. The pipeline forward must match this exactly (the parity
        oracle, mirroring SURVEY.md §7's 'loss-curve equivalence' criterion
        for model-parallel ports)."""
        h = x
        if self.prologue is not None:
            h = self.prologue(params["prologue"], h)
        for s in range(self.n_stages):
            h = self.block(jax.tree.map(lambda p, s=s: p[s], params["stages"]), h)
        if self.epilogue is not None:
            h = self.epilogue(params["epilogue"], h)
        return h
