"""GSPMD model parallelism: parameters sharded over a mesh axis, one
jitted step, XLA inserts the inter-device transfers.

Re-design of the reference's task4 RPC model parallelism (codes/task4/
model.py): there, LeNet is split into SubNetConv/SubNetFC living in other
processes, every forward is two blocking RPC round-trips shipping
activations (model.py:57-60), gradients flow through ``dist_autograd`` and
a ``DistributedOptimizer`` steps parameters where they live via RRefs
(model.py:75-84,126). Here the SAME observable contract — model weights
split across devices, activations moving between them, gradient computation
and optimizer updates happening where each parameter lives — is expressed
as sharding annotations on ONE jitted program: a rule maps each parameter
leaf to a PartitionSpec over the ``stage`` axis, optimizer state inherits
its parameter's spec (the DistributedOptimizer/parameter-server analogue,
also ZeRO-style state sharding), and the XLA SPMD partitioner schedules the
activation collectives on ICI that the reference performed with rpc_sync.

Note on naming: the reference's checklist calls this split "horizontal"
while task4's prose calls the layer split "vertical" (SURVEY.md §2.2). The
GSPMD rule here shards each layer's output features/channels across the
axis — the intra-layer (tensor-parallel flavored) split; the inter-layer
pipelined split is a separate engine (micro-batched pipeline over stacked
stages). Parity is defined by
loss-curve equivalence to single-device training (SURVEY.md §7), which
tests assert for both.

Composable with data parallelism: pass ``batch_axis="data"`` on a 2-D
mesh {"data": D, "stage": S} and the batch shards over ``data`` while
params shard over ``stage`` — GSPMD derives the gradient psum over the
data axis automatically (no explicit collective code).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudml.capabilities import reject
from tpudml.nn.layers import Module
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.obs.tracer import NULL_SPAN, Tracer
from tpudml.optim import Optimizer
from tpudml.parallel.sharding import DispatchThrottle
from tpudml.train import (
    TrainState,
    accumulate_grads,
    make_loss_fn,
    resolve_aux_loss_weight,
)

PyTree = Any

RuleFn = Callable[[tuple, jax.ShapeDtypeStruct], P]


def stage_sharding_rules(axis_name: str = "stage") -> RuleFn:
    """Default rule: shard each weight's OUTPUT dimension over the axis.

    kernel[in, out] -> P(None, axis); conv kernel[h, w, in, out] ->
    P(None, None, None, axis); bias[out] -> P(axis). Leaves whose output
    dim does not divide the axis size fall back to replicated at placement
    time (see :func:`apply_rules`).
    """

    def rule(path: tuple, leaf) -> P:
        name = path[-1] if path else ""
        if name == "kernel" and leaf.ndim == 2:
            return P(None, axis_name)
        if name == "kernel" and leaf.ndim == 4:
            return P(None, None, None, axis_name)
        if name == "bias" and leaf.ndim == 1:
            return P(axis_name)
        return P()

    return rule


def replicated_rules() -> RuleFn:
    return lambda path, leaf: P()


def tensor_parallel_rules(axis_name: str = "model") -> RuleFn:
    """Megatron-style intra-layer tensor parallelism for the transformer
    family (beyond reference parity — SURVEY.md §2.3 lists TP as the
    GSPMD-nearly-free stretch row).

    Column-parallel then row-parallel pairs so each block needs one
    all-reduce per sub-layer, which the XLA SPMD partitioner inserts from
    the shardings alone: QKV and MLP-up kernels split on the output
    (head/hidden) dimension, the attention-out and MLP-down kernels split
    on the input dimension; embeddings split on vocab; norms replicated.
    Non-transformer leaves fall back to the generic output-dim rule so the
    rule set still works for mixed models.
    """
    generic = stage_sharding_rules(axis_name)

    def rule(path: tuple, leaf) -> P:
        names = set(path)
        last2 = tuple(path[-2:]) if len(path) >= 2 else ()
        if "attn" in names:
            if last2 and last2[0] in ("q", "k", "v"):
                # Column-parallel: output dim shards head-aligned (the
                # projections are separate kernels, see MultiHeadAttention).
                return P(None, axis_name) if last2[1] == "kernel" else P(axis_name)
            if last2 == ("out", "kernel"):
                return P(axis_name, None)  # row: contracted dim shard
            return P()  # out bias (+ anything else) replicated
        if last2 and last2[0] == "fc1":
            return P(None, axis_name) if last2[1] == "kernel" else P(axis_name)
        if last2 and last2[0] == "fc2":
            return P(axis_name, None) if last2[1] == "kernel" else P()
        if path and path[-1] == "tok_embed":
            return P(axis_name, None)  # vocab shard
        if path and path[-1] == "pos_embed":
            return P()
        if last2 and last2[0] == "head":
            # LM head: column-parallel vocab projection.
            return P(None, axis_name) if last2[1] == "kernel" else P(axis_name)
        if "ln1" in names or "ln2" in names or "ln_f" in names:
            return P()
        return generic(path, leaf)

    return rule


from tpudml.core.pytree import path_names as _path_names  # shared classifier


def apply_rules(rule: RuleFn, params: PyTree, mesh: Mesh) -> PyTree:
    """Per-leaf PartitionSpec tree, demoting specs that don't tile evenly.

    A spec naming mesh axes whose product doesn't divide the corresponding
    leaf dimension is demoted to replicated on that dimension — the
    framework-level guarantee that any model works on any mesh (degenerate
    placements are correct, just less parallel).
    """

    def leaf_spec(key_path, leaf):
        spec = rule(_path_names(key_path), leaf)
        out = []
        for dim, names in enumerate(spec):
            if names is None:
                out.append(None)
                continue
            axis_tuple = names if isinstance(names, tuple) else (names,)
            size = 1
            for a in axis_tuple:
                size *= mesh.shape[a]
            out.append(names if leaf.shape[dim] % size == 0 else None)
        return P(*out)

    return jax.tree_util.tree_map_with_path(leaf_spec, params)


class GSPMDParallel:
    """Model-(+data-)parallel training engine driven by sharding rules.

    Usage::

        mp = GSPMDParallel(model, opt, mesh)           # mesh {"stage": S}
        ts = mp.create_state(key)                      # params sharded
        step = mp.make_train_step()                    # one jitted program

    With a 2-D mesh and ``batch_axis="data"``, DP composes in for free.
    """

    def __init__(
        self,
        model: Module,
        optimizer: Optimizer,
        mesh: Mesh,
        rule: RuleFn | None = None,
        axis_name: str = "stage",
        batch_axis: str | None = None,
        rng_root: jax.Array | None = None,
        accum_steps: int = 1,
        loss: Callable = softmax_cross_entropy,
        aux_loss_weight: float | None = None,
        fused_xent: bool = False,
        save_scores: bool | None = None,
        sentinel: bool | dict = False,
        obs: bool | Tracer = False,
        flash_attn: bool = False,
    ):
        if save_scores and not fused_xent:
            reject("save_scores_needs_fused_xent")
        if fused_xent and (accum_steps != 1 or loss is not softmax_cross_entropy):
            reject("gspmd_fused_xent_accum")
        # flash_attn: run the dense causal trunk on the Pallas flash
        # kernel (same capability row as the DP engine — GSPMD shards
        # batch/heads, never the softmax's sequence axis, so the kernel
        # composes with TP/FSDP rules unchanged).
        self.flash_attn = flash_attn
        if flash_attn:
            import dataclasses

            if getattr(model, "impl", None) != "full" or getattr(
                model, "seq_sharded", False
            ):
                reject("train_flash_attn_dense")
            model = dataclasses.replace(model, impl="flash")
        self.model = model
        self.optimizer = optimizer
        # In-graph step sentinel (tpudml.resilience): under jit/GSPMD the
        # grads the optimizer consumes are logically global arrays —
        # isfinite/norm reductions compile to the right collectives
        # automatically, so the wrapper needs no explicit axis psum.
        self.sentinel = None
        if sentinel:
            from tpudml.resilience.sentinel import attach_sentinel, find_sentinel

            kw = dict(sentinel) if isinstance(sentinel, dict) else {}
            self.optimizer = attach_sentinel(self.optimizer, (), **kw)
            self.sentinel = find_sentinel(self.optimizer)
        self.mesh = mesh
        self.axis_name = axis_name
        if rule is None and axis_name not in mesh.shape:
            raise ValueError(
                f"axis_name {axis_name!r} not in mesh axes {tuple(mesh.shape)}"
            )
        if batch_axis is not None and batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} not in mesh axes {tuple(mesh.shape)}"
            )
        self.batch_axis = batch_axis
        self.rule = rule or stage_sharding_rules(axis_name)
        self.rng_root = rng_root
        self.accum_steps = accum_steps
        # Dense-MoE runs get the Switch load-balancing pressure by default
        # (None → α=0.01 when the model contains MoE layers).
        self._loss_fn = make_loss_fn(
            model, loss, resolve_aux_loss_weight(model, aux_loss_weight)
        )
        self.fused_xent = fused_xent
        self.save_scores = save_scores
        self._aux_loss_weight = aux_loss_weight
        self._specs = None  # computed at create_state
        self._throttle = DispatchThrottle(mesh)
        # Observability (tpudml.obs, same knob as the DP engine): one
        # "step" span per dispatch plus the in-graph StepStats pytree in
        # metrics. ``comm_bytes`` stays 0 here — this engine's collectives
        # are inserted by the SPMD partitioner at compile time, so no
        # body-level ring-model price exists (the static analyzer has the
        # same blind spot; see make_train_step's note).
        self.tracer: Tracer | None = None
        self._obs_stats = False
        if obs:
            self.tracer = obs if isinstance(obs, Tracer) else Tracer()
            self._obs_stats = True

    # ---------------------------------------------------------------- state

    def state_specs(self, ts: TrainState) -> TrainState:
        """PartitionSpec tree for the whole TrainState."""
        param_specs = apply_rules(self.rule, ts.params, self.mesh)
        # model_state (e.g. BN stats) follows the same rule; opt state
        # mirrors its parameters (parameter-server semantic, see
        # Optimizer.init_spec).
        state_specs = apply_rules(self.rule, ts.model_state, self.mesh)
        opt_specs = self.optimizer.init_spec(param_specs)
        return TrainState(
            params=param_specs,
            model_state=state_specs,
            opt_state=opt_specs,
            step=P(),
        )

    def _shardings(self, spec_tree):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            spec_tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    def create_state(self, key: jax.Array) -> TrainState:
        ts = TrainState.create(self.model, self.optimizer, key)
        self._specs = self.state_specs(ts)
        return jax.device_put(ts, self._shardings(self._specs))

    # ----------------------------------------------------------------- step

    def make_train_step(self) -> Callable:
        if self._specs is None:
            raise RuntimeError("call create_state() before make_train_step()")
        batch_spec = P(self.batch_axis) if self.batch_axis else P()
        state_shardings = self._shardings(self._specs)
        batch_sharding = NamedSharding(self.mesh, batch_spec)

        fused_loss_fn = None
        if self.fused_xent:
            # Built lazily HERE (not __init__): the sharded loss derives
            # its shard_map region from the head kernel's placed spec,
            # which exists only after create_state ran apply_rules.
            spec_params = self._specs.params
            if not isinstance(spec_params, dict) or "head" not in spec_params:
                raise ValueError(
                    "fused_xent needs a model with a 'head' Dense subtree "
                    "and apply_features (TransformerLM)"
                )
            from tpudml.train import make_lm_fused_sharded_loss_fn

            fused_loss_fn = make_lm_fused_sharded_loss_fn(
                self.model,
                self.mesh,
                kernel_spec=spec_params["head"]["kernel"],
                batch_axis=self.batch_axis,
                save_scores=self.save_scores,
                aux_loss_weight=self._aux_loss_weight,
            )

        def step_impl(ts: TrainState, images, labels):
            rng = None
            if self.rng_root is not None:
                rng = jax.random.fold_in(self.rng_root, ts.step)
            if fused_loss_fn is not None:
                (loss, model_state), grads = jax.value_and_grad(
                    fused_loss_fn, has_aux=True
                )(ts.params, ts.model_state, images, labels, rng)
                metrics = {"loss": loss}
            else:
                grads, model_state, metrics = accumulate_grads(
                    self._loss_fn, ts.params, ts.model_state, images, labels,
                    rng, self.accum_steps, taint=self.sentinel is not None,
                )
            new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
            if self._obs_stats:
                from tpudml.obs.stepstats import grad_normsq, make_step_stats

                # Grads here are logically global arrays, so this is the
                # exact global grad norm (XLA inserts the reductions).
                metrics = dict(metrics)
                metrics["step_stats"] = make_step_stats(
                    metrics["loss"], grad_normsq(grads), new_opt, 0.0, ts.step
                )
            new_ts = TrainState(
                params=new_params,
                model_state=model_state,
                opt_state=new_opt,
                step=ts.step + 1,
            )
            return new_ts, metrics

        # Donated TrainState (as in the DP engine): params + optimizer state
        # update in place instead of double-buffering — these are the
        # largest live buffers on exactly this engine. Input state is
        # CONSUMED; callers must rebind ts every step.
        jitted = jax.jit(
            step_impl,
            in_shardings=(state_shardings, batch_sharding, batch_sharding),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,),
        )

        def step(ts: TrainState, images, labels):
            images = jax.device_put(jnp.asarray(images), batch_sharding)
            labels = jax.device_put(jnp.asarray(labels), batch_sharding)
            with self._obs_span("train_step"):
                out = jitted(ts, images, labels)
                self._throttle.after_step(out[1]["loss"])
            return out

        # Raw program for tpudml.analysis (wrapper does host-side work).
        # in_specs/mesh_axes seed the dataflow interpreter's top-level
        # states; note GSPMD inserts this engine's collectives at
        # partitioning time, so the static --cost comm volume here only
        # covers explicit shard_map regions (e.g. the fused sharded head).
        step.jitted = jitted
        step.in_specs = (self._specs, batch_spec, batch_spec)
        step.mesh_axes = dict(self.mesh.shape)
        return step

    def _obs_span(self, name: str):
        """Per-dispatch tracer span; a shared no-op object when obs is
        off (the hot path must not allocate per step)."""
        if self.tracer is None:
            return NULL_SPAN
        return self.tracer.span(name, cat="step")

    # ------------------------------------------------------------- evaluate

    def make_eval_step(self) -> Callable:
        if self._specs is None:
            raise RuntimeError("call create_state() before make_eval_step()")
        param_shardings = self._shardings(self._specs.params)
        state_shardings = self._shardings(self._specs.model_state)
        batch_sharding = NamedSharding(
            self.mesh, P(self.batch_axis) if self.batch_axis else P()
        )

        def eval_impl(params, model_state, images, labels):
            logits, _ = self.model.apply(params, model_state, images, train=False)
            return jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.int32))

        jitted = jax.jit(
            eval_impl,
            in_shardings=(param_shardings, state_shardings, batch_sharding, batch_sharding),
        )

        def step(params, model_state, images, labels):
            images = jax.device_put(jnp.asarray(images), batch_sharding)
            labels = jax.device_put(jnp.asarray(labels), batch_sharding)
            return jitted(params, model_state, images, labels)

        return step
