"""Parallelism engines: DP (shard_map+psum), MP (GSPMD stage sharding),
sharded optimizer state (parameter-server analogue).

Re-designs of the reference's three strategies (SURVEY.md §2.3): task2/3's
replicated-model gradient-allreduce DP, task4's RPC inter-layer model split,
and task4's DistributedOptimizer parameter-server pattern — all expressed as
sharding annotations over one ``jax.sharding.Mesh`` instead of process
groups and RPC.
"""

from tpudml.parallel.sharding import (
    data_sharding,
    replicate,
    replicated_sharding,
    shard_batch,
    shard_map_fn,
)
from tpudml.parallel.cp import ContextParallel, ring_attention, ulysses_attention
from tpudml.parallel.dp import DataParallel, make_dp_train_step
from tpudml.parallel.ep import ExpertParallel, expert_specs
from tpudml.parallel.fsdp import FSDP, fsdp_sharding_rules
from tpudml.parallel.mp import (
    GSPMDParallel,
    apply_rules,
    stage_sharding_rules,
    tensor_parallel_rules,
)
from tpudml.parallel.pp import (
    GPipe,
    HeteroOneFOneB,
    HeteroPipeline,
    Interleaved1F1B,
    OneFOneB,
)

__all__ = [
    "ContextParallel",
    "DataParallel",
    "ExpertParallel",
    "expert_specs",
    "FSDP",
    "fsdp_sharding_rules",
    "GPipe",
    "HeteroOneFOneB",
    "HeteroPipeline",
    "Interleaved1F1B",
    "OneFOneB",
    "GSPMDParallel",
    "ring_attention",
    "tensor_parallel_rules",
    "ulysses_attention",
    "make_dp_train_step",
    "apply_rules",
    "stage_sharding_rules",
    "data_sharding",
    "replicate",
    "replicated_sharding",
    "shard_batch",
    "shard_map_fn",
]
