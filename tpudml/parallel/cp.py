"""Context (sequence) parallelism: ring attention and Ulysses all-to-all.

Absent from the reference (SURVEY.md §5.7: no attention, no sequence
dimension), but first-class here: long sequences are sharded over a mesh
``seq`` axis so activation memory per chip scales 1/W, and only K/V blocks
(ring) or head-groups (Ulysses) move over ICI.

- **Ring attention**: each device keeps its Q shard resident and rotates
  K/V shards around the ring with ``lax.ppermute``, folding each arriving
  block into a numerically-stable online softmax (running max + running
  normalizer, flash-attention style, accumulated in float32). W steps see
  every block exactly once; communication overlaps compute tick by tick.
  Causal masking uses *global* positions derived from the block's origin
  device, so semantics are identical to full attention.
- **Ulysses**: ``lax.all_to_all`` transposes the sharding from sequence to
  heads ([B,T/W,H,D] → [B,T,H/W,D]), runs ordinary full attention on the
  now-complete sequence for the local head group, and transposes back.
  Needs num_heads % W == 0; two collectives per attention instead of W
  ring hops.

Both are pure jittable functions (must run under shard_map with
``axis_name`` bound) and differentiate exactly — ppermute/all_to_all
transpose to their inverses, so gradients route back to the owning shard.

Causal layouts: the default contiguous sharding skips fully-masked blocks
(halves FLOPs, but the last device computes every tick, bounding lockstep
latency); ``layout="striped"`` (token t on device t mod W — the striped-
attention layout) makes every ring block a balanced triangular tile, so
per-tick work is equal across the ring (~2× faster causal wall-clock on
the kernel path). Positions stay affine under striping (idx + W·j), which
is why it threads cleanly through RoPE/pos-embed and the flash kernel's
shifted-diagonal mask.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudml.capabilities import reject
from tpudml.comm.collectives import all_to_all, axis_size, pmean_tree, ppermute_ring
from tpudml.nn.attention import NEG_INF
from tpudml.nn.layers import Module
from tpudml.nn.losses import accuracy, softmax_cross_entropy
from tpudml.optim import Optimizer
from tpudml.parallel.sharding import (
    make_counting_eval_step,
    DispatchThrottle,
    shard_map_fn,
)
from tpudml.train import (
    TrainState,
    evaluate_counts,
    make_loss_fn,
    resolve_aux_loss_weight,
)

PyTree = Any


def _block_scores(q, kb, diag: bool, k_shift: int = 0) -> jax.Array:
    """Shared scaled-masked score tile [B,H,Tq,Tk] f32 — forward and
    backward recompute through this one function so the mask/scale
    convention can never diverge between them. ``diag`` applies the
    aligned same-length causal mask (the ring's diagonal block) with the
    key positions offset by ``k_shift`` (striped layout: a block from a
    later-striped device is visible only STRICTLY below the diagonal);
    visible off-diagonal blocks pass False."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = (
        jnp.einsum("bqhd,bkhd->bhqk", q, kb, preferred_element_type=jnp.float32)
        * scale
    )
    if diag:
        t = q.shape[1]
        mask = jnp.arange(t)[:, None] >= (jnp.arange(t)[None, :] + k_shift)
        s = jnp.where(mask[None, None], s, NEG_INF)
    return s


def _block_fwd_math(q, kb, vb, diag: bool, k_shift=0):
    """Reference-math per-block attention partial: (out [B,Tl,H,D] f32,
    lse [B,H,Tl] f32)."""
    s = _block_scores(q, kb, diag, k_shift)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vb, preferred_element_type=jnp.float32)
    out = out / l.transpose(0, 2, 1)[..., None]
    return out, m + jnp.log(l)


def _block_bwd_math(q, kb, vb, do, lse, delta, diag: bool, k_shift=0):
    """Reference-math per-block flash backward with global (lse, Δ):
    p = exp(s − lse); dv = pᵀ·dO; ds = p ⊙ (dO·Vᵀ − Δ); dq = scale·ds·K;
    dk = scale·dsᵀ·Q. Summing over blocks gives the exact gradients."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    s = _block_scores(q, kb, diag, k_shift)
    p = jnp.exp(s - lse[..., None])  # [B,H,Tq,Tk]
    dof = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vb.astype(jnp.float32))
    ds = p * (dp - delta[..., None])
    dq = scale * jnp.einsum("bhqk,bkhd->bqhd", ds, kb.astype(jnp.float32))
    dk = scale * jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
    return dq, dk, dv


def _merge_blocks(acc, out_b, lse_b):
    """Online log-sum-exp merge of per-block partial attentions: given
    normalized block outputs with their lse, the exact combination is
    out = Σ_b out_b · exp(lse_b − lse_total)."""
    num, m, den = acc
    m_new = jnp.maximum(m, lse_b)
    c_old = jnp.exp(m - m_new)
    c_new = jnp.exp(lse_b - m_new)
    num = (
        num * c_old.transpose(0, 2, 1)[..., None]
        + out_b * c_new.transpose(0, 2, 1)[..., None]
    )
    return num, m_new, den * c_old + c_new


def _ring_fwd(axis_name, causal, flash_cfg, q, k, v):
    """Forward ring pass → (out, lse) local shards.

    Two causal regimes by token layout:

    - **contiguous** (device i owns tokens [i·Tl, (i+1)·Tl)): fully-masked
      blocks (src > idx) are SKIPPED — the lax.cond leaves their compute
      out of the runtime entirely. Halves total FLOPs, but lockstep
      latency is still bounded by the last device, which computes at every
      tick.
    - **striped** (device i owns tokens {t : t mod W == i}): every block
      pair is a (shifted-)triangular causal tile — src ≤ idx masks at the
      diagonal, src > idx strictly below it — so every device does the
      SAME half-tile work each tick: balanced, ~2× faster wall-clock on
      the kernel path (whose tile-skipping realizes the triangle).

    The ppermute rotation runs every tick regardless — collectives must
    stay unconditional across the mesh."""
    use_flash, interpret, striped = flash_cfg
    world = axis_size(axis_name)
    # Only the causal masks read the device index. Keep axis_index out of
    # the non-causal program entirely: a dead partition-id survives into
    # the lowered module and the CPU SPMD partitioner rejects it.
    idx = lax.axis_index(axis_name) if causal else None
    b, t_local, h, d = q.shape

    def block_fwd(q_, kb, vb, diag, k_shift=0):
        if use_flash:
            from tpudml.ops import flash_forward_lse

            return flash_forward_lse(
                q_, kb, vb, causal=diag, k_shift=k_shift, interpret=interpret
            )
        return _block_fwd_math(q_, kb, vb, diag, k_shift)

    init = (
        jnp.zeros((b, t_local, h, d), jnp.float32),
        jnp.full((b, h, t_local), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, t_local), jnp.float32),
    )
    # Tick 0: the resident local (diagonal) block — no communication.
    acc0 = _merge_blocks(init, *block_fwd(q, k, v, causal))

    def tick(carry, step):
        acc, kb, vb = carry
        kb = ppermute_ring(kb, axis_name)
        vb = ppermute_ring(vb, axis_name)
        src = (idx - step) % world if causal else None
        if causal and striped:
            # k_shift must be static for the kernel; both variants are the
            # same triangular tile up to the diagonal inclusion.
            acc = lax.cond(
                src > idx,
                lambda a: _merge_blocks(a, *block_fwd(q, kb, vb, True, 1)),
                lambda a: _merge_blocks(a, *block_fwd(q, kb, vb, True, 0)),
                acc,
            )
        elif causal:
            acc = lax.cond(
                src < idx,
                lambda a: _merge_blocks(a, *block_fwd(q, kb, vb, False)),
                lambda a: a,
                acc,
            )
        else:
            acc = _merge_blocks(acc, *block_fwd(q, kb, vb, False))
        return (acc, kb, vb), None

    ((num, m, den), _, _), _ = lax.scan(tick, (acc0, k, v), jnp.arange(1, world))
    out = (num / den.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    return out, m + jnp.log(den)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ring_attn(axis_name, causal, flash_cfg, q, k, v):
    out, _ = _ring_fwd(axis_name, causal, flash_cfg, q, k, v)
    return out


def _ring_attn_fwd(axis_name, causal, flash_cfg, q, k, v):
    out, lse = _ring_fwd(axis_name, causal, flash_cfg, q, k, v)
    return out, (q, k, v, out, lse)


def _ring_attn_bwd(axis_name, causal, flash_cfg, res, g):
    """Backward ring pass (the ring-attention recipe): with the globally
    merged (lse, Δ = rowsum(dO ⊙ O)), each block's exact gradient
    contribution is an independent flash backward — dq accumulates
    locally, while (dk, dv) accumulators TRAVEL with their K/V block and
    arrive home after a full ring revolution. Nothing from the forward
    scan is stored (flash-style recompute), so residual memory is O(local
    shard), independent of the ring size."""
    use_flash, interpret, striped = flash_cfg
    q, k, v, out, lse = res
    world = axis_size(axis_name)
    # As in the forward: a dead partition-id breaks CPU SPMD partitioning.
    idx = lax.axis_index(axis_name) if causal else None

    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)  # [B, H, Tl]

    def block_bwd(q_, kb, vb, diag, k_shift=0):
        if use_flash:
            from tpudml.ops import flash_block_grads

            return flash_block_grads(
                q_, kb, vb, g, lse, delta, causal=diag, k_shift=k_shift,
                interpret=interpret,
            )
        return _block_bwd_math(q_, kb, vb, g, lse, delta, diag, k_shift)

    # Tick 0: local diagonal block. Gradient accumulators (stationary dq,
    # traveling dk/dv) stay float32 regardless of the model dtype.
    dq0, dk0, dv0 = block_bwd(q, k, v, causal)
    f32 = lambda x: x.astype(jnp.float32)

    def tick(carry, step):
        dq_acc, kb, vb, dkb, dvb = carry
        kb = ppermute_ring(kb, axis_name)
        vb = ppermute_ring(vb, axis_name)
        dkb = ppermute_ring(dkb, axis_name)
        dvb = ppermute_ring(dvb, axis_name)
        src = (idx - step) % world if causal else None

        def fold(args, diag=False, k_shift=0):
            dq_acc, dkb, dvb = args
            dq_i, dk_i, dv_i = block_bwd(q, kb, vb, diag, k_shift)
            return dq_acc + f32(dq_i), dkb + f32(dk_i), dvb + f32(dv_i)

        if causal and striped:
            dq_acc, dkb, dvb = lax.cond(
                src > idx,
                lambda a: fold(a, True, 1),
                lambda a: fold(a, True, 0),
                (dq_acc, dkb, dvb),
            )
        elif causal:
            dq_acc, dkb, dvb = lax.cond(
                src < idx, fold, lambda a: a, (dq_acc, dkb, dvb)
            )
        else:
            dq_acc, dkb, dvb = fold((dq_acc, dkb, dvb))
        return (dq_acc, kb, vb, dkb, dvb), None

    (dq_acc, _, _, dkb, dvb), _ = lax.scan(
        tick,
        (f32(dq0), k, v, f32(dk0), f32(dv0)),
        jnp.arange(1, world),
    )
    # The traveling accumulators sit one hop short of home: one final
    # rotation completes the revolution (W moves total).
    dk = ppermute_ring(dkb, axis_name)
    dv = ppermute_ring(dvb, axis_name)
    return dq_acc.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attn.defvjp(_ring_attn_fwd, _ring_attn_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    remat: bool = False,
    use_flash: bool | None = None,
    interpret: bool = False,
    layout: str = "contiguous",
) -> jax.Array:
    """Ring self-attention over a sharded sequence axis.

    Args are the local shards [B, T/W, H, D]. Returns the local output
    shard, equal to full attention on the gathered sequence up to float
    accumulation order. Per ring tick each arriving K/V block is folded
    as a flash-attention partial (out_b, lse_b) and merged by
    log-sum-exp; on TPU the per-block fold runs the Pallas flash kernel
    (``tpudml.ops``), elsewhere the reference math — ``use_flash``
    overrides the auto-dispatch, ``interpret`` forces the Pallas
    interpreter for kernel tests off-TPU.

    Causal mode with the default ``layout="contiguous"`` skips
    fully-masked blocks outright (src > idx never reaches the MXU — ~2×
    the ring's FLOPs saved); ``layout="striped"`` instead interprets the
    local shard as tokens {t : t mod W == device} (the caller permutes the
    sequence accordingly — ``ContextParallel(layout="striped")`` does)
    and every block becomes a balanced triangular tile, fixing the
    contiguous layout's last-device latency bottleneck. The custom-VJP
    backward runs a second ring revolution with the flash decomposition
    (global lse/Δ), storing no per-tick residuals; ``remat`` is therefore
    implied and the flag is accepted for API compatibility.
    """
    del remat  # the custom-VJP backward always recomputes (flash-style)
    if layout not in ("contiguous", "striped"):
        raise ValueError(f"unknown ring layout {layout!r}")
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    return _ring_attn(
        axis_name, causal, (use_flash, interpret, layout == "striped"), q, k, v
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) attention: reshard sequence→
    heads, full attention locally, reshard back."""
    from tpudml.nn.attention import dot_product_attention

    world = axis_size(axis_name)
    if q.shape[2] % world:
        raise ValueError(
            f"ulysses needs num_heads {q.shape[2]} divisible by axis size {world}"
        )
    qg, kg, vg = (
        all_to_all(a, axis_name, split_axis=2, concat_axis=1) for a in (q, k, v)
    )
    o = dot_product_attention(qg, kg, vg, causal=causal)
    return all_to_all(o, axis_name, split_axis=1, concat_axis=2)


def _stripe_time(x, world):
    """Contiguous [B, T, ...] → striped: shard-slice i holds tokens
    {t : t mod world == i} in order (host-side reorder; the device_put
    that follows hands each device exactly its stripe)."""
    b, t = x.shape[:2]
    tl = t // world
    return x.reshape(b, tl, world, *x.shape[2:]).swapaxes(1, 2).reshape(x.shape)


def _unstripe_time(x, world):
    b, t = x.shape[:2]
    tl = t // world
    return x.reshape(b, world, tl, *x.shape[2:]).swapaxes(1, 2).reshape(x.shape)


class ContextParallel:
    """Sequence-parallel training engine over a mesh ``seq`` axis.

    The model must be built seq-sharded (e.g. ``TransformerLM(...,
    impl="ring", seq_sharded=True)``); parameters stay replicated, the
    time axis of inputs/labels is sharded, and parameter gradients are
    pmean-ed over the axis (per-shard token-mean losses of equal-size
    shards average to the global token mean).

    Composes with data parallelism on a 2-D mesh: pass
    ``batch_axis="data"`` with a {"data": D, "seq": S} mesh and the batch
    dim shards over ``data`` while the time dim shards over ``seq`` —
    ring/Ulysses collectives stay within each data replica's seq subgroup,
    and gradients average over both axes.
    """

    def __init__(
        self,
        model: Module,
        optimizer: Optimizer,
        mesh: Mesh,
        axis_name: str = "seq",
        batch_axis: str | None = None,
        rng_root: jax.Array | None = None,
        aux_loss_weight: float | None = None,
        layout: str = "contiguous",
        fused_xent: bool = False,
        save_scores: bool | None = None,
    ):
        if layout not in ("contiguous", "striped"):
            raise ValueError(f"unknown layout {layout!r}")
        if save_scores and not fused_xent:
            reject("save_scores_needs_fused_xent")
        model_layout = getattr(model, "seq_layout", "contiguous")
        if model_layout != layout:
            raise ValueError(
                f"engine layout {layout!r} != model seq_layout "
                f"{model_layout!r}; build the model with seq_layout="
                f"{layout!r} so positions/masks match the token placement"
            )
        self.layout = layout
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.axis_name = axis_name
        self.rng_root = rng_root  # per-step/per-shard dropout streams
        # Dense-MoE runs get the Switch load-balancing pressure by default
        # (None → α=0.01 when the model contains MoE layers).
        # fused_xent: the head runs through the fused linear-cross-entropy
        # kernel instead of materializing logits — token-parallel, so the
        # same per-shard-mean → pmean structure holds under the seq
        # sharding; metrics carry loss only (no logits ⇒ no accuracy).
        self.fused_xent = fused_xent
        if fused_xent:
            from tpudml.train import make_lm_fused_loss_fn

            self._fused_loss_fn = make_lm_fused_loss_fn(
                model, save_scores, aux_loss_weight
            )
        self._loss_fn = make_loss_fn(
            model, softmax_cross_entropy,
            resolve_aux_loss_weight(model, aux_loss_weight),
        )
        if batch_axis is not None and batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} not in mesh axes {tuple(mesh.shape)}"
            )
        self.batch_axis = batch_axis
        self.world = mesh.shape[axis_name]
        self._throttle = DispatchThrottle(mesh)
        self._eval_step = None

    def create_state(self, key: jax.Array) -> TrainState:
        from tpudml.parallel.sharding import replicate

        return replicate(
            TrainState.create(self.model, self.optimizer, key), self.mesh
        )

    def _batch_spec(self) -> P:
        # [B, T, ...]: time sharded over seq; batch over data when composed.
        return P(self.batch_axis, self.axis_name)

    def make_forward(self) -> Callable:
        fwd = jax.jit(
            shard_map_fn(
                lambda params, x: self.model(params, x),
                self.mesh,
                in_specs=(P(), self._batch_spec()),
                out_specs=self._batch_spec(),
            )
        )
        if self.layout != "striped":
            return fwd

        world = self.world

        @jax.jit
        def striped_fwd(params, x):
            # Stripe/unstripe inside the jit, consistent with the
            # train/eval paths (fused by XLA, no eager pre-dispatch).
            return _unstripe_time(fwd(params, _stripe_time(x, world)), world)

        return striped_fwd

    def _mean_axes(self) -> tuple[str, ...]:
        # One fused all-reduce over the combined (seq[, data]) group.
        return (self.axis_name,) + (
            (self.batch_axis,) if self.batch_axis is not None else ()
        )

    def make_eval_step(self) -> Callable:
        """Jitted sharded eval: (params, model_state, tokens, labels) →
        (correct_predictions, token_count), summed over every shard.
        Cached on the engine, so repeated evaluate() calls reuse one
        compiled program."""
        if self._eval_step is None:
            spec = self._batch_spec()
            inner = make_counting_eval_step(
                self.model, self.mesh, (P(), P(), spec, spec), self._mean_axes()
            )
            if self.layout == "striped":
                world = self.world
                self._eval_step = jax.jit(
                    lambda p, s, x, y: inner(
                        p, s, _stripe_time(x, world), _stripe_time(y, world)
                    )
                )
            else:
                self._eval_step = inner
        return self._eval_step

    def evaluate(self, ts: TrainState, loader) -> float:
        """Token-level top-1 accuracy over a loader of (tokens, labels);
        striping (when configured) happens inside the compiled eval step."""
        return evaluate_counts(self.make_eval_step(), ts, loader)

    def make_train_step(self) -> Callable:
        axis = self.axis_name

        def spmd(ts: TrainState, tokens, labels):
            rng = None
            if self.rng_root is not None:
                # Distinct dropout streams per step AND per sequence shard
                # (a replicated key would reuse one mask on every shard).
                rng = jax.random.fold_in(
                    jax.random.fold_in(self.rng_root, ts.step),
                    lax.axis_index(axis),
                )

            if self.fused_xent:
                (loss, model_state), grads = jax.value_and_grad(
                    self._fused_loss_fn, has_aux=True
                )(ts.params, ts.model_state, tokens, labels, rng)
                metrics = {}
            else:
                (loss, (model_state, logits)), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True
                )(ts.params, ts.model_state, tokens, labels, rng)
                metrics = {"accuracy": accuracy(logits, labels)}
            axes = self._mean_axes()
            grads = pmean_tree(grads, axes)
            # Shard-consistent model state (e.g. norm running stats), same
            # treatment as the DP engine: averaged so replicas stay equal.
            model_state = pmean_tree(model_state, axes)
            new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
            metrics = {
                "loss": lax.pmean(loss, axes),
                **{k: lax.pmean(v, axes) for k, v in metrics.items()},
            }
            new_ts = TrainState(
                params=new_params,
                model_state=model_state,
                opt_state=new_opt,
                step=ts.step + 1,
            )
            return new_ts, metrics

        spec = self._batch_spec()
        sharded = shard_map_fn(
            spmd,
            self.mesh,
            in_specs=(P(), spec, spec),
            out_specs=(P(), P()),
        )
        striped = self.layout == "striped"
        world = self.world

        def outer(ts: TrainState, tokens, labels):
            if striped:
                # Reorder INSIDE the jit (fused by XLA with the embedding
                # gather) so the contiguous shard-slices the in_spec hands
                # out ARE the stripes (token t mod W).
                tokens = _stripe_time(tokens, world)
                labels = _stripe_time(labels, world)
            return sharded(ts, tokens, labels)

        # Donate the TrainState: replicated params/opt-state update in place.
        # Input state is CONSUMED; callers must rebind ts every step.
        jitted = jax.jit(outer, donate_argnums=(0,))

        def step(ts: TrainState, tokens, labels):
            out = jitted(ts, jnp.asarray(tokens), jnp.asarray(labels))
            self._throttle.after_step(out[1]["loss"])
            return out

        # Raw program for tpudml.analysis (wrapper does host-side work);
        # in_specs/mesh_axes seed the dataflow interpreter and --cost.
        step.jitted = jitted
        step.in_specs = (P(), spec, spec)
        step.mesh_axes = dict(self.mesh.shape)
        return step
