"""Context (sequence) parallelism: ring attention and Ulysses all-to-all.

Absent from the reference (SURVEY.md §5.7: no attention, no sequence
dimension), but first-class here: long sequences are sharded over a mesh
``seq`` axis so activation memory per chip scales 1/W, and only K/V blocks
(ring) or head-groups (Ulysses) move over ICI.

- **Ring attention**: each device keeps its Q shard resident and rotates
  K/V shards around the ring with ``lax.ppermute``, folding each arriving
  block into a numerically-stable online softmax (running max + running
  normalizer, flash-attention style, accumulated in float32). W steps see
  every block exactly once; communication overlaps compute tick by tick.
  Causal masking uses *global* positions derived from the block's origin
  device, so semantics are identical to full attention.
- **Ulysses**: ``lax.all_to_all`` transposes the sharding from sequence to
  heads ([B,T/W,H,D] → [B,T,H/W,D]), runs ordinary full attention on the
  now-complete sequence for the local head group, and transposes back.
  Needs num_heads % W == 0; two collectives per attention instead of W
  ring hops.

Both are pure jittable functions (must run under shard_map with
``axis_name`` bound) and differentiate exactly — ppermute/all_to_all
transpose to their inverses, so gradients route back to the owning shard.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpudml.comm.collectives import all_to_all, pmean_tree, ppermute_ring
from tpudml.nn.attention import NEG_INF
from tpudml.nn.layers import Module
from tpudml.nn.losses import accuracy, softmax_cross_entropy
from tpudml.optim import Optimizer
from tpudml.parallel.sharding import (
    make_counting_eval_step,
    serialize_dispatch,
    shard_map_fn,
)
from tpudml.train import (
    TrainState,
    evaluate_counts,
    make_loss_fn,
    resolve_aux_loss_weight,
)

PyTree = Any


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    remat: bool = False,
) -> jax.Array:
    """Ring self-attention over a sharded sequence axis.

    Args are the local shards [B, T/W, H, D]. Returns the local output
    shard, bitwise-independent of W up to float accumulation order.
    ``remat=True`` rematerializes each ring tick in the backward pass
    (scores/probs recomputed instead of stored — W× less attention
    residual memory, the flash-attention trade, for very long contexts).
    """
    world = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    q_pos = idx * t_local + jnp.arange(t_local)

    def fold(acc, kb, vb, src):
        """Merge one K/V block into the online-softmax accumulator
        (associative, so block arrival order doesn't matter)."""
        o, m, l = acc
        k_pos = src * t_local + jnp.arange(t_local)
        s = (
            jnp.einsum("bqhd,bkhd->bhqk", q, kb, preferred_element_type=jnp.float32)
            * scale
        )
        if causal:
            s = jnp.where((q_pos[:, None] >= k_pos[None, :])[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb, preferred_element_type=jnp.float32)
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
        return o_new, m_new, l_new

    # Step 0: the resident local block — no communication. Steps 1..W-1:
    # rotate, then fold the block that originated on device (idx - step);
    # rotating at the top of the body avoids a W-th ppermute whose result
    # would be discarded.
    acc0 = fold(
        (
            jnp.zeros((b, t_local, h, d), jnp.float32),
            jnp.full((b, h, t_local), -jnp.inf, jnp.float32),
            jnp.zeros((b, h, t_local), jnp.float32),
        ),
        k,
        v,
        idx,
    )

    def tick(carry, step):
        acc, kb, vb = carry
        kb = ppermute_ring(kb, axis_name)
        vb = ppermute_ring(vb, axis_name)
        acc = fold(acc, kb, vb, (idx - step) % world)
        return (acc, kb, vb), None

    if remat:
        tick = jax.checkpoint(tick)
    ((o, _, l), _, _), _ = lax.scan(tick, (acc0, k, v), jnp.arange(1, world))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) attention: reshard sequence→
    heads, full attention locally, reshard back."""
    from tpudml.nn.attention import dot_product_attention

    world = lax.axis_size(axis_name)
    if q.shape[2] % world:
        raise ValueError(
            f"ulysses needs num_heads {q.shape[2]} divisible by axis size {world}"
        )
    qg, kg, vg = (
        all_to_all(a, axis_name, split_axis=2, concat_axis=1) for a in (q, k, v)
    )
    o = dot_product_attention(qg, kg, vg, causal=causal)
    return all_to_all(o, axis_name, split_axis=1, concat_axis=2)


class ContextParallel:
    """Sequence-parallel training engine over a mesh ``seq`` axis.

    The model must be built seq-sharded (e.g. ``TransformerLM(...,
    impl="ring", seq_sharded=True)``); parameters stay replicated, the
    time axis of inputs/labels is sharded, and parameter gradients are
    pmean-ed over the axis (per-shard token-mean losses of equal-size
    shards average to the global token mean).

    Composes with data parallelism on a 2-D mesh: pass
    ``batch_axis="data"`` with a {"data": D, "seq": S} mesh and the batch
    dim shards over ``data`` while the time dim shards over ``seq`` —
    ring/Ulysses collectives stay within each data replica's seq subgroup,
    and gradients average over both axes.
    """

    def __init__(
        self,
        model: Module,
        optimizer: Optimizer,
        mesh: Mesh,
        axis_name: str = "seq",
        batch_axis: str | None = None,
        rng_root: jax.Array | None = None,
        aux_loss_weight: float | None = None,
    ):
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.axis_name = axis_name
        self.rng_root = rng_root  # per-step/per-shard dropout streams
        # Dense-MoE runs get the Switch load-balancing pressure by default
        # (None → α=0.01 when the model contains MoE layers).
        self._loss_fn = make_loss_fn(
            model, softmax_cross_entropy,
            resolve_aux_loss_weight(model, aux_loss_weight),
        )
        if batch_axis is not None and batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} not in mesh axes {tuple(mesh.shape)}"
            )
        self.batch_axis = batch_axis
        self.world = mesh.shape[axis_name]
        self._sync_each_step = serialize_dispatch(mesh)
        self._eval_step = None

    def create_state(self, key: jax.Array) -> TrainState:
        from tpudml.parallel.sharding import replicate

        return replicate(
            TrainState.create(self.model, self.optimizer, key), self.mesh
        )

    def _batch_spec(self) -> P:
        # [B, T, ...]: time sharded over seq; batch over data when composed.
        return P(self.batch_axis, self.axis_name)

    def make_forward(self) -> Callable:
        fwd = shard_map_fn(
            lambda params, x: self.model(params, x),
            self.mesh,
            in_specs=(P(), self._batch_spec()),
            out_specs=self._batch_spec(),
        )
        return jax.jit(fwd)

    def _mean_axes(self) -> tuple[str, ...]:
        # One fused all-reduce over the combined (seq[, data]) group.
        return (self.axis_name,) + (
            (self.batch_axis,) if self.batch_axis is not None else ()
        )

    def make_eval_step(self) -> Callable:
        """Jitted sharded eval: (params, model_state, tokens, labels) →
        (correct_predictions, token_count), summed over every shard.
        Cached on the engine, so repeated evaluate() calls reuse one
        compiled program."""
        if self._eval_step is None:
            spec = self._batch_spec()
            self._eval_step = make_counting_eval_step(
                self.model, self.mesh, (P(), P(), spec, spec), self._mean_axes()
            )
        return self._eval_step

    def evaluate(self, ts: TrainState, loader) -> float:
        """Token-level top-1 accuracy over a loader of (tokens, labels)."""
        return evaluate_counts(self.make_eval_step(), ts, loader)

    def make_train_step(self) -> Callable:
        axis = self.axis_name

        def spmd(ts: TrainState, tokens, labels):
            rng = None
            if self.rng_root is not None:
                # Distinct dropout streams per step AND per sequence shard
                # (a replicated key would reuse one mask on every shard).
                rng = jax.random.fold_in(
                    jax.random.fold_in(self.rng_root, ts.step),
                    lax.axis_index(axis),
                )

            (loss, (model_state, logits)), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True
            )(ts.params, ts.model_state, tokens, labels, rng)
            axes = self._mean_axes()
            grads = pmean_tree(grads, axes)
            # Shard-consistent model state (e.g. norm running stats), same
            # treatment as the DP engine: averaged so replicas stay equal.
            model_state = pmean_tree(model_state, axes)
            new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
            metrics = {
                "loss": lax.pmean(loss, axes),
                "accuracy": lax.pmean(accuracy(logits, labels), axes),
            }
            new_ts = TrainState(
                params=new_params,
                model_state=model_state,
                opt_state=new_opt,
                step=ts.step + 1,
            )
            return new_ts, metrics

        spec = self._batch_spec()
        # Donate the TrainState: replicated params/opt-state update in place.
        # Input state is CONSUMED; callers must rebind ts every step.
        jitted = jax.jit(
            shard_map_fn(
                spmd,
                self.mesh,
                in_specs=(P(), spec, spec),
                out_specs=(P(), P()),
            ),
            donate_argnums=(0,),
        )

        def step(ts: TrainState, tokens, labels):
            out = jitted(ts, jnp.asarray(tokens), jnp.asarray(labels))
            if self._sync_each_step:
                jax.block_until_ready(out[1]["loss"])
            return out

        return step
