"""Collective-matmul overlap: chunked psum-overlapped TP matmuls.

ROADMAP item 4(c). A tensor-parallel block ends its attention and MLP
branches with a row-sharded matmul whose partial products must be
psum-merged across the model axis — in the naive composition the whole
[rows, d] product finishes before the allreduce starts, so the wire
time is fully EXPOSED on the step's critical path. The overlap form
(arXiv 2204.06514's collective-matmul placement) splits the row axis
into K chunks and reduces each chunk's partial product as soon as it
exists, so chunk i's allreduce rides under chunk i+1's matmul and only
the LAST chunk's reduce (1/K of the wire bytes) stays exposed.

This module is the runnable shard_map-level primitive plus the marker
contract; the *placement* decision lives in the planner —
``plan/score.py`` prices a ``tp_overlap`` candidate with the
exposed-vs-hidden wire split (hidden (K−1)/K, exposed 1/K) and
``plan/space.py`` enumerates it per TP-capable mesh, pruned by the
capability table's ``tp_overlap_needs_model_axis`` row.

The function is jitted under a NAMED inner jit (``TP_OVERLAP_MARKER``)
so any step claiming overlapped TP matmuls carries a recognizable pjit
equation — analysis rule J119's overlap check verifies the claim
against the marker, the same discipline as the fused xent/decode
markers. XLA inlines the marker at lowering; the chunked loop itself is
what lets the latency-hiding scheduler start reduce i during matmul
i+1.

Exactness: ``concat_i(psum(x_i @ w)) == psum(x @ w)`` — the chunk split
is over rows, which the reduce never mixes; pinned by the parity tests
under TP and FSDP×TP meshes in both value and gradient.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpudml.capabilities import reject

# Default chunk count: 4 hides 3/4 of the reduce behind compute while
# keeping per-chunk matmuls MXU-shaped at flagship row counts (8k rows /
# 4 = 2k-row chunks); the planner prices this constant (plan/score.py).
OVERLAP_CHUNKS = 4


def _tp_overlap_matmul(x, w, axis_name, chunks):
    parts = []
    for xc in jnp.split(x, chunks, axis=0):
        p = jax.lax.dot_general(
            xc, w, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # Reduce THIS chunk immediately: the next chunk's matmul issues
        # while this allreduce is on the wire.
        parts.append(jax.lax.psum(p, axis_name))
    return jnp.concatenate(parts, axis=0).astype(x.dtype)


TP_OVERLAP_MARKER = _tp_overlap_matmul.__name__

_tp_overlap_matmul_jit = jax.jit(_tp_overlap_matmul, static_argnums=(2, 3))


def tp_overlap_matmul(
    x: jax.Array,
    w: jax.Array,
    *,
    axis_name: str,
    chunks: int = OVERLAP_CHUNKS,
) -> jax.Array:
    """psum-overlapped row-sharded matmul: ``psum(x @ w, axis_name)``
    computed as ``chunks`` row-chunks with per-chunk reduces (module
    docstring). Call INSIDE a ``shard_map`` region where ``axis_name``
    is bound, with ``x`` [rows, k_local] the feature-sharded activation
    and ``w`` [k_local, m] the local weight shard; rows must divide by
    ``chunks``. Differentiable: autodiff transposes each per-chunk psum
    exactly as it does the single fused reduce."""
    if chunks < 1:
        raise ValueError(f"chunks must be >= 1, got {chunks}")
    try:  # bound-axis introspection has no stable public API
        from jax._src.core import get_axis_env

        size = get_axis_env().axis_size(axis_name)
    except Exception:
        size = None  # unbound axis: the psum below raises its own error
    if size is not None and size <= 1:
        # Same condition as the planner's capability row: without a
        # model axis there is no reduce to hide — the chunked loop
        # would only cost concat/split overhead.
        reject("tp_overlap_needs_model_axis")
    rows = x.shape[0]
    if rows % chunks:
        raise ValueError(
            f"rows {rows} must divide by chunks {chunks} (pad the batch "
            f"or pick a divisor; uneven chunks would recompile per shape)"
        )
    return _tp_overlap_matmul_jit(x, w, axis_name, chunks)
