"""Expert parallelism: training engine for MoE models.

Tokens and experts shard over the same mesh axis (the GShard layout):
each shard routes its own tokens, MoE layers ship capacity buffers by
``all_to_all`` (see ``tpudml.nn.moe``), and parameters split into two
gradient classes —

- **expert parameters** (any leaf under an ``"experts"`` key): already
  receive the cross-shard sum of cotangents through the all_to_all
  transpose, so the engine only divides by the axis size to turn the sum
  into the global-mean gradient;
- **everything else** (router, embeddings, dense layers): replicated,
  per-shard gradients are pmean-ed, exactly like data parallelism.

The parity oracle (tests): EP training over W shards matches dense
single-device training on the concatenated batch, step for step, when no
capacity drops occur.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudml.nn.layers import Module
from tpudml.nn.losses import accuracy
from tpudml.optim import Optimizer, shard_aware_clip
from tpudml.parallel.sharding import (
    make_counting_eval_step,
    DispatchThrottle,
    shard_map_fn,
)
from tpudml.train import TrainState, evaluate_counts, make_loss_fn

PyTree = Any


def _is_expert_path(key_path) -> bool:
    from tpudml.core.pytree import key_name

    return any(key_name(k) == "experts" for k in key_path)


def expert_specs(params: PyTree, axis_name: str) -> PyTree:
    """Per-leaf PartitionSpec: expert leaves shard their stacked leading
    (num_experts) dim over the axis; everything else is replicated."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: P(axis_name) if _is_expert_path(path) else P(),
        params,
    )


class ExpertParallel:
    """EP training engine over a mesh ``expert`` axis.

    The model must build its MoE layers with ``axis_name`` equal to this
    engine's axis (e.g. ``MoELayer(..., axis_name="expert")``); batches
    are global and get sharded over the axis by the step function.
    """

    def __init__(
        self,
        model: Module,
        optimizer: Optimizer,
        mesh: Mesh,
        axis_name: str = "expert",
        aux_loss_weight: float = 1e-2,
        batch_axis: str | None = None,
    ):
        self.model = model
        if batch_axis is not None and (
            batch_axis not in mesh.shape or batch_axis == axis_name
        ):
            raise ValueError(
                f"batch_axis {batch_axis!r} must be a mesh axis distinct "
                f"from the expert axis {axis_name!r} (mesh: {tuple(mesh.shape)})"
            )
        # EP×DP on a 2-D {"data": D, "expert": E} mesh: the batch dim
        # shards over BOTH axes (D·E token shards), experts shard over
        # ``expert`` and replicate over ``data``; the MoE all_to_all stays
        # within each data replica's expert subgroup.
        self.batch_axis = batch_axis
        # The update runs inside shard_map with expert grads device-local:
        # a global-norm clip must psum its norm over the expert axis
        # (expert leaves local, router/dense replicated) or shards would
        # clip by different scales and de-sync the replicated parameters.
        self.optimizer = shard_aware_clip(
            optimizer, (axis_name,), _is_expert_path
        )
        self.mesh = mesh
        self.axis_name = axis_name
        self.world = mesh.shape[axis_name]
        # Switch load-balancing pressure on by default for MoE training
        # (the canonical α≈0.01); pass 0.0 to disable.
        self._loss_fn = make_loss_fn(model, aux_loss_weight=aux_loss_weight)
        self._throttle = DispatchThrottle(mesh)
        self._eval_step = None
        # Specs derive from the model structure alone (eval_shape — no
        # compute), so step functions can be built before/without
        # create_state, e.g. when restoring a checkpointed TrainState.
        abstract = jax.eval_shape(
            lambda: TrainState.create(self.model, self.optimizer, jax.random.key(0))
        )
        param_specs = expert_specs(abstract.params, axis_name)
        self._specs = TrainState(
            params=param_specs,
            model_state=expert_specs(abstract.model_state, axis_name),
            opt_state=self.optimizer.init_spec(param_specs),
            step=P(),
        )

    def create_state(self, key: jax.Array) -> TrainState:
        ts = TrainState.create(self.model, self.optimizer, key)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self._specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        return jax.device_put(ts, shardings)

    def _all_axes(self):
        return (
            (self.batch_axis, self.axis_name)
            if self.batch_axis is not None
            else self.axis_name
        )

    def _batch_spec(self) -> P:
        # Batch dim sharded over (data, expert) combined when composed —
        # by construction the same axes the means reduce over.
        return P(self._all_axes())

    def _mean_grads(self, grads: PyTree) -> PyTree:
        world = self.world
        batch_axis = self.batch_axis

        def fix(path, g):
            if _is_expert_path(path):
                g = g / world  # a2a transpose already summed across shards
                # Experts replicate over the data axis: average the data
                # replicas' contributions like any replicated parameter.
                return lax.pmean(g, batch_axis) if batch_axis else g
            return lax.pmean(g, self._all_axes())

        return jax.tree_util.tree_map_with_path(fix, grads)

    def make_forward(self) -> Callable:
        spec = self._batch_spec()
        fwd = shard_map_fn(
            lambda params, x: self.model(params, x),
            self.mesh,
            in_specs=(self._specs.params, spec),
            out_specs=spec,
        )
        return jax.jit(fwd)

    def make_eval_step(self) -> Callable:
        """Jitted sharded eval: (params, model_state, x, labels) →
        (correct, count) summed over the expert-data shards. Cached on the
        engine so repeated evaluate() calls reuse one compiled program."""
        if self._eval_step is None:
            spec = self._batch_spec()
            self._eval_step = make_counting_eval_step(
                self.model,
                self.mesh,
                (self._specs.params, self._specs.model_state, spec, spec),
                self._all_axes(),
            )
        return self._eval_step

    def evaluate(self, ts: TrainState, loader) -> float:
        return evaluate_counts(self.make_eval_step(), ts, loader)

    def make_train_step(self) -> Callable:
        def spmd(ts: TrainState, x, labels):
            def loss_fn(params):
                loss, aux = self._loss_fn(params, ts.model_state, x, labels, None)
                return loss, aux

            (loss, (model_state, logits)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(ts.params)
            grads = self._mean_grads(grads)
            # Replicated (non-expert) model state, e.g. BN stats, must stay
            # shard-consistent — same treatment as the DP/CP engines;
            # expert-owned state stays local to its expert shard (averaged
            # over data replicas when composed).
            batch_axis = self.batch_axis
            model_state = jax.tree_util.tree_map_with_path(
                lambda path, s: (
                    (lax.pmean(s, batch_axis) if batch_axis else s)
                    if _is_expert_path(path)
                    else lax.pmean(s, self._all_axes())
                ),
                model_state,
            )
            new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
            metrics = {
                "loss": lax.pmean(loss, self._all_axes()),
                "accuracy": lax.pmean(accuracy(logits, labels), self._all_axes()),
            }
            new_ts = TrainState(
                params=new_params,
                model_state=model_state,
                opt_state=new_opt,
                step=ts.step + 1,
            )
            return new_ts, metrics

        specs = self._specs
        # Donate the TrainState: expert params/opt-state rewrite in place.
        # Input state is CONSUMED; callers must rebind ts every step.
        batch_spec = self._batch_spec()
        jitted = jax.jit(
            shard_map_fn(
                spmd,
                self.mesh,
                in_specs=(specs, batch_spec, batch_spec),
                out_specs=(specs, P()),
            ),
            donate_argnums=(0,),
        )

        def step(ts: TrainState, x, labels):
            out = jitted(ts, jnp.asarray(x), jnp.asarray(labels))
            self._throttle.after_step(out[1]["loss"])
            return out

        # Raw program for tpudml.analysis (wrapper does host-side work);
        # in_specs/mesh_axes seed the dataflow interpreter and --cost.
        step.jitted = jitted
        step.in_specs = (specs, batch_spec, batch_spec)
        step.mesh_axes = dict(self.mesh.shape)
        return step
