"""Sharding utilities over a named device mesh.

The thin layer every parallel engine shares: NamedSharding constructors,
host→mesh placement helpers, and a version-portable ``shard_map`` wrapper.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # JAX ≥ 0.4.35 exposes shard_map at top level
    _shard_map = jax.shard_map  # type: ignore[attr-defined]
except AttributeError:  # pragma: no cover - older JAX
    from jax.experimental.shard_map import shard_map as _shard_map

PyTree = Any


def shard_map_fn(fn, mesh: Mesh, in_specs, out_specs, check_rep: bool = False):
    """``shard_map`` with this repo's defaults (rep-check off: collective
    aggregation intentionally produces replicated outputs from sharded
    inputs, which the static replication checker can't always verify)."""
    try:
        return _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_rep
        )
    except TypeError:  # pragma: no cover - JAX < 0.6 spells it check_rep
        return _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_rep
        )


def serialize_dispatch(mesh: Mesh) -> bool:
    """Whether a mesh needs dispatch throttling at all. XLA:CPU's
    collective rendezvous deadlocks (and then aborts the process) when many
    in-flight partitioned programs oversubscribe the host thread pool —
    seen with >~50 async-queued steps on a 1-core box. Real TPU keeps full
    async pipelining."""
    return all(d.platform == "cpu" for d in mesh.devices.flat)


class DispatchThrottle:
    """Bound the number of in-flight dispatched steps on CPU meshes.

    Full per-step serialization (round 1's workaround) hid the real TPU
    execution mode from every simulated run: nothing ever had more than
    one step in flight, so async multi-step pipelining went untested.
    Instead, keep a window of ``max_in_flight`` un-materialized step
    outputs and block only on the OLDEST once the window fills — the
    simulated mesh now genuinely overlaps dispatch (window > 1) while the
    rendezvous pool stays bounded. On non-CPU meshes this is a no-op.
    """

    def __init__(self, mesh: Mesh, max_in_flight: int = 8):
        self.enabled = serialize_dispatch(mesh)
        self.max_in_flight = max_in_flight
        self._pending: list = []
        self.max_pending_seen = 0  # observability (asserted in tests)

    def after_step(self, out_leaf) -> None:
        """Call with one device value from each dispatched step."""
        if not self.enabled:
            return
        self._pending.append(out_leaf)
        self.max_pending_seen = max(self.max_pending_seen, len(self._pending))
        if len(self._pending) >= self.max_in_flight:
            jax.block_until_ready(self._pending.pop(0))


def make_counting_eval_step(model, mesh: Mesh, in_specs, axes):
    """Jitted sharded eval kernel shared by the parallel engines:
    (params, model_state, x, labels) → (correct, count), psum-ed over
    ``axes``. ``in_specs`` = (param_specs, state_specs, batch_spec,
    batch_spec)."""
    import jax.numpy as jnp
    from jax import lax

    def spmd(params, model_state, x, labels):
        logits, _ = model.apply(params, model_state, x, train=False)
        correct = jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.int32))
        return lax.psum(correct, axes), lax.psum(labels.size, axes)

    return jax.jit(
        shard_map_fn(spmd, mesh, in_specs=in_specs, out_specs=(P(), P()))
    )


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, axis_name: str = "data") -> NamedSharding:
    """Leading-axis (batch) sharding over the mesh's data axis."""
    return NamedSharding(mesh, P(axis_name))


def replicate(tree: PyTree, mesh: Mesh) -> PyTree:
    """Place a host pytree replicated on every mesh device.

    The TPU-idiomatic analogue of the reference's one-time rank-0 parameter
    broadcast (``init_parameters``, codes/task2/dist_utils.py:33-37): one
    host copy becomes one replicated device array — no collective needed,
    and all replicas are bitwise identical by construction.
    """
    return jax.device_put(tree, replicated_sharding(mesh))


def shard_batch(batch: PyTree, mesh: Mesh, axis_name: str = "data") -> PyTree:
    """Place a global host batch sharded along its leading dim."""
    return jax.device_put(batch, data_sharding(mesh, axis_name))
