"""Data parallelism: one jitted SPMD step over a mesh ``data`` axis.

Re-design of the reference's DDP loop (codes/task2/model.py:40-72,
codes/task3/model.py:39-64): replicated params, per-replica data shard,
per-step gradient aggregation. Where the reference runs one process per
rank and issues one NCCL collective per parameter tensor (SURVEY.md §3.2),
here the entire step — forward, backward, aggregation, optimizer update —
is ONE XLA program sharded over the mesh; XLA schedules the gradient
collectives on ICI and fuses them with the update.

Two execution modes:

- **fused** (default): maximum-performance single program.
- **split / measure_comm**: the step compiles as separate XLA programs for
  (local grads) and (aggregate), so the host can time the communication
  span and inject a straggler delay before the collective — reproducing
  task2's comm-time accounting and bottleneck-node experiment
  (codes/task2/model-mp.py:47-66, sections/task2.tex:18-19).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpudml.comm.collectives import broadcast_from, get_aggregator, pmean_tree
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.comm.timing import CommStats
from tpudml.core.dist import process_index
from tpudml.nn.layers import Module
from tpudml.optim import Optimizer
from tpudml.parallel.sharding import (
    data_sharding,
    replicate,
    DispatchThrottle,
    shard_map_fn,
)
from tpudml.train import (
    TrainState,
    accumulate_grads,
    make_loss_fn,
    resolve_aux_loss_weight,
)

PyTree = Any


class DataParallel:
    """DP training engine over a mesh ``data`` axis.

    Usage::

        dp = DataParallel(model, opt, mesh, aggregation="allreduce")
        ts = dp.create_state(key)          # replicated on the mesh
        step = dp.make_train_step()        # (ts, images, labels) -> (ts, metrics)

    ``images``/``labels`` are global batches (leading dim = world ×
    per-replica batch); the engine shards them over the data axis.
    """

    def __init__(
        self,
        model: Module,
        optimizer: Optimizer,
        mesh: Mesh,
        axis_name: str = "data",
        aggregation: str = "allreduce",
        measure_comm: bool = False,
        bottleneck_rank: int | None = None,
        bottleneck_delay_s: float = 0.1,
        rng_root: jax.Array | None = None,
        accum_steps: int = 1,
        loss: Callable = softmax_cross_entropy,
        stacked_batches: bool | None = None,
        aux_loss_weight: float | None = None,
        fused_xent: bool = False,
        save_scores: bool | None = None,
    ):
        if save_scores and not fused_xent:
            raise ValueError("save_scores requires fused_xent=True")
        if fused_xent and (
            measure_comm or accum_steps != 1
            or loss is not softmax_cross_entropy
        ):
            # The fused head IS the loss fn (linear cross-entropy); the
            # split-step timing path, scan-accumulation, and custom
            # ``loss`` callables all wrap the LOGITS loss fn — wire them
            # up when a use case appears rather than silently ignoring
            # the arguments.
            raise ValueError(
                "fused_xent composes with the fused step and the "
                "built-in cross-entropy only (measure_comm=False, "
                "accum_steps=1, default loss)"
            )
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.axis_name = axis_name
        # True: batches arrive in the ShardedDataLoader's stacked
        # [world, B, ...] form; False: plain global [world×B, ...] batches;
        # None: infer per batch (see shard_batch).
        self.stacked_batches = stacked_batches
        self.aggregation = aggregation
        self.aggregator = get_aggregator(aggregation)
        self.measure_comm = measure_comm
        self.bottleneck_rank = bottleneck_rank
        self.bottleneck_delay_s = bottleneck_delay_s
        self.rng_root = rng_root
        self.accum_steps = accum_steps
        self.comm_stats = CommStats()
        self.world = mesh.shape[axis_name]
        # Dense-MoE runs get the Switch load-balancing pressure by default
        # (None → α=0.01 when the model contains MoE layers).
        # fused_xent: the LM head runs through the fused linear-cross-
        # entropy kernel (token-parallel, so a batch-sharded trunk needs
        # no resharding); metrics carry loss only.
        self.fused_xent = fused_xent
        if fused_xent:
            from tpudml.train import make_lm_fused_loss_fn

            self._fused_loss_fn = make_lm_fused_loss_fn(
                model, save_scores, aux_loss_weight
            )
        self._loss_fn = make_loss_fn(
            model, loss, resolve_aux_loss_weight(model, aux_loss_weight)
        )
        self._throttle = DispatchThrottle(mesh)

    # ---------------------------------------------------------------- state

    def create_state(self, key: jax.Array) -> TrainState:
        """Init once on host, place replicated on every mesh device.

        Covers the reference's ``init_parameters`` broadcast contract
        (codes/task2/dist_utils.py:33-37): every replica starts from
        bitwise-identical params — here by construction rather than by a
        rank-0 collective (see also :meth:`broadcast_params`).
        """
        ts = TrainState.create(self.model, self.optimizer, key)
        return replicate(ts, self.mesh)

    def broadcast_params(self, ts: TrainState, root: int = 0) -> TrainState:
        """Explicit rank-``root`` parameter broadcast (reference-mechanism
        parity; needed only when replicas may have diverged, e.g. after a
        per-host restore)."""
        fn = shard_map_fn(
            lambda p: broadcast_from(p, self.axis_name, root),
            self.mesh,
            in_specs=P(),
            out_specs=P(),
        )
        return TrainState(
            params=jax.jit(fn)(ts.params),
            model_state=ts.model_state,
            opt_state=ts.opt_state,
            step=ts.step,
        )

    def shard_batch(self, images, labels):
        """Place a global [world×B, ...] host batch sharded over the data
        axis. Accepts the ShardedDataLoader's stacked [world, B, ...] form
        too (flattened so device r receives replica r's rows) — explicitly
        when the engine was built with ``stacked_batches=True``, else by
        inference: stacked iff the leading dim is the world size AND the
        inputs carry at least two more dims than the labels (image-shaped
        samples). 2-D LM token batches ([B, T] inputs + [B, T] labels)
        never match the inference even when B == world — construct with an
        explicit ``stacked_batches`` to bypass inference entirely."""
        sharding = data_sharding(self.mesh, self.axis_name)
        images = jnp.asarray(images)
        labels = jnp.asarray(labels)
        stacked = self.stacked_batches
        if stacked is None:
            stacked = (
                labels.ndim >= 2
                and labels.shape[0] == self.world
                and images.ndim >= labels.ndim + 2
            )
        if stacked:
            if images.shape[0] != self.world:
                raise ValueError(
                    f"stacked batch leading dim {images.shape[0]} != "
                    f"{self.world}-way data mesh"
                )
            images = images.reshape(-1, *images.shape[2:])
            labels = labels.reshape(-1, *labels.shape[2:])
        if images.shape[0] % self.world:
            # Catch it here (every caller: tasks, facade, direct use) with a
            # actionable message instead of an opaque XLA sharding error.
            raise ValueError(
                f"global batch of {images.shape[0]} rows is not divisible by "
                f"the {self.world}-way data mesh; pick a divisible batch_size "
                "(drop_remainder=True avoids ragged final batches)"
            )
        return jax.device_put(images, sharding), jax.device_put(labels, sharding)

    # ----------------------------------------------------------- fused step

    def make_train_step(self) -> Callable:
        if self.measure_comm:
            return self._make_split_step()
        return self._make_fused_step()

    def _spmd_body(self, ts: TrainState, images, labels):
        """Per-shard step body (runs under shard_map)."""
        rng = None
        if self.rng_root is not None:
            # Distinct dropout streams per replica and per step.
            rng = jax.random.fold_in(
                jax.random.fold_in(self.rng_root, ts.step),
                jax.lax.axis_index(self.axis_name),
            )
        if self.fused_xent:
            (loss, model_state), grads = jax.value_and_grad(
                self._fused_loss_fn, has_aux=True
            )(ts.params, ts.model_state, images, labels, rng)
            local = {"loss": loss}
        else:
            grads, model_state, local = accumulate_grads(
                self._loss_fn, ts.params, ts.model_state, images, labels, rng,
                self.accum_steps,
            )
        grads = self.aggregator(grads, self.axis_name)
        # Cross-replica-consistent BN stats: average the running stats so
        # every replica holds the same model_state (the reference's DDP
        # leaves them divergent per rank; averaged is strictly better and
        # keeps params/state replicated).
        model_state = pmean_tree(model_state, self.axis_name)
        new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
        metrics = {
            k: jax.lax.pmean(v, self.axis_name) for k, v in local.items()
        }
        new_ts = TrainState(
            params=new_params,
            model_state=model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return new_ts, metrics

    def _make_fused_step(self) -> Callable:
        spmd = shard_map_fn(
            self._spmd_body,
            self.mesh,
            in_specs=(P(), P(self.axis_name), P(self.axis_name)),
            out_specs=(P(), P()),
        )
        # Donate the TrainState: params/opt-state buffers update in place,
        # halving their HBM traffic per step. The input state is CONSUMED
        # on every backend — callers must rebind ts each step.
        jitted = jax.jit(spmd, donate_argnums=(0,))

        def step(ts: TrainState, images, labels):
            images, labels = self.shard_batch(images, labels)
            out = jitted(ts, images, labels)
            self._throttle.after_step(out[1]["loss"])
            return out

        # Expose the raw program for tpudml.analysis: the wrapper above
        # does host work (shard_batch, throttle) that make_jaxpr must not
        # see, but the jitted step is exactly what runs on the chip.
        step.jitted = jitted
        return step

    # ----------------------------------------------------------- split step

    def _make_split_step(self) -> Callable:
        """Two XLA programs + host-timed communication span.

        Program A (per-shard grads, no collectives) → [host: optional
        straggler sleep, reference model-mp.py:47,64-65] → program B
        (aggregate; TIMED — the ``comm_time_sum`` span of model-mp.py:61-66)
        → program C (optimizer apply).
        """
        axis = self.axis_name

        def local_grads(ts: TrainState, images, labels):
            rng = None
            if self.rng_root is not None:
                rng = jax.random.fold_in(
                    jax.random.fold_in(self.rng_root, ts.step),
                    jax.lax.axis_index(axis),
                )
            grads, model_state, local = accumulate_grads(
                self._loss_fn, ts.params, ts.model_state, images, labels, rng,
                self.accum_steps,
            )
            # Stack per-replica values on a leading axis so the host gets
            # them un-aggregated (out_spec P(axis) ⇒ [world, ...]).
            stack = lambda t: jax.tree.map(lambda x: x[None], t)
            return stack(grads), stack(model_state), stack(local)

        grad_fn = jax.jit(
            shard_map_fn(
                local_grads,
                self.mesh,
                in_specs=(P(), P(axis), P(axis)),
                out_specs=(P(axis), P(axis), P(axis)),
            )
        )

        def aggregate(stacked_grads, stacked_state):
            unstack = lambda t: jax.tree.map(lambda x: x[0], t)
            grads = self.aggregator(unstack(stacked_grads), axis)
            model_state = pmean_tree(unstack(stacked_state), axis)
            return grads, model_state

        agg_fn = jax.jit(
            shard_map_fn(
                aggregate,
                self.mesh,
                in_specs=(P(axis), P(axis)),
                out_specs=(P(), P()),
            )
        )

        @jax.jit
        def apply_fn(ts: TrainState, grads, model_state):
            new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
            return TrainState(
                params=new_params,
                model_state=model_state,
                opt_state=new_opt,
                step=ts.step + 1,
            )

        def step(ts: TrainState, images, labels):
            images, labels = self.shard_batch(images, labels)
            stacked_grads, stacked_state, stacked_metrics = grad_fn(ts, images, labels)
            jax.block_until_ready(stacked_grads)
            if (
                self.bottleneck_rank is not None
                and process_index() == self.bottleneck_rank % max(jax.process_count(), 1)
            ):
                # Straggler injection: this host enters the collective late
                # (reference: time.sleep(bottle_neck_delay) on one rank,
                # model-mp.py:47,64-65). In synchronous SPMD the whole step
                # inherits the delay — the effect task2 asks students to
                # observe (sections/checking.tex:22).
                time.sleep(self.bottleneck_delay_s)
            t0 = time.perf_counter()
            grads, model_state = agg_fn(stacked_grads, stacked_state)
            jax.block_until_ready(grads)
            self.comm_stats.add(time.perf_counter() - t0)
            new_ts = apply_fn(ts, grads, model_state)
            metrics = {
                "loss": jnp.mean(stacked_metrics["loss"]),
                "accuracy": jnp.mean(stacked_metrics["accuracy"]),
            }
            return new_ts, metrics

        # The three device programs, exposed for tpudml.analysis (the
        # wrapper interleaves host timing/sleep between dispatches).
        step.programs = (grad_fn, agg_fn, apply_fn)
        return step


def make_dp_train_step(
    model: Module,
    optimizer: Optimizer,
    mesh: Mesh,
    axis_name: str = "data",
    aggregation: str = "allreduce",
    rng_root: jax.Array | None = None,
) -> Callable:
    """Functional shortcut for the fused DP step."""
    return DataParallel(
        model, optimizer, mesh, axis_name, aggregation, rng_root=rng_root
    ).make_train_step()
