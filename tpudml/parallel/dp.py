"""Data parallelism: one jitted SPMD step over a mesh ``data`` axis.

Re-design of the reference's DDP loop (codes/task2/model.py:40-72,
codes/task3/model.py:39-64): replicated params, per-replica data shard,
per-step gradient aggregation. Where the reference runs one process per
rank and issues one NCCL collective per parameter tensor (SURVEY.md §3.2),
here the entire step — forward, backward, aggregation, optimizer update —
is ONE XLA program sharded over the mesh; XLA schedules the gradient
collectives on ICI and fuses them with the update.

Two execution modes:

- **fused** (default): maximum-performance single program.
- **split / measure_comm**: the step compiles as separate XLA programs for
  (local grads) and (aggregate), so the host can time the communication
  span and inject a straggler delay before the collective — reproducing
  task2's comm-time accounting and bottleneck-node experiment
  (codes/task2/model-mp.py:47-66, sections/task2.tex:18-19).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudml.capabilities import reject
from tpudml.comm.collectives import broadcast_from, get_aggregator, pmean_tree
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.comm.timing import CommStats
from tpudml.core.dist import process_index
from tpudml.nn.layers import Module
from tpudml.obs.tracer import NULL_SPAN, Tracer
from tpudml.optim import Optimizer, ZeRO1
from tpudml.parallel.sharding import (
    data_sharding,
    replicate,
    DispatchThrottle,
    shard_map_fn,
)
from tpudml.train import (
    TrainState,
    accumulate_fused_grads,
    accumulate_grads,
    make_loss_fn,
    resolve_aux_loss_weight,
)

PyTree = Any


def _program_wire_bytes(fn, *args) -> float:
    """Ring-model bytes/device the program's explicit collectives move,
    from a static walk of its traced jaxpr (analysis/dataflow — the same
    wire model the ``--cost`` reports use, so measured ``CommStats``
    byte counters and the static cost tables stay comparable). Traced
    once per step build; returns 0 when the walk cannot run."""
    from tpudml.analysis.dataflow import analyze_dataflow

    try:
        closed = jax.make_jaxpr(fn)(*args)
        flow = analyze_dataflow(closed)
        return sum(ev.wire_bytes * ev.trips for ev in flow.comm_events)
    except Exception:
        return 0.0


class DataParallel:
    """DP training engine over a mesh ``data`` axis.

    Usage::

        dp = DataParallel(model, opt, mesh, aggregation="allreduce")
        ts = dp.create_state(key)          # replicated on the mesh
        step = dp.make_train_step()        # (ts, images, labels) -> (ts, metrics)

    ``images``/``labels`` are global batches (leading dim = world ×
    per-replica batch); the engine shards them over the data axis.
    """

    def __init__(
        self,
        model: Module,
        optimizer: Optimizer,
        mesh: Mesh,
        axis_name: str = "data",
        aggregation: str = "allreduce",
        measure_comm: bool = False,
        bottleneck_rank: int | None = None,
        bottleneck_delay_s: float = 0.1,
        rng_root: jax.Array | None = None,
        accum_steps: int = 1,
        loss: Callable = softmax_cross_entropy,
        stacked_batches: bool | None = None,
        aux_loss_weight: float | None = None,
        fused_xent: bool = False,
        save_scores: bool | None = None,
        zero1: bool = False,
        zero1_overlap: bool = False,
        sentinel: bool | dict = False,
        obs: bool | Tracer = False,
        flash_attn: bool = False,
    ):
        if save_scores and not fused_xent:
            reject("save_scores_needs_fused_xent")
        if fused_xent and (
            measure_comm or loss is not softmax_cross_entropy
        ):
            # The fused head IS the loss fn (linear cross-entropy); the
            # split-step timing path and custom ``loss`` callables wrap
            # the LOGITS loss fn — wire them up when a use case appears
            # rather than silently ignoring the arguments. (Gradient
            # accumulation composes: accumulate_fused_grads runs the
            # fused loss through the same micro-batch scan.)
            reject("dp_fused_xent_split_step")
        if zero1_overlap and not zero1:
            reject("zero1_overlap_needs_zero1")
        if zero1 and aggregation != "allreduce":
            # ZeRO-1 REPLACES gradient aggregation: the reduce-scatter
            # inside the sharded update is the aggregation. Accepting an
            # alternative strategy here would silently not use it.
            reject("zero1_replaces_aggregation")
        if zero1_overlap and accum_steps < 2:
            reject("zero1_overlap_needs_accum")
        if zero1_overlap and measure_comm:
            reject("zero1_overlap_measure_comm")
        if isinstance(optimizer, ZeRO1):
            if not zero1:
                reject("zero1_optimizer_needs_zero1")
            if optimizer.axis_name != axis_name or (
                optimizer.world != mesh.shape[axis_name]
            ):
                raise ValueError(
                    f"ZeRO1(axis_name={optimizer.axis_name!r}, "
                    f"world={optimizer.world}) does not match the engine's "
                    f"{axis_name!r} axis of size {mesh.shape[axis_name]}"
                )
        # flash_attn: swap the dense causal attention trunk onto the
        # Pallas flash kernel (ops/attention_kernel.py) via the model's
        # own ``impl`` dispatch — a capability-table row, not an ad-hoc
        # flag: the rejection condition (non-"full" trunks, which already
        # run their own fused sequence-sharded attention) lives in ONE
        # place shared with the planner's candidate pruning.
        self.flash_attn = flash_attn
        if flash_attn:
            import dataclasses

            if getattr(model, "impl", None) != "full" or getattr(
                model, "seq_sharded", False
            ):
                reject("train_flash_attn_dense")
            model = dataclasses.replace(model, impl="flash")
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.axis_name = axis_name
        # True: batches arrive in the ShardedDataLoader's stacked
        # [world, B, ...] form; False: plain global [world×B, ...] batches;
        # None: infer per batch (see shard_batch).
        self.stacked_batches = stacked_batches
        self.aggregation = aggregation
        self.aggregator = get_aggregator(aggregation)
        self.measure_comm = measure_comm
        self.bottleneck_rank = bottleneck_rank
        self.bottleneck_delay_s = bottleneck_delay_s
        self.rng_root = rng_root
        self.accum_steps = accum_steps
        self.comm_stats = CommStats()
        self.world = mesh.shape[axis_name]
        # Observability (tpudml.obs, one knob): obs=True builds a fresh
        # Tracer, an existing Tracer passes through. The tracer receives
        # one "step" span per dispatched step plus every measured comm
        # span (via comm_stats.tracer), and the jitted step additionally
        # returns the in-graph StepStats pytree under
        # metrics["step_stats"] — no host callbacks, so the fused step
        # stays one program and the off position allocates zero spans.
        self.tracer: Tracer | None = None
        self._obs_stats = False
        if obs:
            self.tracer = obs if isinstance(obs, Tracer) else Tracer()
            self._obs_stats = True
            self.comm_stats.tracer = self.tracer
        # ZeRO-1 (arXiv 2004.13336): wrap the optimizer so it reduce-
        # scatters grads and updates a 1/N param/state shard per chip
        # (see tpudml.optim.zero1). ``zero1_overlap`` additionally keeps
        # param CHUNKS in TrainState and gathers them at the START of the
        # step, so XLA overlaps the all_gather with the first micro-
        # batches' forward.
        self.zero1 = zero1
        self.zero1_overlap = zero1_overlap
        if zero1 and not isinstance(optimizer, ZeRO1):
            self.optimizer = ZeRO1(
                optimizer, axis_name=axis_name, world=self.world
            )
        # In-graph step sentinel (tpudml.resilience): under zero1 it is
        # inserted INSIDE the ZeRO1 wrapper — the chunk grads it then
        # guards are disjoint over the data axis, so attach_sentinel
        # psums the anomaly predicate over it; without zero1 the grads
        # are already aggregated when the optimizer runs (and the
        # measure_comm split step applies it OUTSIDE shard_map), so the
        # predicate needs no collective at all.
        self.sentinel = None
        if sentinel:
            from tpudml.resilience.sentinel import attach_sentinel, find_sentinel

            kw = dict(sentinel) if isinstance(sentinel, dict) else {}
            self.optimizer = attach_sentinel(self.optimizer, (), **kw)
            self.sentinel = find_sentinel(self.optimizer)
        self._param_template = None
        self._gather_fn = None
        # Dense-MoE runs get the Switch load-balancing pressure by default
        # (None → α=0.01 when the model contains MoE layers).
        # fused_xent: the LM head runs through the fused linear-cross-
        # entropy kernel (token-parallel, so a batch-sharded trunk needs
        # no resharding); metrics carry loss only.
        self.fused_xent = fused_xent
        if fused_xent:
            from tpudml.train import make_lm_fused_loss_fn

            self._fused_loss_fn = make_lm_fused_loss_fn(
                model, save_scores, aux_loss_weight
            )
        self._loss_fn = make_loss_fn(
            model, loss, resolve_aux_loss_weight(model, aux_loss_weight)
        )
        self._throttle = DispatchThrottle(mesh)

    # ---------------------------------------------------------------- state

    def _state_spec(self):
        """TrainState PartitionSpec (prefix) tree for the step's shard_map
        in/out specs and the state placement. Fully replicated unless
        zero1: then the optimizer state shards 1/N over the data axis
        (ZeRO1.init_spec), and the overlap variant's param chunks do too."""
        if not self.zero1:
            return P()
        return TrainState(
            params=P(self.axis_name) if self.zero1_overlap else P(),
            model_state=P(),
            opt_state=self.optimizer.init_spec(P()),
            step=P(),
        )

    def create_state(self, key: jax.Array) -> TrainState:
        """Init once on host, place on the mesh: fully replicated in the
        default engine; under zero1 the optimizer-state moments land
        sharded 1/N over the data axis (this is the HBM win — each chip
        holds only its chunk of m/v), and the overlap variant stores the
        params in the same flat chunk layout.

        Covers the reference's ``init_parameters`` broadcast contract
        (codes/task2/dist_utils.py:33-37): every replica starts from
        bitwise-identical params — here by construction rather than by a
        rank-0 collective (see also :meth:`broadcast_params`).
        """
        ts = TrainState.create(self.model, self.optimizer, key)
        if not self.zero1:
            return replicate(ts, self.mesh)
        if self.zero1_overlap:
            # The step needs the ORIGINAL param shapes to gather back into;
            # remember them before flattening to the chunk layout.
            self._param_template = jax.tree.map(
                lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), ts.params
            )
            ts = TrainState(
                params=self.optimizer.flatten_params(ts.params),
                model_state=ts.model_state,
                opt_state=ts.opt_state,
                step=ts.step,
            )
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self._state_spec(),
            is_leaf=lambda x: isinstance(x, P),
        )
        return jax.device_put(ts, shardings)

    def gather_params(self, ts: TrainState):
        """Original-shape full params from a TrainState — the identity
        unless ``zero1_overlap`` (whose states carry flat param chunks);
        eval/checkpoint/parity flows call this instead of ``ts.params``."""
        if not self.zero1_overlap:
            return ts.params
        if self._param_template is None:
            raise ValueError(
                "zero1_overlap: create_state must run before gather_params "
                "(the original param shapes come from it)"
            )
        if self._gather_fn is None:
            fn = shard_map_fn(
                lambda p: self.optimizer.gather_params(p, self._param_template),
                self.mesh,
                in_specs=(P(self.axis_name),),
                out_specs=P(),
            )
            self._gather_fn = jax.jit(fn)
        return self._gather_fn(ts.params)

    def broadcast_params(self, ts: TrainState, root: int = 0) -> TrainState:
        """Explicit rank-``root`` parameter broadcast (reference-mechanism
        parity; needed only when replicas may have diverged, e.g. after a
        per-host restore)."""
        if self.zero1_overlap:
            raise ValueError(
                "broadcast_params is meaningless under zero1_overlap: the "
                "per-chip param chunks are distinct BY DESIGN, not divergent"
            )
        fn = shard_map_fn(
            lambda p: broadcast_from(p, self.axis_name, root),
            self.mesh,
            in_specs=P(),
            out_specs=P(),
        )
        return TrainState(
            params=jax.jit(fn)(ts.params),
            model_state=ts.model_state,
            opt_state=ts.opt_state,
            step=ts.step,
        )

    def shard_batch(self, images, labels):
        """Place a global [world×B, ...] host batch sharded over the data
        axis. Accepts the ShardedDataLoader's stacked [world, B, ...] form
        too (flattened so device r receives replica r's rows) — explicitly
        when the engine was built with ``stacked_batches=True``, else by
        inference: stacked iff the leading dim is the world size AND the
        inputs carry at least two more dims than the labels (image-shaped
        samples). 2-D LM token batches ([B, T] inputs + [B, T] labels)
        never match the inference even when B == world — construct with an
        explicit ``stacked_batches`` to bypass inference entirely."""
        sharding = data_sharding(self.mesh, self.axis_name)
        images = jnp.asarray(images)
        labels = jnp.asarray(labels)
        stacked = self.stacked_batches
        if stacked is None:
            stacked = (
                labels.ndim >= 2
                and labels.shape[0] == self.world
                and images.ndim >= labels.ndim + 2
            )
        if stacked:
            if images.shape[0] != self.world:
                raise ValueError(
                    f"stacked batch leading dim {images.shape[0]} != "
                    f"{self.world}-way data mesh"
                )
            images = images.reshape(-1, *images.shape[2:])
            labels = labels.reshape(-1, *labels.shape[2:])
        if images.shape[0] % self.world:
            # Catch it here (every caller: tasks, facade, direct use) with a
            # actionable message instead of an opaque XLA sharding error.
            raise ValueError(
                f"global batch of {images.shape[0]} rows is not divisible by "
                f"the {self.world}-way data mesh; pick a divisible batch_size "
                "(drop_remainder=True avoids ragged final batches)"
            )
        return jax.device_put(images, sharding), jax.device_put(labels, sharding)

    # ----------------------------------------------------------- fused step

    def make_train_step(self) -> Callable:
        if self.measure_comm:
            if self.zero1:
                return self._make_zero1_split_step()
            return self._make_split_step()
        return self._make_fused_step()

    def _obs_span(self, name: str):
        """The per-dispatch tracer span; a shared no-op object when obs
        is off (the hot path must not allocate per step)."""
        if self.tracer is None:
            return NULL_SPAN
        return self.tracer.span(name, cat="step")

    def _obs_step_stats(self, metrics: dict, grads, model_state, new_opt, step):
        """Append the in-graph StepStats pytree to the step's metrics
        (obs mode only). Under zero1 the optimizer-boundary grads are the
        PRE-reduce-scatter per-replica grads, so the reported norm is the
        RMS of per-replica gradient norms (pmean of the squared norms) —
        an upper bound on the true mean-grad norm; plain DP reports the
        exact global norm of the aggregated gradient."""
        if not self._obs_stats:
            return metrics
        from tpudml.obs.stepstats import (
            dp_wire_bytes_per_step,
            grad_normsq,
            make_step_stats,
        )

        normsq = grad_normsq(grads)
        if self.zero1:
            normsq = jax.lax.pmean(normsq, self.axis_name)
        bps = dp_wire_bytes_per_step(
            grads, model_state, self.world,
            aggregation=self.aggregation, zero1=self.zero1,
        )
        metrics = dict(metrics)
        metrics["step_stats"] = make_step_stats(
            metrics["loss"], normsq, new_opt, bps, step
        )
        return metrics

    def _agg_metrics(self, local: dict) -> dict:
        """Cross-replica metric aggregation: means, except the sentinel's
        ``bad_micro`` index which is a max (-1 means clean; a mean over
        replicas would mangle the integer)."""
        return {
            k: (
                jax.lax.pmax(v, self.axis_name)
                if k == "bad_micro"
                else jax.lax.pmean(v, self.axis_name)
            )
            for k, v in local.items()
        }

    def _spmd_body(self, ts: TrainState, images, labels):
        """Per-shard step body (runs under shard_map)."""
        rng = None
        if self.rng_root is not None:
            # Distinct dropout streams per replica and per step.
            rng = jax.random.fold_in(
                jax.random.fold_in(self.rng_root, ts.step),
                jax.lax.axis_index(self.axis_name),
            )
        taint = self.sentinel is not None
        if self.fused_xent:
            grads, model_state, local = accumulate_fused_grads(
                self._fused_loss_fn, ts.params, ts.model_state, images,
                labels, rng, self.accum_steps, taint=taint,
            )
        else:
            grads, model_state, local = accumulate_grads(
                self._loss_fn, ts.params, ts.model_state, images, labels, rng,
                self.accum_steps, taint=taint,
            )
        if not self.zero1:
            # Under zero1 the reduce-scatter inside optimizer.update IS
            # the aggregation (mean chunks land on their owning chips);
            # a pmean here would double the gradient traffic for nothing.
            grads = self.aggregator(grads, self.axis_name)
        # Cross-replica-consistent BN stats: average the running stats so
        # every replica holds the same model_state (the reference's DDP
        # leaves them divergent per rank; averaged is strictly better and
        # keeps params/state replicated).
        model_state = pmean_tree(model_state, self.axis_name)
        new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
        metrics = self._agg_metrics(local)
        metrics = self._obs_step_stats(metrics, grads, model_state, new_opt, ts.step)
        new_ts = TrainState(
            params=new_params,
            model_state=model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return new_ts, metrics

    def _spmd_body_overlap(self, ts: TrainState, images, labels):
        """Overlap-variant body: ``ts.params`` carries this chip's flat
        param CHUNKS, so the step OPENS with the all_gather of the
        previous step's updated params and closes with the sharded update
        (no trailing gather). The micro-batch scan that follows consumes
        the gathered params as constants, and XLA is free to schedule
        each leaf's gather against the early layers' compute — this is
        the double-buffering: step k's gather hides behind step k's first
        micro-batches instead of serializing after step k−1's update."""
        opt = self.optimizer
        params = opt.gather_params(ts.params, self._param_template)
        rng = None
        if self.rng_root is not None:
            rng = jax.random.fold_in(
                jax.random.fold_in(self.rng_root, ts.step),
                jax.lax.axis_index(self.axis_name),
            )
        taint = self.sentinel is not None
        if self.fused_xent:
            grads, model_state, local = accumulate_fused_grads(
                self._fused_loss_fn, params, ts.model_state, images, labels,
                rng, self.accum_steps, taint=taint,
            )
        else:
            grads, model_state, local = accumulate_grads(
                self._loss_fn, params, ts.model_state, images, labels, rng,
                self.accum_steps, taint=taint,
            )
        model_state = pmean_tree(model_state, self.axis_name)
        new_chunks, new_opt = opt.update_shards(grads, ts.opt_state, ts.params)
        metrics = self._agg_metrics(local)
        metrics = self._obs_step_stats(metrics, grads, model_state, new_opt, ts.step)
        new_ts = TrainState(
            params=new_chunks,
            model_state=model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return new_ts, metrics

    def _make_fused_step(self) -> Callable:
        body = self._spmd_body
        if self.zero1_overlap:
            if self._param_template is None:
                raise ValueError(
                    "zero1_overlap: call create_state before "
                    "make_train_step (the step gathers into the original "
                    "param shapes recorded there)"
                )
            body = self._spmd_body_overlap
        spec = self._state_spec()
        spmd = shard_map_fn(
            body,
            self.mesh,
            in_specs=(spec, P(self.axis_name), P(self.axis_name)),
            out_specs=(spec, P()),
        )
        # Donate the TrainState: params/opt-state buffers update in place,
        # halving their HBM traffic per step. The input state is CONSUMED
        # on every backend — callers must rebind ts each step.
        jitted = jax.jit(spmd, donate_argnums=(0,))

        def step(ts: TrainState, images, labels):
            images, labels = self.shard_batch(images, labels)
            with self._obs_span("train_step"):
                out = jitted(ts, images, labels)
                self._throttle.after_step(out[1]["loss"])
            return out

        # Expose the raw program for tpudml.analysis: the wrapper above
        # does host work (shard_batch, throttle) that make_jaxpr must not
        # see, but the jitted step is exactly what runs on the chip. The
        # in_specs/mesh_axes metadata seeds the dataflow interpreter's
        # top-level replication states and the --cost per-device math.
        step.jitted = jitted
        step.in_specs = (spec, P(self.axis_name), P(self.axis_name))
        step.mesh_axes = dict(self.mesh.shape)
        return step

    # ----------------------------------------------------------- split step

    def _make_split_step(self) -> Callable:
        """Two XLA programs + host-timed communication span.

        Program A (per-shard grads, no collectives) → [host: optional
        straggler sleep, reference model-mp.py:47,64-65] → program B
        (aggregate; TIMED — the ``comm_time_sum`` span of model-mp.py:61-66)
        → program C (optimizer apply).
        """
        axis = self.axis_name

        def local_grads(ts: TrainState, images, labels):
            rng = None
            if self.rng_root is not None:
                rng = jax.random.fold_in(
                    jax.random.fold_in(self.rng_root, ts.step),
                    jax.lax.axis_index(axis),
                )
            grads, model_state, local = accumulate_grads(
                self._loss_fn, ts.params, ts.model_state, images, labels, rng,
                self.accum_steps,
            )
            # Stack per-replica values on a leading axis so the host gets
            # them un-aggregated (out_spec P(axis) ⇒ [world, ...]).
            stack = lambda t: jax.tree.map(lambda x: x[None], t)
            return stack(grads), stack(model_state), stack(local)

        grad_fn = jax.jit(
            shard_map_fn(
                local_grads,
                self.mesh,
                in_specs=(P(), P(axis), P(axis)),
                out_specs=(P(axis), P(axis), P(axis)),
            )
        )

        def aggregate(stacked_grads, stacked_state):
            unstack = lambda t: jax.tree.map(lambda x: x[0], t)
            grads = self.aggregator(unstack(stacked_grads), axis)
            model_state = pmean_tree(unstack(stacked_state), axis)
            return grads, model_state

        agg_fn = jax.jit(
            shard_map_fn(
                aggregate,
                self.mesh,
                in_specs=(P(axis), P(axis)),
                out_specs=(P(), P()),
            )
        )

        @jax.jit
        def apply_fn(ts: TrainState, grads, model_state):
            new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
            return TrainState(
                params=new_params,
                model_state=model_state,
                opt_state=new_opt,
                step=ts.step + 1,
            )

        wire_bytes_cache: list = []

        def step(ts: TrainState, images, labels):
            images, labels = self.shard_batch(images, labels)
            with self._obs_span("train_step"):
                stacked_grads, stacked_state, stacked_metrics = grad_fn(
                    ts, images, labels)
                jax.block_until_ready(stacked_grads)
                if (
                    self.bottleneck_rank is not None
                    and process_index() == self.bottleneck_rank % max(jax.process_count(), 1)
                ):
                    # Straggler injection: this host enters the collective late
                    # (reference: time.sleep(bottle_neck_delay) on one rank,
                    # model-mp.py:47,64-65). In synchronous SPMD the whole step
                    # inherits the delay — the effect task2 asks students to
                    # observe (sections/checking.tex:22).
                    time.sleep(self.bottleneck_delay_s)
                t0 = time.perf_counter()
                grads, model_state = agg_fn(stacked_grads, stacked_state)
                jax.block_until_ready(grads)
                if not wire_bytes_cache:
                    wire_bytes_cache.append(
                        _program_wire_bytes(agg_fn, stacked_grads, stacked_state))
                self.comm_stats.add(time.perf_counter() - t0,
                                    nbytes=wire_bytes_cache[0])
                new_ts = apply_fn(ts, grads, model_state)
                metrics = {
                    "loss": jnp.mean(stacked_metrics["loss"]),
                    "accuracy": jnp.mean(stacked_metrics["accuracy"]),
                }
                if self._obs_stats:
                    # Split mode is already the measurability-over-fusion
                    # trade, so StepStats assembles HOST-side here from
                    # the aggregated grads (the fused paths bake it into
                    # the program instead).
                    from tpudml.obs.stepstats import (
                        dp_wire_bytes_per_step,
                        grad_normsq,
                        make_step_stats,
                    )

                    metrics["step_stats"] = make_step_stats(
                        metrics["loss"], grad_normsq(grads),
                        new_ts.opt_state,
                        dp_wire_bytes_per_step(
                            grads, model_state, self.world,
                            aggregation=self.aggregation,
                        ),
                        ts.step,
                    )
            return new_ts, metrics

        # The three device programs, exposed for tpudml.analysis (the
        # wrapper interleaves host timing/sleep between dispatches).
        step.programs = (grad_fn, agg_fn, apply_fn)
        return step

    # ------------------------------------------------------------ zero1 aux

    def _zero1_programs(self):
        """The two split ZeRO-1 programs: (local grads — no collectives)
        and (the weight-update exchange — reduce-scatter, 1/N update,
        all_gather, in ONE program). Unlike the replicated split step
        there is no separate optimizer-apply program: under ZeRO-1 the
        update compute is interleaved WITH the collectives, so the
        exchange program is the span comm accounting must charge."""
        axis = self.axis_name
        spec = TrainState(
            params=P(),
            model_state=P(),
            opt_state=self.optimizer.init_spec(P()),
            step=P(),
        )

        def local_grads(ts: TrainState, images, labels):
            rng = None
            if self.rng_root is not None:
                rng = jax.random.fold_in(
                    jax.random.fold_in(self.rng_root, ts.step),
                    jax.lax.axis_index(axis),
                )
            if self.fused_xent:
                grads, model_state, local = accumulate_fused_grads(
                    self._fused_loss_fn, ts.params, ts.model_state, images,
                    labels, rng, self.accum_steps,
                )
            else:
                grads, model_state, local = accumulate_grads(
                    self._loss_fn, ts.params, ts.model_state, images, labels,
                    rng, self.accum_steps,
                )
            stack = lambda t: jax.tree.map(lambda x: x[None], t)
            return stack(grads), stack(model_state), stack(local)

        grad_fn = jax.jit(
            shard_map_fn(
                local_grads,
                self.mesh,
                in_specs=(spec, P(axis), P(axis)),
                out_specs=(P(axis), P(axis), P(axis)),
            )
        )

        def exchange(ts: TrainState, stacked_grads, stacked_state):
            unstack = lambda t: jax.tree.map(lambda x: x[0], t)
            grads = unstack(stacked_grads)
            model_state = pmean_tree(unstack(stacked_state), axis)
            new_params, new_opt = self.optimizer.update(
                grads, ts.opt_state, ts.params
            )
            return TrainState(
                params=new_params,
                model_state=model_state,
                opt_state=new_opt,
                step=ts.step + 1,
            )

        ex_fn = jax.jit(
            shard_map_fn(
                exchange,
                self.mesh,
                in_specs=(spec, P(axis), P(axis)),
                out_specs=spec,
            )
        )
        return grad_fn, ex_fn

    def _make_zero1_split_step(self) -> Callable:
        """measure_comm for the ZeRO-1 step: program A (per-shard grads)
        → [host: optional straggler sleep] → program B (reduce-scatter +
        sharded update + all_gather; TIMED) — same host bracketing as the
        replicated split step, charging the whole weight-update exchange
        to ``comm_stats``."""
        grad_fn, ex_fn = self._zero1_programs()
        wire_bytes_cache: list = []

        def step(ts: TrainState, images, labels):
            images, labels = self.shard_batch(images, labels)
            with self._obs_span("train_step"):
                stacked_grads, stacked_state, stacked_metrics = grad_fn(
                    ts, images, labels
                )
                jax.block_until_ready(stacked_grads)
                if (
                    self.bottleneck_rank is not None
                    and process_index()
                    == self.bottleneck_rank % max(jax.process_count(), 1)
                ):
                    time.sleep(self.bottleneck_delay_s)
                t0 = time.perf_counter()
                new_ts = ex_fn(ts, stacked_grads, stacked_state)
                jax.block_until_ready(new_ts.params)
                if not wire_bytes_cache:
                    wire_bytes_cache.append(_program_wire_bytes(
                        ex_fn, ts, stacked_grads, stacked_state))
                self.comm_stats.add(time.perf_counter() - t0,
                                    nbytes=wire_bytes_cache[0])
                metrics = {
                    "loss": jnp.mean(stacked_metrics["loss"]),
                    "accuracy": jnp.mean(stacked_metrics["accuracy"]),
                }
                if self._obs_stats:
                    # Host-side StepStats from the PRE-reduce-scatter
                    # per-replica grads: the mean of per-replica norm² is
                    # the zero1 RMS-norm convention (_obs_step_stats).
                    from tpudml.obs.stepstats import (
                        dp_wire_bytes_per_step,
                        grad_normsq,
                        make_step_stats,
                    )

                    g0 = jax.tree.map(lambda g: g[0], stacked_grads)
                    s0 = jax.tree.map(lambda s: s[0], stacked_state)
                    metrics["step_stats"] = make_step_stats(
                        metrics["loss"],
                        grad_normsq(stacked_grads) / self.world,
                        new_ts.opt_state,
                        dp_wire_bytes_per_step(
                            g0, s0, self.world, zero1=True
                        ),
                        ts.step,
                    )
            return new_ts, metrics

        step.programs = (grad_fn, ex_fn)
        return step

    def overlap_report(
        self, ts: TrainState, images, labels, iters: int = 10, warmup: int = 2
    ) -> dict:
        """Exposed-vs-hidden comm attribution for the ZeRO-1 step
        (:func:`tpudml.comm.timing.attribute_overlap`). Three programs run
        on the same inputs: the FUSED step (one XLA program — collectives
        free to overlap with compute), the compute-only span (local
        grads), and the weight-update exchange alone (reduce-scatter +
        1/N update + all_gather). ``exposed = clamp(fused − compute, 0,
        comm)`` is comm time the step actually waits on; ``hidden =
        comm − exposed`` is what the schedule absorbed.

        For the overlap variant, ``ts`` may carry param chunks — a
        replicated twin state is rebuilt via :meth:`gather_params` for
        the canonical spans, and the variant's own step time rides along
        as ``overlap_step_s`` (its gain shows up as fused-vs-overlap
        delta, attributable to the hidden gather).
        """
        if not self.zero1:
            raise ValueError("overlap_report requires zero1=True")
        from tpudml.comm.timing import attribute_overlap

        axis = self.axis_name
        images, labels = self.shard_batch(images, labels)

        def timed(fn, *args) -> float:
            for _ in range(warmup):
                jax.block_until_ready(fn(*args))
            runs = []
            for _ in range(iters):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*args))
                runs.append(time.perf_counter() - t0)
            runs.sort()
            return runs[len(runs) // 2]

        overlap_step_s = None
        if self.zero1_overlap:
            spec = self._state_spec()
            own = jax.jit(
                shard_map_fn(
                    self._spmd_body_overlap,
                    self.mesh,
                    in_specs=(spec, P(axis), P(axis)),
                    out_specs=(spec, P()),
                )
            )
            overlap_step_s = timed(own, ts, images, labels)
            full = TrainState(
                params=self.gather_params(ts),
                model_state=ts.model_state,
                opt_state=ts.opt_state,
                step=ts.step,
            )
        else:
            full = ts

        rep_spec = TrainState(
            params=P(),
            model_state=P(),
            opt_state=self.optimizer.init_spec(P()),
            step=P(),
        )
        fused_fn = jax.jit(
            shard_map_fn(
                self._spmd_body,
                self.mesh,
                in_specs=(rep_spec, P(axis), P(axis)),
                out_specs=(rep_spec, P()),
            )
        )
        grad_fn, ex_fn = self._zero1_programs()

        fused_s = timed(fused_fn, full, images, labels)
        compute_s = timed(grad_fn, full, images, labels)
        stacked_grads, stacked_state, _ = grad_fn(full, images, labels)
        comm_s = timed(ex_fn, full, stacked_grads, stacked_state)
        report = attribute_overlap(fused_s, compute_s, comm_s)
        if overlap_step_s is not None:
            report["overlap_step_s"] = overlap_step_s
        return report


def make_dp_train_step(
    model: Module,
    optimizer: Optimizer,
    mesh: Mesh,
    axis_name: str = "data",
    aggregation: str = "allreduce",
    rng_root: jax.Array | None = None,
) -> Callable:
    """Functional shortcut for the fused DP step."""
    return DataParallel(
        model, optimizer, mesh, axis_name, aggregation, rng_root=rng_root
    ).make_train_step()
