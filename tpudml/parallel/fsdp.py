"""Fully-sharded data parallelism (FSDP / ZeRO-3) over the ``data`` axis.

The natural completion of the reference's parameter-server lineage: task4's
``DistributedOptimizer`` updates parameters where they live (RRefs,
reference: codes/task4/model.py:126); plain DP replicates everything and
only shards the batch. FSDP shards the batch AND the parameters, gradients,
and optimizer state over the SAME ``data`` axis — per-chip memory for
params/grads/opt-state scales 1/W while the training math stays exactly DP.

TPU-native design — this is deliberately NOT a hand-scheduled
gather/scatter engine. Each parameter leaf is annotated with a
PartitionSpec that shards its largest divisible dimension over ``data``
(the "1-D parameter sharding" layout used by large JAX trainers), the
batch is sharded over the same axis, and the XLA SPMD partitioner derives
the ZeRO-3 schedule from the shardings alone:

- forward/backward: each weight is **all-gathered on use** (and the
  gather is scheduled/overlapped by XLA, then discarded — activations
  never hold a full copy of every layer at once);
- gradients: the batch-sharded loss makes each weight's gradient a
  partial sum, which XLA materializes as **reduce-scatter** straight into
  the 1/W gradient shard;
- optimizer update: runs shard-local on the 1/W param + opt-state shards
  (the update-where-params-live contract), no collective needed.

Composes with tensor parallelism on a 2-D {"data": D, "model": M} mesh:
pass ``base_rule=tensor_parallel_rules("model")`` and each leaf first takes
its TP sharding, then FSDP shards the largest remaining free dimension
over ``data`` — the standard 2-D layout (TP within, ZeRO across).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh, PartitionSpec as P

from tpudml.nn.layers import Module
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.optim import Optimizer
from tpudml.parallel.mp import GSPMDParallel, RuleFn


def fsdp_sharding_rules(
    axis_name: str = "data",
    base: RuleFn | None = None,
    axis_size: int | None = None,
) -> RuleFn:
    """ZeRO-3 parameter layout: shard each leaf's largest divisible free
    dimension over ``axis_name``.

    ``base`` (e.g. ``tensor_parallel_rules``) claims dimensions first; the
    FSDP axis then takes the largest dimension the base left unsharded and
    that ``axis_size`` divides (when known — the engine passes its mesh
    axis size; without it, largest wins and ``apply_rules`` demotes
    indivisible picks). Leaves with no shardable dimension (small biases,
    odd shapes) stay replicated — correct, just not memory-scaled.
    """

    def rule(path: tuple, leaf) -> P:
        spec = list(base(path, leaf)) if base is not None else []
        spec += [None] * (leaf.ndim - len(spec))
        free = [i for i in range(leaf.ndim) if spec[i] is None]
        if axis_size:
            free = [i for i in free if leaf.shape[i] % axis_size == 0]
        # Largest qualifying dim; ties break toward the LEADING dim —
        # splitting the outermost axis of a C-order array gives contiguous
        # shards, so the all_gather on use is a plain concat.
        best, best_size = None, 0
        for i in free:
            if leaf.shape[i] > best_size:
                best, best_size = i, leaf.shape[i]
        if best is not None:
            spec[best] = axis_name
        while spec and spec[-1] is None:  # canonical form: no trailing Nones
            spec.pop()
        return P(*spec)

    return rule


class FSDP(GSPMDParallel):
    """FSDP/ZeRO-3 training engine: one jitted GSPMD program per step.

    Usage::

        mesh = make_mesh(MeshConfig({"data": 8}))
        eng = FSDP(model, opt, mesh)
        ts = eng.create_state(key)        # params/opt-state 1/8 per chip
        step = eng.make_train_step()      # (ts, x, labels) -> (ts, metrics)

    2-D composition with tensor parallelism::

        mesh = make_mesh(MeshConfig({"data": 2, "model": 4}))
        eng = FSDP(model, opt, mesh,
                   base_rule=tensor_parallel_rules("model"))

    Parity oracle (tests): FSDP over W shards matches replicated DP and
    single-device training step for step — the sharding changes where
    bytes live, never the math.
    """

    def __init__(
        self,
        model: Module,
        optimizer: Optimizer,
        mesh: Mesh,
        axis_name: str = "data",
        base_rule: RuleFn | None = None,
        rng_root: jax.Array | None = None,
        accum_steps: int = 1,
        loss: Callable = softmax_cross_entropy,
        aux_loss_weight: float | None = None,
        fused_xent: bool = False,
        save_scores: bool | None = None,
        sentinel: bool | dict = False,
        obs=False,
        flash_attn: bool = False,
    ):
        if axis_name not in mesh.shape:
            raise ValueError(
                f"FSDP axis {axis_name!r} not in mesh axes {tuple(mesh.shape)}"
            )
        super().__init__(
            model,
            optimizer,
            mesh,
            rule=fsdp_sharding_rules(
                axis_name, base_rule, axis_size=mesh.shape[axis_name]
            ),
            axis_name=axis_name,
            batch_axis=axis_name,
            rng_root=rng_root,
            accum_steps=accum_steps,
            loss=loss,
            aux_loss_weight=aux_loss_weight,
            fused_xent=fused_xent,
            save_scores=save_scores,
            sentinel=sentinel,
            obs=obs,
            flash_attn=flash_attn,
        )
