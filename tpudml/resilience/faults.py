"""Seeded, deterministic fault injection — the harness that PROVES the
resilience layer instead of asserting it.

Three fault families, mirroring the three things production TPU training
actually loses (PAPERS.md arXiv 2204.06514: preemption and loss spikes
are routine, not exceptional):

- **microbatch corruptors** (:func:`corrupt_microbatch`) poison a chosen
  microbatch of a batch with NaN / Inf / 1e30-scale outliers — the
  sentinel's prey;
- **process faults** (:func:`rank_kill_hook`, :func:`straggler_hook`)
  kill or delay a rank mid-run from inside ``train_loop`` — the
  launcher-restart / containment prey;
- **checkpoint vandals** (:func:`vandalize`, registry :data:`VANDALS`)
  corrupt a checkpoint directory the four ways checkpoints really die:
  truncated array file, silent bit flip, missing manifest, and a
  partial ``step_`` dir — ``verify=True`` / ``restore_latest_valid``'s
  prey;
- **re-form adversaries** (:func:`occupy_port`,
  :func:`reform_straggler_hook`, :func:`vandalize_plan`, registry
  :data:`PLAN_VANDALS`) attack the elastic recovery path itself: a
  squatter on the coordinator port the controller wants, a rank that
  stalls only in a chosen re-form round, and a ``plan.json`` corrupted
  between re-plan and relaunch — the adaptive controller's prey.

Every fault is parameterized by an explicit seed and no fault consults
wall-clock or ambient randomness, so an injected run is exactly
reproducible — the end-to-end tests rely on comparing a faulted+healed
run bit-exactly against a clean one.
"""

from __future__ import annotations

import os
import time

import numpy as np

# --------------------------------------------------------- data corruptors


def corrupt_microbatch(
    batch,
    kind: str = "nan",
    micro: int = 0,
    accum_steps: int = 1,
    seed: int = 0,
    frac: float = 0.01,
):
    """A copy of ``batch`` with microbatch ``micro`` poisoned.

    The microbatch split matches ``accumulate_grads``: leading dim
    reshaped to ``[accum_steps, B/accum_steps]``, so with
    ``accum_steps=1`` the whole batch is the single microbatch. ``kind``:
    ``"nan"`` / ``"inf"`` write that value, ``"outlier"`` multiplies by
    1e30 (finite, only a spike test catches it). ``frac`` of the
    microbatch's elements (at least one), at seeded positions.
    """
    if kind not in ("nan", "inf", "outlier"):
        raise ValueError(f"unknown corruption kind {kind!r}")
    x = np.array(batch, dtype=np.float32 if kind != "outlier" else None,
                 copy=True)
    if x.dtype.kind != "f":
        x = x.astype(np.float32)
    n = x.shape[0]
    if n % accum_steps:
        raise ValueError(f"batch dim {n} not divisible by {accum_steps}")
    mb = n // accum_steps
    if not 0 <= micro < accum_steps:
        raise ValueError(f"micro {micro} out of range for {accum_steps}")
    rows = x[micro * mb: (micro + 1) * mb]
    rng = np.random.default_rng(seed)
    k = max(1, int(frac * rows.size))
    idx = rng.choice(rows.size, size=k, replace=False)
    flat = rows.reshape(-1)
    if kind == "nan":
        flat[idx] = np.nan
    elif kind == "inf":
        flat[idx] = np.inf
    else:
        flat[idx] = flat[idx] * 1e30 + 1e30
    return x


# --------------------------------------------------------- process faults


def rank_kill_hook(
    at_step: int,
    *,
    exit_code: int = 17,
    marker: str | None = None,
    rank: int | None = None,
):
    """A ``train_loop`` hook that hard-kills THIS process (``os._exit``,
    no cleanup — a preemption, not a graceful shutdown) the first time
    the loop reaches ``at_step``. With ``marker`` set, the kill happens
    at most once across restarts: the marker file is created atomically
    before exiting, and a restarted run that finds it keeps running —
    exactly the kill→restart→resume sequence the containment tests
    drive. ``rank`` limits the kill to one process (``TPUDML_PROCESS_ID``,
    the launcher's rank env)."""

    def hook(*, step, **_):
        if step != at_step:
            return
        if rank is not None and int(os.environ.get("TPUDML_PROCESS_ID", "0")) != rank:
            return
        if marker is not None:
            try:
                fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                return  # already killed once — this is the restarted run
            os.write(fd, f"killed at step {step}\n".encode())
            os.close(fd)
        os._exit(exit_code)

    return hook


def straggler_hook(
    delay_s: float,
    *,
    at_step: int | None = None,
    rank: int | None = None,
):
    """A ``train_loop`` hook injecting a host-side stall (every step, or
    only ``at_step``) on one rank — the synchronous-collective straggler
    of SURVEY.md §5.3, for timeout/containment tests."""

    def hook(*, step, **_):
        if at_step is not None and step != at_step:
            return
        if rank is not None and int(os.environ.get("TPUDML_PROCESS_ID", "0")) != rank:
            return
        time.sleep(delay_s)

    return hook


def reform_straggler_hook(
    delay_s: float,
    *,
    round: int,
    rank: int | None = None,
):
    """A straggler that fires only in elastic re-form round ``round``
    (``TPUDML_ELASTIC_ROUND``, the controller's per-incarnation env):
    the rank comes back after a failure but stalls before its first
    step, delaying the whole re-formed gang — the slow-rejoiner
    adversary. Fires once (the first hook call of that round)."""
    fired = [False]

    def hook(*, step, **_):
        del step
        if fired[0]:
            return
        if int(os.environ.get("TPUDML_ELASTIC_ROUND", "0")) != round:
            return
        if rank is not None and int(os.environ.get("TPUDML_PROCESS_ID", "0")) != rank:
            return
        fired[0] = True
        time.sleep(delay_s)

    return hook


def occupy_port(port: int, host: str = "127.0.0.1"):
    """Bind-and-listen a squatter socket on ``port`` — the
    coordinator-port-collision adversary. Returns the open socket (close
    it to release the port); raises ``OSError`` if the port is already
    taken. The elastic controller must notice the pinned port is dead
    and fall back to a fresh one instead of crash-looping."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, port))
        s.listen(1)
    except OSError:
        s.close()
        raise
    return s


# -------------------------------------------------------- checkpoint vandals


def _step_dirs(directory: str) -> list[tuple[int, str]]:
    out = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            try:
                out.append((int(name[5:]), os.path.join(directory, name)))
            except ValueError:
                continue
    return sorted(out)


def _array_files(step_dir: str) -> list[str]:
    """The npz payload files of either checkpoint format (store's
    ``leaves.npz``, sharded's ``shards_p{k}.npz``)."""
    return sorted(
        os.path.join(step_dir, f)
        for f in os.listdir(step_dir)
        if f.endswith(".npz")
    )


def _manifest_files(step_dir: str) -> list[str]:
    return sorted(
        os.path.join(step_dir, f)
        for f in os.listdir(step_dir)
        if f.startswith("manifest") and f.endswith(".json")
    )


def vandal_truncate(step_dir: str, seed: int = 0) -> str:
    """Truncate the array payload to half its size (a write cut short)."""
    path = _array_files(step_dir)[0]
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    return path


def vandal_bitflip(step_dir: str, seed: int = 0) -> str:
    """Flip one seeded bit in the array payload (silent media corruption
    — the file stays the right size and the zip stays openable)."""
    path = _array_files(step_dir)[0]
    size = os.path.getsize(path)
    rng = np.random.default_rng(seed)
    # Stay inside member data, away from the zip's central directory at
    # the tail, so the corruption is only catchable by a checksum.
    offset = int(rng.integers(low=min(200, size // 4), high=size // 2))
    with open(path, "r+b") as f:
        f.seek(offset)
        byte = f.read(1)[0]
        f.seek(offset)
        f.write(bytes([byte ^ (1 << int(rng.integers(8)))]))
    return path


def vandal_delete_manifest(step_dir: str, seed: int = 0) -> str:
    """Delete the manifest(s) — metadata loss."""
    paths = _manifest_files(step_dir)
    if not paths:
        raise FileNotFoundError(f"no manifest in {step_dir}")
    for p in paths:
        os.remove(p)
    return paths[0]


def vandal_partial(step_dir: str, seed: int = 0) -> str:
    """Turn the dir into a partial write: manifest present, arrays gone
    (a checkpoint copied or crash-recovered without its payload)."""
    for p in _array_files(step_dir):
        os.remove(p)
    return step_dir


#: name -> vandal(step_dir, seed) -> touched path
VANDALS = {
    "truncate": vandal_truncate,
    "bitflip": vandal_bitflip,
    "no_manifest": vandal_delete_manifest,
    "partial": vandal_partial,
}


def vandalize(
    directory: str,
    kind: str,
    *,
    step: int | None = None,
    seed: int = 0,
) -> str:
    """Apply vandal ``kind`` to the ``step_{step}`` dir under a
    checkpoint ``directory`` (default: the NEWEST step — the one a naive
    restore would trust). Returns the touched path."""
    dirs = _step_dirs(directory)
    if not dirs:
        raise FileNotFoundError(f"no step_* dirs under {directory}")
    if step is None:
        target = dirs[-1][1]
    else:
        by_step = dict(dirs)
        if step not in by_step:
            raise FileNotFoundError(f"no step_{step} under {directory}")
        target = by_step[step]
    return VANDALS[kind](target, seed)


# ----------------------------------------------------------- plan vandals


def plan_vandal_truncate(path: str, seed: int = 0) -> str:
    """Cut the plan file in half mid-JSON (a write torn by a crash)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 2))
    return path


def plan_vandal_garbage(path: str, seed: int = 0) -> str:
    """Replace the plan with non-JSON bytes."""
    rng = np.random.default_rng(seed)
    with open(path, "wb") as f:
        f.write(rng.integers(0, 256, size=64, dtype=np.uint8).tobytes())
    return path


def plan_vandal_bad_version(path: str, seed: int = 0) -> str:
    """Stamp an unsupported schema version into otherwise-valid JSON —
    the one corruption only ``load_plan``'s version gate catches."""
    import json

    with open(path) as f:
        plan = json.load(f)
    plan["version"] = 99
    with open(path, "w") as f:
        json.dump(plan, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


#: name -> vandal(plan_path, seed) -> touched path
PLAN_VANDALS = {
    "truncate": plan_vandal_truncate,
    "garbage": plan_vandal_garbage,
    "bad_version": plan_vandal_bad_version,
}


def vandalize_plan(path: str, kind: str, *, seed: int = 0) -> str:
    """Corrupt a ``plan.json`` the three ways the re-plan path can lose
    it between emit and relaunch. The consumer contract under attack:
    ``Replanner.load_existing`` and the drill child must reject the file
    loudly or fall back, never train under a half-parsed plan."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return PLAN_VANDALS[kind](path, seed)
