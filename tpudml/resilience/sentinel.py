"""In-graph step sentinel as a pure optimizer-wrapper transform.

One poisoned gradient poisons every replica under synchronous
collectives (SURVEY.md §5.3): after the allreduce there is no clean copy
left to fall back to, and a single NaN microbatch turns the whole run
into NaN from that step on. :class:`GradSentinel` closes the numerical
half of that failure mode the same way :class:`~tpudml.optim.zero1.ZeRO1`
closed the optimizer-FLOPs half — as a wrapper any engine composes with
through its existing ``optimizer.update`` call site:

- global grad finiteness (every leaf, every element) and an optional
  grad-norm spike test against a running EMA are evaluated INSIDE the
  jitted program — no host sync, no callbacks, nothing for J103 to flag;
- on anomaly the update is suppressed with a branch-free
  ``jnp.where`` select over the whole ``(params, base_state)`` tree:
  the previous values are carried forward BIT-EXACTLY (a skipped step
  is indistinguishable from that batch never having arrived), the base
  optimizer's internal clock (Adam's ``t``) does not advance, and a
  device-side skip counter increments;
- a consecutive-skip budget escalates host-side: :func:`sentinel_hook`
  periodically reads the counters and raises :class:`SentinelTripped`
  with a diagnostic naming the first non-finite leaf (and, when the
  engine runs gradient accumulation with taint tracking, the poisoned
  microbatch index from ``metrics["bad_micro"]``).

Why select instead of ``lax.cond``: the base update may contain
collectives (ZeRO-1's reduce-scatter/all-gather, a sharded clip's psum),
and a cond whose branches issue different collective sequences is
exactly the J102 deadlock class. Always executing the update and
selecting the result keeps the collective schedule identical on every
device; the NaN flowing through the unselected operand is discarded by
the select.

Placement (``attach_sentinel`` does this for you): OUTERMOST for plain
optimizers, but INSIDE a :class:`ZeRO1` wrapper — the sentinel then
guards the post-reduce-scatter chunk gradients, the ZeRO-1 overlap
machinery (``update_shards``/``gather_params``) is untouched, and on a
skip the all-gather of the unselected old chunks reproduces the old
params bit-exactly. ``axis_names`` lists the mesh axes over which the
gradients seen at the wrapper's position may DIVERGE across devices
(ZeRO-1 chunks over the data axis, pipeline stage-local grads over the
stage axis); the bad flag and norm are psum'd over them so every device
agrees on the skip decision. Engines whose grads are already globally
consistent at the update site (plain DP post-allreduce, GSPMD/FSDP/TP
under jit) use ``axis_names=()``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpudml.optim.optimizers import Optimizer
from tpudml.optim.zero1 import ZeRO1

PyTree = Any

#: keys that identify a GradSentinel state dict inside a nested opt_state
_STATE_KEYS = frozenset(
    {"base", "skips", "consecutive", "good_steps", "norm_ema", "bad_leaf"}
)


class SentinelTripped(RuntimeError):
    """Raised host-side when the consecutive-skip budget is exceeded."""


@dataclass(frozen=True)
class GradSentinel(Optimizer):
    """Suppress non-finite / spiking updates inside the jitted step.

    ``axis_names``: mesh axes over which the grads at this position in
    the optimizer chain may differ per device — the anomaly predicate is
    psum'd over them so the skip decision is globally consistent (see
    module docstring for per-engine values). ``spike_factor`` > 0 also
    skips steps whose global grad norm exceeds ``spike_factor ×`` a
    running EMA (decay ``ema_decay``), armed only after ``warmup_steps``
    non-skipped steps so early-training noise cannot trip it.
    ``skip_budget`` is the number of CONSECUTIVE skips tolerated before
    :func:`sentinel_hook` escalates; the in-graph path never raises.
    """

    base: Optimizer = None  # type: ignore[assignment]
    axis_names: tuple[str, ...] = ()
    skip_budget: int = 3
    spike_factor: float = 0.0
    ema_decay: float = 0.99
    warmup_steps: int = 10

    def __post_init__(self):
        if self.base is None:
            raise ValueError("GradSentinel needs a base optimizer")
        if self.skip_budget < 1:
            raise ValueError("skip_budget must be >= 1")
        if self.spike_factor and self.spike_factor <= 1.0:
            raise ValueError(
                "spike_factor must be > 1 (a ratio vs the running norm "
                "EMA) or 0 to disable the spike test"
            )

    # -- Optimizer contract -----------------------------------------------

    def init(self, params):
        # Distinct arrays per counter: engines donate the TrainState, and
        # XLA rejects the same buffer donated at two argument positions.
        return {
            "base": self.base.init(params),
            "skips": jnp.zeros((), jnp.int32),
            "consecutive": jnp.zeros((), jnp.int32),
            "good_steps": jnp.zeros((), jnp.int32),
            "norm_ema": jnp.zeros((), jnp.float32),
            "bad_leaf": jnp.full((), -1, jnp.int32),
        }

    def init_spec(self, param_specs):
        return {
            "base": self.base.init_spec(param_specs),
            "skips": P(),
            "consecutive": P(),
            "good_steps": P(),
            "norm_ema": P(),
            "bad_leaf": P(),
        }

    def _psum(self, x):
        for axis in self.axis_names:
            x = lax.psum(x, axis)
        return x

    def update(self, grads, state, params):
        leaves = jax.tree_util.tree_leaves(grads)
        # Per-leaf non-finite element counts, psum'd so devices holding
        # different shards (ZeRO-1 chunks, pipeline stages) agree; the
        # argmax below names the FIRST bad leaf for the host diagnostic.
        bad_per_leaf = jnp.stack(
            [jnp.sum(~jnp.isfinite(g), dtype=jnp.int32) for g in leaves]
        )
        bad_per_leaf = self._psum(bad_per_leaf)
        nonfinite = jnp.any(bad_per_leaf > 0)
        bad_leaf_now = jnp.where(
            nonfinite, jnp.argmax(bad_per_leaf > 0).astype(jnp.int32), -1
        )

        normsq = self._psum(
            sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        )
        # A non-finite grad makes normsq non-finite too; keep the EMA
        # clean by never folding skipped steps into it (below).
        norm = jnp.sqrt(normsq)
        skip = nonfinite
        if self.spike_factor:
            armed = state["good_steps"] >= self.warmup_steps
            spike = armed & (norm > self.spike_factor * state["norm_ema"])
            skip = skip | spike

        # Always run the base update (identical collective schedule on
        # every device — see module docstring), then select old vs new.
        new_params, new_base = self.base.update(grads, state["base"], params)
        keep_old = lambda old, new: jax.tree_util.tree_map(
            lambda o, n: jnp.where(skip, o, n), old, new
        )
        out_params = keep_old(params, new_params)
        out_base = keep_old(state["base"], new_base)

        good = jnp.where(skip, 0, 1).astype(jnp.int32)
        new_ema = jnp.where(
            skip,
            state["norm_ema"],
            jnp.where(
                state["good_steps"] == 0,
                norm,
                self.ema_decay * state["norm_ema"]
                + (1.0 - self.ema_decay) * norm,
            ),
        )
        new_state = {
            "base": out_base,
            "skips": state["skips"] + (1 - good),
            "consecutive": jnp.where(
                skip, state["consecutive"] + 1, 0
            ).astype(jnp.int32),
            "good_steps": state["good_steps"] + good,
            "norm_ema": new_ema,
            "bad_leaf": jnp.where(skip, bad_leaf_now, state["bad_leaf"]),
        }
        return out_params, new_state


# -------------------------------------------------------------- placement


def attach_sentinel(
    optimizer: Optimizer,
    divergent_axes: tuple[str, ...] = (),
    **kwargs,
) -> Optimizer:
    """Insert a :class:`GradSentinel` at the correct point of a chain:
    inside a :class:`ZeRO1` (guarding the post-reduce-scatter chunk
    grads, with the data axis appended to ``divergent_axes`` since the
    chunks are disjoint over it), outermost otherwise. ``kwargs`` pass
    through to :class:`GradSentinel` (``skip_budget``, ``spike_factor``,
    ...)."""
    if isinstance(optimizer, ZeRO1):
        sent = GradSentinel(
            optimizer.base,
            axis_names=tuple(divergent_axes) + (optimizer.axis_name,),
            **kwargs,
        )
        return dataclasses.replace(optimizer, base=sent)
    return GradSentinel(
        optimizer, axis_names=tuple(divergent_axes), **kwargs
    )


def find_sentinel(optimizer: Optimizer) -> GradSentinel | None:
    """The GradSentinel in an optimizer chain (walking ``.base`` links),
    or None."""
    opt = optimizer
    while isinstance(opt, Optimizer):
        if isinstance(opt, GradSentinel):
            return opt
        opt = getattr(opt, "base", None)
    return None


def find_sentinel_state(opt_state) -> dict | None:
    """The sentinel's state dict inside a (possibly nested) optimizer
    state tree, or None. Works on device trees and host snapshots."""
    if isinstance(opt_state, dict):
        if _STATE_KEYS <= set(opt_state):
            return opt_state
        for v in opt_state.values():
            hit = find_sentinel_state(v)
            if hit is not None:
                return hit
    elif isinstance(opt_state, (tuple, list)):
        for v in opt_state:
            hit = find_sentinel_state(v)
            if hit is not None:
                return hit
    return None


# ------------------------------------------------------------- host side


def param_leaf_names(params: PyTree) -> list[str]:
    """Leaf path strings in ``tree_flatten`` order — the order
    ``bad_leaf`` indexes (ZeRO-1's flatten preserves tree structure, so
    the order matches the original params)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return [jax.tree_util.keystr(path) for path, _ in flat]


def sentinel_stats(opt_state) -> dict:
    """One blocking fetch of the sentinel counters as python scalars."""
    st = find_sentinel_state(opt_state)
    if st is None:
        raise ValueError("no GradSentinel state in this optimizer state")
    return {
        "skips": int(st["skips"]),
        "consecutive": int(st["consecutive"]),
        "good_steps": int(st["good_steps"]),
        "norm_ema": float(st["norm_ema"]),
        "bad_leaf": int(st["bad_leaf"]),
    }


def sentinel_hook(
    sentinel: GradSentinel,
    params_template: PyTree | None = None,
    check_every: int = 1,
):
    """A ``train_loop`` hook escalating the consecutive-skip budget.

    Every ``check_every`` steps it fetches the device-side counters (the
    only host sync the sentinel ever causes — the hot loop itself is
    sync-free) and raises :class:`SentinelTripped` once ``consecutive``
    exceeds ``sentinel.skip_budget``, naming the first non-finite leaf
    and, when the metrics carry accumulation taint, the microbatch
    index that poisoned the sum.
    """
    names = (
        param_leaf_names(params_template)
        if params_template is not None
        else None
    )

    def hook(*, step, train_state, metrics=None, **_):
        if check_every > 1 and step % check_every:
            return
        st = find_sentinel_state(train_state.opt_state)
        if st is None:
            return
        consecutive = int(st["consecutive"])
        if consecutive <= sentinel.skip_budget:
            return
        leaf = int(st["bad_leaf"])
        if names is not None and 0 <= leaf < len(names):
            leaf_desc = f"leaf {leaf} ({names[leaf]})"
        else:
            leaf_desc = f"leaf {leaf}" if leaf >= 0 else "no non-finite leaf"
        micro = ""
        if metrics is not None and "bad_micro" in metrics:
            idx = int(metrics["bad_micro"])
            if idx >= 0:
                micro = f", first poisoned microbatch {idx}"
        from tpudml.obs.tracer import get_tracer

        # Ambient flight recorder (tpudml.obs): the trip lands on the
        # trace as an instant before the raise unwinds the train loop.
        get_tracer().instant(
            "sentinel_trip", cat="sentinel",
            args={
                "step": int(step),
                "consecutive": consecutive,
                "skips": int(st["skips"]),
                "bad_leaf": leaf,
            },
        )
        raise SentinelTripped(
            f"sentinel skipped {consecutive} consecutive steps "
            f"(budget {sentinel.skip_budget}) at step {step}: first "
            f"non-finite {leaf_desc}{micro}; total skips "
            f"{int(st['skips'])}, norm_ema {float(st['norm_ema']):.3g}"
        )

    return hook
