"""tpudml.resilience — fault tolerance for the training and serving path.

Three parts (docs/RESILIENCE.md is the user guide):

- :mod:`sentinel` — :class:`GradSentinel`, an in-graph step guard in the
  same pure optimizer-wrapper style as :mod:`tpudml.optim.zero1`: grad
  finiteness (and an optional norm-spike test) is evaluated INSIDE the
  jitted step and anomalous updates are suppressed by a branch-free
  select, carrying the previous ``TrainState`` forward bit-exactly.
- checkpoint integrity + fallback — lives in :mod:`tpudml.checkpoint`
  (per-leaf checksums, ``verify=True`` restores,
  ``restore_latest_valid``); re-exported here for discoverability.
- :mod:`faults` — a seeded, deterministic fault-injection harness
  (microbatch corruptors, rank killer, straggler, checkpoint vandals)
  that the resilience tests use to PROVE the above end to end.
"""

from tpudml.resilience.faults import (
    VANDALS,
    corrupt_microbatch,
    rank_kill_hook,
    straggler_hook,
    vandalize,
)
from tpudml.resilience.sentinel import (
    GradSentinel,
    SentinelTripped,
    attach_sentinel,
    find_sentinel,
    find_sentinel_state,
    param_leaf_names,
    sentinel_hook,
    sentinel_stats,
)

__all__ = [
    "GradSentinel",
    "SentinelTripped",
    "VANDALS",
    "attach_sentinel",
    "corrupt_microbatch",
    "find_sentinel",
    "find_sentinel_state",
    "param_leaf_names",
    "rank_kill_hook",
    "sentinel_hook",
    "sentinel_stats",
    "straggler_hook",
    "vandalize",
]
