"""tpu-dml: a TPU-native (JAX/XLA) distributed machine-learning framework.

Provides the full capability surface of the Tsinghua "Distributed Machine
Learning" course lab suite (reference: Enigmatisms/
Distributed-Machine-Learning-Experiment-Document, see SURVEY.md), re-designed
TPU-first:

- ``tpudml.core``     — config, mesh/device discovery, distributed init, PRNG.
- ``tpudml.nn``       — functional (init/apply) neural-net module system.
- ``tpudml.models``   — LeNet-style CNN, MLP, staged split nets.
- ``tpudml.optim``    — hand-written GD / SGD(+momentum) / Adam as pure pytree
                        transforms (reference: codes/task1/pytorch/MyOptimizer.py).
- ``tpudml.data``     — MNIST/CIFAR-10 loaders (IDX parser + synthetic
                        fallback), sampler framework (random partition /
                        random sampling), per-host sharding.
- ``tpudml.metrics``  — scalar metrics writer (reference: codes/datawriter.py).
"""

__version__ = "0.1.0"
