"""tpu-dml: a TPU-native (JAX/XLA) distributed machine-learning framework.

Provides the full capability surface of the Tsinghua "Distributed Machine
Learning" course lab suite (reference: Enigmatisms/
Distributed-Machine-Learning-Experiment-Document, see SURVEY.md), re-designed
TPU-first:

- ``tpudml.core``     — config, mesh/device discovery, distributed init, PRNG.
- ``tpudml.nn``       — functional (init/apply) neural-net module system incl.
                        multi-head attention (full/flash/ring/ulysses).
- ``tpudml.models``   — LeNet-style CNN, MLP, ResNet-18/34, staged split nets,
                        decoder-only TransformerLM.
- ``tpudml.optim``    — hand-written GD / SGD(+momentum) / Adam as pure pytree
                        transforms (reference: codes/task1/pytorch/MyOptimizer.py).
- ``tpudml.data``     — MNIST/CIFAR-10 loaders (IDX parser + synthetic
                        fallbacks), uint8-resident storage, sampler framework
                        (random partition / random sampling), per-host sharding.
- ``tpudml.comm``     — pytree collectives + aggregation strategies + comm stats.
- ``tpudml.parallel`` — DP (shard_map), GSPMD stage/tensor parallelism, GPipe
                        micro-batched pipeline, ring/Ulysses context parallelism.
- ``tpudml.ops``      — Pallas TPU kernels (fused attention).
- ``tpudml.native``   — C++ host data-plane (fused gather+dequantize, byteswap).
- ``tpudml.checkpoint`` — atomic pytree checkpoints + budget-based resume.
- ``tpudml.metrics``  — scalar writer (JSONL/TensorBoard), profiler, span timers
                        (reference: codes/datawriter.py).
- ``tpudml.launch``   — supervised multi-process launcher (compose replacement).
- ``tpudml.api``      — high-level Model(train/eval) facade (MindSpore-track).
"""

__version__ = "0.1.0"
