"""Launch / deployment subsystem.

TPU-native replacement for the reference's launch layer (SURVEY.md L1):
manual per-rank CLIs (sections/task2.tex:86-93), ``mp.spawn``
(codes/task2/model-mp.py:146-148), and the docker-compose topologies whose
YAML doubled as cluster config (codes/task2/docker-compose.yml,
codes/task4/docker-compose.yml). One launcher covers CPU-simulated
multi-process, single-host multi-chip, and multi-host TPU — the task code
never changes, only the ClusterSpec.

It also fills the reference's failure-detection gap (SURVEY.md §5.3: if
one rank dies the others hang forever in the collective): the monitor
terminates the whole job as soon as any rank fails, and enforces an
optional wall-clock timeout. Straggler/fault injection (the task2
bottleneck-node experiment, sections/checking.tex:22) is first-class via
spec fields exported to the ranks' environment.
"""

from tpudml.launch.cluster import ClusterSpec
from tpudml.launch.launcher import LaunchResult, launch

__all__ = ["ClusterSpec", "LaunchResult", "launch"]
