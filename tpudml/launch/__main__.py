"""CLI: ``python -m tpudml.launch [options] -- <command ...>``.

The one-line replacement for the reference's three launch mechanisms
(N manual terminals / mp.spawn / docker compose up — SURVEY.md §4):

    # 2-process simulated cluster, task2, bottleneck on rank 1:
    python -m tpudml.launch --num_processes 2 --bottleneck_rank 1 -- \
        python -m tasks.task2 --dataset synthetic --epochs 1

    # reference-style explicit per-rank flags via templating:
    python -m tpudml.launch -n 2 -- \
        python -m tasks.task2 --n_devices {world} --rank {rank}

``--config cluster.json`` loads a ClusterSpec (the compose-file analogue);
CLI flags override it.
"""

from __future__ import annotations

import argparse
import sys

from tpudml.launch.cluster import ClusterSpec
from tpudml.launch.launcher import launch

# ``--check`` child: the smallest real cross-process collective. Each rank
# holds one row of a ['data']-sharded vector and psums it; a wrong wiring
# (no gloo → XLA:CPU rejects multi-process computations outright) fails
# the child, which fails the check.
_CHECK_CHILD = """
import numpy as np, jax
from jax.sharding import NamedSharding, PartitionSpec as P
from tpudml.core.config import DistributedConfig, MeshConfig
from tpudml.core.dist import distributed_init, make_mesh, process_index
from tpudml.parallel.sharding import shard_map_fn

distributed_init(DistributedConfig.from_env())
mesh = make_mesh(MeshConfig({"data": -1}))
world = int(np.prod(mesh.devices.shape))
x = jax.make_array_from_callback(
    (world,), NamedSharding(mesh, P("data")),
    lambda idx: np.arange(world, dtype=np.float32)[idx])
total = shard_map_fn(
    lambda v: jax.lax.psum(v.sum(), "data"), mesh, (P("data"),), P())(x)
expect = world * (world - 1) / 2
assert float(total) == expect, (float(total), expect)
print(f"[check] rank {process_index()}/{world} psum {float(total)} OK",
      flush=True)
"""


def run_check(spec: ClusterSpec) -> int:
    """``python -m tpudml.launch --check``: prove the multi-process CPU
    wiring (gloo collectives + rendezvous + containment) with a 2-process
    psum; exit 0 iff every rank computed the correct global sum."""
    if spec.timeout_s is None:
        spec.timeout_s = 120.0
    result = launch([sys.executable, "-u", "-c", _CHECK_CHILD], spec)
    if result.success:
        print(
            f"launch --check: OK ({spec.num_processes}-process cross-host "
            f"psum in {result.elapsed_s:.1f}s)"
        )
        return 0
    print(
        f"launch --check: FAILED (rcs={result.returncodes}, "
        f"timed_out={result.timed_out})",
        file=sys.stderr,
    )
    return 1


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        argv, cmd = argv[:split], argv[split + 1 :]
    else:
        argv, cmd = argv, []
    p = argparse.ArgumentParser(prog="tpudml.launch")
    p.add_argument("--config", type=str, default=None, help="ClusterSpec JSON")
    p.add_argument("-n", "--num_processes", type=int, default=None)
    p.add_argument("--coordinator_host", type=str, default=None)
    p.add_argument("--coordinator_port", type=int, default=None)
    p.add_argument(
        "--platform",
        type=str,
        default=None,
        help='"cpu" = simulated cluster; "none" = inherit (TPU pods)',
    )
    p.add_argument("--devices_per_process", type=int, default=None)
    p.add_argument("--timeout_s", type=float, default=None)
    p.add_argument("--bottleneck_rank", type=int, default=None)
    p.add_argument("--bottleneck_delay_s", type=float, default=None)
    p.add_argument("--max_restarts", type=int, default=None,
                   help="relaunch a failed job up to N times (pair the "
                        "command with --ckpt_dir/--resume to continue)")
    p.add_argument("--check", action="store_true",
                   help="no command: run a 2-process gloo psum smoke test "
                        "of the multi-process wiring and exit 0/1")
    args = p.parse_args(argv)
    if not cmd and not args.check:
        p.error("no command given; usage: python -m tpudml.launch [opts] -- cmd ...")

    spec = ClusterSpec.from_json(args.config) if args.config else ClusterSpec()
    for name in (
        "num_processes",
        "coordinator_host",
        "coordinator_port",
        "platform",
        "devices_per_process",
        "timeout_s",
        "bottleneck_rank",
        "bottleneck_delay_s",
        "max_restarts",
    ):
        val = getattr(args, name)
        if val is not None:
            setattr(spec, name, val)
    if spec.platform == "none":
        spec.platform = None

    if args.check:
        return run_check(spec)
    result = launch(cmd, spec)
    if result.timed_out:
        print(f"launch: TIMEOUT after {result.elapsed_s:.1f}s", file=sys.stderr)
    elif result.failed_rank is not None:
        print(
            f"launch: rank {result.failed_rank} failed "
            f"(rc={result.returncodes[result.failed_rank]}); job terminated",
            file=sys.stderr,
        )
    return 0 if result.success else 1


if __name__ == "__main__":
    sys.exit(main())
