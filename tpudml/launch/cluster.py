"""Cluster topology specification (the docker-compose.yml replacement)."""

from __future__ import annotations

import dataclasses
import json
import os
import re
import socket
from dataclasses import dataclass, field


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class ClusterSpec:
    """Everything the launcher needs to stand up an N-process job.

    The reference encodes this per-node in compose YAML — image, mount,
    rank flags, rendezvous DNS name (codes/task2/docker-compose.yml:4-45).
    Here it is one typed, JSON-serializable object; rendezvous is the JAX
    coordinator (``coordinator_address``) instead of MASTER_ADDR/PORT.
    """

    num_processes: int = 2
    coordinator_host: str = "127.0.0.1"
    coordinator_port: int = 0  # 0 → pick a free port at launch
    # "cpu" = simulated cluster on the host (the mp.spawn analogue);
    # None = inherit whatever platform the environment provides (TPU pods).
    platform: str | None = "cpu"
    devices_per_process: int = 1  # virtual host devices per rank (cpu sim)
    timeout_s: float | None = None  # whole-job wall-clock limit
    grace_s: float = 5.0  # SIGTERM → SIGKILL escalation delay
    # Elastic recovery: relaunch the whole job after a failure/timeout up
    # to this many times. Pair the command with --ckpt_dir/--resume so
    # each restart continues from the last checkpoint (SURVEY.md §5.3/5.4:
    # checkpoint/restart IS the recovery story).
    max_restarts: int = 0
    # Seeded exponential backoff between restart attempts: attempt k waits
    # restart_backoff_s * restart_backoff_factor**(k-1), plus a uniform
    # jitter of up to restart_backoff_jitter × that delay drawn from
    # random.Random(restart_backoff_seed) — deterministic per spec, but
    # decorrelated across jobs so a mass preemption doesn't produce a
    # thundering-herd reconnect. 0 (the default) restarts immediately,
    # preserving the pre-backoff behaviour.
    restart_backoff_s: float = 0.0
    restart_backoff_factor: float = 2.0
    restart_backoff_jitter: float = 0.0
    restart_backoff_seed: int = 0
    # Straggler/fault injection (task2 bottleneck-node experiment).
    bottleneck_rank: int | None = None
    bottleneck_delay_s: float = 0.1
    env: dict[str, str] = field(default_factory=dict)  # extra env, all ranks
    rank_env: dict[int, dict[str, str]] = field(default_factory=dict)

    def coordinator_address(self) -> str:
        if self.coordinator_port == 0:
            # Resolved once per launch; persisted so every rank agrees.
            self.coordinator_port = _free_port()
        return f"{self.coordinator_host}:{self.coordinator_port}"

    def environ_for_rank(self, rank: int) -> dict[str, str]:
        """Child-process environment for ``rank`` (layered over os.environ):
        the TPUDML_* rendezvous contract read by DistributedConfig.from_env,
        platform simulation knobs, and fault-injection exports."""
        env = dict(os.environ)
        env.update(self.env)
        env.update(self.rank_env.get(rank, {}))
        env.update(
            TPUDML_COORDINATOR=self.coordinator_address(),
            TPUDML_NUM_PROCESSES=str(self.num_processes),
            TPUDML_PROCESS_ID=str(rank),
        )
        if self.platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["PALLAS_AXON_POOL_IPS"] = ""  # don't let a TPU relay latch on
            # Strip any inherited device-count flag: the spec owns the
            # simulated topology (devices_per_process × num_processes).
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+",
                "",
                env.get("XLA_FLAGS", ""),
            )
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{self.devices_per_process}"
            ).strip()
        elif self.platform:
            env["JAX_PLATFORMS"] = self.platform
        if self.bottleneck_rank is not None:
            env["TPUDML_BOTTLENECK_RANK"] = str(self.bottleneck_rank)
            env["TPUDML_BOTTLENECK_DELAY_S"] = str(self.bottleneck_delay_s)
        return env

    # ------------------------------------------------------------- serde

    def to_json(self, path: str | os.PathLike) -> None:
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2)

    @classmethod
    def from_json(cls, path: str | os.PathLike) -> "ClusterSpec":
        with open(path) as f:
            raw = json.load(f)
        raw["rank_env"] = {int(k): v for k, v in raw.get("rank_env", {}).items()}
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown ClusterSpec fields: {sorted(unknown)}")
        return cls(**raw)
