"""Process launcher with rank-tagged output and failure containment."""

from __future__ import annotations

import dataclasses
import random
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

from tpudml.launch.cluster import ClusterSpec

POLL_S = 0.2


@dataclass
class LaunchResult:
    returncodes: list[int]
    elapsed_s: float
    timed_out: bool = False
    failed_rank: int | None = None
    attempts: int = 1
    # Backoff delay actually slept before each restart (empty when the
    # job succeeded first try or restart_backoff_s == 0).
    backoffs_s: list[float] = field(default_factory=list)

    @property
    def success(self) -> bool:
        return not self.timed_out and all(rc == 0 for rc in self.returncodes)


def restart_backoff(spec: ClusterSpec, rng: random.Random, attempt: int) -> float:
    """Seeded exponential backoff delay before restart ``attempt`` (1-based):
    ``restart_backoff_s * factor**(attempt-1)`` plus uniform jitter drawn
    from ``rng`` — the one backoff schedule shared by :func:`launch`'s
    whole-job restarts and the elastic controller's re-forms
    (``tpudml.elastic``), so both are deterministic per (spec, seed)."""
    if spec.restart_backoff_s <= 0:
        return 0.0
    delay = spec.restart_backoff_s * spec.restart_backoff_factor ** (attempt - 1)
    if spec.restart_backoff_jitter > 0:
        delay += rng.uniform(0, spec.restart_backoff_jitter * delay)
    return delay


def _substitute(cmd: list[str], rank: int, world: int) -> list[str]:
    """Per-rank command templating: ``{rank}``/``{world}`` placeholders —
    the analogue of compose's per-service ``--rank={0,1}`` lines
    (codes/task2/docker-compose.yml:9-17,30-38)."""
    return [a.replace("{rank}", str(rank)).replace("{world}", str(world)) for a in cmd]


def _pump(proc: subprocess.Popen, rank: int, sink) -> threading.Thread:
    """Forward a child's merged output line-by-line with a rank tag (the
    compose service-name prefix analogue; reference relies on `python -u`
    prints per rank, sections/task2.tex:157)."""

    def run():
        for line in proc.stdout:  # type: ignore[union-attr]
            sink.write(f"[rank {rank}] {line}")
            sink.flush()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def launch(
    cmd: list[str],
    spec: ClusterSpec | None = None,
    *,
    sink=None,
) -> LaunchResult:
    """Spawn ``spec.num_processes`` copies of ``cmd`` and supervise them.

    Containment semantics (the reference's gap, SURVEY.md §5.3: with
    synchronous collectives one dead rank leaves every other rank blocked
    forever): the first rank to exit non-zero triggers SIGTERM (then
    SIGKILL after ``grace_s``) of the whole job; ``timeout_s`` bounds total
    wall clock the same way. With ``spec.max_restarts`` > 0 a failed or
    timed-out job is relaunched whole (fresh rendezvous port) up to that
    many times — combine with the tasks' ``--ckpt_dir ... --resume`` flags
    so restarts continue from the last checkpoint. ``attempts`` on the
    result counts the runs. ``spec.restart_backoff_s`` > 0 inserts a
    seeded exponential (+ jitter) delay before each relaunch — recorded
    per attempt in ``result.backoffs_s`` and charged against
    ``timeout_s`` like any other elapsed time.
    """
    spec = spec or ClusterSpec()
    out = sink or sys.stdout
    # Each attempt runs on a COPY of the spec: an auto-picked rendezvous
    # port (coordinator_port=0) is re-picked per attempt, an explicitly
    # configured port is kept; the caller's spec is never mutated.
    auto_port = spec.coordinator_port == 0
    budget = spec.timeout_s  # whole-job wall clock, spent across attempts

    def attempt_spec(remaining: float | None) -> ClusterSpec:
        return dataclasses.replace(
            spec,
            coordinator_port=0 if auto_port else spec.coordinator_port,
            timeout_s=remaining,
        )

    # Seeded restart backoff: deterministic per (spec, seed) so restart
    # cadence is reproducible in tests, decorrelated across jobs by seed.
    rng = random.Random(spec.restart_backoff_seed)

    result = _launch_once(cmd, attempt_spec(budget), sink)
    total_elapsed = result.elapsed_s
    backoffs: list[float] = []
    attempt = 1
    while not result.success and attempt <= spec.max_restarts:
        delay = restart_backoff(spec, rng, attempt)
        remaining = None if budget is None else budget - total_elapsed - delay
        if remaining is not None and remaining <= 0:
            break  # whole-job budget exhausted — don't relaunch
        why = "timeout" if result.timed_out else f"rank {result.failed_rank} failed"
        tail = f" after {delay:.2f}s backoff" if delay > 0 else ""
        out.write(
            f"[launch] {why}; restart {attempt}/{spec.max_restarts}{tail}\n"
        )
        out.flush()
        from tpudml.obs.tracer import get_tracer

        # Ambient flight recorder (tpudml.obs): restarts land on the
        # supervisor's trace as instants (no-op when no tracer installed).
        get_tracer().instant(
            "launch_restart", cat="launch",
            args={"attempt": attempt, "why": why, "backoff_s": delay},
        )
        if delay > 0:
            time.sleep(delay)
            total_elapsed += delay
        backoffs.append(delay)
        result = _launch_once(cmd, attempt_spec(remaining), sink)
        total_elapsed += result.elapsed_s
        attempt += 1
    result.attempts = attempt
    result.elapsed_s = total_elapsed
    result.backoffs_s = backoffs
    return result


def launch_once(
    cmd: list[str],
    spec: ClusterSpec,
    sink=None,
) -> LaunchResult:
    """Single-attempt launch — the containment core without the restart
    loop. This is the primitive the multi-gang supervisors build rounds
    from: ``tpudml.elastic`` runs one per incarnation, ``tpudml.mpmd``
    runs one per *stage group* concurrently (each stage is its own gloo
    world with its own rendezvous)."""
    return _launch_once(cmd, spec, sink)


def _launch_once(
    cmd: list[str],
    spec: ClusterSpec,
    sink=None,
) -> LaunchResult:
    sink = sink or sys.stdout
    world = spec.num_processes
    spec.coordinator_address()  # resolve the port once, before any spawn
    procs: list[subprocess.Popen] = []
    pumps: list[threading.Thread] = []
    t0 = time.monotonic()
    timed_out = False
    failed_rank: int | None = None
    try:
        for rank in range(world):
            p = subprocess.Popen(
                _substitute(cmd, rank, world),
                env=spec.environ_for_rank(rank),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            procs.append(p)
            pumps.append(_pump(p, rank, sink))

        while True:
            codes = [p.poll() for p in procs]
            for rank, rc in enumerate(codes):
                if rc is not None and rc != 0 and failed_rank is None:
                    failed_rank = rank
            done = all(rc is not None for rc in codes)
            over_time = (
                spec.timeout_s is not None
                and time.monotonic() - t0 > spec.timeout_s
            )
            if done:
                break
            if failed_rank is not None or over_time:
                timed_out = over_time and failed_rank is None
                _terminate_all(procs, spec.grace_s)
                break
            time.sleep(POLL_S)
    except BaseException:
        # A mid-spawn failure (fork error, Ctrl-C) must not leak earlier
        # ranks as live orphans blocked in the rendezvous.
        _terminate_all(procs, spec.grace_s)
        raise
    for p in procs:
        p.wait()
    for t in pumps:
        t.join(timeout=2)
    return LaunchResult(
        returncodes=[p.returncode for p in procs],
        elapsed_s=time.monotonic() - t0,
        timed_out=timed_out,
        failed_rank=failed_rank,
    )


def _terminate_all(procs: list[subprocess.Popen], grace_s: float) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline and any(p.poll() is None for p in procs):
        time.sleep(POLL_S)
    for p in procs:
        if p.poll() is None:
            p.kill()
