"""TPU-VM pod provisioning — the environment-bootstrap layer.

The reference dedicates a chapter to getting an environment at all
(sections/env_setup.tex: local CUDA+conda :5-145, Docker image workflow
:147-283, the SIGS GPU cluster :285-360, Huawei ModelArts :364-443). The
TPU-native analogue is the TPU-VM lifecycle: create a pod slice, run the
SAME per-worker command on every host (jax.distributed discovers the
coordinator from the TPU metadata, so no MASTER_ADDR plumbing), and
delete it when done.

Design: pure COMMAND BUILDERS over a typed spec + a thin CLI that prints
(``--dry_run``, the default) or executes them. The builders are the
tested, load-bearing part — this box has no gcloud and no pod, so
execution is deliberately a subprocess one-liner around the exact
commands the dry run shows (an operator can always copy-paste them).
"""

from __future__ import annotations

import dataclasses
import shlex
import subprocess
import sys
from dataclasses import dataclass


@dataclass
class TpuVmSpec:
    """One TPU-VM pod slice (the ClusterSpec analogue for real hardware).

    ``accelerator_type`` encodes generation and chip count (e.g.
    "v5litepod-8" = 8 v5e chips on 2 hosts, "v4-32" = 16 chips / 4 hosts);
    the per-host process layout follows from it, so unlike the reference's
    compose YAML there is no rank bookkeeping to keep consistent.
    """

    name: str
    zone: str = "us-central2-b"
    accelerator_type: str = "v5litepod-8"
    # Must match the accelerator generation: v5e slices use the
    # v2-alpha-tpuv5-lite runtime (the v4 default would be
    # tpu-ubuntu2204-base) — a mismatch is rejected at create time.
    runtime_version: str = "v2-alpha-tpuv5-lite"
    project: str | None = None
    preemptible: bool = False

    def _common(self) -> list[str]:
        out = ["--zone", self.zone]
        if self.project:
            out += ["--project", self.project]
        return out


def create_command(spec: TpuVmSpec) -> list[str]:
    cmd = [
        "gcloud", "compute", "tpus", "tpu-vm", "create", spec.name,
        *spec._common(),
        "--accelerator-type", spec.accelerator_type,
        "--version", spec.runtime_version,
    ]
    if spec.preemptible:
        cmd.append("--preemptible")
    return cmd


def delete_command(spec: TpuVmSpec) -> list[str]:
    return [
        "gcloud", "compute", "tpus", "tpu-vm", "delete", spec.name,
        *spec._common(), "--quiet",
    ]


def run_command(spec: TpuVmSpec, command: str) -> list[str]:
    """Run ``command`` on EVERY worker host simultaneously (--worker=all):
    the pod-scale launch primitive. The same task entrypoints run
    unchanged — ``jax.distributed.initialize()`` with no arguments
    resolves coordinator/rank/world from the TPU-VM metadata, which is why
    no MASTER_ADDR/--rank templating exists here (contrast the
    reference's per-service compose commands,
    codes/task2/docker-compose.yml:9-17,30-38)."""
    return [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", spec.name,
        *spec._common(), "--worker=all", "--command", command,
    ]


def scp_command(spec: TpuVmSpec, src: str, dst: str) -> list[str]:
    """Copy the code/data to every worker (the bind-mount analogue of the
    reference's ``.:/workspace`` volumes)."""
    return [
        "gcloud", "compute", "tpus", "tpu-vm", "scp", "--recurse", src,
        f"{spec.name}:{dst}", *spec._common(), "--worker=all",
    ]


def pod_workflow(
    spec: TpuVmSpec, task_command: str, repo_dir: str = ".", dst: str = "~"
) -> list[list[str]]:
    """The full create → push code → run → delete lifecycle as a command
    list (what ``python -m tpudml.launch.tpu_vm workflow`` prints).

    ``scp --recurse SRC name:DST`` lands the repo at DST/<basename(SRC)>
    (scp -r semantics when DST exists — and the home dir always does), so
    the run step cd's into exactly that path; any ``repo_dir`` works, not
    just ".".
    """
    import os

    workdir = dst.rstrip("/") + "/" + os.path.basename(os.path.realpath(repo_dir))
    return [
        create_command(spec),
        scp_command(spec, repo_dir, dst),
        run_command(spec, f"cd {workdir} && {task_command}"),
        delete_command(spec),
    ]


def _execute(cmd: list[str]) -> int:
    print("+ " + " ".join(shlex.quote(c) for c in cmd), flush=True)
    return subprocess.call(cmd)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tpudml.launch.tpu_vm",
        description="TPU-VM pod lifecycle (prints gcloud commands; "
        "--execute runs them)",
    )
    p.add_argument("action", choices=["create", "run", "scp", "delete", "workflow"])
    p.add_argument("--name", required=True)
    for f in dataclasses.fields(TpuVmSpec):
        if f.name in ("name", "preemptible"):
            continue
        p.add_argument(f"--{f.name}", default=f.default)
    p.add_argument("--preemptible", action="store_true")
    p.add_argument("--command", default="python -m tasks.north_star --epochs 10")
    p.add_argument("--src", default=".")
    p.add_argument("--dst", default="~",
                   help="remote parent dir; the repo lands at "
                   "DST/<basename(src)> (scp -r semantics)")
    p.add_argument("--execute", action="store_true",
                   help="run the commands instead of printing them")
    args = p.parse_args(argv)

    spec = TpuVmSpec(
        name=args.name, zone=args.zone,
        accelerator_type=args.accelerator_type,
        runtime_version=args.runtime_version,
        project=args.project, preemptible=args.preemptible,
    )
    cmds = {
        "create": [create_command(spec)],
        "delete": [delete_command(spec)],
        "run": [run_command(spec, args.command)],
        "scp": [scp_command(spec, args.src, args.dst)],
        "workflow": pod_workflow(spec, args.command, args.src, dst=args.dst),
    }[args.action]

    if not args.execute:
        for cmd in cmds:
            print(" ".join(shlex.quote(c) for c in cmd))
        return 0

    if args.action != "workflow":
        rc = 0
        for cmd in cmds:
            rc = _execute(cmd)
            if rc:
                break
        return rc

    # workflow --execute: once the pod exists it MUST be torn down even if
    # the push or the training command fails, raises, or is interrupted —
    # a leaked slice keeps billing until someone notices.
    create, push, run_, delete = cmds
    rc = _execute(create)
    if rc:
        return rc
    try:
        for cmd in (push, run_):
            rc = _execute(cmd)
            if rc:
                break
    finally:
        drc = _execute(delete)
    return rc or drc


if __name__ == "__main__":
    sys.exit(main())
