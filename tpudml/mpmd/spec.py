"""MPMD pipeline topology: stage partition, boundary dataflow, quorum.

An MPMD pipeline (arXiv 2412.14374) is S independent process groups —
one per stage — that agree only on a *wire contract*. This module is
that contract, kept deliberately jax-free so the controller
(``mpmd/groups.py``), the meshless fixture replay (``mpmd/fixture.py``)
and the unit tests reason about topology without a backend:

- :class:`StageSpec` / :class:`PipelineSpec` — the partition of the
  cluster into stage groups. Stages may differ in data parallelism,
  microbatch count, compute precision, and model code; the *global
  batch* is the one shared unit of account. Composition limits are
  table rejections (``tpudml/capabilities.py`` ``mpmd_*`` entries), so
  the planner prunes infeasible MPMD candidates with receipts instead
  of discovering them as crashes.
- :func:`boundary_plan` — the deterministic transfer list for one
  stage boundary. Global batch rows are the common currency: stage
  ``b`` partitions them by its microbatches then its dp ranks
  (contiguously), stage ``b+1`` by *its* microbatches and ranks, and
  every transfer is an intersection of two such intervals. Both sides
  derive the identical list, which is what makes the (step, microbatch,
  edge) framing in ``comm/p2p.py`` deterministic: the frame's
  microbatch field is the transfer's index in this list.
- :func:`warmup_microbatches` — the 1F1B warmup depth, generalized to
  heterogeneous microbatch counts by measuring warmup in *rows* rather
  than microbatches (the homogeneous formula ``S-1-s`` deadlocks when
  a downstream stage chunks finer than its producer).
- :func:`replace_pipeline` / :func:`drain_order` — re-mesh-in-place
  bookkeeping: which ranks drain in what canonical order after a
  membership event, and what the shrunken pipeline looks like
  (:class:`StageQuorumError` when a stage falls below ``min_world``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace

from tpudml.capabilities import reject

__all__ = [
    "StageSpec",
    "PipelineSpec",
    "Transfer",
    "StageQuorumError",
    "boundary_plan",
    "warmup_microbatches",
    "replace_pipeline",
    "drain_order",
]


class StageQuorumError(ValueError):
    """A membership event left some stage below its ``min_world``: the
    pipeline cannot re-form and the controller must halt (the MPMD
    analogue of ``ElasticController``'s min_world stop)."""


@dataclass(frozen=True)
class StageSpec:
    """One pipeline stage: its own gloo world, schedule and precision.

    ``dtype`` is the stage's *compute and wire* precision — parameters
    are kept in f32 master copies by the runtime regardless.
    ``microbatches`` is per-stage: a bf16 trunk may chunk the global
    batch finer than the f32 head consuming it. ``min_world`` is the
    stage's survival quorum under re-mesh.
    """

    name: str
    dp: int = 1
    microbatches: int = 1
    dtype: str = "float32"
    min_world: int = 1
    moe_experts: int = 0
    fused_xent: bool = False

    def candidate(self) -> dict:
        """This stage as a planner candidate dict — the capability
        table's ``when`` predicates read exactly these keys."""
        return {
            "engine": "mpmd",
            "mpmd": True,
            "moe_experts": self.moe_experts,
            "fused_xent": self.fused_xent,
        }


@dataclass(frozen=True)
class PipelineSpec:
    """A full MPMD pipeline: ordered stages plus the global batch size
    they jointly process. Slots (global process indices) are laid out
    contiguously per stage, in stage order."""

    stages: tuple = ()
    global_batch: int = 0
    serve: bool = False

    def __post_init__(self):
        object.__setattr__(self, "stages", tuple(self.stages))
        if len(self.stages) < 1:
            raise ValueError("PipelineSpec needs at least one stage")
        if self.global_batch < 1:
            raise ValueError("global_batch must be >= 1")
        names = [s.name for s in self.stages]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stage names: {names}")
        for i, s in enumerate(self.stages):
            if s.dp < 1 or s.microbatches < 1:
                raise ValueError(
                    f"stage {s.name}: dp and microbatches must be >= 1"
                )
            if not (1 <= s.min_world <= s.dp):
                raise ValueError(
                    f"stage {s.name}: min_world must be in [1, dp={s.dp}]"
                )
            rows = self.global_batch
            if rows % s.microbatches:
                raise ValueError(
                    f"stage {s.name}: global_batch={rows} not divisible "
                    f"by microbatches={s.microbatches}"
                )
            if (rows // s.microbatches) % s.dp:
                raise ValueError(
                    f"stage {s.name}: microbatch of "
                    f"{rows // s.microbatches} rows not divisible by "
                    f"dp={s.dp}"
                )
            # Literal reject() call sites per composition rule — the
            # capability table's source scan maps each key to its guard.
            if s.moe_experts:
                reject("mpmd_moe_aux_loss")
            if s.fused_xent:
                reject("mpmd_fused_xent_head")
            if self.serve:
                reject("mpmd_serve")

    # ------------------------------------------------------ slot layout

    @property
    def total_slots(self) -> int:
        return sum(s.dp for s in self.stages)

    def stage_slots(self, s: int) -> range:
        """Global slot range of stage ``s`` (contiguous, stage order)."""
        lo = sum(st.dp for st in self.stages[:s])
        return range(lo, lo + self.stages[s].dp)

    def slot_of(self, stage: int, rank: int) -> int:
        return self.stage_slots(stage)[rank]

    def locate(self, slot: int):
        """Global slot -> (stage, stage-local rank)."""
        for s in range(len(self.stages)):
            r = self.stage_slots(s)
            if slot in r:
                return s, slot - r.start
        raise ValueError(f"slot {slot} out of range [0, {self.total_slots})")

    # -------------------------------------------------- row bookkeeping

    def rows_per_rank(self, s: int) -> int:
        st = self.stages[s]
        return self.global_batch // (st.microbatches * st.dp)

    def row_interval(self, s: int, microbatch: int, rank: int):
        """Global row interval [lo, hi) that (stage, microbatch, rank)
        owns under the contiguous layout."""
        st = self.stages[s]
        mb_rows = self.global_batch // st.microbatches
        per_rank = mb_rows // st.dp
        lo = microbatch * mb_rows + rank * per_rank
        return lo, lo + per_rank

    def to_dict(self) -> dict:
        return {
            "global_batch": self.global_batch,
            "serve": self.serve,
            "stages": [
                {
                    "name": s.name,
                    "dp": s.dp,
                    "microbatches": s.microbatches,
                    "dtype": s.dtype,
                    "min_world": s.min_world,
                }
                for s in self.stages
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PipelineSpec":
        return cls(
            stages=tuple(StageSpec(**st) for st in d["stages"]),
            global_batch=int(d["global_batch"]),
            serve=bool(d.get("serve", False)),
        )


@dataclass(frozen=True)
class Transfer:
    """One contiguous row interval crossing one stage boundary: the
    intersection of a (src microbatch, src rank) interval with a
    (dst microbatch, dst rank) interval. ``index`` is the transfer's
    position in the boundary's sorted plan — the deterministic
    ``microbatch`` field of its wire frames."""

    index: int
    edge: str
    src_stage: int
    dst_stage: int
    src_rank: int
    dst_rank: int
    src_microbatch: int
    dst_microbatch: int
    rows: tuple  # global [lo, hi)
    src_rows: tuple  # local to the src rank's microbatch shard
    dst_rows: tuple  # local to the dst rank's microbatch shard


def boundary_plan(spec: PipelineSpec, b: int) -> tuple:
    """Deterministic transfer list for the boundary stage b -> b+1.

    Sorted by global row, which increases with src microbatch, src
    rank, dst microbatch and dst rank simultaneously (contiguous
    layout) — so per-channel frame order agrees with both the sender's
    and the receiver's schedule order, and the 1F1B loops on either
    side can send/recv strictly in plan order without deadlock.
    """
    if not (0 <= b < len(spec.stages) - 1):
        raise ValueError(f"no boundary {b} in a {len(spec.stages)}-stage pipeline")
    src, dst = spec.stages[b], spec.stages[b + 1]
    out = []
    for i in range(src.microbatches):
        for r in range(src.dp):
            slo, shi = spec.row_interval(b, i, r)
            for j in range(dst.microbatches):
                for q in range(dst.dp):
                    dlo, dhi = spec.row_interval(b + 1, j, q)
                    lo, hi = max(slo, dlo), min(shi, dhi)
                    if lo >= hi:
                        continue
                    out.append(
                        Transfer(
                            index=0,
                            edge=f"s{b}r{r}->s{b + 1}r{q}",
                            src_stage=b,
                            dst_stage=b + 1,
                            src_rank=r,
                            dst_rank=q,
                            src_microbatch=i,
                            dst_microbatch=j,
                            rows=(lo, hi),
                            src_rows=(lo - slo, hi - slo),
                            dst_rows=(lo - dlo, hi - dlo),
                        )
                    )
    out.sort(key=lambda t: t.rows)
    return tuple(replace(t, index=k) for k, t in enumerate(out))


def warmup_microbatches(spec: PipelineSpec, s: int) -> int:
    """1F1B warmup depth for stage ``s``, in *its own* microbatches.

    The homogeneous rule (inject ``S-1-s`` microbatches before the
    steady state) assumes every stage chunks the batch identically.
    With per-stage microbatch counts the correct measure is rows: a
    stage must keep enough rows in flight to fill the downstream
    stages' first forward each — ``sum_{t>s} global_batch/m_t`` rows —
    and converts that to its own microbatch granularity, rounding up.
    Reduces to ``S-1-s`` when all counts are equal; caps at ``m_s``.
    """
    stages = spec.stages
    if not (0 <= s < len(stages)):
        raise ValueError(f"no stage {s}")
    if s == len(stages) - 1:
        return 0
    downstream_rows = sum(
        spec.global_batch // stages[t].microbatches
        for t in range(s + 1, len(stages))
    )
    own_rows = spec.global_batch // stages[s].microbatches
    return min(stages[s].microbatches, math.ceil(downstream_rows / own_rows))


def replace_pipeline(spec: PipelineSpec, dead_slots) -> tuple:
    """Shrink the pipeline onto the surviving slots.

    Returns ``(new_spec, slot_map)`` where ``slot_map`` maps every
    surviving old global slot to its new global slot (stage order and
    surviving-rank order are preserved, so a rank's checkpoint shards
    stay attributable). Raises :class:`StageQuorumError` when any
    stage's survivors fall below its ``min_world``, and ``ValueError``
    when the surviving dp no longer divides the stage's microbatch rows
    (the spec validation re-runs on construction).
    """
    dead = set(dead_slots)
    unknown = dead - set(range(spec.total_slots))
    if unknown:
        raise ValueError(f"unknown slots {sorted(unknown)}")
    new_stages = []
    slot_map = {}
    new_slot = 0
    for s, st in enumerate(spec.stages):
        survivors = [r for r in spec.stage_slots(s) if r not in dead]
        if len(survivors) < st.min_world:
            raise StageQuorumError(
                f"stage {st.name}: {len(survivors)} survivors < "
                f"min_world={st.min_world}"
            )
        new_stages.append(replace(st, dp=len(survivors)))
        for old in survivors:
            slot_map[old] = new_slot
            new_slot += 1
    return (
        PipelineSpec(
            stages=tuple(new_stages),
            global_batch=spec.global_batch,
            serve=spec.serve,
        ),
        slot_map,
    )


def drain_order(spec: PipelineSpec, dead_slots) -> tuple:
    """Canonical drain order after a membership event: deepest stage
    first (it holds the fewest in-flight microbatches and its exit
    unblocks the upstream wire), ranks ascending within a stage,
    victims excluded. The fixture replay and the drill's event log both
    emit drains in exactly this order, which is what makes the logs
    byte-deterministic."""
    dead = set(dead_slots)
    out = []
    for s in reversed(range(len(spec.stages))):
        for slot in spec.stage_slots(s):
            if slot not in dead:
                out.append((s, slot - spec.stage_slots(s).start))
    return tuple(out)
