"""Per-stage programs and the heterogeneous 1F1B host loop.

Where the SPMD pipeline engine (``tpudml/parallel/pp.py``) expresses
1F1B as one jitted scan over stacked stage weights — every process
running the same program — the MPMD runtime gives each stage its *own*
jitted programs and drives the schedule as a host loop
(:class:`StageWorker`): forward and backward are per-microbatch jits,
activations and cotangents cross stage boundaries as host arrays over
the ``comm/p2p`` wire, and the only intra-stage collective is the
step-end gradient allreduce over the stage's data axis.

Precision contract (what "a bf16 stage feeding an f32 head" means):

- parameters are **f32 master copies** everywhere; a stage casts them
  (and its input) to its compute ``dtype`` at program entry, so the
  cast's VJP returns parameter gradients in f32.
- the wire carries activations in the *producer's* dtype and
  cotangents in that same dtype (the consumer's entry cast has an
  ``astype`` VJP, so its input gradient lands in the producer's dtype
  with no explicit conversion code).
- the head's per-microbatch loss contribution is ``sum(row CE) /
  global_batch`` — a *local, exact* share of the global mean loss, so
  cotangents need no cross-stage rescaling and gradients accumulate as
  plain sums: microbatch sums on each rank, then one SUM allreduce
  over the stage group (:class:`GroupReducer`). This is what makes a
  2-stage×2-dp MPMD step mathematically identical to the
  single-program reference (:func:`reference_step_fn`) up to f32
  summation order.

The worker is deliberately runnable two ways: spawned children
(``mpmd/drill.py``, real gloo worlds) and in-process threads over
``socketpair`` channels (the grad-parity tests) — same code path, only
the channel construction and the reducer's world differ.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from tpudml.comm.p2p import TAG_ACT, TAG_GRAD, PeerDeadError, _resolve_dtype
from tpudml.mpmd.spec import PipelineSpec, boundary_plan, warmup_microbatches

__all__ = [
    "DrainSignal",
    "StageProgram",
    "GroupReducer",
    "StageWorker",
    "stage_layer_dims",
    "init_stage_params",
    "make_batch_fn",
    "reference_step_fn",
]


class DrainSignal(Exception):
    """A peer (or the group's drain barrier) declared this step dead:
    discard in-flight microbatches, do not touch params, exit the step
    loop cleanly. Carries the step it fired at."""

    def __init__(self, step: int, why: str):
        super().__init__(f"drain at step {step}: {why}")
        self.step = step
        self.why = why


# ------------------------------------------------------------ the model


def stage_layer_dims(feature_dim: int, hidden, classes: int,
                     n_stages: int) -> list:
    """Split the MLP's layer chain ``[feature] + hidden + [classes]``
    contiguously across ``n_stages``: each stage gets a list of
    ``(d_in, d_out)`` pairs; the last stage owns the logits layer."""
    dims = [feature_dim, *hidden, classes]
    n_layers = len(dims) - 1
    if n_layers < n_stages:
        raise ValueError(
            f"{n_layers} layers cannot split over {n_stages} stages"
        )
    splits = np.array_split(np.arange(n_layers), n_stages)
    return [
        [(dims[l], dims[l + 1]) for l in part] for part in splits
    ]


def init_stage_params(stage: int, n_stages: int, feature_dim: int, hidden,
                      classes: int, seed: int) -> list:
    """Deterministic f32 host-numpy init, seeded per *global* layer
    index — so the per-stage trees concatenate to exactly the params
    the single-program reference initializes."""
    splits = np.array_split(
        np.arange(len([feature_dim, *hidden, classes]) - 1), n_stages
    )
    dims = stage_layer_dims(feature_dim, hidden, classes, n_stages)[stage]
    out = []
    for l, (din, dout) in zip(splits[stage], dims):
        rng = np.random.default_rng(seed * 7919 + int(l))
        out.append({
            "w": (rng.standard_normal((din, dout)) / math.sqrt(din)).astype(
                np.float32
            ),
            "b": np.zeros((dout,), np.float32),
        })
    return out


def make_batch_fn(global_batch: int, feature_dim: int, classes: int,
                  seed: int):
    """Teacher-labeled batches as a pure function of the step index —
    the elastic drill's replayability contract (any incarnation at any
    world sees the same global rows for step k)."""
    teacher = (
        np.random.default_rng(seed + 777)
        .standard_normal((feature_dim, classes))
        .astype(np.float32)
    )

    def batch_for(step: int):
        rng = np.random.default_rng(seed * 1_000_003 + step)
        x = rng.standard_normal((global_batch, feature_dim)).astype(np.float32)
        y = np.argmax(x @ teacher, axis=1).astype(np.int32)
        return x, y

    return batch_for


class StageProgram:
    """One stage's jitted programs: forward, recompute-backward, and —
    for the head stage — the fused loss/gradient program. Parameters
    stay f32; the entry casts define the precision boundary."""

    def __init__(self, spec: PipelineSpec, stage: int, *, feature_dim: int,
                 hidden, classes: int, seed: int, lr: float, momentum: float):
        import jax
        import jax.numpy as jnp

        self.spec = spec
        self.stage = stage
        self.is_first = stage == 0
        self.is_head = stage == len(spec.stages) - 1
        self.dtype = jnp.dtype(spec.stages[stage].dtype)
        self.params = init_stage_params(
            stage, len(spec.stages), feature_dim, hidden, classes, seed
        )
        self.momentum = jax.tree.map(np.zeros_like, self.params)
        self.out_features = stage_layer_dims(
            feature_dim, hidden, classes, len(spec.stages)
        )[stage][-1][1]
        dtype = self.dtype
        head = self.is_head
        gb = spec.global_batch

        def apply(p, h):
            h = h.astype(dtype)
            last = len(p) - 1
            for i, layer in enumerate(p):
                h = h @ layer["w"].astype(dtype) + layer["b"].astype(dtype)
                if not (head and i == last):
                    h = jax.nn.relu(h)
            return h

        def loss_contrib(p, a, y):
            logits = apply(p, a).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            rows = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
            return rows.sum() / gb

        self._fwd = jax.jit(apply)

        def bwd(p, x, ct):
            _, vjp = jax.vjp(lambda pp, xx: apply(pp, xx), p, x)
            gp, gx = vjp(ct)
            return gp, gx

        self._bwd = jax.jit(bwd)
        self._loss_bwd = jax.jit(
            jax.value_and_grad(loss_contrib, argnums=(0, 1))
        )

        def update(p, m, g):
            new_m = jax.tree.map(
                lambda mm, gg: momentum * mm + gg, m, g
            )
            new_p = jax.tree.map(lambda pp, mm: pp - lr * mm, p, new_m)
            return new_p, new_m

        self._update = jax.jit(update)

    def fwd(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._fwd(self.params, x))

    def bwd(self, x: np.ndarray, ct: np.ndarray):
        gp, gx = self._bwd(self.params, x, ct)
        return gp, np.asarray(gx)

    def loss_bwd(self, a: np.ndarray, y: np.ndarray):
        loss, (gp, ga) = self._loss_bwd(self.params, a, y)
        return float(loss), gp, np.asarray(ga)

    def apply_update(self, grads) -> None:
        import jax

        p, m = self._update(self.params, self.momentum, grads)
        self.params = jax.tree.map(np.asarray, p)
        self.momentum = jax.tree.map(np.asarray, m)


class GroupReducer:
    """SUM-allreduce of host-numpy trees over the stage's data axis.

    The grads live on the host (they fall out of per-microbatch jits),
    so the cross-process reduction is expressed by *stacking over the
    data axis*: each process contributes its tree as one row of a
    ``("data",)``-sharded global array and a tiny jitted ``sum(0)``
    makes XLA (gloo-backed across processes) perform the allreduce.
    World 1 short-circuits to identity — the in-process parity tests
    never touch ``jax.distributed``.
    """

    def __init__(self, dp: int):
        self.dp = int(dp)
        if self.dp > 1:
            import jax
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            devs = np.asarray(jax.devices()[: self.dp])
            if devs.size < self.dp:
                raise ValueError(
                    f"GroupReducer: {devs.size} devices < dp {self.dp}"
                )
            self._mesh = Mesh(devs, ("data",))
            self._sharded = NamedSharding(self._mesh, P("data"))
            self._sum = jax.jit(
                lambda t: jax.tree.map(lambda a: a.sum(0), t),
                out_shardings=NamedSharding(self._mesh, P()),
            )

    def sum(self, tree):
        if self.dp == 1:
            return tree
        import jax

        def lift(a):
            a = np.ascontiguousarray(np.asarray(a))
            return jax.make_array_from_callback(
                (self.dp, *a.shape), self._sharded, lambda idx, v=a: v[None]
            )

        out = self._sum(jax.tree.map(lift, tree))
        return jax.tree.map(
            lambda d: np.asarray(d.addressable_data(0)), out
        )


@dataclass
class _BoundaryIO:
    """This rank's slice of one boundary plan, grouped by the microbatch
    index on this rank's side."""

    by_mb: dict = field(default_factory=dict)

    @classmethod
    def build(cls, transfers, *, key) -> "_BoundaryIO":
        out = cls()
        for t in transfers:
            out.by_mb.setdefault(key(t), []).append(t)
        for lst in out.by_mb.values():
            lst.sort(key=lambda t: t.index)
        return out


class StageWorker:
    """One rank of one stage: runs the heterogeneous 1F1B schedule.

    Per step: ``warmup_microbatches`` forwards, then strict
    forward/backward alternation, then the backward tail; then the
    group drain vote (:class:`~tpudml.comm.p2p.DrainBarrier`), and only
    on a unanimous ``ok`` the gradient SUM-allreduce and the replicated
    SGD+momentum update. Any :class:`~tpudml.comm.p2p.PeerDeadError`
    (or a ``drain`` verdict) raises :class:`DrainSignal` — parameters
    are left at the last completed step, which is exactly the state the
    checkpoint protocol resumes from.
    """

    def __init__(self, spec: PipelineSpec, stage: int, rank: int, *,
                 program: StageProgram, batch_for,
                 up_features: int | None = None,
                 up_channels: dict | None = None,
                 down_channels: dict | None = None,
                 barrier=None, reducer: GroupReducer | None = None):
        self.spec = spec
        self.stage = stage
        self.rank = rank
        self.program = program
        self.batch_for = batch_for
        self.up = dict(up_channels or {})      # edge -> Channel (to stage-1)
        self.down = dict(down_channels or {})  # edge -> Channel (to stage+1)
        self.barrier = barrier
        self.reducer = reducer or GroupReducer(1)
        st = spec.stages[stage]
        self.m = st.microbatches
        self.warmup = warmup_microbatches(spec, stage)
        self.in_plan = None
        self.out_plan = None
        if stage > 0:
            self.in_plan = _BoundaryIO.build(
                [t for t in boundary_plan(spec, stage - 1)
                 if t.dst_rank == rank],
                key=lambda t: t.dst_microbatch,
            )
            self.up_dtype = _resolve_dtype(spec.stages[stage - 1].dtype)
            if up_features is None:
                raise ValueError(
                    "non-first stages need up_features (the upstream "
                    "stage's output width)"
                )
            self._up_features = int(up_features)
        if stage < len(spec.stages) - 1:
            self.out_plan = _BoundaryIO.build(
                [t for t in boundary_plan(spec, stage)
                 if t.src_rank == rank],
                key=lambda t: t.src_microbatch,
            )
        self.rows = spec.rows_per_rank(stage)
        self.losses: list = []

    # ------------------------------------------------------- microbatch

    def _input_for(self, step: int, mb: int) -> np.ndarray:
        if self.stage == 0:
            x, _ = self.batch_for(step)
            lo, hi = self.spec.row_interval(0, mb, self.rank)
            return x[lo:hi]
        arr = np.zeros((self.rows, self._up_features), self.up_dtype)
        for t in self.in_plan.by_mb.get(mb, []):
            chunk = self.up[t.edge].recv(
                step=step, microbatch=t.index, tag=TAG_ACT
            )
            arr[t.dst_rows[0]: t.dst_rows[1]] = chunk
        return arr

    def _labels_for(self, step: int, mb: int) -> np.ndarray:
        _, y = self.batch_for(step)
        lo, hi = self.spec.row_interval(self.stage, mb, self.rank)
        return y[lo:hi]

    def _forward(self, step: int, mb: int, stash: dict) -> None:
        x = self._input_for(step, mb)
        stash[mb] = x
        if self.program.is_head:
            return  # the head's forward is fused into its loss program
        act = self.program.fwd(x)
        for t in self.out_plan.by_mb.get(mb, []):
            self.down[t.edge].send(
                act[t.src_rows[0]: t.src_rows[1]],
                step=step, microbatch=t.index, tag=TAG_ACT,
            )

    def _send_up(self, step: int, mb: int, gx: np.ndarray) -> None:
        for t in self.in_plan.by_mb.get(mb, []):
            self.up[t.edge].send(
                gx[t.dst_rows[0]: t.dst_rows[1]],
                step=step, microbatch=t.index, tag=TAG_GRAD,
            )

    def _backward(self, step: int, mb: int, stash: dict, acc: dict) -> None:
        import jax

        x = stash.pop(mb)
        if self.program.is_head:
            loss, gp, ga = self.program.loss_bwd(x, self._labels_for(step, mb))
            acc["loss"] += loss
            if self.stage > 0:
                self._send_up(step, mb, ga)
        else:
            ct = np.zeros(
                (self.rows, self.program.out_features), self.program.dtype
            )
            for t in self.out_plan.by_mb.get(mb, []):
                chunk = self.down[t.edge].recv(
                    step=step, microbatch=t.index, tag=TAG_GRAD
                )
                ct[t.src_rows[0]: t.src_rows[1]] = chunk
            gp, _gx = self.program.bwd(x, ct)
            if self.stage > 0:
                self._send_up(step, mb, _gx)
        acc["g"] = (
            gp if acc["g"] is None
            else jax.tree.map(np.add, acc["g"], jax.tree.map(np.asarray, gp))
        )

    # -------------------------------------------------------------- step

    def run_step(self, step: int) -> float:
        """One full 1F1B step; returns the stage-group global loss (the
        head stage's mean CE; NaN elsewhere). Raises
        :class:`DrainSignal` instead of touching params on any peer
        death or drain verdict."""
        import jax

        stash: dict = {}
        acc = {"g": None, "loss": 0.0}
        w, m = self.warmup, self.m
        try:
            for k in range(w):
                self._forward(step, k, stash)
            for i in range(m - w):
                self._forward(step, w + i, stash)
                self._backward(step, i, stash, acc)
            for i in range(m - w, m):
                self._backward(step, i, stash, acc)
        except PeerDeadError as e:
            if self.barrier is not None:
                self.barrier.vote(step, ok=False)
            raise DrainSignal(step, f"peer dead on edge {e.edge}") from e
        if self.barrier is not None and not self.barrier.vote(step, ok=True):
            raise DrainSignal(step, "group drain verdict")
        acc["g"] = jax.tree.map(np.asarray, acc["g"])
        reduced = self.reducer.sum(
            {"g": acc["g"], "loss": np.float32(acc["loss"])}
        )
        self.program.apply_update(reduced["g"])
        loss = (
            float(reduced["loss"]) if self.program.is_head else float("nan")
        )
        self.losses.append(np.float32(loss if loss == loss else 0.0))
        return loss


# ---------------------------------------------- single-program reference


def reference_step_fn(spec: PipelineSpec, *, feature_dim: int, hidden,
                      classes: int, seed: int, lr: float, momentum: float):
    """The *equivalent single-program* the heterogeneity test compares
    against: one jitted step applying every stage's program with the
    SAME per-stage chunking and the SAME entry casts made explicit —
    the trunk runs per trunk-microbatch chunk and concatenates, the
    head sums per head-microbatch loss contributions — so autodiff
    reproduces the identical per-chunk low-precision roundings and the
    remaining difference to the MPMD run is f32 summation order.

    Returns ``(params, step_fn)`` where ``step_fn(params, mom, x, y) ->
    (params, mom, loss, grads)``.
    """
    import jax
    import jax.numpy as jnp

    n = len(spec.stages)
    programs = [
        StageProgram(spec, s, feature_dim=feature_dim, hidden=hidden,
                     classes=classes, seed=seed, lr=lr, momentum=momentum)
        for s in range(n)
    ]
    params = [p.params for p in programs]
    mom = [p.momentum for p in programs]
    dtypes = [jnp.dtype(st.dtype) for st in spec.stages]
    gb = spec.global_batch

    def apply_stage(s, p, h):
        h = h.astype(dtypes[s])
        last = len(p) - 1
        is_head = s == n - 1
        for i, layer in enumerate(p):
            h = h @ layer["w"].astype(dtypes[s]) + layer["b"].astype(dtypes[s])
            if not (is_head and i == last):
                h = jax.nn.relu(h)
        return h

    def loss_fn(all_params, x, y):
        h = x
        for s in range(n - 1):
            mchunks = jnp.split(h, spec.stages[s].microbatches, axis=0)
            h = jnp.concatenate(
                [apply_stage(s, all_params[s], c) for c in mchunks], axis=0
            )
        head = n - 1
        hchunks = jnp.split(h, spec.stages[head].microbatches, axis=0)
        ychunks = jnp.split(y, spec.stages[head].microbatches, axis=0)
        loss = 0.0
        for c, yc in zip(hchunks, ychunks):
            logits = apply_stage(head, all_params[head], c).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            loss = loss + (
                -jnp.take_along_axis(logp, yc[:, None], axis=-1)[:, 0]
            ).sum() / gb
        return loss

    @jax.jit
    def step_fn(all_params, all_mom, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(all_params, x, y)
        new_mom = jax.tree.map(
            lambda mm, gg: momentum * mm + gg, all_mom, grads
        )
        new_params = jax.tree.map(
            lambda pp, mm: pp - lr * mm, all_params, new_mom
        )
        return new_params, new_mom, loss, grads

    return params, mom, step_fn
