"""CLI: ``python -m tpudml.mpmd`` — the MPMD drills.

- re-mesh drill (SIGKILL one stage rank → drain → fail-open re-plan →
  re-form in place → bit-exact resume vs an uninterrupted reference of
  the re-meshed pipeline; exit 0 iff the verdict holds)::

    JAX_PLATFORMS=cpu python -m tpudml.mpmd --drill

- with ``--naive``: also run the whole-world-restart A/B arm (peers
  abort on peer death instead of draining, so every group's containment
  fires) and compare MTTRs;

- fixture replay (meshless CI mode: no processes, no sockets, no jax —
  replays a recorded membership/transfer event stream and checks the
  byte-deterministic event log's CRC against the fixture's golden)::

    python -m tpudml.mpmd --fixture tests/mpmd_fixtures/shrink_stage.json

The last stdout line is always the JSON report; the event stream /
child output goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpudml.mpmd")
    p.add_argument("--drill", action="store_true",
                   help="run the 2-stage×2-dp re-mesh drill; exit 0 iff "
                        "the resumed pipeline is CRC-identical to an "
                        "uninterrupted reference")
    p.add_argument("--fixture", type=str, default=None,
                   help="replay a recorded membership/transfer event "
                        "fixture — no processes, no mesh")
    p.add_argument("--naive", action="store_true",
                   help="with --drill: also run the whole-world-restart "
                        "A/B arm and compare MTTRs")
    p.add_argument("--dir", type=str, default=None,
                   help="drill working dir (default: a fresh temp dir)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--ckpt_every", type=int, default=5)
    p.add_argument("--kill_step", type=int, default=13)
    p.add_argument("--kill_stage", type=int, default=1)
    p.add_argument("--kill_rank", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--backoff_s", type=float, default=0.25)
    p.add_argument("--timeout_s", type=float, default=600.0)
    args = p.parse_args(argv)

    if args.fixture:
        from tpudml.mpmd.fixture import replay_fixture

        report = replay_fixture(
            args.fixture,
            emit=lambda line: print(f"[replay] {line}", file=sys.stderr),
        )
        report.pop("lines", None)
        print(json.dumps(report, sort_keys=True))
        return 0 if report["ok"] else 1

    if args.drill:
        from tpudml.mpmd.drill import run_mpmd_drill

        base = args.dir or tempfile.mkdtemp(prefix="tpudml_mpmd_")
        report = run_mpmd_drill(
            base,
            steps=args.steps,
            ckpt_every=args.ckpt_every,
            kill_step=args.kill_step,
            kill_stage=args.kill_stage,
            kill_rank=args.kill_rank,
            seed=args.seed,
            backoff_s=args.backoff_s,
            timeout_s=args.timeout_s,
            include_naive=args.naive,
            sink=sys.stderr,
        )
        print(json.dumps(report, sort_keys=True))
        return 0 if report["ok"] else 1

    p.error("one of --drill / --fixture is required")
    return 2


if __name__ == "__main__":
    sys.exit(main())
