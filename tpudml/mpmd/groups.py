"""MPMD stage-group controller: form, supervise, re-mesh in place.

The elastic controller (``tpudml/elastic/controller.py``) supervises
ONE gloo world. :class:`MPMDController` generalizes it to a *fleet of
worlds*: each pipeline stage is its own process group with its own
coordinator rendezvous, spawned via the launcher's single-attempt core
(:func:`tpudml.launch.launcher.launch_once`) in one thread per stage.
The same membership machinery drives formation and teardown:

- **fresh ports per round** — every incarnation reserves, by
  bind-and-hold, one coordinator port per stage plus the p2p boundary
  listener ports and the intra-stage ctl (drain barrier) ports, all
  guaranteed never-reused within the job (the elastic controller's
  zombie-rendezvous defense, per stage);
- **wiring file** — the round's full topology (stages, slots, boundary
  listeners, ctl hubs) is written as ``wiring_r{N}.json`` before
  spawning; children read it instead of guessing peers;
- **drain classification** — a SIGKILLed rank exits non-zero and its
  group's containment SIGTERMs the group; every *surviving* rank (in
  any group) drains at a step boundary, writes a
  ``drain_s{S}_r{R}.json`` marker into the round dir and exits 0 — so
  the victim is always the unique rank with a non-zero rc, and drained
  ranks are never mistaken for failures;
- **pre-launch protocol gate** — before any round spawns, the static
  cross-rank protocol checker (``tpudml/analysis/protocol.py``) runs
  over the round's ``PipelineSpec`` — the initial spec and every
  ``replace_pipeline`` result alike. Error-severity findings (P300
  boundary asymmetry, P301 wait-for cycles, P302 collective-sequence
  divergence) refuse the launch with machine-readable receipts
  (``protocol_report.json`` in the run dir, ``stop_reason=
  "protocol_rejected"``) instead of a hung drill burning its timeout;
- **re-mesh in place** — the PR 16 ``Replanner`` is consulted
  fail-open at the surviving world, the pipeline shrinks via
  :func:`~tpudml.mpmd.spec.replace_pipeline` (``StageQuorumError``
  stops the job, the ``min_world``-per-stage quorum), the common
  resume step is computed from the per-stage checkpoint directories
  (newest step present and manifest-complete in EVERY stage dir —
  a jax-free scan; children do the CRC-verified restore), and the
  surviving groups re-form on fresh ports — no whole-world restart.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import re
import socket
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from tpudml.launch.cluster import ClusterSpec
from tpudml.launch.launcher import launch_once, restart_backoff
from tpudml.mpmd.spec import PipelineSpec, StageQuorumError, replace_pipeline

#: Env contract for MPMD children, alongside the launcher's TPUDML_*
#: rendezvous variables (which are per-STAGE here: TPUDML_PROCESS_ID is
#: the stage-local rank).
ROUND_ENV = "TPUDML_MPMD_ROUND"
STAGE_ENV = "TPUDML_MPMD_STAGE"

WIRING_VERSION = 1

_STEP_RE = re.compile(r"^step_(\d+)$")


def write_wiring(path: Path, *, round_no: int, pipeline: PipelineSpec,
                 coordinator_ports: list, boundary_ports: dict,
                 ctl_ports: dict, host: str = "127.0.0.1") -> dict:
    """The round's topology document. ``boundary_ports`` maps boundary
    index -> {dst_rank: port} (the downstream rank listens, the upstream
    rank dials); ``ctl_ports`` maps stage index -> hub port (stage-local
    rank 0 listens) for every dp>1 stage."""
    doc = {
        "version": WIRING_VERSION,
        "round": round_no,
        "host": host,
        "pipeline": pipeline.to_dict(),
        "coordinator_ports": [int(p) for p in coordinator_ports],
        "boundaries": [
            {
                "from": b,
                "to": b + 1,
                "listeners": {
                    str(q): {"host": host, "port": int(p)}
                    for q, p in sorted(boundary_ports[b].items())
                },
            }
            for b in sorted(boundary_ports)
        ],
        "ctl": {
            str(s): {"host": host, "port": int(p)}
            for s, p in sorted(ctl_ports.items())
        },
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    return doc


def drain_marker_path(round_dir: Path, stage: int, rank: int) -> Path:
    return Path(round_dir) / f"drain_s{stage}_r{rank}.json"


def read_drain_markers(round_dir: Path) -> dict:
    """(stage, rank) -> marker dict for every drain marker in the round
    dir. Tolerant of torn writes (a SIGTERM handler wrote them)."""
    out = {}
    round_dir = Path(round_dir)
    if not round_dir.is_dir():
        return out
    for p in sorted(round_dir.glob("drain_s*_r*.json")):
        m = re.match(r"drain_s(\d+)_r(\d+)\.json$", p.name)
        if not m:
            continue
        try:
            out[(int(m.group(1)), int(m.group(2)))] = json.loads(p.read_text())
        except (OSError, ValueError):
            out[(int(m.group(1)), int(m.group(2)))] = {}
    return out


def stage_ckpt_dir(ckpt_dir, stage: int) -> Path:
    return Path(ckpt_dir) / f"stage{stage}"


def _complete_steps(stage_dir: Path) -> set:
    """Steps under one stage's checkpoint dir whose manifest set is
    complete (every process manifest the writers declared is present).
    Pure filesystem + JSON — no jax, usable from the controller."""
    steps = set()
    if not stage_dir.is_dir():
        return steps
    for name in os.listdir(stage_dir):
        m = _STEP_RE.match(name)
        if not m:
            continue
        path = stage_dir / name
        manifests = sorted(p for p in os.listdir(path)
                           if p.startswith("manifest_p"))
        if not manifests:
            continue
        try:
            expect = int(
                json.loads((path / manifests[0]).read_text())["num_processes"]
            )
        except (OSError, ValueError, KeyError, TypeError):
            continue
        if len(manifests) == expect:
            steps.add(int(m.group(1)))
    return steps


def common_resume_step(ckpt_dir, n_stages: int) -> int:
    """Newest step checkpointed by EVERY stage (0 = fresh start). The
    stages checkpoint independently, so after a mid-step kill their
    newest steps can disagree; resuming anywhere but the intersection
    would desynchronize the pipeline's replayed trajectory."""
    common = None
    for s in range(n_stages):
        steps = _complete_steps(stage_ckpt_dir(ckpt_dir, s))
        common = steps if common is None else (common & steps)
        if not common:
            return 0
    return max(common) if common else 0


@dataclass
class StageRound:
    """One stage group's outcome within one round."""

    stage: int
    world: int
    coordinator_port: int
    returncodes: list
    failed_rank: int | None
    timed_out: bool
    elapsed_s: float


@dataclass
class MPMDReformRecord:
    """One incarnation of the whole pipeline (round 0 = first form)."""

    round: int
    pipeline: dict
    stage_worlds: list
    coordinator_ports: list
    stages: list  # list[StageRound as dict]
    victim: dict | None  # {stage, rank, slot, rc} for the failed rank
    drained: list  # [(stage, rank), ...] markers observed
    resume_step: int
    backoff_s: float
    elapsed_s: float
    t_start: float
    t_end: float

    @property
    def success(self) -> bool:
        return all(
            not s["timed_out"] and all(rc == 0 for rc in s["returncodes"])
            for s in self.stages
        ) and not self.drained

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclass
class MPMDResult:
    records: list = field(default_factory=list)
    replans: list = field(default_factory=list)
    protocol: list = field(default_factory=list)  # per-round gate receipts
    success: bool = False
    total_elapsed_s: float = 0.0
    stop_reason: str = ""

    @property
    def reforms(self) -> int:
        return max(0, len(self.records) - 1)

    @property
    def final_stage_worlds(self) -> list:
        return self.records[-1].stage_worlds if self.records else []

    def to_dict(self) -> dict:
        return {
            "records": [r.to_dict() for r in self.records],
            "replans": [dict(r) for r in self.replans],
            "protocol": [dict(r) for r in self.protocol],
            "success": self.success,
            "total_elapsed_s": self.total_elapsed_s,
            "stop_reason": self.stop_reason,
            "reforms": self.reforms,
            "final_stage_worlds": self.final_stage_worlds,
        }


class _Tee:
    def __init__(self, *sinks):
        self.sinks = [s for s in sinks if s is not None]
        self._lock = threading.Lock()

    def write(self, s):
        with self._lock:
            for k in self.sinks:
                k.write(s)
        return len(s)

    def flush(self):
        for k in self.sinks:
            k.flush()


class MPMDController:
    """Supervise an MPMD pipeline across rank death with in-place
    re-meshes.

    ``cmd`` is the per-rank child argv template (typically
    ``python -m tpudml.mpmd.drill ...``); the controller appends
    ``--stage S --wiring FILE --round_dir DIR --resume_step N`` per
    stage per round. ``spec`` supplies the per-stage ClusterSpec
    template (timeouts, grace, backoff seed); ``num_processes`` and
    ``coordinator_port`` are overwritten per stage. ``replanner`` is
    duck-typed exactly like the elastic controller's (fail-open: a
    replanner exception is recorded, never fatal).
    """

    def __init__(self, cmd, pipeline: PipelineSpec,
                 spec: ClusterSpec | None = None, *,
                 run_dir, ckpt_dir, max_reforms: int = 2,
                 replanner=None, victim_rc: int | None = None, sink=None,
                 protocol_checker=None):
        self.cmd = list(cmd)
        self.pipeline = pipeline
        self.spec = (dataclasses.replace(spec) if spec is not None
                     else ClusterSpec())
        self.run_dir = Path(run_dir)
        self.ckpt_dir = Path(ckpt_dir)
        self.max_reforms = max_reforms
        self.replanner = replanner
        # When peers die loudly instead of draining (the naive
        # whole-world-restart arm aborts rc 75 on peer death), "first
        # failed rank" is ambiguous: victim_rc pins attribution to the
        # fault injector's exit code.
        self.victim_rc = victim_rc
        self.sink = sink
        # PipelineSpec -> list[Finding]; defaults to the static protocol
        # analyzer. Injectable so tests can force a rejection without
        # constructing a genuinely broken (hence unconstructible) spec.
        self.protocol_checker = protocol_checker

    # ---------------------------------------------------- protocol gate

    def _check_protocol(self, pipeline: PipelineSpec, rnd: int,
                        res: MPMDResult) -> bool:
        """Run the cross-rank protocol checker on the spec about to be
        spawned; append the receipt (clean or not) and keep the run
        dir's ``protocol_report.json`` current. Returns False — refuse
        to launch — on any error-severity finding."""
        checker = self.protocol_checker
        if checker is None:
            from tpudml.analysis.protocol import analyze_pipeline

            def checker(p):
                return analyze_pipeline(p, entrypoint=f"round{rnd}")
        findings = checker(pipeline)
        errors = [f for f in findings
                  if getattr(f, "severity", "error") == "error"]
        res.protocol.append({
            "round": rnd,
            "pipeline": pipeline.to_dict(),
            "ok": not errors,
            "findings": [
                {
                    "rule": f.rule,
                    "severity": f.severity,
                    "message": f.message,
                    "file": f.file,
                    "line": f.line,
                    "entrypoint": f.entrypoint,
                }
                for f in findings
            ],
        })
        report = {
            "version": 1,
            "ok": all(r["ok"] for r in res.protocol),
            "checks": res.protocol,
        }
        (self.run_dir / "protocol_report.json").write_text(
            json.dumps(report, indent=2, sort_keys=True) + "\n")
        return not errors

    # ------------------------------------------------------------- ports

    def _reserve(self, used: set):
        """Bind-and-hold a never-used port: ``(sock, port)`` — the
        elastic controller's reservation discipline, shared by the
        coordinator, boundary and ctl ports alike."""
        for _ in range(128):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.bind((self.spec.coordinator_host, 0))
            except OSError:
                s.close()
                continue
            port = s.getsockname()[1]
            if port in used:
                s.close()
                continue
            used.add(port)
            return s, port
        raise RuntimeError("could not reserve a fresh port")

    def _round_ports(self, pipeline: PipelineSpec, used: set):
        """All port reservations for one round: per-stage coordinator,
        per-boundary per-dst-rank p2p listener, per-dp>1-stage ctl hub.
        Returns (reservations, coord_ports, boundary_ports, ctl_ports)."""
        holds = []
        coord = []
        for _ in pipeline.stages:
            s, p = self._reserve(used)
            holds.append(s)
            coord.append(p)
        boundary: dict = {}
        for b in range(len(pipeline.stages) - 1):
            boundary[b] = {}
            for q in range(pipeline.stages[b + 1].dp):
                s, p = self._reserve(used)
                holds.append(s)
                boundary[b][q] = p
        ctl: dict = {}
        for si, st in enumerate(pipeline.stages):
            if st.dp > 1:
                s, p = self._reserve(used)
                holds.append(s)
                ctl[si] = p
        return holds, coord, boundary, ctl

    # --------------------------------------------------------------- run

    def run(self) -> MPMDResult:
        from tpudml.obs.tracer import get_tracer

        out = self.sink or sys.stdout
        spec = self.spec
        budget = spec.timeout_s
        pipeline = self.pipeline
        rng = random.Random(spec.restart_backoff_seed)
        used_ports: set = set()
        res = MPMDResult()
        backoff = 0.0
        # Scan the checkpoint dirs even for round 0: a controller pointed
        # at an existing per-stage checkpoint tree (the drill's reference
        # arm, an operator restart) resumes from the common step.
        resume_step = common_resume_step(self.ckpt_dir, len(pipeline.stages))
        self.run_dir.mkdir(parents=True, exist_ok=True)

        for rnd in range(self.max_reforms + 1):
            # Pre-launch gate: the initial spec AND every re-meshed spec
            # must pass the static protocol checks before any process
            # (or port reservation) is spent on them.
            if not self._check_protocol(pipeline, rnd, res):
                out.write(
                    f"[mpmd] round {rnd}: protocol checker rejected the "
                    f"pipeline spec — refusing to launch (receipts in "
                    f"protocol_report.json)\n"
                )
                out.flush()
                res.stop_reason = "protocol_rejected"
                break
            holds, coord, boundary, ctl = self._round_ports(
                pipeline, used_ports
            )
            round_dir = self.run_dir / f"round_{rnd}"
            round_dir.mkdir(parents=True, exist_ok=True)
            wiring = self.run_dir / f"wiring_r{rnd}.json"
            write_wiring(
                wiring, round_no=rnd, pipeline=pipeline,
                coordinator_ports=coord, boundary_ports=boundary,
                ctl_ports=ctl, host=spec.coordinator_host,
            )
            remaining = None if budget is None else budget - res.total_elapsed_s
            out.write(
                f"[mpmd] round {rnd}: stage worlds "
                f"{[st.dp for st in pipeline.stages]}, resume_step "
                f"{resume_step}, fresh ports {coord}\n"
            )
            out.flush()
            get_tracer().instant(
                "mpmd_form", cat="mpmd",
                args={
                    "round": rnd,
                    "stage_worlds": [st.dp for st in pipeline.stages],
                    "resume_step": resume_step,
                },
            )

            # Release every reservation at the last instant, then spawn
            # all stage groups concurrently — one launch_once per stage.
            for h in holds:
                h.close()
            results: list = [None] * len(pipeline.stages)
            threads = []
            t_start = time.time()
            for s, st in enumerate(pipeline.stages):
                stage_spec = dataclasses.replace(
                    spec,
                    num_processes=st.dp,
                    coordinator_port=coord[s],
                    timeout_s=remaining,
                    max_restarts=0,
                    env={
                        **spec.env,
                        ROUND_ENV: str(rnd),
                        STAGE_ENV: str(s),
                    },
                )
                stage_cmd = self.cmd + [
                    "--stage", str(s),
                    "--wiring", str(wiring),
                    "--round_dir", str(round_dir),
                    "--resume_step", str(resume_step),
                ]
                prefix = _StagePrefix(out, s)

                def work(i=s, c=stage_cmd, sp=stage_spec, pf=prefix):
                    results[i] = launch_once(c, sp, pf)

                t = threading.Thread(target=work, daemon=True)
                threads.append(t)
                t.start()
            for t in threads:
                t.join()
            t_end = time.time()
            elapsed = t_end - t_start
            res.total_elapsed_s += elapsed

            markers = read_drain_markers(round_dir)
            victim = None
            timed_out = any(r.timed_out for r in results)
            if self.victim_rc is not None:
                for s, r in enumerate(results):
                    for rank, rc in enumerate(r.returncodes):
                        if rc == self.victim_rc and victim is None:
                            victim = {
                                "stage": s,
                                "rank": rank,
                                "slot": pipeline.slot_of(s, rank),
                                "rc": rc,
                            }
            for s, r in enumerate(results):
                if r.failed_rank is not None and victim is None:
                    victim = {
                        "stage": s,
                        "rank": r.failed_rank,
                        "slot": pipeline.slot_of(s, r.failed_rank),
                        "rc": r.returncodes[r.failed_rank],
                    }
            rec = MPMDReformRecord(
                round=rnd,
                pipeline=pipeline.to_dict(),
                stage_worlds=[st.dp for st in pipeline.stages],
                coordinator_ports=list(coord),
                stages=[
                    dataclasses.asdict(StageRound(
                        stage=s,
                        world=pipeline.stages[s].dp,
                        coordinator_port=coord[s],
                        returncodes=list(r.returncodes),
                        failed_rank=r.failed_rank,
                        timed_out=r.timed_out,
                        elapsed_s=r.elapsed_s,
                    ))
                    for s, r in enumerate(results)
                ],
                victim=victim,
                drained=sorted(markers),
                resume_step=resume_step,
                backoff_s=backoff,
                elapsed_s=elapsed,
                t_start=t_start,
                t_end=t_end,
            )
            res.records.append(rec)

            if rec.success:
                res.success = True
                res.stop_reason = "success"
                break
            if timed_out:
                res.stop_reason = "timeout"
                break
            if rnd == self.max_reforms:
                res.stop_reason = "max_reforms"
                break
            if victim is None:
                # Drains without an attributable victim (e.g. an operator
                # SIGTERM of a whole group) — nothing to shrink on.
                res.stop_reason = "unattributable_failure"
                break

            why = (
                f"stage {victim['stage']} rank {victim['rank']} "
                f"(slot {victim['slot']}) failed rc={victim['rc']}"
            )
            # Consult the planner at the surviving world — fail-open,
            # exactly the elastic controller's contract: a replanner
            # crash is recorded and recovery proceeds on the old plan.
            surviving = pipeline.total_slots - 1
            if self.replanner is not None:
                t0 = time.time()
                try:
                    rep = self.replanner.replan(surviving, why=why)
                    rep_d = (rep.to_dict() if hasattr(rep, "to_dict")
                             else dict(rep))
                except Exception as e:
                    rep_d = {
                        "trigger": "membership",
                        "why": why,
                        "old_world": pipeline.total_slots,
                        "new_world": surviving,
                        "switched": False,
                        "receipts": [],
                        "error": f"{type(e).__name__}: {e}",
                    }
                latency = time.time() - t0
                res.total_elapsed_s += latency
                rep_d["round"] = rnd + 1
                res.replans.append(rep_d)
                if rep_d.get("error"):
                    out.write(
                        f"[mpmd] re-plan at world {surviving} failed "
                        f"({rep_d['error']}); keeping the old plan\n"
                    )
                else:
                    out.write(
                        f"[mpmd] re-plan at world {surviving}: "
                        f"{rep_d.get('old_key')} -> {rep_d.get('new_key')}"
                        + (" (switched)" if rep_d.get("switched")
                           else " (retained)") + "\n"
                    )
                out.flush()
                get_tracer().instant(
                    "mpmd_replan", cat="mpmd",
                    args={
                        "round": rnd + 1,
                        "world": surviving,
                        "switched": bool(rep_d.get("switched")),
                        "error": rep_d.get("error"),
                    },
                )
            try:
                pipeline, slot_map = replace_pipeline(
                    pipeline, {victim["slot"]}
                )
            except StageQuorumError as e:
                out.write(f"[mpmd] {why}; {e} — cannot re-form\n")
                out.flush()
                res.stop_reason = "below_stage_quorum"
                break
            except ValueError as e:
                out.write(f"[mpmd] {why}; shrink infeasible: {e}\n")
                out.flush()
                res.stop_reason = "infeasible_shrink"
                break
            resume_step = common_resume_step(
                self.ckpt_dir, len(pipeline.stages)
            )
            backoff = restart_backoff(spec, rng, rnd + 1)
            if budget is not None and res.total_elapsed_s + backoff >= budget:
                res.stop_reason = "budget_exhausted"
                break
            out.write(
                f"[mpmd] {why}; re-mesh {rnd + 1}/{self.max_reforms}: "
                f"stage worlds {rec.stage_worlds} -> "
                f"{[st.dp for st in pipeline.stages]}, resume_step "
                f"{resume_step}, fresh ports"
                + (f", {backoff:.2f}s backoff" if backoff > 0 else "")
                + "\n"
            )
            out.flush()
            get_tracer().instant(
                "mpmd_reform", cat="mpmd",
                args={
                    "round": rnd + 1,
                    "why": why,
                    "stage_worlds": [st.dp for st in pipeline.stages],
                    "resume_step": resume_step,
                    "backoff_s": backoff,
                },
            )
            if backoff > 0:
                time.sleep(backoff)
                res.total_elapsed_s += backoff
        return res


class _StagePrefix:
    """Per-stage sink wrapper: prefixes the launcher's ``[rank R]`` tags
    with the stage, so interleaved multi-gang output stays attributable
    (``[stage 1][rank 0] ...``)."""

    def __init__(self, sink, stage: int):
        self.sink = sink
        self.prefix = f"[stage {stage}]"

    def write(self, s):
        return self.sink.write(
            "".join(
                f"{self.prefix}{line}" if line.strip() else line
                for line in s.splitlines(keepends=True)
            )
        )

    def flush(self):
        self.sink.flush()
