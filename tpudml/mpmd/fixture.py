"""Meshless MPMD replay: drive the re-mesh state machine from a
recorded membership/transfer event stream — no processes, no sockets,
no jax.

The e2e drill (``mpmd/drill.py``) proves the runtime against real
SIGKILLs but costs process spawns; this replay keeps the *semantics* in
tier-1 for free. A fixture file is a JSON document::

    {
      "version": 1,
      "pipeline": {... PipelineSpec.to_dict() ...},
      "engines": ["dp", "zero1"],        # planner lattice to consult
      "bytes_per_row": 64,               # boundary payload per batch row
      "events": [
        {"type": "step", "count": 3},    # run N pipeline steps
        {"type": "checkpoint"},          # all stages checkpoint now
        {"type": "kill", "slot": 3, "why": "sigkill"},
        {"type": "step", "count": 2}
      ],
      "expect": {"events_crc32": 1234}   # optional golden
    }

Replaying emits one canonical JSON line per simulated event — group
formation (fresh deterministic ports per round), per-step boundary
transfers priced by the shared wire model (``p2p_wire_bytes``), drains
in :func:`~tpudml.mpmd.spec.drain_order`, the fail-open planner consult
(the real PR 16 :class:`~tpudml.elastic.replan.Replanner`, meshless),
and the in-place reform or quorum halt. The log is byte-deterministic:
lines are sorted-keys/compact JSON, ports are a counter, nothing reads
a clock — so its CRC-32 is a golden the committed fixtures pin.
"""

from __future__ import annotations

import json
import zlib
from pathlib import Path

from tpudml.comm.p2p import p2p_wire_bytes
from tpudml.mpmd.spec import (
    PipelineSpec,
    StageQuorumError,
    boundary_plan,
    drain_order,
    replace_pipeline,
)

FIXTURE_VERSION = 1

#: Simulated port space — purely symbolic (never bound), but laid out
#: like the controller's reservations so "fresh ports per round" is a
#: checkable property of the log.
_PORT_BASE = 51000


def canonical_event(row: dict) -> str:
    return json.dumps(row, sort_keys=True, separators=(",", ":"))


def events_crc32(lines: list) -> int:
    return zlib.crc32("\n".join(lines).encode())


class _Ports:
    def __init__(self):
        self.next = _PORT_BASE

    def take(self, n: int) -> list:
        out = list(range(self.next, self.next + n))
        self.next += n
        return out


def _round_port_layout(pipeline: PipelineSpec, ports: _Ports) -> dict:
    """The controller's per-round reservation shape, simulated: one
    coordinator per stage, one boundary listener per downstream rank,
    one ctl hub per dp>1 stage."""
    coord = ports.take(len(pipeline.stages))
    boundary = {
        b: dict(zip(range(pipeline.stages[b + 1].dp),
                    ports.take(pipeline.stages[b + 1].dp)))
        for b in range(len(pipeline.stages) - 1)
    }
    ctl = {
        s: ports.take(1)[0]
        for s, st in enumerate(pipeline.stages) if st.dp > 1
    }
    return {"coordinator": coord, "boundary": boundary, "ctl": ctl}


def replay_fixture(fixture, *, replanner=None, emit=None) -> dict:
    """Replay one fixture; returns the verdict dict.

    ``fixture`` is a path or an already-parsed dict. ``replanner``
    defaults to a fresh meshless :class:`Replanner` over the fixture's
    ``engines``; pass your own to replay against a live plan file (the
    vandalized-plan tests do). ``emit`` receives each canonical event
    line as it is produced (the CLI's ``[replay]`` stream).
    """
    if not isinstance(fixture, dict):
        fixture = json.loads(Path(fixture).read_text())
    if fixture.get("version") != FIXTURE_VERSION:
        raise ValueError(
            f"unsupported fixture version {fixture.get('version')!r} "
            f"(want {FIXTURE_VERSION})"
        )
    pipeline = PipelineSpec.from_dict(fixture["pipeline"])
    bytes_per_row = int(fixture.get("bytes_per_row", 64))
    if replanner is None:
        from tpudml.elastic.replan import Replanner

        replanner = Replanner(
            engines=fixture.get("engines"), verify=False
        )
    replanner.initial_plan(pipeline.total_slots)

    ports = _Ports()
    lines: list = []

    def record(row: dict) -> None:
        line = canonical_event(row)
        lines.append(line)
        if emit is not None:
            emit(line)

    def form(rnd: int, resume: int) -> None:
        layout = _round_port_layout(pipeline, ports)
        record({
            "event": "form",
            "round": rnd,
            "stage_worlds": [st.dp for st in pipeline.stages],
            "coordinator_ports": layout["coordinator"],
            "ctl_ports": layout["ctl"],
            "resume_step": resume,
        })

    rnd = 0
    step = 0
    last_ckpt = 0
    halted = None
    replans = 0
    form(rnd, 0)
    for ev in fixture.get("events", ()):
        if halted is not None:
            break
        kind = ev["type"]
        if kind == "step":
            for _ in range(int(ev.get("count", 1))):
                record({"event": "step", "step": step})
                for b in range(len(pipeline.stages) - 1):
                    for t in boundary_plan(pipeline, b):
                        nbytes = (t.rows[1] - t.rows[0]) * bytes_per_row
                        record({
                            "event": "transfer",
                            "step": step,
                            "index": t.index,
                            "edge": t.edge,
                            "bytes": nbytes,
                            "wire_bytes": p2p_wire_bytes(nbytes),
                        })
                step += 1
        elif kind == "checkpoint":
            last_ckpt = step
            record({"event": "checkpoint", "step": step})
        elif kind == "kill":
            slot = int(ev["slot"])
            s, r = pipeline.locate(slot)
            record({
                "event": "kill",
                "slot": slot,
                "stage": s,
                "rank": r,
                "why": ev.get("why", "sigkill"),
            })
            for ds, dr in drain_order(pipeline, {slot}):
                record({
                    "event": "drain",
                    "stage": ds,
                    "rank": dr,
                    "step": step,
                })
            surviving = pipeline.total_slots - 1
            try:
                rep = replanner.replan(
                    surviving, why=f"slot {slot} killed"
                )
                rep_d = (rep.to_dict() if hasattr(rep, "to_dict")
                         else dict(rep))
            except Exception as e:  # fail open, like the controller
                rep_d = {"switched": False, "error": f"{type(e).__name__}"}
            replans += 1
            record({
                "event": "replan",
                "world": surviving,
                "old_key": rep_d.get("old_key"),
                "new_key": rep_d.get("new_key"),
                "switched": bool(rep_d.get("switched")),
                "error": rep_d.get("error"),
            })
            try:
                pipeline, _slot_map = replace_pipeline(pipeline, {slot})
            except StageQuorumError:
                halted = "below_stage_quorum"
                record({"event": "halt", "reason": halted})
                continue
            except ValueError:
                halted = "infeasible_shrink"
                record({"event": "halt", "reason": halted})
                continue
            rnd += 1
            step = last_ckpt
            form(rnd, last_ckpt)
        else:
            raise ValueError(f"unknown fixture event type {kind!r}")

    crc = events_crc32(lines)
    expect = (fixture.get("expect") or {}).get("events_crc32")
    return {
        "ok": expect is None or crc == expect,
        "mode": "mpmd_replay",
        "events": len(lines),
        "events_crc32": crc,
        "expect_crc32": expect,
        "rounds": rnd + 1,
        "replans": replans,
        "halted": halted,
        "final_stage_worlds": [st.dp for st in pipeline.stages],
        "final_step": step,
        "lines": lines,
    }
