"""The MPMD re-mesh drill: SIGKILL one stage rank → drain → re-mesh →
bit-exact resume.

Mirrors ``tpudml/elastic/drill.py`` one level up the stack:

- :func:`child_main` — one rank of one *stage group*
  (``python -m tpudml.mpmd.drill``): reads the round's wiring file,
  forms its stage's gloo world (its own coordinator — ``jax.distributed``
  never spans stages), dials/accepts the boundary p2p channels and the
  intra-stage drain-barrier star, and runs the heterogeneous 1F1B
  schedule (:class:`~tpudml.mpmd.runtime.StageWorker`). Batches are a
  pure function of the step index, per-stage sharded CRC-verified
  checkpoints land every k steps, and a peer death drains the rank
  cleanly at the step boundary: marker file + rc 0 (so the controller's
  victim attribution stays unambiguous). ``--drain_mode abort`` is the
  *naive* arm: peer death exits rc 75 immediately, which trips every
  group's containment — the measured whole-world-restart baseline.

- :func:`run_mpmd_drill` — the e2e evidence: a 2-stage×2-dp pipeline
  (bf16 trunk with 2 microbatches feeding an f32 head with 1 — the
  heterogeneity is in the drill, not just the unit tests), one head
  rank SIGKILLed mid-training, surviving groups drain, the planner is
  consulted fail-open, the pipeline re-forms in place (trunk keeps its
  world; only the victim stage shrinks), and the continued run must be
  CRC-identical per surviving (stage, rank) to an uninterrupted
  reference run of the re-meshed configuration started from a pristine
  copy of the same checkpoint. MTTR is anchored on the kill marker's
  mtime (the failure instant) → the last rank's resume print, so the
  in-place and naive arms are compared on the same clock.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import re
import shutil
import signal
import socket as socketlib
import sys
import time
import zlib
from pathlib import Path

import numpy as np

# --------------------------------------------------------------- child


def _params_crc(tree) -> int:
    """CRC-32 over the concatenated little-endian bytes of every leaf in
    ``jax.tree.leaves`` order — the elastic drill's bit-exactness
    witness, reused verbatim."""
    import jax

    crc = 0
    for leaf in jax.tree.leaves(tree):
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(leaf)).tobytes(), crc)
    return crc


def child_main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpudml.mpmd.drill")
    ap.add_argument("--stage", type=int, required=True)
    ap.add_argument("--wiring", type=str, required=True)
    ap.add_argument("--round_dir", type=str, required=True)
    ap.add_argument("--resume_step", type=int, default=0)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--ckpt_dir", type=str, required=True)
    ap.add_argument("--ckpt_every", type=int, default=5)
    ap.add_argument("--feature_dim", type=int, default=8)
    ap.add_argument("--hidden", type=str, default="16")
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kill_step", type=int, default=-1)
    ap.add_argument("--kill_stage", type=int, default=-1)
    ap.add_argument("--kill_rank", type=int, default=1)
    ap.add_argument("--kill_marker", type=str, default=None)
    ap.add_argument("--drain_mode", type=str, default="drain",
                    choices=("drain", "abort"))
    ap.add_argument("--obs_dir", type=str, default=None)
    args = ap.parse_args(argv)

    from tpudml.checkpoint.sharded import (
        restore_sharded_checkpoint,
        save_sharded_checkpoint,
    )
    from tpudml.comm.p2p import (
        DrainBarrier,
        accept_channels,
        connect_channel,
    )
    from tpudml.core.config import DistributedConfig
    from tpudml.core.dist import distributed_init
    from tpudml.mpmd.groups import drain_marker_path, stage_ckpt_dir
    from tpudml.mpmd.runtime import (
        DrainSignal,
        GroupReducer,
        StageProgram,
        StageWorker,
        make_batch_fn,
        stage_layer_dims,
    )
    from tpudml.mpmd.spec import PipelineSpec, boundary_plan
    from tpudml.obs.tracer import Tracer, set_tracer
    from tpudml.resilience.faults import rank_kill_hook

    wiring = json.loads(Path(args.wiring).read_text())
    if wiring.get("version") != 1:
        raise SystemExit(f"unsupported wiring version {wiring.get('version')}")
    spec = PipelineSpec.from_dict(wiring["pipeline"])
    stage = args.stage
    st = spec.stages[stage]
    rank = int(os.environ.get("TPUDML_PROCESS_ID", "0"))
    round_no = int(os.environ.get("TPUDML_MPMD_ROUND", wiring["round"]))
    hidden = tuple(int(h) for h in args.hidden.split(",") if h)
    n_stages = len(spec.stages)

    if st.dp > 1:
        distributed_init(DistributedConfig.from_env())

    # The drain marker must be written even when this rank is torn down
    # by its group's containment (the victim's peers get SIGTERM before
    # they observe the death themselves): a drained rank ALWAYS exits 0
    # with a marker, so the controller can tell victims from survivors.
    # Installed after distributed_init so it wins over jax's handler.
    state = {"step": args.resume_step}

    def _drain_and_exit(signum, frame):
        try:
            drain_marker_path(args.round_dir, stage, rank).write_text(
                json.dumps({"step": state["step"], "why": "sigterm",
                            "round": round_no}) + "\n"
            )
        except OSError:
            pass
        os._exit(0)

    signal.signal(signal.SIGTERM, _drain_and_exit)

    tracer = Tracer()
    set_tracer(tracer)

    program = StageProgram(
        spec, stage, feature_dim=args.feature_dim, hidden=hidden,
        classes=args.classes, seed=args.seed, lr=args.lr,
        momentum=args.momentum,
    )
    batch_for = make_batch_fn(
        spec.global_batch, args.feature_dim, args.classes, args.seed
    )

    # Resume from the controller-designated common step: the exact-step
    # CRC-verified restore (not newest-valid — stages must agree).
    if args.resume_step > 0:
        target = {
            "mom": program.momentum,
            "params": program.params,
            "step": np.zeros((), np.int64),
        }
        restored = restore_sharded_checkpoint(
            stage_ckpt_dir(args.ckpt_dir, stage) / f"step_{args.resume_step}",
            target,
            verify=True,
        )
        program.params = restored["params"]
        program.momentum = restored["mom"]
        print(
            f"[mpmd] stage {stage} rank {rank} resumed step "
            f"{args.resume_step} wall {time.time():.3f}",
            flush=True,
        )
        tracer.instant(
            "mpmd_resume", cat="mpmd",
            args={"stage": stage, "rank": rank, "step": args.resume_step},
        )

    # ------------------------------------------------ wire the topology
    host = wiring["host"]
    deadline_s = 60.0
    listeners = []
    up_listener = None
    if stage > 0:
        b = wiring["boundaries"][stage - 1]
        port = b["listeners"][str(rank)]["port"]
        up_listener = socketlib.socket(socketlib.AF_INET,
                                       socketlib.SOCK_STREAM)
        up_listener.setsockopt(socketlib.SOL_SOCKET,
                               socketlib.SO_REUSEADDR, 1)
        up_listener.bind((host, port))
        n_up = len({
            t.src_rank for t in boundary_plan(spec, stage - 1)
            if t.dst_rank == rank
        })
        up_listener.listen(n_up)
        listeners.append(up_listener)
    ctl_listener = None
    if st.dp > 1 and rank == 0:
        port = wiring["ctl"][str(stage)]["port"]
        ctl_listener = socketlib.socket(socketlib.AF_INET,
                                        socketlib.SOCK_STREAM)
        ctl_listener.setsockopt(socketlib.SOL_SOCKET,
                                socketlib.SO_REUSEADDR, 1)
        ctl_listener.bind((host, port))
        ctl_listener.listen(st.dp - 1)
        listeners.append(ctl_listener)

    down_channels = {}
    if stage < n_stages - 1:
        b = wiring["boundaries"][stage]
        for q in sorted({
            t.dst_rank for t in boundary_plan(spec, stage)
            if t.src_rank == rank
        }):
            edge = f"s{stage}r{rank}->s{stage + 1}r{q}"
            down_channels[edge] = connect_channel(
                b["listeners"][str(q)]["host"],
                b["listeners"][str(q)]["port"],
                edge=edge,
                hello={"stage": stage, "rank": rank, "edge": edge},
                deadline_s=deadline_s,
                tracer=tracer,
            )
    barrier = None
    if st.dp > 1 and rank != 0:
        edge = f"ctl:s{stage}r{rank}"
        ch = connect_channel(
            wiring["ctl"][str(stage)]["host"],
            wiring["ctl"][str(stage)]["port"],
            edge=edge,
            hello={"stage": stage, "rank": rank, "edge": edge},
            deadline_s=deadline_s,
            tracer=tracer,
        )
        barrier = DrainBarrier(hub=False, channels={rank: ch})

    up_channels = {}
    if up_listener is not None:
        accepted = accept_channels(
            up_listener, n_up, deadline_s=deadline_s, tracer=tracer
        )
        up_channels = {edge: ch for edge, (ch, _hello) in accepted.items()}
    if ctl_listener is not None:
        accepted = accept_channels(
            ctl_listener, st.dp - 1, deadline_s=deadline_s, tracer=tracer
        )
        barrier = DrainBarrier(
            hub=True,
            channels={
                int(hello["rank"]): ch
                for _edge, (ch, hello) in accepted.items()
            },
        )

    up_features = (
        stage_layer_dims(args.feature_dim, hidden, args.classes,
                         n_stages)[stage - 1][-1][1]
        if stage > 0
        else None
    )
    worker = StageWorker(
        spec, stage, rank,
        program=program,
        batch_for=batch_for,
        up_features=up_features,
        up_channels=up_channels,
        down_channels=down_channels,
        barrier=barrier,
        reducer=GroupReducer(st.dp),
    )

    kill = None
    if args.kill_step >= 0 and args.kill_stage == stage:
        kill = rank_kill_hook(
            args.kill_step, marker=args.kill_marker, rank=args.kill_rank
        )

    loss = float("nan")
    drained_at = None
    t_loop = time.perf_counter()
    final_step = args.steps
    for step in range(args.resume_step, args.steps):
        state["step"] = step
        if kill is not None:
            kill(step=step)
        try:
            with tracer.span("mpmd_step", cat="step",
                             args={"step": step, "stage": stage}):
                loss = worker.run_step(step)
        except DrainSignal as e:
            drained_at = e.step
            final_step = e.step
            if args.drain_mode == "abort":
                # Naive arm: no cooperative drain — die loudly so every
                # group's containment tears the whole world down.
                print(
                    f"[mpmd] stage {stage} rank {rank} aborted step "
                    f"{e.step} ({e.why})",
                    flush=True,
                )
                return 75
            drain_marker_path(args.round_dir, stage, rank).write_text(
                json.dumps({"step": e.step, "why": e.why,
                            "round": round_no}) + "\n"
            )
            print(
                f"[mpmd] stage {stage} rank {rank} drained step {e.step} "
                f"({e.why})",
                flush=True,
            )
            tracer.instant(
                "mpmd_drain", cat="mpmd",
                args={"stage": stage, "rank": rank, "step": e.step,
                      "why": e.why},
            )
            break
        if args.ckpt_every and (step + 1) % args.ckpt_every == 0:
            with tracer.span("mpmd_checkpoint", cat="ckpt",
                             args={"step": step + 1, "stage": stage}):
                save_sharded_checkpoint(
                    stage_ckpt_dir(args.ckpt_dir, stage),
                    {
                        "mom": program.momentum,
                        "params": program.params,
                        "step": np.int64(step + 1),
                    },
                    step + 1,
                )
    wall = time.perf_counter() - t_loop
    executed = max(0, final_step - args.resume_step)
    sps = executed / wall if wall > 0 else 0.0

    crc = _params_crc(program.params)
    loss_crc = zlib.crc32(
        np.asarray(worker.losses, np.float32).tobytes()
    )
    print(
        f"[mpmd] stage {stage} rank {rank} world {st.dp} dtype {st.dtype} "
        f"mb {st.microbatches} final_step {final_step} "
        f"loss {float(loss):.6f} params_crc {crc:08x} "
        f"loss_crc {loss_crc:08x} steps_per_s {sps:.3f}",
        flush=True,
    )
    for ch in (*up_channels.values(), *down_channels.values()):
        ch.close()
    if args.obs_dir:
        tracer.export(
            Path(args.obs_dir) / f"trace_s{stage}_p{rank}.json"
        )
    return 0


# --------------------------------------------------------------- driver

_FINAL_RE = re.compile(
    r"\[mpmd\] stage (\d+) rank (\d+) world (\d+) dtype (\S+) mb (\d+) "
    r"final_step (\d+) loss [-0-9.einfa]+ params_crc ([0-9a-f]{8}) "
    r"loss_crc ([0-9a-f]{8}) steps_per_s ([0-9.]+)"
)
_RESUME_RE = re.compile(
    r"\[mpmd\] stage (\d+) rank (\d+) resumed step (\d+) wall ([0-9.]+)"
)
_DRAIN_RE = re.compile(
    r"\[mpmd\] stage (\d+) rank (\d+) drained step (\d+)"
)


def _parse_finals(log: str) -> dict:
    """(stage, rank) → the final-line evidence record; later lines (the
    re-meshed incarnation) overwrite earlier ones."""
    out = {}
    for m in _FINAL_RE.finditer(log):
        out[(int(m.group(1)), int(m.group(2)))] = {
            "world": int(m.group(3)),
            "dtype": m.group(4),
            "microbatches": int(m.group(5)),
            "final_step": int(m.group(6)),
            "params_crc": m.group(7),
            "loss_crc": m.group(8),
            "steps_per_s": float(m.group(9)),
        }
    return out


def _parse_resumes(log: str) -> list:
    return [
        (int(m.group(1)), int(m.group(2)), int(m.group(3)),
         float(m.group(4)))
        for m in _RESUME_RE.finditer(log)
    ]


def _parse_drains(log: str) -> list:
    return [
        (int(m.group(1)), int(m.group(2)), int(m.group(3)))
        for m in _DRAIN_RE.finditer(log)
    ]


def _copy_stage_ckpts(src_ckpt: Path, step: int, dst_ckpt: Path,
                      n_stages: int) -> None:
    """Pristine per-stage copies of one common step — the restore point
    the reference/naive arms start from."""
    for s in range(n_stages):
        src = Path(src_ckpt) / f"stage{s}" / f"step_{step}"
        dst = Path(dst_ckpt) / f"stage{s}" / f"step_{step}"
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copytree(src, dst)


def _drill_pipeline(global_batch: int = 8):
    from tpudml.mpmd.spec import PipelineSpec, StageSpec

    return PipelineSpec(
        stages=(
            StageSpec("trunk", dp=2, microbatches=2, dtype="bfloat16"),
            StageSpec("head", dp=2, microbatches=1, dtype="float32"),
        ),
        global_batch=global_batch,
    )


def _merge_stage_traces(obs_dir: Path, n_stages: int, controller_doc=None):
    """One pid track per stage group: the stage leaders' (local rank 0)
    exported docs re-pidded to the stage index, plus the controller's
    track at pid ``n_stages``. Returns (merged_doc_or_None, pids)."""
    from tpudml.obs.tracer import merge_chrome_traces, validate_chrome_trace

    docs = []
    for s in range(n_stages):
        p = Path(obs_dir) / f"trace_s{s}_p0.json"
        if not p.is_file():
            return None, []
        doc = json.loads(p.read_text())
        for e in doc.get("traceEvents", []):
            e["pid"] = s
            if e.get("ph") == "M" and e.get("name") == "process_name":
                e["args"] = {"name": f"mpmd stage {s}"}
        docs.append(doc)
    if controller_doc is not None:
        for e in controller_doc.get("traceEvents", []):
            e["pid"] = n_stages
            if e.get("ph") == "M" and e.get("name") == "process_name":
                e["args"] = {"name": "mpmd controller"}
        docs.append(controller_doc)
    try:
        merged = merge_chrome_traces(docs)
        validate_chrome_trace(merged)
    except ValueError:
        return None, []
    pids = sorted({e["pid"] for e in merged["traceEvents"]
                   if e["ph"] != "M"})
    return merged, pids


def run_mpmd_drill(
    base_dir: str,
    *,
    steps: int = 20,
    ckpt_every: int = 5,
    kill_step: int = 13,
    kill_stage: int = 1,
    kill_rank: int = 1,
    backoff_s: float = 0.25,
    timeout_s: float = 600.0,
    seed: int = 0,
    include_naive: bool = False,
    sink=None,
) -> dict:
    """The full re-mesh drill; returns the evidence dict the CLI / tests
    gate on (``ok``)."""
    from tpudml.elastic.replan import Replanner
    from tpudml.launch.cluster import ClusterSpec
    from tpudml.mpmd.groups import MPMDController, _Tee
    from tpudml.mpmd.spec import PipelineSpec
    from tpudml.obs.tracer import Tracer, set_tracer
    from tpudml.plan.space import flagship_lm

    base = Path(base_dir)
    base.mkdir(parents=True, exist_ok=True)
    obs_dir = base / "obs"
    obs_dir.mkdir(parents=True, exist_ok=True)
    pipeline = _drill_pipeline()
    n_stages = len(pipeline.stages)
    plan_path = base / "plan.json"
    ckpt_dir = base / "ckpt"
    marker = base / "kill.marker"

    tracer = Tracer()
    prev_tracer = set_tracer(tracer)
    try:
        rp = Replanner(
            flagship_lm(), engines=["dp", "zero1"], verify=False,
            plan_path=plan_path,
        )
        rp.initial_plan(pipeline.total_slots)

        child = [
            sys.executable, "-u", "-m", "tpudml.mpmd.drill",
            "--steps", str(steps),
            "--ckpt_every", str(ckpt_every),
            "--ckpt_dir", str(ckpt_dir),
            "--seed", str(seed),
            "--obs_dir", str(obs_dir),
            "--kill_step", str(kill_step),
            "--kill_stage", str(kill_stage),
            "--kill_rank", str(kill_rank),
            "--kill_marker", str(marker),
        ]
        spec = ClusterSpec(
            num_processes=2,  # overwritten per stage
            timeout_s=timeout_s,
            grace_s=3.0,
            restart_backoff_s=backoff_s,
            restart_backoff_jitter=0.5,
            restart_backoff_seed=seed,
        )
        drill_log = io.StringIO()
        ctrl = MPMDController(
            child, pipeline, spec,
            run_dir=base / "run",
            ckpt_dir=ckpt_dir,
            max_reforms=2,
            replanner=rp,
            sink=_Tee(drill_log, sink),
        )
        mres = ctrl.run()
        log = drill_log.getvalue()
        finals = _parse_finals(log)
        resumes = _parse_resumes(log)
        drains = _parse_drains(log)
        (obs_dir / "mpmd_elastic.json").write_text(
            json.dumps(mres.to_dict(), indent=2, sort_keys=True) + "\n"
        )

        resume_step = min((s for _, _, s, _ in resumes), default=None)
        kill_wall = marker.stat().st_mtime if marker.is_file() else None
        remesh_mttr = (
            max(w for _, _, _, w in resumes) - kill_wall
            if resumes and kill_wall is not None
            else None
        )
        final_pipeline = (
            PipelineSpec.from_dict(mres.records[-1].pipeline)
            if mres.records else None
        )

        # Reference arm: the re-meshed configuration, uninterrupted, from
        # a pristine copy of the same checkpoint — per-(stage, rank) CRC
        # comparison is the bit-exactness verdict.
        bit_exact = False
        ref_finals = {}
        if (
            mres.success
            and resume_step is not None
            and final_pipeline is not None
        ):
            _copy_stage_ckpts(ckpt_dir, resume_step, base / "ref_ckpt",
                              n_stages)
            ref_obs = base / "ref_obs"
            ref_child = [
                sys.executable, "-u", "-m", "tpudml.mpmd.drill",
                "--steps", str(steps),
                "--ckpt_every", "0",
                "--ckpt_dir", str(base / "ref_ckpt"),
                "--seed", str(seed),
                "--obs_dir", str(ref_obs),
            ]
            ref_log = io.StringIO()
            ref_ctrl = MPMDController(
                ref_child, final_pipeline, spec,
                run_dir=base / "ref_run",
                ckpt_dir=base / "ref_ckpt",
                max_reforms=0,
                sink=_Tee(ref_log, sink),
            )
            ref_res = ref_ctrl.run()
            ref_finals = _parse_finals(ref_log.getvalue())
            bit_exact = (
                ref_res.success
                and set(ref_finals) == set(finals)
                and all(
                    finals[k]["params_crc"] == ref_finals[k]["params_crc"]
                    and finals[k]["loss_crc"] == ref_finals[k]["loss_crc"]
                    for k in ref_finals
                )
            )

        # Naive A/B arm: same kill, but peers abort instead of draining —
        # every group's containment fires and the whole world restarts.
        naive = None
        if include_naive:
            naive_ckpt = base / "naive_ckpt"
            naive_marker = base / "naive_kill.marker"
            naive_child = [
                sys.executable, "-u", "-m", "tpudml.mpmd.drill",
                "--steps", str(steps),
                "--ckpt_every", str(ckpt_every),
                "--ckpt_dir", str(naive_ckpt),
                "--seed", str(seed),
                "--obs_dir", str(base / "naive_obs"),
                "--kill_step", str(kill_step),
                "--kill_stage", str(kill_stage),
                "--kill_rank", str(kill_rank),
                "--kill_marker", str(naive_marker),
                "--drain_mode", "abort",
            ]
            naive_log = io.StringIO()
            naive_ctrl = MPMDController(
                naive_child, pipeline, spec,
                run_dir=base / "naive_run",
                ckpt_dir=naive_ckpt,
                max_reforms=2,
                victim_rc=17,
                sink=_Tee(naive_log, sink),
            )
            naive_res = naive_ctrl.run()
            naive_resumes = _parse_resumes(naive_log.getvalue())
            naive_kill_wall = (
                naive_marker.stat().st_mtime
                if naive_marker.is_file() else None
            )
            naive_mttr = (
                max(w for _, _, _, w in naive_resumes) - naive_kill_wall
                if naive_resumes and naive_kill_wall is not None
                else None
            )
            naive = {
                "success": naive_res.success,
                "reforms": naive_res.reforms,
                "restart_mttr_s": naive_mttr,
                "resume_step": min(
                    (s for _, _, s, _ in naive_resumes), default=None
                ),
            }

        # Trace evidence: one pid per stage group + the controller track.
        tracer_doc = tracer.chrome_trace()
        merged, pids = _merge_stage_traces(obs_dir, n_stages, tracer_doc)
        if merged is not None:
            (obs_dir / "trace.json").write_text(
                json.dumps(merged, sort_keys=True, separators=(",", ":"))
                + "\n"
            )

        ports = [p for r in mres.records for p in r.coordinator_ports]
        replan = mres.replans[0] if mres.replans else None
        receipts = list(replan.get("receipts", [])) if replan else []
        in_place = (
            len(mres.records) == 2
            and mres.records[0].stage_worlds == [2, 2]
            and mres.records[1].stage_worlds
            == [2 if s != kill_stage else 1 for s in range(n_stages)]
        )
        victim = mres.records[0].victim if mres.records else None
        ok = (
            mres.success
            and mres.reforms == 1
            and in_place
            and victim is not None
            and victim["stage"] == kill_stage
            and victim["rank"] == kill_rank
            and replan is not None
            and not replan.get("error")
            and bool(receipts)
            and resume_step is not None
            and kill_step - resume_step >= 0
            and bool(drains)
            and bit_exact
            and len(set(ports)) == len(ports)
            and merged is not None
            and pids == list(range(n_stages + 1))
        )
        result = {
            "ok": ok,
            "mode": "mpmd_remesh",
            "bit_exact": bit_exact,
            "pipeline": pipeline.to_dict(),
            "final_stage_worlds": mres.final_stage_worlds,
            "in_place": in_place,
            "steps": steps,
            "kill_step": kill_step,
            "kill_stage": kill_stage,
            "kill_rank": kill_rank,
            "victim": victim,
            "drains": drains,
            "resume_step": resume_step,
            "steps_lost": (
                kill_step - resume_step if resume_step is not None else None
            ),
            "reforms": mres.reforms,
            "stop_reason": mres.stop_reason,
            "coordinator_ports": ports,
            "fresh_ports": len(set(ports)) == len(ports),
            "remesh_mttr_s": remesh_mttr,
            "replan_receipts": receipts,
            "replan_error": replan.get("error") if replan else None,
            "steps_per_s": {
                f"s{s}r{r}": f["steps_per_s"]
                for (s, r), f in sorted(finals.items())
            },
            "params_crc": {
                f"s{s}r{r}": f["params_crc"]
                for (s, r), f in sorted(finals.items())
            },
            "naive": naive,
            "remesh_beats_naive": (
                remesh_mttr is not None
                and naive is not None
                and naive["restart_mttr_s"] is not None
                and remesh_mttr < naive["restart_mttr_s"]
            )
            if include_naive
            else None,
            "trace_pids": pids,
        }
        (obs_dir / "mpmd.json").write_text(
            json.dumps(result, indent=2, sort_keys=True) + "\n"
        )
        return result
    finally:
        set_tracer(prev_tracer)


if __name__ == "__main__":
    sys.exit(child_main())
