"""MPMD pipeline runtime: multi-controller stage groups with
point-to-point transfer and re-mesh-in-place.

One ``jax.distributed`` world cannot span programs that differ in code,
precision, or schedule — so an MPMD pipeline (arXiv 2412.14374) runs S
*independent* gloo worlds, one per stage, agreeing only on a wire
contract:

- ``tpudml.mpmd.spec`` — the jax-free topology layer: stage partition,
  deterministic boundary transfer plans, heterogeneous 1F1B warmup
  depths, re-mesh bookkeeping (quorum, drain order);
- ``tpudml.comm.p2p`` — the boundary channel: (step, microbatch, edge)
  framed tensors over TCP, priced in the shared ring wire model, plus
  the intra-stage drain barrier;
- ``tpudml.mpmd.runtime`` — per-stage programs (own microbatch count,
  own compute dtype, f32 master params) and the 1F1B host loop;
- ``tpudml.mpmd.groups`` — :class:`MPMDController`: forms every stage
  group on fresh ports per round, supervises them concurrently, and on
  rank death drains survivors, consults the PR 16 planner fail-open,
  and re-forms the shrunken pipeline *in place* from the common
  checkpoint step — no whole-world restart;
- ``tpudml.mpmd.drill`` / ``tpudml.mpmd.fixture`` — the e2e kill drill
  (CRC bit-exactness vs an uninterrupted reference) and the meshless
  membership/transfer event replay that keeps the semantics in tier-1.

Only the jax-free layers are imported eagerly; ``runtime`` and
``drill`` pull in jax on first use.
"""

from tpudml.mpmd.groups import (
    MPMDController,
    MPMDReformRecord,
    MPMDResult,
    common_resume_step,
    drain_marker_path,
    read_drain_markers,
    stage_ckpt_dir,
    write_wiring,
)
from tpudml.mpmd.spec import (
    PipelineSpec,
    StageQuorumError,
    StageSpec,
    Transfer,
    boundary_plan,
    drain_order,
    replace_pipeline,
    warmup_microbatches,
)

__all__ = [
    "MPMDController",
    "MPMDReformRecord",
    "MPMDResult",
    "PipelineSpec",
    "StageQuorumError",
    "StageSpec",
    "Transfer",
    "boundary_plan",
    "common_resume_step",
    "drain_marker_path",
    "drain_order",
    "read_drain_markers",
    "replace_pipeline",
    "stage_ckpt_dir",
    "warmup_microbatches",
    "write_wiring",
]
