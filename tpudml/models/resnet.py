"""ResNet for CIFAR — the north-star model (BASELINE.json `configs`:
"task1: single-process ResNet-18 on CIFAR-10"; headline metric "CIFAR-10
ResNet-18 DDP: imgs/sec/chip").

TPU-first design decisions (not in the reference, which has no ResNet code —
only the metric definition):

- **NHWC layout** end-to-end — XLA:TPU's preferred convolution layout.
- **bfloat16 compute path**: parameters live in float32 (master copy; the
  optimizer update stays full-precision), activations and conv/dense kernels
  are cast to ``compute_dtype`` inside ``apply`` so the matmuls/convs hit the
  MXU at bf16 throughput. Batch-norm statistics are always computed in
  float32 — bf16 mean/var is numerically unstable at CIFAR batch sizes.
- CIFAR stem (3x3 stride-1 conv, no max-pool) for 32x32 inputs; ImageNet
  stem (7x7 stride-2 + 3x3 max-pool) selectable via ``stem="imagenet"``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from tpudml.nn.layers import BatchNorm, Conv2D, Dense, Module


def _cast(tree, dtype):
    return jax.tree.map(lambda a: a.astype(dtype), tree)


@dataclass(frozen=True)
class BasicBlock(Module):
    """Two 3x3 convs + identity/projection shortcut (ResNet-18/34 block)."""

    in_channels: int
    out_channels: int
    stride: int = 1
    compute_dtype: Any = jnp.float32

    @property
    def has_projection(self) -> bool:
        return self.stride != 1 or self.in_channels != self.out_channels

    def _layers(self):
        conv1 = Conv2D(
            self.in_channels, self.out_channels, 3, self.stride, "SAME", use_bias=False
        )
        conv2 = Conv2D(self.out_channels, self.out_channels, 3, 1, "SAME", use_bias=False)
        bn1 = BatchNorm(self.out_channels)
        bn2 = BatchNorm(self.out_channels)
        proj = (
            Conv2D(self.in_channels, self.out_channels, 1, self.stride, "SAME", use_bias=False)
            if self.has_projection
            else None
        )
        return conv1, bn1, conv2, bn2, proj

    def init(self, key):
        conv1, bn1, conv2, bn2, proj = self._layers()
        k1, k2, k3, k4, k5 = jax.random.split(key, 5)
        params = {
            "conv1": conv1.init(k1)[0],
            "conv2": conv2.init(k2)[0],
            "bn1": bn1.init(k3)[0],
            "bn2": bn2.init(k4)[0],
        }
        state = {"bn1": bn1.init(k3)[1], "bn2": bn2.init(k4)[1]}
        if proj is not None:
            params["proj"] = proj.init(k5)[0]
            pbn = BatchNorm(self.out_channels)
            params["proj_bn"], state["proj_bn"] = pbn.init(k5)
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        conv1, bn1, conv2, bn2, proj = self._layers()
        cdt = self.compute_dtype
        new_state = {}
        shortcut = x
        y, _ = conv1.apply(_cast(params["conv1"], cdt), {}, x)
        y, new_state["bn1"] = self._bn(bn1, params["bn1"], state["bn1"], y, train)
        y = jax.nn.relu(y)
        y, _ = conv2.apply(_cast(params["conv2"], cdt), {}, y)
        y, new_state["bn2"] = self._bn(bn2, params["bn2"], state["bn2"], y, train)
        if proj is not None:
            shortcut, _ = proj.apply(_cast(params["proj"], cdt), {}, x)
            shortcut, new_state["proj_bn"] = self._bn(
                BatchNorm(self.out_channels),
                params["proj_bn"],
                state["proj_bn"],
                shortcut,
                train,
            )
        return jax.nn.relu(y + shortcut), new_state

    def _bn(self, bn, params, state, x, train):
        # BN stats/normalize run in f32 INSIDE BatchNorm.apply (f32-accumulated
        # reductions straight off the bf16 stream); pre-casting here would
        # materialize an f32 copy of the activation and double the HBM traffic
        # of every stat pass.
        y, new_state = bn.apply(params, state, x, train=train)
        return y.astype(self.compute_dtype), new_state


@dataclass(frozen=True)
class BottleneckBlock(Module):
    """1x1 reduce → 3x3 → 1x1 expand (×4) + shortcut — the ResNet-50/101
    block. The 1x1 convs are pure channel matmuls, which XLA maps straight
    onto the MXU; compute dtype handling mirrors BasicBlock (bf16 convs,
    f32 batch-norm)."""

    in_channels: int
    mid_channels: int
    stride: int = 1
    compute_dtype: Any = jnp.float32

    EXPANSION = 4

    @property
    def out_channels(self) -> int:
        return self.mid_channels * self.EXPANSION

    @property
    def has_projection(self) -> bool:
        return self.stride != 1 or self.in_channels != self.out_channels

    def _layers(self):
        conv1 = Conv2D(self.in_channels, self.mid_channels, 1, 1, "SAME", use_bias=False)
        conv2 = Conv2D(
            self.mid_channels, self.mid_channels, 3, self.stride, "SAME", use_bias=False
        )
        conv3 = Conv2D(self.mid_channels, self.out_channels, 1, 1, "SAME", use_bias=False)
        proj = (
            Conv2D(self.in_channels, self.out_channels, 1, self.stride, "SAME", use_bias=False)
            if self.has_projection
            else None
        )
        return conv1, conv2, conv3, proj

    def init(self, key):
        conv1, conv2, conv3, proj = self._layers()
        k1, k2, k3, k4 = jax.random.split(key, 4)
        params, state = {}, {}
        for name, conv, ch, k in (
            ("1", conv1, self.mid_channels, k1),
            ("2", conv2, self.mid_channels, k2),
            ("3", conv3, self.out_channels, k3),
        ):
            params[f"conv{name}"] = conv.init(k)[0]
            params[f"bn{name}"], state[f"bn{name}"] = BatchNorm(ch).init(k)
        if proj is not None:
            params["proj"] = proj.init(k4)[0]
            params["proj_bn"], state["proj_bn"] = BatchNorm(self.out_channels).init(k4)
        return params, state

    def _bn(self, ch, params, state, x, train):
        # No f32 pre-cast — BatchNorm.apply accumulates its stats in f32 off
        # the bf16 stream (see BasicBlock._bn).
        y, new_state = BatchNorm(ch).apply(params, state, x, train=train)
        return y.astype(self.compute_dtype), new_state

    def apply(self, params, state, x, *, train=False, rng=None):
        conv1, conv2, conv3, proj = self._layers()
        cdt = self.compute_dtype
        new_state = {}
        shortcut = x
        y, _ = conv1.apply(_cast(params["conv1"], cdt), {}, x)
        y, new_state["bn1"] = self._bn(self.mid_channels, params["bn1"], state["bn1"], y, train)
        y = jax.nn.relu(y)
        y, _ = conv2.apply(_cast(params["conv2"], cdt), {}, y)
        y, new_state["bn2"] = self._bn(self.mid_channels, params["bn2"], state["bn2"], y, train)
        y = jax.nn.relu(y)
        y, _ = conv3.apply(_cast(params["conv3"], cdt), {}, y)
        y, new_state["bn3"] = self._bn(self.out_channels, params["bn3"], state["bn3"], y, train)
        if proj is not None:
            shortcut, _ = proj.apply(_cast(params["proj"], cdt), {}, x)
            shortcut, new_state["proj_bn"] = self._bn(
                self.out_channels, params["proj_bn"], state["proj_bn"], shortcut, train
            )
        return jax.nn.relu(y + shortcut), new_state


@dataclass(frozen=True)
class ResNet(Module):
    """Configurable ResNet: basic blocks (18/34) or bottlenecks (50/101)."""

    stage_sizes: tuple[int, ...] = (2, 2, 2, 2)
    num_classes: int = 10
    width: int = 64
    stem: str = "cifar"  # "cifar" (3x3/s1) or "imagenet" (7x7/s2 + pool)
    in_channels: int = 3
    block: str = "basic"  # "basic" | "bottleneck"
    compute_dtype: Any = jnp.float32

    def _stem_conv(self):
        if self.stem == "imagenet":
            return Conv2D(self.in_channels, self.width, 7, 2, "SAME", use_bias=False)
        return Conv2D(self.in_channels, self.width, 3, 1, "SAME", use_bias=False)

    def _blocks(self):
        blocks = []
        in_ch = self.width
        for stage, n in enumerate(self.stage_sizes):
            ch = self.width * (2**stage)
            for i in range(n):
                stride = 2 if (stage > 0 and i == 0) else 1
                if self.block == "bottleneck":
                    blk = BottleneckBlock(
                        in_ch, ch, stride, compute_dtype=self.compute_dtype
                    )
                    in_ch = blk.out_channels
                else:
                    blk = BasicBlock(
                        in_ch, ch, stride, compute_dtype=self.compute_dtype
                    )
                    in_ch = ch
                blocks.append(blk)
        return blocks

    @property
    def feature_dim(self) -> int:
        top = self.width * (2 ** (len(self.stage_sizes) - 1))
        return top * BottleneckBlock.EXPANSION if self.block == "bottleneck" else top

    def init(self, key):
        stem = self._stem_conv()
        blocks = self._blocks()
        head = Dense(self.feature_dim, self.num_classes)
        keys = jax.random.split(key, len(blocks) + 3)
        params = {"stem": stem.init(keys[0])[0]}
        bn = BatchNorm(self.width)
        params["stem_bn"], stem_bn_state = bn.init(keys[1])
        state = {"stem_bn": stem_bn_state}
        for i, (blk, k) in enumerate(zip(blocks, keys[2:-1])):
            params[f"block{i}"], state[f"block{i}"] = blk.init(k)
        params["head"] = head.init(keys[-1])[0]
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        cdt = self.compute_dtype
        stem = self._stem_conv()
        blocks = self._blocks()
        new_state = {}
        x = x.astype(cdt)
        y, _ = stem.apply(_cast(params["stem"], cdt), {}, x)
        bn = BatchNorm(self.width)
        y, new_state["stem_bn"] = bn.apply(
            params["stem_bn"], state["stem_bn"], y, train=train
        )
        y = jax.nn.relu(y).astype(cdt)
        if self.stem == "imagenet":
            y = jax.lax.reduce_window(
                y, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
            )
        for i, blk in enumerate(blocks):
            y, new_state[f"block{i}"] = blk.apply(
                params[f"block{i}"], state[f"block{i}"], y, train=train
            )
        y = jnp.mean(y, axis=(1, 2))  # global average pool
        head = Dense(self.feature_dim, self.num_classes)
        logits, _ = head.apply(_cast(params["head"], cdt), {}, y)
        return logits.astype(jnp.float32), new_state


def ResNet18(num_classes: int = 10, compute_dtype: Any = jnp.float32, **kw) -> ResNet:
    return ResNet(
        stage_sizes=(2, 2, 2, 2),
        num_classes=num_classes,
        compute_dtype=compute_dtype,
        **kw,
    )


def ResNet34(num_classes: int = 10, compute_dtype: Any = jnp.float32, **kw) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        num_classes=num_classes,
        compute_dtype=compute_dtype,
        **kw,
    )


def ResNet50(num_classes: int = 10, compute_dtype: Any = jnp.float32, **kw) -> ResNet:
    """Bottleneck ResNet-50 — the MindSpore auto-parallel parity config of
    BASELINE.json (`configs`: "MindSpore auto-parallel ResNet-50 ...");
    runs under the same engines (DP/FSDP/GSPMD) as ResNet-18."""
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        num_classes=num_classes,
        compute_dtype=compute_dtype,
        block="bottleneck",
        **kw,
    )
