"""ForwardMLP — MindSpore-track parity model.

Architecture parity with ``ForwardNN`` in the reference's MindSpore notebook
(codes/task1/mindspore/model.ipynb cell 4): flatten(784) → 512 → 256 → 128 →
64 → 32 → 10, relu between layers. The notebook's softmax head is folded
into the loss (softmax cross-entropy over logits), as its
``SoftmaxCrossEntropyWithLogits`` training path effectively does.
"""

from __future__ import annotations

import jax

from tpudml.nn import Activation, Dense, Flatten, Sequential


def ForwardMLP(
    in_features: int = 784,
    hidden: tuple[int, ...] = (512, 256, 128, 64, 32),
    num_classes: int = 10,
) -> Sequential:
    layers: list = [Flatten()]
    prev = in_features
    for h in hidden:
        layers += [Dense(prev, h), Activation(jax.nn.relu)]
        prev = h
    layers.append(Dense(prev, num_classes))
    return Sequential(layers=tuple(layers))
