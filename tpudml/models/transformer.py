"""Transformer blocks and a decoder-only LM.

No analogue exists in the reference (its models are a LeNet CNN and an MLP
— SURVEY.md §5.7 records the absence of any sequence model), but
long-context capability is first-class here, so the transformer is the
framework's flagship sequence model:

- ``TransformerBlock`` is stateless and shape-preserving — exactly the
  homogeneous-stage contract of the GPipe engine (``tpudml.parallel.pp``),
  so depth scales by pipeline stages;
- attention ``impl`` ("full" | "ring" | "ulysses") selects single-chip or
  sequence-sharded execution (``tpudml.parallel.cp``) from one model
  definition;
- position embeddings are computed from *global* offsets when the sequence
  axis is sharded, so the same weights give identical math either way.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpudml.comm.collectives import axis_size
from tpudml.nn.attention import MultiHeadAttention, sharded_positions
from tpudml.nn.layers import Dense, LayerNorm, Module


# Bound on the one-hot transient the matmul backward materializes
# (elements of [N, V] in dy.dtype). 512M elements (~1 GiB bf16) keeps
# the flagship (8k×32k = 2^28) and chip-filling (16k×32k = 2^29) configs
# on the single-matmul fast path — chunking them was measured to cost
# ~3 ms/step at the flagship (23.3 vs 20.3 ms, fori A/B on v5e: 128
# sequential [2k, 32k] scan steps lose the big matmul's pipelining).
# Past the cap the backward chunks the token axis so memory stays
# O(cap + V·d) instead of O(N·V) — the 131k-token × 32k-vocab regime
# (2^32 elements, ~8.6 GB unchunked) runs as 8 × 1 GiB chunks, exactly
# the O(N·V) blow-up this bound exists to stop (ADVICE r4).
_ONEHOT_ELEM_CAP = 512 * 1024 * 1024


@jax.custom_vjp
def embed_lookup(table: jax.Array, tokens: jax.Array) -> jax.Array:
    """Token-embedding gather with a matmul backward.

    Forward is the plain gather ``table[tokens]``. The backward computes
    dTable = one_hot(tokens)ᵀ @ dy as an MXU matmul instead of autodiff's
    scatter-add: on v5e at [8·1024 tokens, 32k vocab, d=512] the
    scatter-add path measures 3.6 ms vs 1.0 ms for the one-hot matmul
    (tools/micro_lm.py embed) — TPU scatter serializes per-index updates
    while the matmul is dense MXU work. Same math (each table row sums
    the cotangents of its occurrences); f32 accumulation, cast to the
    table dtype. Above ``_ONEHOT_ELEM_CAP`` one-hot elements the token
    axis is chunked under ``lax.scan`` so the transient stays bounded at
    any sequence length."""
    return table[tokens]


def _embed_lookup_fwd(table, tokens):
    # The table rides along for its static shape/dtype only (a reference,
    # not a copy — it is a live parameter either way).
    return table[tokens], (tokens, table)


def _embed_lookup_bwd(res, dy):
    import numpy as np

    tokens, table = res
    v = table.shape[0]
    d = dy.shape[-1]
    toks = tokens.reshape(-1)
    dyf = dy.reshape(-1, d)
    n = toks.shape[0]
    if n * v <= _ONEHOT_ELEM_CAP:
        oh = jax.nn.one_hot(toks, v, dtype=dy.dtype)
        dtable = lax.dot_general(
            oh, dyf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        # Chunk the token axis: each scan step materializes one
        # [chunk, V] one-hot tile and accumulates its matmul into the
        # f32 dTable. Padded rows carry dy = 0, so their (token 0)
        # one-hot contributes nothing.
        chunk = max(_ONEHOT_ELEM_CAP // v, 8)
        pad = (-n) % chunk
        if pad:
            toks = jnp.pad(toks, (0, pad))
            dyf = jnp.pad(dyf, ((0, pad), (0, 0)))
        toks_c = toks.reshape(-1, chunk)
        dy_c = dyf.reshape(-1, chunk, d)

        def body(acc, args):
            t, g = args
            oh = jax.nn.one_hot(t, v, dtype=g.dtype)
            return acc + lax.dot_general(
                oh, g, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ), None

        dtable, _ = lax.scan(body, jnp.zeros((v, d), jnp.float32), (toks_c, dy_c))
    return (
        dtable.astype(table.dtype),
        np.zeros(tokens.shape, dtype=jax.dtypes.float0),
    )


embed_lookup.defvjp(_embed_lookup_fwd, _embed_lookup_bwd)


@dataclass(frozen=True)
class TransformerBlock(Module):
    """Pre-LN decoder block: x + MHA(LN(x)); x + FFN(LN(x)).

    ``moe_experts > 0`` swaps the dense FFN for a Switch-style
    mixture-of-experts layer (``tpudml.nn.moe``); set ``moe_axis`` to run
    the experts sharded under the ExpertParallel engine.
    """

    embed_dim: int
    num_heads: int
    causal: bool = True
    impl: str = "full"
    axis_name: str = "seq"
    remat: bool = False
    num_kv_heads: int | None = None
    rope: bool = False
    rope_base: float = 10000.0
    seq_sharded: bool = False
    seq_layout: str = "contiguous"
    dropout: float = 0.0  # on attention + FFN outputs (train mode, needs rng)
    mlp_ratio: int = 4
    moe_experts: int = 0
    moe_axis: str | None = None
    moe_capacity_factor: float = 2.0
    moe_top_k: int = 1
    moe_dispatch: str = "gather"
    moe_ragged_dw: str = "grouped"  # ragged backward: grouped-dW kernel / stock transpose
    # Fuse the block's ln2 junction (x + attn_out → LayerNorm) into one
    # add+LN Pallas kernel per direction. This is the PIPELINE-stage form
    # of the LM's deferred trunk: the block keeps its shape-preserving
    # x → x contract (the closing residual add stays unfused, so the
    # stage payload is still one tensor), fusing 1 of its 2 junctions —
    # the LM's ``fused_ln`` trunk fuses 2L of 2L+1 by deferring adds
    # across block boundaries, which a pipeline cut cannot do. The FFN
    # branch may be the dense MLP or the MoE layer — the junction kernel
    # fuses the residual ADD, not the branch.
    fused_ln: bool = False
    dtype: Any = jnp.float32

    def _parts(self):
        d = self.embed_dim
        parts = {
            "ln1": LayerNorm(d, dtype=self.dtype),
            "attn": MultiHeadAttention(
                d,
                self.num_heads,
                causal=self.causal,
                impl=self.impl,
                axis_name=self.axis_name,
                remat=self.remat,
                num_kv_heads=self.num_kv_heads,
                rope=self.rope,
                rope_base=self.rope_base,
                seq_sharded=self.seq_sharded,
                seq_layout=self.seq_layout,
                dtype=self.dtype,
            ),
            "ln2": LayerNorm(d, dtype=self.dtype),
        }
        if self.moe_experts:
            from tpudml.nn.moe import MoELayer

            parts["moe"] = MoELayer(
                d,
                self.moe_experts,
                mlp_ratio=self.mlp_ratio,
                capacity_factor=self.moe_capacity_factor,
                top_k=self.moe_top_k,
                axis_name=self.moe_axis,
                dispatch=self.moe_dispatch,
                ragged_dw=self.moe_ragged_dw,
                dtype=self.dtype,
            )
        else:
            parts["fc1"] = Dense(d, self.mlp_ratio * d, dtype=self.dtype)
            parts["fc2"] = Dense(self.mlp_ratio * d, d, dtype=self.dtype)
        return parts

    def init(self, key):
        parts = self._parts()
        keys = jax.random.split(key, len(parts))
        params, states = {}, {}
        for (n, m), k in zip(parts.items(), keys):
            p, s = m.init(k)
            params[n] = p
            if s:
                states[n] = s  # e.g. the MoE aux-loss slot
        return params, states

    def _drop(self, h, train, rng, salt):
        """Inverted dropout via the shared nn.Dropout module; the salt
        fold keeps the attention/FFN masks independent."""
        if not train or self.dropout == 0.0:
            return h
        if rng is None:
            raise ValueError("TransformerBlock dropout requires an rng in train mode")
        from tpudml.nn.layers import Dropout

        return Dropout(self.dropout)(
            {}, h, train=True, rng=jax.random.fold_in(rng, salt)
        )

    def _ffn_branch(self, parts, params, state, y, train):
        """Post-norm FFN branch — dense MLP or MoE. The ONE site that
        encodes the branch contract for every trunk form (block fused/
        unfused, LM deferred); returns (h, per-block state update)."""
        if self.moe_experts:
            h, moe_state = parts["moe"].apply(
                params["moe"], state.get("moe", {}), y, train=train
            )
            return h, {"moe": moe_state}
        h = jax.nn.gelu(parts["fc1"](params["fc1"], y))
        return parts["fc2"](params["fc2"], h), {}

    def apply(self, params, state, x, *, train=False, rng=None):
        parts = self._parts()
        h = parts["ln1"](params["ln1"], x)
        h = parts["attn"](params["attn"], h)
        if self.fused_ln:
            from tpudml.ops.layernorm_kernel import fused_add_layernorm

            s, y2 = fused_add_layernorm(
                x,
                self._drop(h, train, rng, 1),
                params["ln2"]["scale"],
                params["ln2"]["bias"],
            )
            h, new_state = self._ffn_branch(parts, params, state, y2, train)
            return s + self._drop(h, train, rng, 2), new_state
        x = x + self._drop(h, train, rng, 1)
        h = parts["ln2"](params["ln2"], x)
        h, new_state = self._ffn_branch(parts, params, state, h, train)
        return x + self._drop(h, train, rng, 2), new_state


@dataclass(frozen=True)
class TransformerEmbed(Module):
    """Token + learned position embedding. Doubles as the pipeline
    prologue (GPipe runs it replicated ahead of the staged trunk) and as
    TransformerLM's embedding stage; with ``seq_sharded=True`` position
    lookup uses the device's global offset along ``axis_name`` (run under
    shard_map with the time axis sharded)."""

    vocab_size: int
    embed_dim: int
    max_len: int = 1024
    axis_name: str = "seq"
    seq_sharded: bool = False
    seq_layout: str = "contiguous"  # "striped" = balanced causal-ring layout
    use_pos_embed: bool = True  # False when positions come from RoPE
    dtype: Any = jnp.float32

    def init(self, key):
        ke, kp = jax.random.split(key)
        params = {
            "tok_embed": 0.02
            * jax.random.normal(ke, (self.vocab_size, self.embed_dim), self.dtype),
        }
        if self.use_pos_embed:
            params["pos_embed"] = 0.02 * jax.random.normal(
                kp, (self.max_len, self.embed_dim), self.dtype
            )
        return params, {}

    def apply(self, params, state, tokens, *, train=False, rng=None):
        t_local = tokens.shape[1]
        t_global = (
            axis_size(self.axis_name) * t_local if self.seq_sharded else t_local
        )
        if self.use_pos_embed and t_global > self.max_len:
            # Trace-time guard: out-of-range gathers clamp silently under
            # jit, which would reuse pos_embed[max_len-1] for the overflow
            # and corrupt position information without any signal. RoPE
            # (use_pos_embed=False) has no table to overflow — lengths
            # beyond max_len are legitimate extrapolation.
            raise ValueError(
                f"sequence length {t_global} exceeds max_len {self.max_len}"
            )
        h = embed_lookup(params["tok_embed"], tokens)
        if self.use_pos_embed:
            positions = sharded_positions(
                self.axis_name, t_local, self.seq_sharded, self.seq_layout
            )
            h = h + params["pos_embed"][positions]
        return h, state


@dataclass(frozen=True)
class TransformerHead(Module):
    """Final LayerNorm + vocab projection — the pipeline epilogue."""

    embed_dim: int
    vocab_size: int
    dtype: Any = jnp.float32

    def init(self, key):
        kl, kh = jax.random.split(key)
        return {
            "ln_f": LayerNorm(self.embed_dim, dtype=self.dtype).init(kl)[0],
            "head": Dense(self.embed_dim, self.vocab_size, dtype=self.dtype).init(kh)[0],
        }, {}

    def apply(self, params, state, x, *, train=False, rng=None):
        h = LayerNorm(self.embed_dim, dtype=self.dtype)(params["ln_f"], x)
        head = Dense(self.embed_dim, self.vocab_size, dtype=self.dtype)
        return head(params["head"], h), state


@dataclass(frozen=True)
class TransformerLM(Module):
    """Decoder-only language model: token + learned position embeddings,
    N pre-LN blocks, final LayerNorm, vocab projection.

    ``seq_sharded=True`` makes position lookup use the device's global
    offset along ``axis_name`` (the model then must run under shard_map
    with the time axis sharded — the ContextParallel engine's regime).
    """

    vocab_size: int
    embed_dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 1024
    impl: str = "full"
    axis_name: str = "seq"
    seq_sharded: bool = False
    seq_layout: str = "contiguous"
    remat: bool = False
    num_kv_heads: int | None = None
    rope: bool = False
    rope_base: float = 10000.0
    dropout: float = 0.0
    moe_experts: int = 0
    moe_axis: str | None = None
    moe_capacity_factor: float = 2.0
    moe_top_k: int = 1
    moe_dispatch: str = "gather"
    moe_ragged_dw: str = "grouped"  # ragged backward: grouped-dW kernel / stock transpose
    dtype: Any = jnp.float32
    # Fused residual-add + LayerNorm junctions (tpudml.ops.layernorm_kernel
    # .fused_add_layernorm): the trunk defers each block's closing residual
    # add into the NEXT norm's kernel, so all 2L adds and 2L of the 2L+1
    # norms run as one Pallas kernel per direction with the backward's
    # residual-gradient merge folded in (round-3 ablation: the in-situ LN
    # cost is fusion structure, not arithmetic — BASELINE.md). Identical
    # math to the unfused path (the sum rounds to the stream dtype before
    # the f32 statistics); the FFN branch may be dense or MoE (the kernel
    # fuses the residual ADD, not the branch — MoE aux state threads
    # through the deferred trunk). On non-TPU backends the op dispatches
    # to reference math, so the flag is safe everywhere.
    fused_ln: bool = False
    # Mixed precision, ResNet-style: parameters stay in ``dtype`` (the f32
    # master copy the optimizer updates) and are cast per-apply to
    # ``compute_dtype`` so the matmuls hit the MXU at bf16 throughput.
    # Norm scales/biases and the router stay f32 (LayerNorm statistics and
    # routing softmax are computed in f32 regardless); logits stay in the
    # compute dtype (softmax_cross_entropy computes its statistics in f32
    # from bf16 logits without materializing an f32 copy).
    # None means "compute in the parameter dtype" — NOT the same as
    # jnp.float32: the legacy all-bf16 mode (dtype=bf16, compute_dtype
    # unset) must keep computing in bf16, not get upcast.
    compute_dtype: Any = None

    def _block(self) -> TransformerBlock:
        return TransformerBlock(
            self.embed_dim,
            self.num_heads,
            causal=True,
            impl=self.impl,
            axis_name=self.axis_name,
            remat=self.remat,
            num_kv_heads=self.num_kv_heads,
            rope=self.rope,
            rope_base=self.rope_base,
            seq_sharded=self.seq_sharded,
            seq_layout=self.seq_layout,
            dropout=self.dropout,
            moe_experts=self.moe_experts,
            moe_axis=self.moe_axis,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_top_k=self.moe_top_k,
            moe_dispatch=self.moe_dispatch,
            moe_ragged_dw=self.moe_ragged_dw,
            dtype=self.dtype,
        )

    # Composition: the LM IS embed → blocks → head, with the param tree
    # kept FLAT (tok_embed/pos_embed/block{i}/ln_f/head) so checkpoints,
    # TP sharding rules, and pipeline prologue/epilogue trees stay in one
    # format regardless of which engine runs the model.

    def _embed(self) -> TransformerEmbed:
        return TransformerEmbed(
            self.vocab_size,
            self.embed_dim,
            self.max_len,
            axis_name=self.axis_name,
            seq_sharded=self.seq_sharded,
            seq_layout=self.seq_layout,
            use_pos_embed=not self.rope,
            dtype=self.dtype,
        )

    def _head(self) -> "TransformerHead":
        return TransformerHead(self.embed_dim, self.vocab_size, dtype=self.dtype)

    def init(self, key):
        ke, kb, kh = jax.random.split(key, 3)
        params = dict(self._embed().init(ke)[0])
        params.update(self._head().init(kh)[0])
        block = self._block()
        states = {}
        for i, k in enumerate(jax.random.split(kb, self.num_layers)):
            p, s = block.init(k)
            params[f"block{i}"] = p
            if s:
                states[f"block{i}"] = s  # MoE aux-loss slots
        return params, states

    def _cast_params(self, params):
        if self.compute_dtype is None:
            return params
        keep_f32 = {"ln1", "ln2", "ln_f", "router"}

        from tpudml.core.pytree import path_names

        def cast(path, p):
            names = set(path_names(path))
            return p if names & keep_f32 else p.astype(self.compute_dtype)

        return jax.tree_util.tree_map_with_path(cast, params)

    def _trunk(self, params, state, tokens, train, rng):
        """embed → blocks (params already cast); no final norm/head."""
        embed_keys = ("tok_embed",) + (() if self.rope else ("pos_embed",))
        h = self._embed()({k: params[k] for k in embed_keys}, tokens)
        block = self._block()
        new_state = {}
        for i in range(self.num_layers):
            h, s = block.apply(
                params[f"block{i}"], state.get(f"block{i}", {}), h,
                train=train,
                rng=None if rng is None else jax.random.fold_in(rng, i),
            )
            if s:
                new_state[f"block{i}"] = s
        return h, new_state

    def _trunk_deferred(self, params, state, tokens, train, rng):
        """Fused-junction trunk (``fused_ln=True``): embed → blocks with
        each residual add deferred into the next norm's fused add+LN
        kernel. The FFN branch is the dense MLP or the MoE layer — the
        junction kernel is FFN-agnostic (it fuses the residual ADD, not
        the branch). Returns ``(s, pend, new_state)`` — the residual
        stream, the still-unadded final FFN branch (so the caller can
        close the last junction inside the final-norm fusion too), and
        the threaded model state (MoE aux-loss slots)."""
        from tpudml.ops.layernorm_kernel import fused_add_layernorm

        embed_keys = ("tok_embed",) + (() if self.rope else ("pos_embed",))
        s = self._embed()({k: params[k] for k in embed_keys}, tokens)
        block = self._block()
        parts = block._parts()
        pend = None
        new_state = {}
        for i in range(self.num_layers):
            p = params[f"block{i}"]
            brng = None if rng is None else jax.random.fold_in(rng, i)
            if pend is None:
                y = parts["ln1"](p["ln1"], s)
            else:
                s, y = fused_add_layernorm(
                    s, pend, p["ln1"]["scale"], p["ln1"]["bias"]
                )
            a = parts["attn"](p["attn"], y)
            s, y2 = fused_add_layernorm(
                s,
                block._drop(a, train, brng, 1),
                p["ln2"]["scale"],
                p["ln2"]["bias"],
            )
            h, st = block._ffn_branch(
                parts, p, state.get(f"block{i}", {}), y2, train
            )
            if st:
                new_state[f"block{i}"] = st
            pend = block._drop(h, train, brng, 2)
        return s, pend, new_state

    def _features_deferred(self, params, state, tokens, train, rng):
        """Deferred trunk closed through the final norm: the last block's
        residual add fuses into ln_f."""
        from tpudml.ops.layernorm_kernel import fused_add_layernorm

        s, pend, new_state = self._trunk_deferred(params, state, tokens, train, rng)
        _, y = fused_add_layernorm(
            s, pend, params["ln_f"]["scale"], params["ln_f"]["bias"]
        )
        return y, new_state

    def _use_fused_ln(self):
        # num_layers=0 leaves no junction to fuse (pend would stay None).
        return self.fused_ln and self.num_layers > 0

    def apply(self, params, state, tokens, *, train=False, rng=None):
        params = self._cast_params(params)
        if self._use_fused_ln():
            y, new_state = self._features_deferred(params, state, tokens, train, rng)
            head = Dense(self.embed_dim, self.vocab_size, dtype=self.dtype)
            return head(params["head"], y), new_state
        h, new_state = self._trunk(params, state, tokens, train, rng)
        logits = self._head()({k: params[k] for k in ("ln_f", "head")}, h)
        # Logits stay in compute dtype: softmax_cross_entropy computes its
        # statistics in f32 from bf16 logits without materializing an f32
        # copy (a [B·T, 32k] cast is ~1 GB of HBM traffic at LM scale),
        # and argmax/accuracy are dtype-insensitive.
        return logits, new_state

    # ----------------------------------------------------- serving paths
    # KV-cached incremental decode + chunked prefill (tpudml.serve). Both
    # run the UNFUSED pre-LN math with train=False — exactly _trunk's
    # composition — so greedy decode is logit-exact against apply() (the
    # tests/test_serve.py parity contract). MoE is rejected: routing a
    # single token re-runs the full dispatch machinery for no cache
    # reuse; PP likewise has no serve composition (docs/API.md).

    def _serve_guard(self):
        if self.moe_experts:
            raise NotImplementedError(
                "serve decode does not compose with MoE blocks yet"
            )
        if self._use_fused_ln():
            # fused_add_layernorm is a throughput fusion for [B, T≫1, d]
            # streams; a one-token decode step gains nothing and the
            # unfused math is the parity reference. Reject rather than
            # silently diverge from the training-time configuration.
            raise NotImplementedError(
                "serve decode runs the unfused-LN math; build the serving "
                "model with fused_ln=False"
            )
        if self.seq_sharded:
            raise ValueError("serve decode requires seq_sharded=False")

    def init_decode_cache(self, batch: int, max_len: int | None = None,
                          kind: str = "f32"):
        """Per-layer KV caches for ``batch`` decode slots: a tuple of
        ``num_layers`` ``serve.cache.KVCache`` pytrees, each
        [batch, max_len, kv_heads, head_dim] (GQA shrinks the head axis;
        TP shards it). ``kind`` selects f32/bf16/int8 storage."""
        from tpudml.serve.cache import init_cache

        self._serve_guard()
        max_len = self.max_len if max_len is None else max_len
        if not self.rope and max_len > self.max_len:
            raise ValueError(
                f"cache max_len {max_len} exceeds the position table "
                f"({self.max_len}); only RoPE models extrapolate"
            )
        head_dim = self.embed_dim // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads
        return tuple(
            init_cache(batch, max_len, kv_heads, head_dim, kind)
            for _ in range(self.num_layers)
        )

    def init_paged_cache(self, num_pages: int, page_size: int,
                         kind: str = "f32"):
        """Per-layer page pools: a tuple of ``num_layers``
        ``serve.paged.PagedKVCache`` pytrees, each
        [num_pages, page_size, kv_heads, head_dim]. The slot→page table
        lives with the engine, not the pool — every slot reads through
        its table rows, so pool size is an HBM budget, not a sequence
        bound (per-slot capacity is the table width × page_size)."""
        from tpudml.serve.paged import init_pool

        self._serve_guard()
        head_dim = self.embed_dim // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads
        return tuple(
            init_pool(num_pages, page_size, kv_heads, head_dim, kind)
            for _ in range(self.num_layers)
        )

    def _decode_embed(self, params, tokens, pos):
        """[B] tokens at per-slot positions ``pos`` [B] → [B, 1, d]."""
        h = params["tok_embed"][tokens][:, None, :]
        if not self.rope:
            h = h + params["pos_embed"][pos][:, None, :]
        return h

    def _decode_embed_window(self, params, tokens, pos):
        """[B, Q] window tokens, first at per-slot positions ``pos`` [B]
        → [B, Q, d]."""
        h = params["tok_embed"][tokens]
        if not self.rope:
            positions = pos[:, None] + jnp.arange(tokens.shape[1])[None, :]
            h = h + params["pos_embed"][positions]
        return h

    def _serve_blocks(self, params, caches, h, attend):
        """Shared block loop of both serving paths: pre-LN attention (via
        ``attend(attn_module, block_params, cache, y)``) and the dense
        FFN, threading per-layer caches."""
        block = self._block()
        parts = block._parts()
        new_caches = []
        for i, cache in enumerate(caches):
            p = params[f"block{i}"]
            y = parts["ln1"](p["ln1"], h)
            a, cache = attend(parts["attn"], p["attn"], cache, y)
            h = h + a
            y2 = parts["ln2"](p["ln2"], h)
            f = parts["fc2"](p["fc2"], jax.nn.gelu(parts["fc1"](p["fc1"], y2)))
            h = h + f
            new_caches.append(cache)
        return h, tuple(new_caches)

    def apply_decode(self, params, caches, tokens, pos):
        """One incremental decode step: ``tokens`` [B] at per-slot
        positions ``pos`` [B] → (logits [B, V], updated caches). Each
        slot's K/V land in its cache row at ``pos``; attention covers
        the slot's written prefix only. Cost per emitted token is O(L·d)
        — never the O(T²) training kernel."""
        self._serve_guard()
        params = self._cast_params(params)
        h = self._decode_embed(params, tokens, pos)
        h, new_caches = self._serve_blocks(
            params, caches, h,
            lambda attn, p, cache, y: attn.apply_decode(p, cache, y, pos),
        )
        logits = self._head()({k: params[k] for k in ("ln_f", "head")}, h)
        return logits[:, 0, :], new_caches

    def apply_decode_features(self, params, caches, tokens, pos):
        """One incremental decode step STOPPING AT THE FEATURES: embed →
        cached blocks → final LayerNorm, without the vocab projection —
        (features [B, d], updated caches). The input contract of the
        fused decode head (``tpudml.ops.decode_head``), which consumes
        features + head weights and never materializes the [B, V]
        logits; the serving twin of ``apply_features``."""
        self._serve_guard()
        params = self._cast_params(params)
        h = self._decode_embed(params, tokens, pos)
        h, new_caches = self._serve_blocks(
            params, caches, h,
            lambda attn, p, cache, y: attn.apply_decode(p, cache, y, pos),
        )
        h = LayerNorm(self.embed_dim, dtype=self.dtype)(params["ln_f"], h)
        return h[:, 0, :], new_caches

    def apply_decode_window(self, params, caches, tokens, pos):
        """Decode a window of Q consecutive tokens per slot over the
        dense cache: ``tokens`` [B, Q], first token at ``pos`` [B] →
        (logits [B, Q, V], updated caches). The speculative verify step:
        one model pass scores all Q positions; greedy acceptance then
        commits a prefix of them. Q=1 matches apply_decode exactly."""
        self._serve_guard()
        params = self._cast_params(params)
        h = self._decode_embed_window(params, tokens, pos)
        h, new_caches = self._serve_blocks(
            params, caches, h,
            lambda attn, p, cache, y: attn.apply_decode_window(p, cache, y, pos),
        )
        logits = self._head()({k: params[k] for k in ("ln_f", "head")}, h)
        return logits, new_caches

    def apply_decode_paged(self, params, caches, table, tokens, pos):
        """Decode over paged pools: ``table`` [B, max_pages] maps each
        slot to its pages, ``tokens`` [B, Q] (Q=1 plain decode, Q=K+1
        spec verify), ``pos`` [B] → (logits [B, Q, V], updated pools)."""
        self._serve_guard()
        params = self._cast_params(params)
        h = self._decode_embed_window(params, tokens, pos)
        h, new_caches = self._serve_blocks(
            params, caches, h,
            lambda attn, p, pool, y: attn.apply_decode_paged(p, pool, table, y, pos),
        )
        logits = self._head()({k: params[k] for k in ("ln_f", "head")}, h)
        return logits, new_caches

    def apply_prefill_paged(self, params, caches, table_row, chunk, start: int):
        """Paged prefill of one chunk: ``table_row`` [max_pages] is the
        admitted slot's page map, ``chunk`` [1, C] tokens at positions
        [start, start+C) → updated pools. ``start`` static, like the
        dense path."""
        self._serve_guard()
        params = self._cast_params(params)
        c = chunk.shape[1]
        h = params["tok_embed"][chunk]
        if not self.rope:
            if start + c > self.max_len:
                raise ValueError(
                    f"prefill window {start + c} exceeds max_len {self.max_len}"
                )
            h = h + params["pos_embed"][start:start + c][None]
        _, new_caches = self._serve_blocks(
            params, caches, h,
            lambda attn, p, pool, y: attn.apply_prefill_paged(
                p, pool, table_row, y, start
            ),
        )
        return new_caches

    def apply_prefill(self, params, caches, chunk, slot, start: int):
        """Prefill one chunk of one slot's prompt: ``chunk`` [1, C]
        tokens at global positions [start, start+C) → updated caches.
        ``start`` is static (one compiled program per chunk index); no
        logits — the engine feeds the prompt's LAST token through
        ``apply_decode`` to emit the first generated token."""
        self._serve_guard()
        params = self._cast_params(params)
        c = chunk.shape[1]
        h = params["tok_embed"][chunk]
        if not self.rope:
            if start + c > self.max_len:
                raise ValueError(
                    f"prefill window {start + c} exceeds max_len {self.max_len}"
                )
            h = h + params["pos_embed"][start:start + c][None]
        _, new_caches = self._serve_blocks(
            params, caches, h,
            lambda attn, p, cache, y: attn.apply_prefill(p, cache, y, slot, start),
        )
        return new_caches

    def apply_features(self, params, state, tokens, *, train=False, rng=None):
        """Pre-head features: embed → blocks → final LayerNorm, WITHOUT
        the vocab projection — the input contract of the fused
        linear-cross-entropy kernel (``tpudml.ops.xent_kernel``), which
        consumes features + head weights and never materializes the
        [B·T, V] logits."""
        params = self._cast_params(params)
        if self._use_fused_ln():
            y, new_state = self._features_deferred(params, state, tokens, train, rng)
            return y, new_state
        h, new_state = self._trunk(params, state, tokens, train, rng)
        h = LayerNorm(self.embed_dim, dtype=self.dtype)(params["ln_f"], h)
        return h, new_state
