from tpudml.models.lenet import LeNet
from tpudml.models.mlp import ForwardMLP
from tpudml.models.resnet import ResNet, ResNet18, ResNet34, ResNet50
from tpudml.models.staged import StagedModel, lenet_stages
from tpudml.models.transformer import (
    TransformerBlock,
    TransformerEmbed,
    TransformerHead,
    TransformerLM,
)

__all__ = [
    "LeNet",
    "ForwardMLP",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "StagedModel",
    "lenet_stages",
    "TransformerBlock",
    "TransformerEmbed",
    "TransformerHead",
    "TransformerLM",
]
