from tpudml.models.lenet import LeNet
from tpudml.models.mlp import ForwardMLP
from tpudml.models.staged import StagedModel, lenet_stages

__all__ = ["LeNet", "ForwardMLP", "StagedModel", "lenet_stages"]
