"""Stage-partitioned models for inter-layer model parallelism (task4).

The reference splits the LeNet into ``SubNetConv`` (conv stages, worker1)
and ``SubNetFC`` (fc stages, worker2) chained by blocking RPC
(codes/task4/model.py:18-66). Here a ``StagedModel`` is the same partition
expressed as data: an ordered list of (name, Module) stages whose parameter
subtrees are sharding units. ``tpudml.parallel.mp`` assigns each stage's
params to a mesh ``stage`` coordinate via GSPMD — XLA then inserts the
inter-stage activation transfers that the reference performed with
``rpc_sync`` round-trips, and gradients/optimizer updates happen where the
parameters live (the DistributedOptimizer-over-RRefs semantic,
codes/task4/model.py:126, by construction).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax

from tpudml.nn import Activation, Conv2D, Dense, Flatten, MaxPool, Module, Sequential


@dataclass(frozen=True)
class StagedModel(Module):
    """Sequential-of-stages; params/state are keyed by stage name so a
    sharding rule can map ``params[name] -> stage s`` wholesale."""

    stages: Sequence[tuple[str, Module]] = ()

    def init(self, key):
        params, state = {}, {}
        keys = jax.random.split(key, max(len(self.stages), 1))
        for (name, stage), k in zip(self.stages, keys):
            p, s = stage.init(k)
            params[name] = p
            if s:
                state[name] = s
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None):
        new_state = {}
        for name, stage in self.stages:
            x, s2 = stage.apply(params[name], state.get(name, {}), x, train=train, rng=rng)
            if s2:
                new_state[name] = s2
        return x, new_state

    def stage_names(self) -> list[str]:
        return [name for name, _ in self.stages]


def lenet_stages(num_classes: int = 10, in_channels: int = 1) -> StagedModel:
    """The reference's exact 2-way split: conv stage / fc stage
    (codes/task4/model.py:18-47)."""
    conv = Sequential(
        layers=(
            Conv2D(in_channels, 6, kernel_size=5, padding=2),
            Activation(jax.nn.relu),
            MaxPool(2),
            Conv2D(6, 16, kernel_size=5, padding="VALID"),
            Activation(jax.nn.relu),
            MaxPool(2),
            Flatten(),
        )
    )
    fc = Sequential(
        layers=(
            Dense(400, 120),
            Activation(jax.nn.relu),
            Dense(120, num_classes),
        )
    )
    return StagedModel(stages=(("conv", conv), ("fc", fc)))
