"""LeNet-style CNN — the workhorse model of reference tasks 1–4.

Architecture parity with the reference's ``Net`` (codes/task1/pytorch/
model.py:16-35, reused verbatim in task2/task3 and split in task4):
conv(1→6, k5, pad 2) → relu → maxpool2 → conv(6→16, k5, valid) → relu →
maxpool2 → flatten(400) → fc(400→120) → relu → fc(120→10).

Implemented NHWC (the XLA:TPU-preferred conv layout); the flatten ordering
therefore differs from torch's NCHW flatten, which is immaterial — it is a
permutation absorbed by the first fc kernel.
"""

from __future__ import annotations

import jax

from tpudml.nn import Activation, Conv2D, Dense, Flatten, MaxPool, Sequential


def LeNet(num_classes: int = 10, in_channels: int = 1) -> Sequential:
    return Sequential(
        layers=(
            Conv2D(in_channels, 6, kernel_size=5, padding=2),
            Activation(jax.nn.relu),
            MaxPool(2),
            Conv2D(6, 16, kernel_size=5, padding="VALID"),
            Activation(jax.nn.relu),
            MaxPool(2),
            Flatten(),
            Dense(400, 120),
            Activation(jax.nn.relu),
            Dense(120, num_classes),
        )
    )
