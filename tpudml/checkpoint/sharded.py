"""Sharded (per-host) checkpointing for GSPMD-partitioned state.

The base store (``tpudml.checkpoint.store``) gathers every leaf to
process 0 — right for replicated DP state, wasteful for pod-scale sharded
state where one host cannot (and should not) hold the whole model. Here
each process writes exactly the shards it owns:

- layout: ``{dir}/step_{N}/shards_p{K}.npz`` + ``manifest_p{K}.json`` per
  process; a shard's global placement travels with it as the per-dimension
  [start, stop) window from ``jax.Array.addressable_shards[...].index``;
- replicated leaves (or replicated copies of sharded ones) are written
  once globally: only the shard with ``replica_id == 0``, by whichever
  process owns it;
- per-process files are written atomically (tmp + rename); the manifest
  records ``num_processes`` so restore can verify every host's file
  arrived before trusting the checkpoint;
- every shard entry carries a CRC-32 of its encoded bytes (format 2,
  mirroring the base store): ``restore_sharded_checkpoint(...,
  verify=True)`` (the default) re-hashes each shard on read and raises
  :class:`~tpudml.checkpoint.store.CheckpointCorruptError` on mismatch,
  so a bit-flipped or truncated shard file can never silently poison a
  resumed run; format-1 checkpoints (no CRCs) still restore with
  structural checks only;
- restore reads ALL shard files and reassembles full host arrays into the
  target pytree — placement back onto a mesh stays the caller's job
  (``jax.device_put`` with the engine's shardings), so any process
  topology can restore any other topology's checkpoint.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any

import jax
import numpy as np

from tpudml.checkpoint.store import (
    CheckpointCorruptError,
    _crc,
    _decode_leaf,
    _encode_leaf,
)
from tpudml.core.dist import process_count, process_index

PyTree = Any

_NPZ = "shards_p{k}.npz"
_MANIFEST = "manifest_p{k}.json"


def _norm_index(index, shape) -> list[list[int]]:
    """slice-tuple → [[start, stop], ...] (full-dim slices normalized)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded_checkpoint(
    directory: str | os.PathLike, tree: PyTree, step: int
) -> str:
    """Write this process's shards of ``tree`` under
    ``directory/step_{step}``; returns that path. Call on EVERY process."""
    directory = os.fspath(directory)
    path = os.path.join(directory, f"step_{step}")
    os.makedirs(path, exist_ok=True)
    proc = process_index()
    arrays: dict[str, np.ndarray] = {}
    meta: dict[str, Any] = {}
    leaves = jax.tree.leaves(tree)
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            shards = leaf.addressable_shards
        else:  # host-side leaf (plain numpy/scalar): process 0 owns it
            if proc != 0:
                continue
            arr, desc = _encode_leaf(np.asarray(leaf))
            key = f"leaf{i}_full"
            arrays[key] = arr
            meta[key] = {
                "leaf": i,
                "index": _norm_index(
                    (slice(None),) * np.ndim(leaf), np.shape(leaf)
                ),
                "desc": desc,
                "crc": _crc(arr),
            }
            continue
        for j, sh in enumerate(shards):
            if sh.replica_id != 0:
                continue  # replicated copy: written once globally
            arr, desc = _encode_leaf(np.asarray(sh.data))
            key = f"leaf{i}_s{j}"
            arrays[key] = arr
            meta[key] = {
                "leaf": i,
                "index": _norm_index(sh.index, leaf.shape),
                "desc": desc,
                "crc": _crc(arr),
            }
    fd, tmp = tempfile.mkstemp(dir=path, suffix=".tmp.npz")
    os.close(fd)
    try:
        np.savez(tmp, **arrays)
        os.replace(tmp, os.path.join(path, _NPZ.format(k=proc)))
    except BaseException:
        os.unlink(tmp)
        raise
    manifest = {
        "format": 2,
        "step": int(step),
        "process": proc,
        "num_processes": process_count(),
        "num_leaves": len(leaves),
        "entries": meta,
    }
    tmp_m = os.path.join(path, f".manifest_p{proc}.tmp")
    with open(tmp_m, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp_m, os.path.join(path, _MANIFEST.format(k=proc)))
    if process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"tpudml.ckpt.sharded.{step}")
    return path


def _read_shard_manifests(path: str) -> list[dict]:
    """All per-process manifests, validated for presence + agreement."""
    manifests = sorted(
        f for f in os.listdir(path) if f.startswith("manifest_p")
    )
    if not manifests:
        raise CheckpointCorruptError(f"no shard manifests under {path}")
    try:
        with open(os.path.join(path, manifests[0])) as f:
            first = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable shard manifest: {e!r}"
        ) from e
    expect = first["num_processes"]
    if len(manifests) != expect:
        raise CheckpointCorruptError(
            f"incomplete checkpoint: {len(manifests)}/{expect} process "
            f"manifests present under {path}"
        )
    out = [first]
    for k in range(1, expect):
        try:
            with open(os.path.join(path, _MANIFEST.format(k=k))) as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                f"{path}: unreadable shard manifest p{k}: {e!r}"
            ) from e
    return out


def restore_sharded_checkpoint(
    path: str | os.PathLike, target: PyTree, *, verify: bool = True
) -> PyTree:
    """Reassemble a sharded checkpoint into full host arrays shaped like
    ``target``. Reads every process's shard file; verifies all hosts'
    manifests are present and every element was covered by some shard.
    With ``verify`` (default) each shard's CRC-32 is re-checked against
    the manifest; mismatches raise :class:`CheckpointCorruptError`."""
    path = os.fspath(path)
    manifests = _read_shard_manifests(path)
    first = manifests[0]
    target_leaves, treedef = jax.tree.flatten(target)
    if first["num_leaves"] != len(target_leaves):
        raise ValueError(
            f"checkpoint has {first['num_leaves']} leaves, target has "
            f"{len(target_leaves)} — structure mismatch"
        )
    out = [None] * len(target_leaves)
    filled = [None] * len(target_leaves)
    for k, man in enumerate(manifests):
        meta = man["entries"]
        try:
            data_ctx = np.load(os.path.join(path, _NPZ.format(k=k)))
        except Exception as e:  # missing/truncated npz payload
            raise CheckpointCorruptError(
                f"{path}: unreadable shard file p{k}: {e!r}"
            ) from e
        with data_ctx as data:
            for key, ent in meta.items():
                i = ent["leaf"]
                try:
                    raw = data[key]
                except Exception as e:
                    raise CheckpointCorruptError(
                        f"{path}: shard {key} missing or undecodable in "
                        f"p{k} payload: {e!r}"
                    ) from e
                if verify and "crc" in ent and _crc(raw) != ent["crc"]:
                    raise CheckpointCorruptError(
                        f"{path}: shard {key} (process {k}) failed CRC "
                        "verification — checkpoint is corrupt"
                    )
                shard = _decode_leaf(raw, ent["desc"])
                window = tuple(slice(a, b) for a, b in ent["index"])
                if out[i] is None:
                    # Windows only bound shards; the target supplies the
                    # full shape (validated below by coverage).
                    shape = np.shape(target_leaves[i])
                    out[i] = np.zeros(shape, shard.dtype)
                    filled[i] = np.zeros(shape, bool)
                out[i][window] = shard
                filled[i][window] = True
    for i, (leaf, mask) in enumerate(zip(out, filled)):
        if leaf is None or not mask.all():
            raise ValueError(
                f"leaf {i}: checkpoint shards do not cover the full array "
                "(corrupt or topology-incompatible checkpoint)"
            )
    return jax.tree.unflatten(treedef, out)


def verify_sharded_checkpoint(path: str | os.PathLike) -> int:
    """Full integrity check of one sharded ``step_*`` dir WITHOUT needing
    a target tree: all process manifests present, every shard decodable,
    every recorded CRC matching. Returns the checkpoint's step. Raises
    :class:`CheckpointCorruptError` on any defect."""
    path = os.fspath(path)
    manifests = _read_shard_manifests(path)
    for k, man in enumerate(manifests):
        try:
            data_ctx = np.load(os.path.join(path, _NPZ.format(k=k)))
        except Exception as e:
            raise CheckpointCorruptError(
                f"{path}: unreadable shard file p{k}: {e!r}"
            ) from e
        with data_ctx as data:
            for key, ent in man["entries"].items():
                try:
                    raw = data[key]
                except Exception as e:
                    raise CheckpointCorruptError(
                        f"{path}: shard {key} missing or undecodable in "
                        f"p{k} payload: {e!r}"
                    ) from e
                if "crc" in ent and _crc(raw) != ent["crc"]:
                    raise CheckpointCorruptError(
                        f"{path}: shard {key} (process {k}) failed CRC "
                        "verification — checkpoint is corrupt"
                    )
    return int(manifests[0]["step"])


def restore_latest_valid_sharded(
    directory: str | os.PathLike, target: PyTree, *, verify: bool = True
) -> PyTree:
    """Sharded counterpart of
    :func:`tpudml.checkpoint.store.restore_latest_valid`: walk the
    ``step_*`` dirs newest-first, restore the first one that passes
    verification, warn (stderr) about each corrupt/partial dir skipped.
    Returns ``target`` untouched when no step dirs exist; raises
    :class:`CheckpointCorruptError` when step dirs exist but none is
    restorable."""
    import sys

    from tpudml.checkpoint.store import _all_step_dirs

    directory = os.fspath(directory)
    dirs = _all_step_dirs(directory)
    if not dirs:
        return target
    failures = []
    for step, path in reversed(dirs):
        try:
            return restore_sharded_checkpoint(path, target, verify=verify)
        except (CheckpointCorruptError, ValueError, OSError, KeyError) as e:
            failures.append(f"step_{step}: {e}")
            print(
                f"[tpudml.checkpoint] skipping invalid sharded checkpoint "
                f"step_{step}: {e}",
                file=sys.stderr,
            )
    raise CheckpointCorruptError(
        f"no valid sharded checkpoint under {directory}; tried "
        + "; ".join(failures)
    )
