"""Checkpoint / resume subsystem.

The reference has no persistence at all: model state exists only for the
process lifetime and is re-synchronised by a rank-0 broadcast at train
start (``init_parameters``, codes/task2/dist_utils.py:33-37;
SURVEY.md §5.4 flags this as a gap to fill, not copy). This module adds
the TPU-pod-grade story: atomic pytree checkpoints written by process 0,
restored identically on every host — the persistent generalisation of the
reference's broadcast-from-rank-0 contract.

Design notes (TPU-first):
- A checkpoint is one ``.npz`` of pytree leaves + a JSON manifest. Leaves
  are fetched with ``jax.device_get`` (one host sync, not per-leaf).
- Extended dtypes (bfloat16 &c.) aren't npz-native; they are stored as raw
  uint16/uint8 views and the true dtype recorded in the manifest.
- Writes go to a temp dir then ``os.replace`` — a crash mid-write never
  corrupts the latest checkpoint (required for preemptible TPU pods).
- Restore takes a *target* pytree (e.g. a freshly built TrainState) and
  refills its leaves, so the treedef never needs serialising.
"""

from tpudml.checkpoint.sharded import (
    restore_sharded_checkpoint,
    save_sharded_checkpoint,
)
from tpudml.checkpoint.store import (
    CheckpointManager,
    checkpoint_hook,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)

__all__ = [
    "CheckpointManager",
    "checkpoint_hook",
    "latest_checkpoint",
    "restore_checkpoint",
    "restore_sharded_checkpoint",
    "save_checkpoint",
    "save_sharded_checkpoint",
]
