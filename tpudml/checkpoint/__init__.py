"""Checkpoint / resume subsystem.

The reference has no persistence at all: model state exists only for the
process lifetime and is re-synchronised by a rank-0 broadcast at train
start (``init_parameters``, codes/task2/dist_utils.py:33-37;
SURVEY.md §5.4 flags this as a gap to fill, not copy). This module adds
the TPU-pod-grade story: atomic pytree checkpoints written by process 0,
restored identically on every host — the persistent generalisation of the
reference's broadcast-from-rank-0 contract.

Design notes (TPU-first):
- A checkpoint is one ``.npz`` of pytree leaves + a JSON manifest. Leaves
  are fetched with ``jax.device_get`` (one host sync, not per-leaf).
- Extended dtypes (bfloat16 &c.) aren't npz-native; they are stored as raw
  uint16/uint8 views and the true dtype recorded in the manifest.
- Writes go to a temp dir then ``os.replace`` — a crash mid-write never
  corrupts the latest checkpoint (required for preemptible TPU pods).
- Restore takes a *target* pytree (e.g. a freshly built TrainState) and
  refills its leaves, so the treedef never needs serialising.
- Integrity (format 2): every leaf/shard carries a CRC-32 in the
  manifest; restores verify by default and raise
  :class:`CheckpointCorruptError` instead of silently loading damaged
  state. :func:`restore_latest_valid` (and the sharded counterpart)
  walks ``step_*`` dirs newest-first past corrupt or partial
  checkpoints, and :class:`CheckpointManager` retention never deletes
  the only valid checkpoint.
"""

from tpudml.checkpoint.sharded import (
    restore_latest_valid_sharded,
    restore_sharded_checkpoint,
    save_sharded_checkpoint,
    verify_sharded_checkpoint,
)
from tpudml.checkpoint.store import (
    CheckpointCorruptError,
    CheckpointHook,
    CheckpointManager,
    checkpoint_hook,
    latest_checkpoint,
    restore_checkpoint,
    restore_latest_valid,
    save_checkpoint,
    verify_checkpoint,
)

__all__ = [
    "CheckpointCorruptError",
    "CheckpointHook",
    "CheckpointManager",
    "checkpoint_hook",
    "latest_checkpoint",
    "restore_checkpoint",
    "restore_latest_valid",
    "restore_latest_valid_sharded",
    "restore_sharded_checkpoint",
    "save_checkpoint",
    "save_sharded_checkpoint",
    "verify_checkpoint",
    "verify_sharded_checkpoint",
]
