"""Pytree checkpoint store: atomic npz + manifest, process-0 writes."""

from __future__ import annotations

import atexit
import json
import os
import re
import shutil
import sys
import tempfile
import threading
from typing import Any, Callable

import jax
import numpy as np

from tpudml.core.dist import process_count, process_index

PyTree = Any

_MANIFEST = "manifest.json"
_LEAVES = "leaves.npz"
_STEP_DIR = re.compile(r"^step_(\d+)$")


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def _encode_leaf(x: np.ndarray) -> tuple[np.ndarray, dict | None]:
    """npz-compatible array + (if the dtype needed masking) a descriptor."""
    if x.dtype.kind in "biufc" and x.dtype.name in np.sctypeDict:
        return x, None
    raw = x.view(np.uint16 if x.dtype.itemsize == 2 else np.uint8)
    return raw, {"dtype": x.dtype.name, "shape": list(x.shape)}


def _decode_leaf(raw: np.ndarray, desc: dict | None) -> np.ndarray:
    if desc is None:
        return raw
    return raw.view(_resolve_dtype(desc["dtype"])).reshape(desc["shape"])


def _fetch_leaf(x: Any) -> Any:
    """Host copy of a leaf. Arrays whose shards span other hosts' devices
    can't be device_get by one process; allgather them across processes
    (every process calls this, so the collective is globally consistent)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(x, tiled=True)
    return jax.device_get(x)


def _barrier(tag: str) -> None:
    if process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"tpudml.checkpoint.{tag}")


def save_checkpoint(
    directory: str | os.PathLike,
    tree: PyTree,
    step: int,
    *,
    metadata: dict | None = None,
) -> str:
    """Write ``tree`` under ``directory/step_{step}``; returns that path.

    Only process 0 writes (shared-filesystem model, like the reference's
    rank-0-owns-the-parameters convention); every process returns after a
    cross-host barrier so a subsequent restore on any host sees the files.
    """
    directory = os.fspath(directory)
    path = os.path.join(directory, f"step_{step}")
    try:
        # Every process materialises the leaves: GSPMD-sharded arrays can
        # span devices process 0 cannot address, so cross-host shards are
        # allgathered (a collective — all processes must participate).
        leaves = [_fetch_leaf(x) for x in jax.tree.leaves(tree)]
        if process_index() == 0:
            arrays, descs = {}, {}
            for i, leaf in enumerate(leaves):
                arr, desc = _encode_leaf(np.asarray(leaf))
                arrays[f"leaf_{i:05d}"] = arr
                if desc is not None:
                    descs[str(i)] = desc
            os.makedirs(directory, exist_ok=True)
            tmp = tempfile.mkdtemp(prefix=".tmp_ckpt_", dir=directory)
            try:
                np.savez(os.path.join(tmp, _LEAVES), **arrays)
                manifest = {
                    "step": int(step),
                    "num_leaves": len(leaves),
                    "extended_dtypes": descs,
                    "metadata": metadata or {},
                }
                with open(os.path.join(tmp, _MANIFEST), "w") as f:
                    json.dump(manifest, f)
                if os.path.isdir(path):
                    shutil.rmtree(path)
                os.replace(tmp, path)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
    finally:
        # Reached on all paths: a process-0 write failure must not leave
        # the other hosts blocked in the barrier forever.
        _barrier(f"save.{step}")
    return path


def latest_checkpoint(directory: str | os.PathLike) -> str | None:
    """Path of the highest-step checkpoint under ``directory`` (None if empty)."""
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        m = _STEP_DIR.match(name)
        if m and os.path.isfile(os.path.join(directory, name, _MANIFEST)):
            steps.append(int(m.group(1)))
    if not steps:
        return None
    return os.path.join(directory, f"step_{max(steps)}")


def restore_checkpoint(path: str | os.PathLike, target: PyTree) -> PyTree:
    """Refill ``target``'s leaves from the checkpoint at ``path``.

    Every process reads the same files, so all hosts resume bitwise
    identical — the persistent form of the reference's start-of-training
    parameter broadcast (codes/task2/dist_utils.py:33-37). Dtypes follow
    the checkpoint; shapes must match the target's.
    """
    path = os.fspath(path)
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    target_leaves, treedef = jax.tree.flatten(target)
    if manifest["num_leaves"] != len(target_leaves):
        raise ValueError(
            f"checkpoint has {manifest['num_leaves']} leaves, target has "
            f"{len(target_leaves)} — structure mismatch"
        )
    descs = manifest["extended_dtypes"]
    with np.load(os.path.join(path, _LEAVES)) as data:
        leaves = [
            _decode_leaf(data[f"leaf_{i:05d}"], descs.get(str(i)))
            for i in range(len(target_leaves))
        ]
    for i, (new, old) in enumerate(zip(leaves, target_leaves)):
        if hasattr(old, "shape") and tuple(new.shape) != tuple(np.shape(old)):
            raise ValueError(
                f"leaf {i}: checkpoint shape {tuple(new.shape)} != target "
                f"shape {tuple(np.shape(old))}"
            )
    return jax.tree.unflatten(treedef, leaves)


class CheckpointManager:
    """Rolling checkpoint directory with retention.

    Usage::

        mgr = CheckpointManager(run_dir, keep=3)
        mgr.save(train_state, step)
        ts = mgr.restore_latest(train_state)   # no-op passthrough if empty

    ``async_write=True`` moves the npz serialization + atomic rename to a
    background thread: ``save`` still synchronously snapshots the leaves to
    host memory (so the training step can donate/overwrite its buffers
    immediately) but returns before the file I/O completes. One write is in
    flight at a time — a new save (or ``wait()``/``restore_latest``) joins
    the previous one first, so on-disk state is always a complete
    checkpoint. Not supported multi-process (the cross-host barrier must
    stay synchronous).
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        keep: int = 3,
        async_write: bool = False,
    ):
        self.directory = os.fspath(directory)
        self.keep = keep
        if async_write and process_count() > 1:
            raise ValueError(
                "async_write is single-process only (the multi-host save "
                "barrier must remain synchronous)"
            )
        self.async_write = async_write
        self._pending: threading.Thread | None = None
        self._pending_error: list[BaseException] = []
        if async_write:
            # A failed FINAL save must not vanish at interpreter exit: the
            # shutdown join alone would discard the stored exception.
            atexit.register(self._warn_on_exit)

    def _warn_on_exit(self) -> None:
        try:
            self.wait()
        except BaseException as e:  # stderr is all we have at exit
            print(f"[tpudml.checkpoint] final async save FAILED: {e!r}", file=sys.stderr)

    def wait(self) -> None:
        """Block until an in-flight async save (if any) has hit disk;
        re-raise its error, if it failed, at this call site."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._pending_error:
            raise self._pending_error.pop()

    def save(self, tree: PyTree, step: int, metadata: dict | None = None) -> str:
        if not self.async_write:
            path = save_checkpoint(self.directory, tree, step, metadata=metadata)
            self._prune()
            return path
        self.wait()  # one write in flight; surface any prior failure
        # Synchronous part: host snapshot (cheap vs the file write) so the
        # caller may mutate/donate device buffers right away.
        leaves = [_fetch_leaf(x) for x in jax.tree.leaves(tree)]
        treedef = jax.tree.structure(tree)
        snapshot = jax.tree.unflatten(treedef, leaves)
        metadata = dict(metadata) if metadata else None  # snapshot by value
        path = os.path.join(self.directory, f"step_{step}")

        def write():
            try:
                save_checkpoint(self.directory, snapshot, step, metadata=metadata)
                self._prune()
            except BaseException as e:  # surfaced on next wait()/save()
                self._pending_error.append(e)

        # Non-daemon: the interpreter joins it at normal exit, so a final
        # save can't be silently truncated by process shutdown.
        self._pending = threading.Thread(target=write, daemon=False)
        self._pending.start()
        return path

    def _prune(self) -> None:
        if process_index() != 0 or not os.path.isdir(self.directory):
            return
        steps = sorted(
            int(m.group(1))
            for name in os.listdir(self.directory)
            if (m := _STEP_DIR.match(name))
        )
        for s in steps[: -self.keep] if self.keep > 0 else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"), True)

    def latest_step(self) -> int | None:
        self.wait()
        path = latest_checkpoint(self.directory)
        if path is None:
            return None
        return int(_STEP_DIR.match(os.path.basename(path)).group(1))

    def restore_latest(self, target: PyTree) -> PyTree:
        self.wait()
        path = latest_checkpoint(self.directory)
        if path is None:
            return target
        return restore_checkpoint(path, target)


def checkpoint_hook(manager: CheckpointManager, every: int) -> Callable:
    """``train_loop`` hook: save the TrainState every ``every`` optimizer
    steps (host-side; does not interrupt the compiled step).

    Saves are keyed by the TrainState's monotonic ``step`` counter — not a
    loop-local count that restarts on resume (which would let retention
    prune new checkpoints in favour of stale ones). The device step is
    synced ONCE (first call) to learn the offset from the loop counter;
    after that the hook is pure host arithmetic, preserving the training
    loop's async dispatch on the iterations that don't save.
    """
    base: int | None = None

    def hook(*, epoch, step, train_state, metrics, **_):
        nonlocal base
        if base is None:
            base = int(train_state.step) - step
        global_step = base + step
        if every and global_step % every == 0:
            manager.save(train_state, global_step, metadata={"epoch": epoch})

    return hook
