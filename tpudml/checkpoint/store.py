"""Pytree checkpoint store: atomic npz + manifest, process-0 writes.

Integrity model (format 2): the manifest carries a CRC-32 per encoded
leaf, computed over exactly the bytes that land in ``leaves.npz``.
``restore_checkpoint`` verifies them by default before decoding, so a
truncated payload, a flipped bit, or a missing file is a
:class:`CheckpointCorruptError` — never silently-wrong params.
:func:`restore_latest_valid` turns that detection into fallback: walk
``step_*`` dirs newest-first and restore the first checkpoint that
verifies, skipping vandalized/partial ones. Format-1 checkpoints (no
``checksums`` key) still verify structurally (every leaf readable).
"""

from __future__ import annotations

import atexit
import json
import os
import re
import shutil
import sys
import tempfile
import threading
import zlib
from typing import Any, Callable

import jax
import numpy as np

from tpudml.core.dist import process_count, process_index

PyTree = Any

_MANIFEST = "manifest.json"
_LEAVES = "leaves.npz"
_STEP_DIR = re.compile(r"^step_(\d+)$")


class CheckpointCorruptError(ValueError):
    """A checkpoint failed verification (missing/truncated/corrupt)."""


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def _encode_leaf(x: np.ndarray) -> tuple[np.ndarray, dict | None]:
    """npz-compatible array + (if the dtype needed masking) a descriptor."""
    if x.dtype.kind in "biufc" and x.dtype.name in np.sctypeDict:
        return x, None
    raw = x.view(np.uint16 if x.dtype.itemsize == 2 else np.uint8)
    return raw, {"dtype": x.dtype.name, "shape": list(x.shape)}


def _decode_leaf(raw: np.ndarray, desc: dict | None) -> np.ndarray:
    if desc is None:
        return raw
    return raw.view(_resolve_dtype(desc["dtype"])).reshape(desc["shape"])


def _fetch_leaf(x: Any) -> Any:
    """Host copy of a leaf. Arrays whose shards span other hosts' devices
    can't be device_get by one process; allgather them across processes
    (every process calls this, so the collective is globally consistent)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(x, tiled=True)
    return jax.device_get(x)


def _barrier(tag: str) -> None:
    if process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"tpudml.checkpoint.{tag}")


def save_checkpoint(
    directory: str | os.PathLike,
    tree: PyTree,
    step: int,
    *,
    metadata: dict | None = None,
) -> str:
    """Write ``tree`` under ``directory/step_{step}``; returns that path.

    Only process 0 writes (shared-filesystem model, like the reference's
    rank-0-owns-the-parameters convention); every process returns after a
    cross-host barrier so a subsequent restore on any host sees the files.
    """
    directory = os.fspath(directory)
    path = os.path.join(directory, f"step_{step}")
    from tpudml.obs.tracer import get_tracer

    # Ambient flight-recorder span (tpudml.obs): a disabled tracer makes
    # this a shared no-op context manager — zero allocation.
    with get_tracer().span(
        "checkpoint_save", cat="checkpoint", args={"step": int(step)}
    ):
        try:
            # Every process materialises the leaves: GSPMD-sharded arrays
            # can span devices process 0 cannot address, so cross-host
            # shards are allgathered (a collective — all processes must
            # participate).
            leaves = [_fetch_leaf(x) for x in jax.tree.leaves(tree)]
            if process_index() == 0:
                arrays, descs, checksums = {}, {}, {}
                for i, leaf in enumerate(leaves):
                    arr, desc = _encode_leaf(np.asarray(leaf))
                    arrays[f"leaf_{i:05d}"] = arr
                    checksums[f"leaf_{i:05d}"] = _crc(arr)
                    if desc is not None:
                        descs[str(i)] = desc
                os.makedirs(directory, exist_ok=True)
                tmp = tempfile.mkdtemp(prefix=".tmp_ckpt_", dir=directory)
                try:
                    np.savez(os.path.join(tmp, _LEAVES), **arrays)
                    manifest = {
                        "format": 2,
                        "step": int(step),
                        "num_leaves": len(leaves),
                        "extended_dtypes": descs,
                        "checksums": checksums,
                        "metadata": metadata or {},
                    }
                    with open(os.path.join(tmp, _MANIFEST), "w") as f:
                        json.dump(manifest, f)
                    if os.path.isdir(path):
                        shutil.rmtree(path)
                    os.replace(tmp, path)
                except BaseException:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
        finally:
            # Reached on all paths: a process-0 write failure must not
            # leave the other hosts blocked in the barrier forever.
            _barrier(f"save.{step}")
    return path


def latest_checkpoint(directory: str | os.PathLike) -> str | None:
    """Path of the highest-step checkpoint under ``directory`` (None if empty)."""
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        m = _STEP_DIR.match(name)
        if m and os.path.isfile(os.path.join(directory, name, _MANIFEST)):
            steps.append(int(m.group(1)))
    if not steps:
        return None
    return os.path.join(directory, f"step_{max(steps)}")


def _read_manifest(path: str) -> dict:
    mpath = os.path.join(path, _MANIFEST)
    try:
        with open(mpath) as f:
            return json.load(f)
    except FileNotFoundError:
        raise CheckpointCorruptError(f"{path}: missing {_MANIFEST}") from None
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointCorruptError(f"{path}: unreadable manifest: {e}") from e


def restore_checkpoint(
    path: str | os.PathLike, target: PyTree, *, verify: bool = True
) -> PyTree:
    """Refill ``target``'s leaves from the checkpoint at ``path``.

    Every process reads the same files, so all hosts resume bitwise
    identical — the persistent form of the reference's start-of-training
    parameter broadcast (codes/task2/dist_utils.py:33-37). Dtypes follow
    the checkpoint; shapes must match the target's.

    ``verify=True`` (default) checks each encoded leaf against the
    manifest's CRC-32 before decoding and raises
    :class:`CheckpointCorruptError` on any mismatch, truncation, or
    unreadable file; ``verify=False`` trusts the bytes.
    """
    path = os.fspath(path)
    from tpudml.obs.tracer import get_tracer

    with get_tracer().span(
        "checkpoint_restore", cat="checkpoint",
        args={"path": os.path.basename(path), "verify": bool(verify)},
    ):
        manifest = _read_manifest(path)
        target_leaves, treedef = jax.tree.flatten(target)
        if manifest["num_leaves"] != len(target_leaves):
            raise ValueError(
                f"checkpoint has {manifest['num_leaves']} leaves, target has "
                f"{len(target_leaves)} — structure mismatch"
            )
        descs = manifest["extended_dtypes"]
        checksums = manifest.get("checksums", {})
        leaves = []
        try:
            with np.load(os.path.join(path, _LEAVES)) as data:
                for i in range(len(target_leaves)):
                    key = f"leaf_{i:05d}"
                    raw = data[key]
                    if (
                        verify and key in checksums
                        and _crc(raw) != checksums[key]
                    ):
                        raise CheckpointCorruptError(
                            f"{path}: leaf {i} checksum mismatch (corrupt data)"
                        )
                    leaves.append(_decode_leaf(raw, descs.get(str(i))))
        except CheckpointCorruptError:
            raise
        except Exception as e:  # truncated zip, missing member, zlib error …
            raise CheckpointCorruptError(
                f"{path}: unreadable {_LEAVES}: {e!r}"
            ) from e
        for i, (new, old) in enumerate(zip(leaves, target_leaves)):
            if hasattr(old, "shape") and tuple(new.shape) != tuple(np.shape(old)):
                raise ValueError(
                    f"leaf {i}: checkpoint shape {tuple(new.shape)} != target "
                    f"shape {tuple(np.shape(old))}"
                )
        return jax.tree.unflatten(treedef, leaves)


def verify_checkpoint(path: str | os.PathLike) -> int:
    """Full integrity check of one ``step_`` dir; returns its step.

    Raises :class:`CheckpointCorruptError` on a missing/unreadable
    manifest, missing/truncated/unreadable ``leaves.npz``, or any leaf
    whose CRC-32 disagrees with the manifest. Format-1 checkpoints
    (no ``checksums``) pass if every leaf is structurally readable.
    """
    path = os.fspath(path)
    from tpudml.obs.tracer import get_tracer

    with get_tracer().span(
        "checkpoint_verify", cat="checkpoint",
        args={"path": os.path.basename(path)},
    ):
        manifest = _read_manifest(path)
        checksums = manifest.get("checksums", {})
        try:
            with np.load(os.path.join(path, _LEAVES)) as data:
                for i in range(int(manifest["num_leaves"])):
                    key = f"leaf_{i:05d}"
                    raw = data[key]
                    if key in checksums and _crc(raw) != checksums[key]:
                        raise CheckpointCorruptError(
                            f"{path}: leaf {i} checksum mismatch (corrupt data)"
                        )
        except CheckpointCorruptError:
            raise
        except Exception as e:
            raise CheckpointCorruptError(
                f"{path}: unreadable {_LEAVES}: {e!r}"
            ) from e
        return int(manifest["step"])


def _all_step_dirs(directory: str) -> list[tuple[int, str]]:
    """(step, path) of every ``step_`` dir, manifest or not, ascending."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _STEP_DIR.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def restore_latest_valid(
    directory: str | os.PathLike, target: PyTree, *, verify: bool = True
) -> PyTree:
    """Restore from the NEWEST checkpoint that verifies, walking
    ``step_*`` dirs newest-first past corrupt/partial ones (each skip is
    reported on stderr). Passthrough of ``target`` when the directory
    holds no ``step_`` dirs at all (fresh start); raises
    :class:`CheckpointCorruptError` when checkpoints exist but NONE is
    restorable — silently restarting from scratch would discard the run.
    """
    directory = os.fspath(directory)
    dirs = _all_step_dirs(directory)
    if not dirs:
        return target
    failures = []
    for step, path in reversed(dirs):
        try:
            return restore_checkpoint(path, target, verify=verify)
        except (CheckpointCorruptError, ValueError, OSError, KeyError) as e:
            failures.append(f"step_{step}: {e}")
            print(
                f"[tpudml.checkpoint] skipping invalid checkpoint "
                f"step_{step}: {e}",
                file=sys.stderr,
            )
    raise CheckpointCorruptError(
        f"{directory}: no valid checkpoint among {len(dirs)} step dirs — "
        + "; ".join(failures)
    )


class CheckpointManager:
    """Rolling checkpoint directory with retention.

    Usage::

        mgr = CheckpointManager(run_dir, keep=3)
        mgr.save(train_state, step)
        ts = mgr.restore_latest(train_state)   # no-op passthrough if empty

    ``async_write=True`` moves the npz serialization + atomic rename to a
    background thread: ``save`` still synchronously snapshots the leaves to
    host memory (so the training step can donate/overwrite its buffers
    immediately) but returns before the file I/O completes. One write is in
    flight at a time — a new save (or ``wait()``/``restore_latest``) joins
    the previous one first, so on-disk state is always a complete
    checkpoint. Not supported multi-process (the cross-host barrier must
    stay synchronous).
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        keep: int = 3,
        async_write: bool = False,
    ):
        self.directory = os.fspath(directory)
        self.keep = keep
        if async_write and process_count() > 1:
            raise ValueError(
                "async_write is single-process only (the multi-host save "
                "barrier must remain synchronous)"
            )
        self.async_write = async_write
        self._pending: threading.Thread | None = None
        self._pending_error: list[BaseException] = []
        if async_write:
            # A failed FINAL save must not vanish at interpreter exit: the
            # shutdown join alone would discard the stored exception.
            atexit.register(self._warn_on_exit)

    def _warn_on_exit(self) -> None:
        try:
            self.wait()
        except BaseException as e:  # stderr is all we have at exit
            print(f"[tpudml.checkpoint] final async save FAILED: {e!r}", file=sys.stderr)

    def wait(self) -> None:
        """Block until an in-flight async save (if any) has hit disk;
        re-raise its error, if it failed, at this call site."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._pending_error:
            raise self._pending_error.pop()

    def save(self, tree: PyTree, step: int, metadata: dict | None = None) -> str:
        if not self.async_write:
            path = save_checkpoint(self.directory, tree, step, metadata=metadata)
            self._prune()
            return path
        self.wait()  # one write in flight; surface any prior failure
        # Synchronous part: host snapshot (cheap vs the file write) so the
        # caller may mutate/donate device buffers right away.
        leaves = [_fetch_leaf(x) for x in jax.tree.leaves(tree)]
        treedef = jax.tree.structure(tree)
        snapshot = jax.tree.unflatten(treedef, leaves)
        metadata = dict(metadata) if metadata else None  # snapshot by value
        path = os.path.join(self.directory, f"step_{step}")

        def write():
            try:
                save_checkpoint(self.directory, snapshot, step, metadata=metadata)
                self._prune()
            except BaseException as e:  # surfaced on next wait()/save()
                self._pending_error.append(e)

        # Non-daemon: the interpreter joins it at normal exit, so a final
        # save can't be silently truncated by process shutdown.
        self._pending = threading.Thread(target=write, daemon=False)
        self._pending.start()
        return path

    def _valid(self, step: int) -> bool:
        try:
            verify_checkpoint(os.path.join(self.directory, f"step_{step}"))
            return True
        except CheckpointCorruptError:
            return False

    def _prune(self) -> None:
        """Keep-last-K retention that never deletes the ONLY valid
        checkpoint: when none of the K newest verifies (e.g. the latest
        saves were vandalized/partial), the newest valid older step is
        spared so ``restore_latest_valid`` always has a fallback. The
        verification reads happen only when something is actually due
        for deletion."""
        if process_index() != 0 or not os.path.isdir(self.directory):
            return
        steps = sorted(
            int(m.group(1))
            for name in os.listdir(self.directory)
            if (m := _STEP_DIR.match(name))
        )
        if self.keep <= 0 or len(steps) <= self.keep:
            return
        kept, candidates = steps[-self.keep:], steps[: -self.keep]
        if not any(self._valid(s) for s in kept):
            for s in reversed(candidates):
                if self._valid(s):
                    candidates = [c for c in candidates if c != s]
                    break
        for s in candidates:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"), True)

    def latest_step(self) -> int | None:
        self.wait()
        path = latest_checkpoint(self.directory)
        if path is None:
            return None
        return int(_STEP_DIR.match(os.path.basename(path)).group(1))

    def restore_latest(self, target: PyTree, *, verify: bool = True) -> PyTree:
        """Restore the newest VALID checkpoint (falling back past corrupt
        ones — see :func:`restore_latest_valid`); passthrough if the
        directory holds no checkpoints."""
        self.wait()
        return restore_latest_valid(self.directory, target, verify=verify)


def checkpoint_hook(manager: CheckpointManager, every: int) -> Callable:
    """``train_loop`` hook: save the TrainState every ``every`` optimizer
    steps (host-side; does not interrupt the compiled step).

    Saves are keyed by the TrainState's monotonic ``step`` counter — not a
    loop-local count that restarts on resume (which would let retention
    prune new checkpoints in favour of stale ones). The device step is
    synced ONCE (first call) to learn the offset from the loop counter;
    after that the hook is pure host arithmetic, preserving the training
    loop's async dispatch on the iterations that don't save.
    """
    base: int | None = None

    def hook(*, epoch, step, train_state, metrics, **_):
        nonlocal base
        if base is None:
            base = int(train_state.step) - step
        global_step = base + step
        if every and global_step % every == 0:
            manager.save(train_state, global_step, metadata={"epoch": epoch})

    return hook


class CheckpointHook:
    """Object form of :func:`checkpoint_hook` for step-granular resume:
    ``CheckpointHook(manager, every_n_steps=50)`` saves every N optimizer
    steps mid-epoch; combined with ``train_loop``'s fast-forwarding
    restore, a run preempted between epoch boundaries resumes bit-exact
    from the last saved step instead of redoing the partial epoch."""

    def __init__(self, manager: CheckpointManager, every_n_steps: int):
        if every_n_steps < 1:
            raise ValueError("every_n_steps must be >= 1")
        self.manager = manager
        self.every_n_steps = every_n_steps
        self._hook = checkpoint_hook(manager, every_n_steps)

    def __call__(self, **kwargs) -> None:
        self._hook(**kwargs)
