"""Serve event log → trace spans: a pure, deterministic conversion.

The serving engine's event log is already byte-deterministic (tuples of
``(kind, rid, slot, step)`` plus ``("spec", rid, slot, step, accepted)``
on the virtual clock — see serve/engine.py), so the observability layer
does NOT instrument the serve loop: it converts the finished report's
events into Chrome trace events after the fact. Two identical runs
therefore produce byte-identical ``trace.json`` files — the determinism
the golden tests pin.

Track model: tid 0 is the queue/admission track (reject / defer /
expire-from-queue, which carry slot −1); tid ``slot+1`` is that decode
slot's track. Each request's residency in a slot becomes one complete
span (``slot<i>:rid<r>``, admit → evict/expire), with the per-event
instants (admit/evict/expire/spec) overlaid on the same track.

Timestamps: ``step × step_time_s`` in microseconds when the engine ran
on its virtual clock, else the raw step index as microseconds — both
integer-exact and run-independent.
"""

from __future__ import annotations

from pathlib import Path

from tpudml.obs.tracer import chrome_trace_doc, dump_trace

QUEUE_EVENTS = ("reject", "defer")


def _ts_us(step: int, step_time_s: float | None) -> int:
    if step_time_s is None:
        return int(step)
    return int(round(step * step_time_s * 1e6))


def serve_trace_events(events: list, step_time_s: float | None = None) -> list[dict]:
    """Chrome trace events (sorted, deterministic) from a serve event log.

    ``events`` is ``ServeReport.events`` verbatim; ``step_time_s`` should
    be the ``ServeConfig.step_time_s`` the run used (None → step-index
    timestamps). Pure function of its inputs."""
    out: list[dict] = []
    open_spans: dict[tuple[int, int], int] = {}  # (rid, slot) -> admit step
    max_step = 0
    for ev in events:
        kind, rid, slot, step = ev[0], int(ev[1]), int(ev[2]), int(ev[3])
        max_step = max(max_step, step)
        tid = 0 if slot < 0 else slot + 1
        args = {"rid": rid, "step": step}
        if kind == "spec":
            args["accepted"] = int(ev[4])
        out.append({
            "name": kind, "cat": "serve", "ph": "i",
            "ts": _ts_us(step, step_time_s), "tid": tid, "s": "t",
            "args": args,
        })
        if kind == "admit":
            open_spans[(rid, slot)] = step
        elif kind in ("evict", "expire") and slot >= 0:
            start = open_spans.pop((rid, slot), None)
            if start is not None:
                out.append(_residency(rid, slot, start, step, step_time_s))
    # Requests still resident when the log ends close at the last step —
    # the honest reading of an in-flight slot.
    for (rid, slot), start in sorted(open_spans.items()):
        out.append(_residency(rid, slot, start, max_step, step_time_s))
    out.sort(key=lambda e: (e["ts"], -e.get("dur", 0), e["tid"],
                            e["name"], repr(e.get("args"))))
    return out


def _residency(rid: int, slot: int, start: int, end: int,
               step_time_s: float | None) -> dict:
    t0 = _ts_us(start, step_time_s)
    return {
        "name": f"slot{slot}:rid{rid}", "cat": "serve", "ph": "X",
        "ts": t0, "dur": max(_ts_us(end, step_time_s) - t0, 0),
        "tid": slot + 1, "args": {"rid": rid, "admit_step": start,
                                  "release_step": end},
    }


def write_serve_trace(
    report,
    path: str | Path,
    step_time_s: float | None = None,
    pid: int | None = None,
) -> Path:
    """``trace.json`` from a finished :class:`ServeReport` — byte-
    deterministic whenever the run itself was (virtual clock + fixed
    workload). ``pid`` defaults to ``jax.process_index()``."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    doc = chrome_trace_doc(serve_trace_events(report.events, step_time_s), pid=pid)
    path.write_text(dump_trace(doc))
    return path
