"""In-graph step telemetry: the :class:`StepStats` pytree.

The engines' jitted steps already return a metrics dict through their
``out_specs``; with ``obs=`` enabled they additionally return a small
:class:`StepStats` pytree under ``metrics["step_stats"]`` — loss, global
gradient norm, the sentinel's device-side skip counters, and accumulated
ring-model comm bytes — ALL computed inside the existing program (no
host callbacks, no extra dispatch, no per-step sync). ``train_loop``
streams the leaves to :class:`MetricsWriter` at its logging cadence,
where the loss materialization already forces the one host sync.

The comm-bytes leaf is priced at trace time from the gradient/state
shapes using the same ring model as the static analyzer and the
measured-path ``CommStats`` (``comm.timing.collective_wire_bytes``),
baked into the program as a constant and multiplied by the step counter
— which is why it costs nothing per step and stays comparable with both
the ``--cost`` reports and the drift monitor.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from tpudml.comm.timing import collective_wire_bytes

PyTree = Any


@jax.tree_util.register_dataclass
@dataclass
class StepStats:
    """One step's in-graph telemetry; every leaf is a replicated scalar."""

    loss: jax.Array
    grad_norm: jax.Array
    skips: jax.Array          # sentinel total skipped steps (0 w/o sentinel)
    consecutive: jax.Array    # sentinel consecutive-skip counter
    comm_bytes: jax.Array     # accumulated ring-model wire bytes/device

    def to_scalars(self) -> dict:
        """Host-side flattening for MetricsWriter/summaries."""
        return {
            "loss": self.loss,
            "grad_norm": self.grad_norm,
            "sentinel_skips": self.skips,
            "sentinel_consecutive": self.consecutive,
            "comm_bytes": self.comm_bytes,
        }


def tree_bytes(tree: PyTree) -> float:
    """Total payload bytes of a pytree's array leaves (trace-time shapes)."""
    return float(sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(tree)
        if hasattr(leaf, "dtype")
    ))


def grad_normsq(grads: PyTree) -> jax.Array:
    """Sum of squared gradient entries as an f32 scalar (in-graph).
    Callers apply whatever cross-replica reduction their sharding needs
    before taking the square root."""
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in jax.tree.leaves(grads)]
    return sum(leaves) if leaves else jnp.float32(0.0)


def dp_wire_bytes_per_step(
    grads: PyTree,
    model_state: PyTree,
    world: int,
    aggregation: str = "allreduce",
    zero1: bool = False,
) -> float:
    """Ring-model wire bytes one DP step moves per device, from trace-time
    shapes: the gradient aggregation (strategy-dependent) plus the
    model-state pmean. ZeRO-1 replaces aggregation with reduce-scatter +
    chunk all_gather — same 2·P·(N−1)/N total as psum, which is why the
    drift monitor sees the two regimes agree with the static reports."""
    gb = tree_bytes(grads)
    msb = tree_bytes(model_state)
    if zero1:
        agg = (collective_wire_bytes("psum_scatter", gb, world)
               + collective_wire_bytes("all_gather", gb / max(world, 1), world))
    elif aggregation == "allgather":
        agg = collective_wire_bytes("all_gather", gb, world)
    else:
        # allreduce; reducescatter's psum_scatter+all_gather decomposition
        # prices identically to psum (its non-divisible leaves pmean).
        agg = collective_wire_bytes("psum", gb, world)
    return agg + collective_wire_bytes("psum", msb, world)


def make_step_stats(
    loss: jax.Array,
    normsq: jax.Array,
    opt_state: PyTree,
    comm_bytes_per_step: float,
    step: jax.Array,
) -> StepStats:
    """Assemble the StepStats pytree inside a traced step body.

    ``opt_state`` is the POST-update optimizer state: when a GradSentinel
    is in the chain its skip/consecutive counters are read straight from
    the state tree (pure structure walk — works on tracers); without one
    the counters are constant zeros.
    """
    from tpudml.resilience.sentinel import find_sentinel_state

    st = find_sentinel_state(opt_state)
    zero = jnp.int32(0)
    return StepStats(
        loss=loss.astype(jnp.float32),
        grad_norm=jnp.sqrt(jnp.maximum(normsq, 0.0)),
        skips=st["skips"].astype(jnp.int32) if st is not None else zero,
        consecutive=(st["consecutive"].astype(jnp.int32)
                     if st is not None else zero),
        comm_bytes=jnp.float32(comm_bytes_per_step) * (step + 1).astype(jnp.float32),
    )
