"""CLI: ``python -m tpudml.obs [--check-drift] [...]``.

Runs the static-vs-measured drift monitor and writes ``obs/drift.json``.
Report-only by default (always exit 0); ``--check-drift`` is the CI gate
— non-zero exit when any entrypoint's relative error exceeds the
threshold, mirroring the analysis CLI's ``--strict`` contract and its
``--format text|json|github`` output modes. ``--fixture`` compares
pre-recorded (static, measured) pairs from a JSON file instead of
running the live world-4 regimes — the seeded-mismatch path the tests
gate on, and the mode a TPU-less CI box can run.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpudml.obs.drift import (
    DEFAULT_THRESHOLD,
    DRIFT_REPORT_PATH,
    REGIMES,
    build_drift_report,
    drift_from_pairs,
    format_drift_table,
    write_drift_report,
)


def _github_lines(report: dict, path: str) -> list[str]:
    out = []
    for r in report["records"]:
        if r["status"] != "WARN":
            continue
        # '::' inside the message would terminate the annotation early.
        msg = (f"static-vs-measured drift {r['rel_err'] * 100:.2f}% > "
               f"{report['threshold'] * 100:.0f}% on {r['entrypoint']} "
               f"(static {r['static_wire_bytes']:.0f} B, measured "
               f"{r['measured_wire_bytes']:.0f} B)").replace("::", ":")
        out.append(f"::warning file={path}::{msg}")
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpudml.obs",
        description="Drift monitor: measured CommStats wire bytes vs the "
                    "static cost model, per analysis entrypoint "
                    "(docs/OBSERVABILITY.md).",
    )
    parser.add_argument("--check-drift", action="store_true",
                        help="gate mode: exit 1 when any entrypoint "
                             "drifts past the threshold")
    parser.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                        help="relative-error WARN threshold "
                             f"(default {DEFAULT_THRESHOLD:.0%})")
    parser.add_argument("--format", default="text", dest="fmt",
                        choices=("text", "json", "github"),
                        help="output format (default: text)")
    parser.add_argument("--out", default=DRIFT_REPORT_PATH,
                        help=f"drift report path (default {DRIFT_REPORT_PATH})")
    parser.add_argument("--fixture", default=None, metavar="JSON",
                        help="compare pre-recorded pairs from this file "
                             "instead of running the live regimes "
                             "(list of {entrypoint, static_wire_bytes, "
                             "measured_wire_bytes} or {'records': [...]})")
    parser.add_argument("--regimes", default=None, metavar="A,B",
                        help="comma-separated live regimes "
                             f"(default: all; known: {', '.join(REGIMES)})")
    args = parser.parse_args(argv)

    if args.threshold <= 0:
        parser.error("--threshold must be > 0")

    if args.fixture is not None:
        with open(args.fixture) as f:
            data = json.load(f)
        pairs = data["records"] if isinstance(data, dict) else data
        records = drift_from_pairs(pairs)
    else:
        names = None
        if args.regimes:
            names = [n.strip() for n in args.regimes.split(",") if n.strip()]
            unknown = [n for n in names if n not in REGIMES]
            if unknown:
                parser.error(f"unknown regimes {unknown}; "
                             f"known: {', '.join(REGIMES)}")
        # The live regimes trace/measure on a world-4 mesh: provision the
        # 8-device CPU host platform before the first backend touch (the
        # same dance as python -m tpudml.analysis / tests/conftest.py).
        from tpudml.analysis.__main__ import _provision_devices

        _provision_devices()
        from tpudml.obs.drift import drift_records

        records = drift_records(names)

    report = build_drift_report(records, threshold=args.threshold)
    path = write_drift_report(report, args.out)

    if args.fmt == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    elif args.fmt == "github":
        for line in _github_lines(report, path):
            print(line)
    else:
        print(format_drift_table(report))
        print(f"wrote {path}")

    if args.check_drift and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
