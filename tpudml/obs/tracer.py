"""Unified flight recorder: nested, thread-safe structured spans with
Chrome-trace-event export (SURVEY.md §5.1's "one timeline" gap).

Every telemetry silo the framework grew — `SpanTimer` wall spans,
`CommStats` collective timings, the serving engine's event log, sentinel
trips, checkpoint save/restore/verify, launcher restarts — feeds one
:class:`Tracer`, which exports a single ``trace.json`` in the Chrome
trace-event format (one ``pid`` track per ``jax.process_index()``),
openable directly in Perfetto (https://ui.perfetto.dev) or
``chrome://tracing``. See docs/OBSERVABILITY.md for the span model.

Determinism contract: the export sorts events by ``(ts, -dur, tid, cat,
name)`` and serializes with sorted keys + canonical separators, so a
fixed event log produces byte-identical ``trace.json`` — the property
the serving-trace golden tests pin (events carry the engine's virtual
clock, not wall time).

Disabled tracers allocate NOTHING: ``Tracer(enabled=False).span(...)``
returns a shared no-op context manager and records no :class:`Span`
(the module-level ``SPANS_ALLOCATED`` counter lets tests assert this),
so the ``obs=`` knob's off position costs one attribute check per step.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from pathlib import Path
from typing import Iterator

TRACE_SCHEMA_VERSION = 1

# Every Span ever constructed bumps this (see tests/test_obs.py's
# tracer-off A/B): the cheapest honest way to prove the disabled path
# allocates zero spans without instrumenting allocators.
SPANS_ALLOCATED = 0


@dataclass
class Span:
    """One structured event: a complete span (``ph='X'``, has ``dur_us``)
    or an instant (``ph='i'``). Timestamps are integer microseconds on
    the owning tracer's clock (wall for live tracing, the serve engine's
    virtual clock for deterministic conversions)."""

    name: str
    cat: str
    ts_us: int
    dur_us: int = 0
    ph: str = "X"
    tid: int = 0
    args: dict | None = None

    def __post_init__(self):
        global SPANS_ALLOCATED
        SPANS_ALLOCATED += 1


class _NullSpan:
    """Reusable no-op context manager — the entire disabled-tracer path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-safe structured-span recorder.

    Usage::

        tracer = Tracer()
        with tracer.span("train_step", cat="step"):
            ts, metrics = step(ts, x, y)
        tracer.instant("sentinel_trip", cat="sentinel", args={"step": 7})
        tracer.export(run_dir / "trace.json")

    Nesting is positional (Chrome complete events nest by containment per
    ``tid``); each OS thread gets its own track, numbered densely in
    first-seen order. ``sync=`` values are blocked on before a span
    closes (``jax.block_until_ready``), charging async-dispatched XLA
    work to the span that launched it — :class:`SpanTimer` semantics.
    """

    def __init__(self, enabled: bool = True, clock=time.perf_counter):
        self.enabled = enabled
        self._clock = clock
        self._t0 = clock() if enabled else 0.0
        self._lock = threading.Lock()
        self.events: list[Span] = []
        self._tids: dict[int, int] = {}

    # ----------------------------------------------------------- recording

    def now_us(self) -> int:
        return int((self._clock() - self._t0) * 1e6)

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def _record(self, span: Span) -> None:
        with self._lock:
            self.events.append(span)

    def span(self, name: str, cat: str = "host", sync=None, args: dict | None = None):
        """Context manager timing a host region as a complete span. No-op
        (and no allocation) when the tracer is disabled."""
        if not self.enabled:
            return NULL_SPAN
        return self._timed_span(name, cat, sync, args)

    @contextmanager
    def _timed_span(self, name, cat, sync, args) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            if sync is not None:
                import jax

                jax.block_until_ready(sync)
            ts_us = int((t0 - self._t0) * 1e6)
            dur_us = int((self._clock() - t0) * 1e6)
            self._record(Span(name, cat, ts_us, dur_us, "X", self._tid(), args))

    def instant(self, name: str, cat: str = "host", args: dict | None = None,
                ts_us: int | None = None) -> None:
        if not self.enabled:
            return
        ts = self.now_us() if ts_us is None else int(ts_us)
        self._record(Span(name, cat, ts, 0, "i", self._tid(), args))

    def add_complete(self, name: str, cat: str, ts_us: int, dur_us: int,
                     args: dict | None = None, tid: int | None = None) -> None:
        """Record a span with explicit timestamps — the feed path for
        already-timed quantities (``CommStats.add``) and deterministic
        conversions (serve events on the virtual clock)."""
        if not self.enabled:
            return
        self._record(Span(name, cat, int(ts_us), int(dur_us), "X",
                          self._tid() if tid is None else int(tid), args))

    def add_events(self, events: list[dict]) -> None:
        """Bulk-ingest pre-built trace events (dicts with name/cat/ph/ts/
        dur/tid/args keys — the output of ``tpudml.obs.convert``)."""
        if not self.enabled:
            return
        for e in events:
            self._record(Span(
                e["name"], e.get("cat", "host"), int(e.get("ts", 0)),
                int(e.get("dur", 0)), e.get("ph", "X"),
                int(e.get("tid", 0)), e.get("args"),
            ))

    # ------------------------------------------------------------- export

    def trace_events(self) -> list[dict]:
        """Deterministically-sorted Chrome trace events (no pid yet)."""
        with self._lock:
            spans = list(self.events)
        return sorted((_event_dict(s) for s in spans), key=_sort_key)

    def chrome_trace(self, pid: int | None = None) -> dict:
        return chrome_trace_doc(self.trace_events(), pid=pid)

    def export(self, path: str | Path, pid: int | None = None) -> Path:
        """Write ``trace.json`` (Chrome trace-event JSON, schema version
        ``TRACE_SCHEMA_VERSION``); returns the path. Byte-deterministic
        for a fixed event log."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(dump_trace(self.chrome_trace(pid=pid)))
        return path

    def summary(self) -> dict:
        """Deterministic per-(cat, name) aggregate: count, total, and
        p50/p99 microseconds (reusing ``CommStats.percentiles`` so every
        percentile in the repo interpolates identically)."""
        from tpudml.comm.timing import CommStats

        groups: dict[tuple[str, str], CommStats] = {}
        with self._lock:
            spans = list(self.events)
        for s in spans:
            groups.setdefault((s.cat, s.name), CommStats()).add(s.dur_us * 1e-6)
        out = {}
        for (cat, name), st in sorted(groups.items()):
            pct = st.percentiles()
            out[f"{cat}/{name}"] = {
                "count": st.calls,
                "total_us": int(st.comm_time_s * 1e6),
                "p50_us": int(pct["p50_s"] * 1e6) if pct else 0,
                "p99_us": int(pct["p99_s"] * 1e6) if pct else 0,
            }
        return {"schema": TRACE_SCHEMA_VERSION, "spans": out}


def _event_dict(s: Span) -> dict:
    e = {"name": s.name, "cat": s.cat, "ph": s.ph, "ts": s.ts_us, "tid": s.tid}
    if s.ph == "X":
        e["dur"] = s.dur_us
    else:
        e["s"] = "t"  # instant scope: thread
    if s.args:
        e["args"] = s.args
    return e


def _sort_key(e: dict):
    # Parents (longer spans) sort before their children at equal ts, which
    # is what trace viewers require for proper nesting.
    return (e["ts"], -e.get("dur", 0), e["tid"], e["cat"], e["name"])


def chrome_trace_doc(events: list[dict], pid: int | None = None) -> dict:
    """Wrap sorted trace events in the Chrome trace-event document:
    metadata naming the process track (one per ``jax.process_index()``),
    then the events stamped with that pid."""
    if pid is None:
        try:
            from tpudml.core.dist import process_index

            pid = process_index()
        except Exception:
            pid = 0
    stamped = [dict(e, pid=pid) for e in events]
    meta = {
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": f"tpudml process {pid}"},
    }
    return {
        "displayTimeUnit": "ms",
        "metadata": {"tpudml_trace_schema": TRACE_SCHEMA_VERSION},
        "traceEvents": [meta] + stamped,
    }


def dump_trace(doc: dict) -> str:
    """Canonical serialization: sorted keys, no whitespace — the byte
    representation the golden/determinism tests pin."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n"


def merge_chrome_traces(docs: list[dict]) -> dict:
    """Merge per-process trace documents (one per rank, distinct pids)
    into a single multi-track document — the pod-level view of a
    multi-process run. Each input must be a valid single-process export;
    two inputs claiming the same pid is an error (two ranks exported with
    the same ``process_index`` — a wiring bug worth failing loudly on).
    Deterministic: metadata tracks sorted by pid, then events in the same
    order :meth:`Tracer.trace_events` uses, pid as the leading key."""
    metas: dict[int, dict] = {}
    events: list[dict] = []
    for doc in docs:
        validate_chrome_trace(doc)
        for e in doc["traceEvents"]:
            if e["ph"] == "M":
                if e["pid"] in metas:
                    raise ValueError(
                        f"duplicate pid {e['pid']} across trace documents"
                    )
                metas[e["pid"]] = e
            else:
                events.append(e)
    events.sort(key=lambda e: (e["pid"],) + _sort_key(e))
    return {
        "displayTimeUnit": "ms",
        "metadata": {"tpudml_trace_schema": TRACE_SCHEMA_VERSION},
        "traceEvents": [metas[p] for p in sorted(metas)] + events,
    }


def validate_chrome_trace(doc: dict) -> None:
    """Schema check for an exported trace document: raises ValueError on
    the first violation of the Chrome trace-event contract the tests (and
    Perfetto) rely on."""
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError("trace document must be a dict with 'traceEvents'")
    if doc.get("metadata", {}).get("tpudml_trace_schema") != TRACE_SCHEMA_VERSION:
        raise ValueError("missing/unknown tpudml_trace_schema version")
    for i, e in enumerate(doc["traceEvents"]):
        for key in ("name", "ph", "pid", "tid"):
            if key not in e:
                raise ValueError(f"event {i} missing {key!r}: {e}")
        if e["ph"] == "X":
            if not isinstance(e.get("ts"), int) or not isinstance(e.get("dur"), int):
                raise ValueError(f"event {i}: complete events need int ts/dur")
        elif e["ph"] == "i":
            if not isinstance(e.get("ts"), int):
                raise ValueError(f"event {i}: instant events need int ts")
        elif e["ph"] != "M":
            raise ValueError(f"event {i}: unknown phase {e['ph']!r}")


# ------------------------------------------------------- ambient tracer
#
# Cross-cutting layers (checkpoint store, launcher, sentinel hook) emit
# into the ambient tracer rather than threading a tracer argument through
# every signature. Defaults to a disabled tracer, so un-instrumented runs
# pay one truthiness check and allocate nothing.

NULL_TRACER = Tracer(enabled=False)
_ambient: Tracer = NULL_TRACER
_ambient_lock = threading.Lock()


def get_tracer() -> Tracer:
    return _ambient


def set_tracer(tracer: Tracer | None) -> Tracer:
    """Install ``tracer`` as the ambient tracer (None → disabled);
    returns the previous one so callers can restore it."""
    global _ambient
    with _ambient_lock:
        prev = _ambient
        _ambient = tracer if tracer is not None else NULL_TRACER
    return prev


@contextmanager
def use_tracer(tracer: Tracer | None) -> Iterator[Tracer]:
    """Scoped :func:`set_tracer` — the task entrypoints' idiom."""
    prev = set_tracer(tracer)
    try:
        yield get_tracer()
    finally:
        set_tracer(prev)
