"""tpudml.obs — the unified observability layer (docs/OBSERVABILITY.md).

- :mod:`tpudml.obs.tracer`    — structured spans → Perfetto ``trace.json``.
- :mod:`tpudml.obs.stepstats` — in-graph :class:`StepStats` telemetry.
- :mod:`tpudml.obs.convert`   — serve event log → trace spans (pure).
- :mod:`tpudml.obs.drift`     — static-vs-measured drift monitor
  (``python -m tpudml.obs --check-drift``). Imported lazily: it pulls in
  the parallel engines, which themselves import this package.
"""

from tpudml.obs.convert import serve_trace_events, write_serve_trace
from tpudml.obs.stepstats import StepStats, make_step_stats
from tpudml.obs.tracer import (
    NULL_SPAN,
    NULL_TRACER,
    TRACE_SCHEMA_VERSION,
    Span,
    Tracer,
    chrome_trace_doc,
    dump_trace,
    get_tracer,
    merge_chrome_traces,
    set_tracer,
    use_tracer,
    validate_chrome_trace,
)

__all__ = [
    "NULL_SPAN",
    "NULL_TRACER",
    "TRACE_SCHEMA_VERSION",
    "Span",
    "StepStats",
    "Tracer",
    "chrome_trace_doc",
    "dump_trace",
    "get_tracer",
    "make_step_stats",
    "merge_chrome_traces",
    "serve_trace_events",
    "set_tracer",
    "use_tracer",
    "validate_chrome_trace",
    "write_serve_trace",
]
