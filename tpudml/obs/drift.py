"""Static-vs-measured drift monitor.

PR 10 pinned the static ``--cost`` byte counts within 5% of the measured
``CommStats`` accounting — once, in a test. The ROADMAP's planner item
needs that agreement tracked CONTINUOUSLY: a planner that prices
candidate configs with a cost model that has silently drifted from the
measured path ranks them wrong. This module re-derives the comparison as
a runtime artifact: per analysis entrypoint, run the engine's
measure_comm path for one step (measured wire bytes + comm time), trace
the fused program through the dataflow interpreter (static wire bytes on
the SAME ring model), and record the relative error. ``obs/drift.json``
carries the records; anything past the threshold (default 10%) is a WARN
and — under ``python -m tpudml.obs --check-drift`` — a non-zero exit.

The live regimes mirror tests/test_analysis.py's world-4 LeNet recipe
exactly (DP/SGD and ZeRO-1/Adam), so a passing drift check reproduces
the PR 10 acceptance pin. File-based comparison (``drift_from_pairs``)
covers pre-recorded fixtures and CI gating without a device mesh.
"""

from __future__ import annotations

import json
import os
from typing import Any

DRIFT_REPORT_VERSION = 1
DEFAULT_THRESHOLD = 0.10
DRIFT_REPORT_PATH = os.path.join("obs", "drift.json")

# Live regimes: name -> engine config. World 4 matches the PR 10 parity
# pin; adam-under-zero1 exercises the sharded moment update's collectives.
REGIMES: dict[str, dict] = {
    "task2_dp": {"zero1": False, "optimizer": "sgd"},
    "dp_zero1": {"zero1": True, "optimizer": "adam"},
}
_WORLD = 4


def measure_regime(name: str) -> dict:
    """One drift record for a live regime: build the engine twice (the
    measured split-step path and the fused static-analysis path), run one
    step, compare wire bytes on the shared ring model."""
    import jax
    import numpy as np

    from tpudml.analysis.dataflow import analyze_dataflow
    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.core.prng import seed_key
    from tpudml.models import LeNet
    from tpudml.optim import make_optimizer
    from tpudml.parallel.dp import DataParallel

    cfg = REGIMES[name]
    if len(jax.devices()) < _WORLD:
        raise RuntimeError(
            f"drift regime {name!r} needs a {_WORLD}-device mesh "
            f"(have {len(jax.devices())}); provision a CPU host platform "
            "as python -m tpudml.obs does")
    mesh = make_mesh(MeshConfig({"data": _WORLD}), jax.devices()[:_WORLD])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(8,)).astype(np.int32)

    measured_dp = DataParallel(
        LeNet(), make_optimizer(cfg["optimizer"], 0.01), mesh,
        measure_comm=True, zero1=cfg["zero1"])
    ts = measured_dp.create_state(seed_key(0))
    measured_dp.make_train_step()(ts, x, y)
    measured = float(measured_dp.comm_stats.comm_bytes)
    comm_time = float(measured_dp.comm_stats.comm_time_s)

    static_dp = DataParallel(
        LeNet(), make_optimizer(cfg["optimizer"], 0.01), mesh,
        zero1=cfg["zero1"])
    ts2 = static_dp.create_state(seed_key(0))
    fused = static_dp.make_train_step()
    closed = jax.make_jaxpr(fused.jitted)(ts2, x, y)
    flow = analyze_dataflow(closed, f"drift-{name}", in_specs=fused.in_specs,
                            mesh_axes=fused.mesh_axes)
    static = float(sum(ev.wire_bytes * ev.trips for ev in flow.comm_events))
    return _record(name, static, measured, measured_comm_time_s=comm_time)


def _record(entrypoint: str, static: float, measured: float,
            **extra: Any) -> dict:
    rel_err = abs(static - measured) / measured if measured > 0 else (
        0.0 if static == 0 else float("inf"))
    return {
        "entrypoint": entrypoint,
        "static_wire_bytes": static,
        "measured_wire_bytes": measured,
        "rel_err": rel_err,
        **extra,
    }


def drift_records(names: list[str] | None = None) -> list[dict]:
    return [measure_regime(n) for n in (names or list(REGIMES))]


def drift_from_pairs(pairs: list[dict]) -> list[dict]:
    """Records from pre-measured (static, measured) pairs — the fixture/
    CI path. Each pair needs ``entrypoint``, ``static_wire_bytes``,
    ``measured_wire_bytes``; extra keys ride along."""
    out = []
    for p in pairs:
        extra = {k: v for k, v in p.items()
                 if k not in ("entrypoint", "static_wire_bytes",
                              "measured_wire_bytes")}
        out.append(_record(p["entrypoint"], float(p["static_wire_bytes"]),
                           float(p["measured_wire_bytes"]), **extra))
    return out


def build_drift_report(records: list[dict],
                       threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Stamp each record OK/WARN against ``threshold`` and roll up."""
    stamped = [dict(r, status="WARN" if r["rel_err"] > threshold else "OK")
               for r in records]
    worst = max((r["rel_err"] for r in stamped), default=0.0)
    return {
        "version": DRIFT_REPORT_VERSION,
        "threshold": threshold,
        "units": "bytes/device (ring model, comm.timing.collective_wire_bytes)",
        "records": stamped,
        "worst_rel_err": worst,
        "ok": all(r["status"] == "OK" for r in stamped),
    }


def write_drift_report(report: dict, path: str = DRIFT_REPORT_PATH) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def format_drift_table(report: dict) -> str:
    lines = [
        f"{'entrypoint':<16} {'static MB':>10} {'measured MB':>12} "
        f"{'rel err':>8}  status",
    ]
    for r in report["records"]:
        lines.append(
            f"{r['entrypoint']:<16} {r['static_wire_bytes'] / 1e6:>10.3f} "
            f"{r['measured_wire_bytes'] / 1e6:>12.3f} "
            f"{r['rel_err'] * 100:>7.2f}%  {r['status']}"
        )
    lines.append(
        f"worst {report['worst_rel_err'] * 100:.2f}% vs threshold "
        f"{report['threshold'] * 100:.0f}% — "
        + ("OK" if report["ok"] else "DRIFT")
    )
    return "\n".join(lines)
