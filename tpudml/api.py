"""High-level Model API — the MindSpore-track parity surface.

The reference's second-framework track trains through
``Model(net, loss, opt, metrics)`` + ``model.train(epochs, ds,
callbacks=[LossMonitor()], dataset_sink_mode=True)`` + ``model.eval``
(codes/task1/mindspore/model.ipynb cells 5-7; sections/mindspore.tex).
SURVEY.md §3.5 notes that sink-mode graph training is the closest thing in
the reference to the JAX execution model — so here "sink mode" IS the
native path (one jitted XLA program per step, data fed device-side), and
``dataset_sink_mode=False`` runs the same math op-by-op un-jitted (the
eager comparison mode, mainly for debugging).
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Sequence

import jax
import jax.numpy as jnp

from tpudml.nn.layers import Module
from tpudml.nn.losses import accuracy, softmax_cross_entropy
from tpudml.optim import Optimizer
from tpudml.train import TrainState, make_loss_fn, make_train_step

_METRIC_FNS: dict[str, Callable] = {
    "accuracy": accuracy,
    "loss": lambda logits, labels: softmax_cross_entropy(logits, labels),
}


class Callback:
    """Training callback; MindSpore-Callback-shaped hooks."""

    def on_train_begin(self, model: "Model") -> None: ...

    def on_step_end(self, model: "Model", step: int, loss: float) -> None: ...

    def on_epoch_end(self, model: "Model", epoch: int, loss: float) -> None: ...

    def on_train_end(self, model: "Model") -> None: ...


class LossMonitor(Callback):
    """Parity with mindspore.train.LossMonitor (notebook cell 6): prints
    the loss every ``per_print_times`` steps."""

    def __init__(self, per_print_times: int = 1):
        self.per_print_times = per_print_times

    def on_step_end(self, model, step, loss):
        if self.per_print_times and step % self.per_print_times == 0:
            print(f"step: {step}, loss is {loss:.6f}")


class Model:
    """``Model(network, loss_fn, optimizer, metrics)`` facade over the
    functional engine.

    Usage (mirrors the notebook, model.ipynb cells 5-7)::

        model = Model(ForwardMLP(), optimizer=make_optimizer("sgd", 0.01),
                      metrics={"Accuracy"})
        model.train(10, train_loader, callbacks=[LossMonitor()])
        print(model.eval(test_loader))   # {"Accuracy": 0.97}
    """

    def __init__(
        self,
        network: Module,
        loss_fn: Callable = softmax_cross_entropy,
        optimizer: Optimizer | None = None,
        metrics: Sequence[str] | set[str] = ("accuracy",),
        seed: int = 0,
        mesh=None,
    ):
        if optimizer is None:
            raise ValueError("Model needs an optimizer")
        unknown = {m.lower() for m in metrics} - set(_METRIC_FNS)
        if unknown:
            raise ValueError(
                f"unknown metrics {sorted(unknown)}; options: {sorted(_METRIC_FNS)}"
            )
        self.network = network
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.metrics = tuple(m.lower() for m in metrics)
        key = jax.random.key(seed)
        self._rng_root = jax.random.fold_in(key, 0x0D0)
        self._sink_step = None
        # ``mesh`` is the auto-parallel analogue of the MindSpore track
        # (sections/mindspore.tex:39): hand the facade a device mesh and
        # sink-mode training becomes the DataParallel SPMD engine — same
        # API, every chip used, gradients aggregated per step.
        self.mesh = mesh
        if mesh is not None:
            from tpudml.parallel.dp import DataParallel

            self._engine = DataParallel(
                network, optimizer, mesh, rng_root=self._rng_root, loss=loss_fn,
                # The facade always feeds plain global [B, ...] batches —
                # never the ShardedDataLoader's stacked [world, B, ...]
                # form — so bypass shape inference entirely (ADVICE r2:
                # the inference misreads stacked flat-feature batches).
                stacked_batches=False,
            )
            self.state = self._engine.create_state(key)
        else:
            self._engine = None
            self.state = TrainState.create(network, optimizer, key)
        self._predict = jax.jit(
            lambda params, state, x: network.apply(params, state, x, train=False)[0]
        )

    # ------------------------------------------------------------- training

    def _eager_step(self, ts: TrainState, images, labels):
        """dataset_sink_mode=False: identical math, no jit (debug mode)."""
        loss_fn = make_loss_fn(self.network, self.loss_fn)
        rng = jax.random.fold_in(self._rng_root, ts.step)
        (loss, (model_state, logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(ts.params, ts.model_state, images, labels, rng)
        new_params, new_opt = self.optimizer.update(grads, ts.opt_state, ts.params)
        ts = TrainState(
            params=new_params,
            model_state=model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return ts, {"loss": loss, "accuracy": accuracy(logits, labels)}

    def train(
        self,
        epochs: int,
        dataset: Iterable,
        callbacks: Sequence[Callback] | None = None,
        dataset_sink_mode: bool = True,
    ) -> "Model":
        """Train in place for ``epochs`` passes over ``dataset`` (any
        iterable of (images, labels); DataLoader and ShardedDataLoader
        supported incl. set_epoch). Returns self for chaining."""
        callbacks = list(callbacks or [])
        if not dataset_sink_mode and self._engine is not None:
            raise ValueError("eager mode is single-device; drop mesh= to use it")
        if self._engine is not None:
            # Structural batch-form tagging (ADVICE r2): the loader TYPE
            # decides stacked [world, B, ...] vs plain global [B, ...]
            # batches — never shape inference, which misreads stacked
            # flat-feature batches.
            from tpudml.data import ShardedDataLoader

            self._engine.stacked_batches = isinstance(dataset, ShardedDataLoader)
        if dataset_sink_mode and self._sink_step is None:
            if self._engine is not None:
                self._sink_step = self._engine.make_train_step()
            else:
                self._sink_step = make_train_step(
                    self.network,
                    self.optimizer,
                    rng_root=self._rng_root,
                    loss=self.loss_fn,
                )
        step_fn = self._sink_step if dataset_sink_mode else self._eager_step
        for cb in callbacks:
            cb.on_train_begin(self)
        t0 = time.time()
        counter = 0
        for epoch in range(epochs):
            if hasattr(dataset, "set_epoch"):
                dataset.set_epoch(epoch)
            metrics = None
            for images, labels in dataset:
                self.state, metrics = step_fn(self.state, images, labels)
                counter += 1
                if callbacks:
                    # Materializing the loss forces a host↔device sync; do
                    # it only when a callback consumes it, so callback-free
                    # training keeps sink mode's async dispatch.
                    loss = float(metrics["loss"])
                    for cb in callbacks:
                        cb.on_step_end(self, counter, loss)
            if callbacks:
                loss = float(metrics["loss"]) if metrics is not None else float("nan")
                for cb in callbacks:
                    cb.on_epoch_end(self, epoch, loss)
        jax.block_until_ready(self.state.params)
        self.train_time_s = time.time() - t0
        for cb in callbacks:
            cb.on_train_end(self)
        return self

    # ------------------------------------------------------------ inference

    def predict(self, images) -> jax.Array:
        """Jitted inference logits (one compiled program per input shape)."""
        return self._predict(
            self.state.params, self.state.model_state, jnp.asarray(images)
        )

    def eval(self, dataset: Iterable) -> dict[str, float]:
        """Metric-name → value over ``dataset`` (capitalized keys, as the
        notebook prints e.g. {'Accuracy': 0.97})."""
        totals = {m: 0.0 for m in self.metrics}
        count = 0
        for images, labels in dataset:
            labels = jnp.asarray(labels)
            logits = self.predict(images)
            n = len(labels)
            for m in self.metrics:
                totals[m] += float(_METRIC_FNS[m](logits, labels)) * n
            count += n
        return {m.capitalize(): v / max(count, 1) for m, v in totals.items()}
