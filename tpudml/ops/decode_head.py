"""Fused greedy decode head (Pallas, TPU): head matmul + argmax + step
statistics with the [B, V] logits row never materialized.

The serving engine's decode step ends in ``logits = feats @ W_head``
([B, V] — 128 KB/slot f32 at V=32k) followed by a SEPARATE argmax tail:
the logits land in HBM, the reduction reads them back, and the step
statistics (max logit, log-sum-exp) need yet another pass. BASELINE.md
round 7 measured that tail at ~1.9 ms/step on the flagship. This kernel
is the xent trick (``ops/xent_kernel.py``) applied to inference: stream
W one vocab tile at a time through VMEM and fold the pick into the
matmul epilogue —

- grid (B-blocks, V-blocks), V innermost. Per tile:
  s = feats_tile @ W_tile + bias (f32 on the MXU), folded into a running
  online softmax (m, l) per row PLUS a running argmax index: the tile's
  first-occurrence max column, kept only when the tile max strictly
  beats the running max — exactly ``jnp.argmax``'s first-occurrence
  tie-breaking, proven by the greedy-parity tests.
- final tile emits tokens [B] int32 and the in-graph step statistics
  (max logit [B], lse [B]) — everything the engine and the obs tier
  read per step, with no [B, V] round-trip to HBM.

The int8 variant takes the quantized head (int8 codes [d, V] + f32
per-output-channel scales [V], ``serve/fleet/quant.py`` layout) and
dequantizes PER TILE inside the kernel with exactly the oracle's op
order (``q.astype(f32) * scale``), so its logits — and therefore its
greedy picks — are bitwise those of the dequantized-weights path.

Inference only: no custom_vjp (the serving engine never differentiates
through decode). Dispatch: compiled kernel on TPU; reference math
elsewhere unless ``interpret=True`` forces the Pallas interpreter
(tests). TPU note: the int8 path wants d a multiple of the int8 sublane
tile (32) for compiled-mode efficiency; the CPU-dryrun fixtures run
interpret mode where tiling is advisory.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudml.ops.xent_kernel import _padded_dims

_INT_SENTINEL = jnp.iinfo(jnp.int32).max


def _head_body(s, col, tok_ref, max_ref, lse_ref, m_ref, l_ref, idx_ref):
    """Shared epilogue: fold one masked f32 score tile into the running
    (max, normalizer, argmax-index) state; finalize on the last tile."""
    vj = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vj == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    tm = jnp.max(s, axis=-1, keepdims=True)  # [bn, 1]
    # First-occurrence column of the tile max; a fully-padded tile is
    # all -inf -> tm = -inf, the strict > below keeps the running state.
    ti = jnp.min(
        jnp.where(s == tm, col, _INT_SENTINEL), axis=-1, keepdims=True
    )
    m_prev = m_ref[:]
    # STRICTLY greater: an equal later tile must not steal the pick —
    # jnp.argmax keeps the first occurrence.
    idx_ref[:] = jnp.where(tm > m_prev, ti, idx_ref[:])
    m_new = jnp.maximum(m_prev, tm)
    l_ref[:] = l_ref[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(s - m_new), axis=-1, keepdims=True
    )
    m_ref[:] = m_new

    @pl.when(vj == nv - 1)
    def _():
        tok_ref[:] = idx_ref[:]
        max_ref[:] = m_ref[:]
        lse_ref[:] = m_ref[:] + jnp.log(l_ref[:])


def _head_kernel(x_ref, w_ref, b_ref, tok_ref, max_ref, lse_ref, m_ref,
                 l_ref, idx_ref, *, block_v: int, v_valid: int):
    vj = pl.program_id(1)
    s = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b_ref[:].astype(jnp.float32)
    col = vj * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if v_valid != block_v * pl.num_programs(1):
        s = jnp.where(col < v_valid, s, -jnp.inf)
    _head_body(s, col, tok_ref, max_ref, lse_ref, m_ref, l_ref, idx_ref)


def _head_kernel_int8(x_ref, wq_ref, scale_ref, b_ref, tok_ref, max_ref,
                      lse_ref, m_ref, l_ref, idx_ref, *, block_v: int,
                      v_valid: int):
    vj = pl.program_id(1)
    # Oracle op order (serve/fleet/quant.py _dequant_kernel): codes to
    # f32 FIRST, then the per-output-channel scale — bitwise equality
    # with the dequantized-params path depends on it.
    w = wq_ref[:].astype(jnp.float32) * scale_ref[:]
    s = jax.lax.dot_general(
        x_ref[:], w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b_ref[:].astype(jnp.float32)
    col = vj * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if v_valid != block_v * pl.num_programs(1):
        s = jnp.where(col < v_valid, s, -jnp.inf)
    _head_body(s, col, tok_ref, max_ref, lse_ref, m_ref, l_ref, idx_ref)


def _head_call(kernel, inputs, vocab_rows, n, d, v, block_n, block_v,
               interpret):
    """Shared pallas_call plumbing for both weight layouts. ``inputs``
    are the pre-padded operands; the first is the [·, d] row operand,
    the rest are vocab-tiled with leading sizes ``vocab_rows`` (d for a
    weight matrix, 1 for scale/bias rows)."""
    block_n, block_v, n_pad, v_pad = _padded_dims(n, v, block_n, block_v)
    grid = (n_pad // block_n, v_pad // block_v)
    row_spec = pl.BlockSpec((block_n, 1), lambda i, j: (i, 0))
    toks, mx, lse = pl.pallas_call(
        partial(kernel, block_v=block_v, v_valid=v),
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[pl.BlockSpec((block_n, d), lambda i, j: (i, 0))]
        + [pl.BlockSpec((rows, block_v), lambda i, j: (0, j))
           for rows in vocab_rows],
        out_specs=[row_spec, row_spec, row_spec],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),  # running max
            pltpu.VMEM((block_n, 1), jnp.float32),  # running normalizer
            pltpu.VMEM((block_n, 1), jnp.int32),    # running argmax col
        ],
        interpret=interpret,
    )(*inputs)
    return toks[:n, 0], mx[:n, 0], lse[:n, 0]


def _pad_operands(x, n, v, block_n, block_v):
    block_n, block_v, n_pad, v_pad = _padded_dims(n, v, block_n, block_v)
    xf = jnp.pad(x, ((0, n_pad - n), (0, 0))) if n_pad != n else x
    return xf, n_pad, v_pad


def _head_forward(x, w, b, block_n, block_v, interpret):
    n, d = x.shape
    d2, v = w.shape
    assert d == d2, (x.shape, w.shape)
    xf, n_pad, v_pad = _pad_operands(x, n, v, block_n, block_v)
    wf = jnp.pad(w, ((0, 0), (0, v_pad - v))) if v_pad != v else w
    bf = (jnp.pad(b, (0, v_pad - v)) if v_pad != v else b)[None, :]
    return _head_call(
        _head_kernel, (xf, wf, bf), (d, 1), n, d, v, block_n, block_v,
        interpret,
    )


def _head_forward_int8(x, wq, scale, b, block_n, block_v, interpret):
    n, d = x.shape
    d2, v = wq.shape
    assert d == d2, (x.shape, wq.shape)
    xf, n_pad, v_pad = _pad_operands(x, n, v, block_n, block_v)
    wqf = jnp.pad(wq, ((0, 0), (0, v_pad - v))) if v_pad != v else wq
    # Padded scale columns are 1.0 so the dequantized pad stays 0 (codes
    # pad to 0); the -inf column mask makes the value irrelevant anyway.
    sf = (jnp.pad(scale, (0, v_pad - v), constant_values=1.0)
          if v_pad != v else scale)[None, :]
    bf = (jnp.pad(b, (0, v_pad - v)) if v_pad != v else b)[None, :]
    return _head_call(
        _head_kernel_int8, (xf, wqf, sf, bf), (d, 1, 1), n, d, v, block_n,
        block_v, interpret,
    )


def _reference_head(x, w, b):
    """XLA reference: materialized logits, same f32 statistics."""
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) + b.astype(jnp.float32)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), m, lse


# The dispatch runs inside NAMED nested jits so the call survives as a
# recognizably-named pjit equation in any traced decode program — the
# marker analysis rule J119 keys on to prove a decode step's head tail
# is fused (mirrored as string literals in tpudml/analysis/jaxpr_pass.py,
# pinned by test_analysis). XLA inlines inner jits at lowering, so the
# marker costs nothing on the chip.
def _fused_decode_head(x, w, b, block_n, block_v, interpret):
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _reference_head(x, w, b)
        interpret = False
    return _head_forward(x, w, b, block_n, block_v, interpret)


FUSED_HEAD_MARKER = _fused_decode_head.__name__

_fused_decode_head_jit = jax.jit(_fused_decode_head, static_argnums=(3, 4, 5))


def _fused_decode_head_int8(x, wq, scale, b, block_n, block_v, interpret):
    if interpret is None:
        if jax.default_backend() != "tpu":
            from tpudml.serve.fleet.quant import _dequant_kernel

            return _reference_head(x, _dequant_kernel(wq, scale), b)
        interpret = False
    return _head_forward_int8(x, wq, scale, b, block_n, block_v, interpret)


FUSED_HEAD_INT8_MARKER = _fused_decode_head_int8.__name__

_fused_decode_head_int8_jit = jax.jit(
    _fused_decode_head_int8, static_argnums=(4, 5, 6)
)


def fused_decode_head(
    x: jax.Array,
    w: jax.Array,
    bias: jax.Array | None = None,
    *,
    block_n: int = 256,
    block_v: int = 2048,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Greedy pick + step statistics of ``x @ w [+ bias]`` without
    materializing the [B, V] logits (module docstring).

    ``x`` [..., d] flattens to [B, d]. Returns ``(tokens [B] int32,
    max_logit [B] f32, lse [B] f32)`` — tokens exactly equal
    ``argmax(x @ w + bias)`` (first-occurrence ties included), and the
    statistics are the f32 online-softmax values (max logit and
    log-sum-exp; entropy-adjacent telemetry derives from their
    difference). On non-TPU backends dispatches to the XLA reference
    unless ``interpret=True`` forces the Pallas interpreter."""
    d = x.shape[-1]
    v = w.shape[-1]
    xn = x.reshape(-1, d)
    b = jnp.zeros((v,), w.dtype) if bias is None else bias
    return _fused_decode_head_jit(xn, w, b, block_n, block_v, interpret)


def fused_decode_head_int8(
    x: jax.Array,
    wq: jax.Array,
    scale: jax.Array,
    bias: jax.Array | None = None,
    *,
    block_n: int = 256,
    block_v: int = 2048,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`fused_decode_head` over the QUANTIZED head: ``wq`` int8
    codes [d, V] with f32 per-output-channel ``scale`` [V]
    (``serve/fleet/quant.py`` layout), dequantized per vocab tile inside
    the kernel in the oracle's exact op order — greedy picks are bitwise
    those of running the f32 kernel on ``dequantize(wq, scale)``."""
    d = x.shape[-1]
    v = wq.shape[-1]
    if scale.shape != (v,):
        raise ValueError(f"scale {scale.shape} must be ({v},)")
    xn = x.reshape(-1, d)
    b = jnp.zeros((v,), jnp.float32) if bias is None else bias
    return _fused_decode_head_int8_jit(
        xn, wq, scale, b, block_n, block_v, interpret
    )
