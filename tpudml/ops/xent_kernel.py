"""Fused linear-cross-entropy (Pallas, TPU): head matmul + softmax loss
with the [N, V] logits matrix never materialized.

The LM loss path computes ``logits = x @ W`` ([N, V] — 0.5 GB bf16 at
N=8k tokens, V=32k) and reduces it to one scalar. Even with the
memory-lean XLA loss (tpudml/nn/losses.py), the logits buffer itself
must exist between the matmul and the reductions, and the backward keeps
it (or recomputes it) at full width. This kernel streams W one vocab
tile at a time through VMEM — flash-attention's trick applied to the
classifier head:

- forward: grid (N-blocks, V-blocks), V innermost. Per tile:
  s = x_tile @ W_tile (f32 on the MXU), folded into a running online
  softmax (m, l) per row plus the label's logit (fused iota-compare
  pick). Emits lse [N] and picked [N]; loss = mean(lse - picked).
  Residuals: x, W, labels, lse — O(N + params), NOT O(N·V).
- backward, lean mode: recompute s per tile; dlogits =
  (exp(s - lse) - onehot)·g/N. Two kernels, mirroring the attention
  backward split:
  dX (V innermost): dx_tile += dlogits @ W_tileᵀ;
  dW (N innermost): dW_tile += x_tileᵀ @ dlogits.
- backward, save-s mode (round 4): the forward additionally streams its
  f32 score tiles to HBM, and both backward kernels read them instead
  of recomputing — the backward drops from 4 matmuls' worth of MXU work
  to the 2 the cotangents actually need (recomputing s cost ~2 ms at
  [8192,512]×[512,32k]; XLA's lean path wins at memory-fitting sizes
  for exactly this reason — it keeps the logits). Saved scores are f32,
  so gradients are bit-identical to the lean mode's recomputation. The
  trade is an N_pad·V_pad·4-byte residual in place of the O(N)
  contract; since round 5 the DEFAULT (``save_s=None``) picks the mode
  automatically — save-s while that residual fits
  ``SAVE_S_AUTO_MAX_BYTES`` (2 GiB), the lean O(N) contract beyond
  (measured in-situ: save-s 19.29 ms/step vs lean 21.54 at the
  flagship, BASELINE.md round 5). Pass ``save_s=False`` to force the
  O(N) guarantee regardless of size.

Exactness: same math as ``softmax_cross_entropy`` over the materialized
logits (f32 statistics); pinned by tests against the XLA reference.
Dispatch: compiled kernel on TPU; reference math elsewhere (tests force
``interpret=True``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from tpudml.ops.tiling import round_up as _round_up  # shared tiling helper


# ---------------------------------------------------------------- forward


def _fwd_body(x_ref, w_ref, b_ref, label_ref, lse_ref, picked_ref, m_ref,
              l_ref, z_ref, s_ref, *, block_v: int, v_valid: int):
    vj = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vj == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        z_ref[:] = jnp.zeros_like(z_ref)

    s = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b_ref[:].astype(jnp.float32)  # [bn, bv] (+ broadcast [1, bv] bias)
    col = vj * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if v_valid != block_v * nv:
        # Padded vocab columns must carry no probability mass.
        s = jnp.where(col < v_valid, s, -jnp.inf)
    if s_ref is not None:
        # save-s mode: stream the masked f32 scores out; the backward
        # reads them instead of recomputing the matmul (padded columns
        # carry -inf → p = 0 there with no masking needed).
        s_ref[:] = s
    label = label_ref[:]  # [bn, 1] int32
    # The pick must exclude padded columns even when a (buggy) label
    # lands in [V, V_pad): such labels see picked = 0 → loss = lse, the
    # SAME no-pull-up semantics as any other out-of-range label, instead
    # of picking the -inf a padded column carries (+inf loss).
    z_ref[:] += jnp.sum(
        jnp.where((col == label) & (col < v_valid), s, 0.0),
        axis=-1, keepdims=True,
    )
    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    l_ref[:] = l_ref[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(s - m_new), axis=-1, keepdims=True
    )
    m_ref[:] = m_new

    @pl.when(vj == nv - 1)
    def _():
        lse_ref[:] = m_ref[:] + jnp.log(l_ref[:])
        picked_ref[:] = z_ref[:]


def _fwd_kernel(x_ref, w_ref, b_ref, label_ref, lse_ref, picked_ref, m_ref,
                l_ref, z_ref, *, block_v: int, v_valid: int):
    _fwd_body(x_ref, w_ref, b_ref, label_ref, lse_ref, picked_ref, m_ref,
              l_ref, z_ref, None, block_v=block_v, v_valid=v_valid)


def _fwd_kernel_save(x_ref, w_ref, b_ref, label_ref, lse_ref, picked_ref,
                     s_ref, m_ref, l_ref, z_ref, *, block_v: int,
                     v_valid: int):
    _fwd_body(x_ref, w_ref, b_ref, label_ref, lse_ref, picked_ref, m_ref,
              l_ref, z_ref, s_ref, block_v=block_v, v_valid=v_valid)


def _fused_forward(x, w, b, labels, block_n, block_v, interpret,
                   save_s=False):
    n, d = x.shape
    d2, v = w.shape
    assert d == d2, (x.shape, w.shape)
    block_n, block_v, n_pad, v_pad = _padded_dims(n, v, block_n, block_v)
    xf = jnp.pad(x, ((0, n_pad - n), (0, 0))) if n_pad != n else x
    wf = jnp.pad(w, ((0, 0), (0, v_pad - v))) if v_pad != v else w
    bf = (jnp.pad(b, (0, v_pad - v)) if v_pad != v else b)[None, :]
    # Padded rows pick label -1 → match no column → picked 0, lse finite.
    lf = jnp.pad(labels.astype(jnp.int32), (0, n_pad - n),
                 constant_values=-1)[:, None]
    out_shape = [
        jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
    ]
    if save_s:
        out_shape.append(
            jax.ShapeDtypeStruct((n_pad, v_pad), jnp.float32)
        )
        out_specs.append(pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)))
    outs = pl.pallas_call(
        partial(_fwd_kernel_save if save_s else _fwd_kernel,
                block_v=block_v, v_valid=v),
        out_shape=out_shape,
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),  # running max
            pltpu.VMEM((block_n, 1), jnp.float32),  # running normalizer
            pltpu.VMEM((block_n, 1), jnp.float32),  # picked accumulator
        ],
        interpret=interpret,
    )(xf, wf, bf, lf)
    if save_s:
        lse, picked, s = outs
        return lse[:n, 0], picked[:n, 0], s
    lse, picked = outs
    return lse[:n, 0], picked[:n, 0]


# --------------------------------------------------------------- backward
# save-s kernels: identical math to the lean kernels below, with the
# score recomputation matmul replaced by a read of the forward's saved
# f32 scores (padded columns already carry -inf → p = 0 unmasked).


def _dx_s_kernel(s_ref, w_ref, label_ref, lse_ref, dx_ref, acc_ref, *,
                 block_v: int, v_valid: int, inv_n: float):
    vj = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s = s_ref[:]
    col = vj * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    p = jnp.exp(s - lse_ref[:])
    onehot = (col == label_ref[:]) & (col < v_valid)
    dlog = (p - onehot.astype(jnp.float32)) * inv_n
    acc_ref[:] += jax.lax.dot_general(
        dlog.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bn, d]

    @pl.when(vj == nv - 1)
    def _():
        dx_ref[:] = acc_ref[:].astype(dx_ref.dtype)


def _dw_s_kernel(s_ref, x_ref, label_ref, lse_ref, dw_ref, db_ref, acc_ref,
                 db_acc, *, block_v: int, v_valid: int, inv_n: float):
    vj = pl.program_id(1)
    ni = pl.program_id(2)
    nn = pl.num_programs(2)

    @pl.when(ni == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        db_acc[:] = jnp.zeros_like(db_acc)

    s = s_ref[:]
    col = vj * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    p = jnp.exp(s - lse_ref[:])
    onehot = (col == label_ref[:]) & (col < v_valid)
    dlog = (p - onehot.astype(jnp.float32)) * inv_n
    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], dlog.astype(x_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [d, bv]
    db_acc[:] += jnp.sum(dlog, axis=0, keepdims=True)

    @pl.when(ni == nn - 1)
    def _():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)
        db_ref[:] = db_acc[:].astype(db_ref.dtype)


def _bwd_prologue(x, w, labels, lse, block_n, block_v):
    """Shared backward setup for BOTH modes: block clamping and the
    padded-row contract — labels pad to -1 (match no column) and lse
    pads to +inf so p = exp(s − lse) = 0 on padded rows, making their
    dlogits exactly zero in every backward kernel."""
    n, d = x.shape
    _, v = w.shape
    block_n, block_v, n_pad, v_pad = _padded_dims(n, v, block_n, block_v)
    xf = jnp.pad(x, ((0, n_pad - n), (0, 0))) if n_pad != n else x
    wf = jnp.pad(w, ((0, 0), (0, v_pad - v))) if v_pad != v else w
    lf = jnp.pad(labels.astype(jnp.int32), (0, n_pad - n),
                 constant_values=-1)[:, None]
    lsef = jnp.pad(lse.astype(jnp.float32), (0, n_pad - n),
                   constant_values=jnp.inf)[:, None]
    return n, d, v, block_n, block_v, n_pad, v_pad, xf, wf, lf, lsef


def _scale_cotangents(dx, dw, db, g, x, w, b):
    """The scalar cotangent g is a traced value, so it cannot fold into
    the kernels' static inv_n; 1/n scales inside, g multiplies outside
    (one fused elementwise pass over dx/dW/db)."""
    gf = g.astype(jnp.float32)
    return (
        (dx.astype(jnp.float32) * gf).astype(x.dtype),
        (dw.astype(jnp.float32) * gf).astype(w.dtype),
        (db * gf).astype(b.dtype),
    )


def _pick_bv_dw(v_pad: int, block_v: int, bv_cap: int) -> int:
    """dW vocab tile: ``block_v`` when it already meets the VMEM cap,
    else the largest 128-multiple divisor of ``v_pad`` under the cap —
    repeated halving could strand a non-power-of-two ``block_v`` (e.g.
    384) above it. When ``block_v`` exceeds the cap it is ≥ 256 and a
    multiple of 128 (small vocabs clamp block_v to v_pad ≤ cap), so 128
    always divides ``v_pad`` and the search cannot come up empty; the
    ``block_v`` fallback keeps the pre-search behavior (tile above cap)
    for any exotic hand-picked block size."""
    cap = max(128, bv_cap)
    if block_v <= cap:
        return block_v
    for cand in range(cap - cap % 128, 127, -128):
        if v_pad % cand == 0:
            return cand
    return block_v


def _fused_backward_saved(x, w, b, labels, lse, s, g, block_n, block_v,
                          interpret):
    (n, d, v, block_n, block_v, n_pad, v_pad, xf, wf, lf, lsef
     ) = _bwd_prologue(x, w, labels, lse, block_n, block_v)
    assert s.shape == (n_pad, v_pad), (s.shape, n_pad, v_pad)
    dx = pl.pallas_call(
        partial(_dx_s_kernel, block_v=block_v, v_valid=v, inv_n=1.0 / n),
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        interpret=interpret,
    )(s, wf, lf, lsef)[:n]
    # dW tile cap: the f32 s tiles + f32 accumulator must fit scoped VMEM
    # (~16 MB): 4·d·bv (acc) + 8·bn·bv (s ×2 buffers) + 8·d·bv (dw out
    # ×2, f32 worst case) ≤ ~12 MB. Pick the largest 128-multiple divisor
    # of v_pad under the cap (_pick_bv_dw) — 128 always qualifies.
    bv_cap = max(
        128, (12 * 1024 * 1024) // (12 * d + 8 * block_n) // 128 * 128
    )
    bv_dw = _pick_bv_dw(v_pad, block_v, bv_cap)
    dw, db = pl.pallas_call(
        partial(_dw_s_kernel, block_v=bv_dw, v_valid=v, inv_n=1.0 / n),
        out_shape=[
            jax.ShapeDtypeStruct(wf.shape, w.dtype),
            jax.ShapeDtypeStruct((1, v_pad), jnp.float32),
        ],
        grid=(1, v_pad // bv_dw, n_pad // block_n),
        in_specs=[
            pl.BlockSpec((block_n, bv_dw), lambda _, j, i: (i, j)),
            pl.BlockSpec((block_n, d), lambda _, j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda _, j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda _, j, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((d, bv_dw), lambda _, j, i: (0, j)),
            pl.BlockSpec((1, bv_dw), lambda _, j, i: (0, j)),
        ],
        scratch_shapes=[
            pltpu.VMEM((d, bv_dw), jnp.float32),
            pltpu.VMEM((1, bv_dw), jnp.float32),
        ],
        interpret=interpret,
    )(s, xf, lf, lsef)
    return _scale_cotangents(dx, dw[:, :v], db[0, :v], g, x, w, b)


def _dx_kernel(x_ref, w_ref, b_ref, label_ref, lse_ref, dx_ref, acc_ref, *,
               block_v: int, v_valid: int, inv_n: float):
    vj = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b_ref[:].astype(jnp.float32)
    col = vj * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    p = jnp.exp(s - lse_ref[:])
    if v_valid != block_v * nv:
        p = jnp.where(col < v_valid, p, 0.0)
    onehot = (col == label_ref[:]) & (col < v_valid)
    dlog = (p - onehot.astype(jnp.float32)) * inv_n
    acc_ref[:] += jax.lax.dot_general(
        dlog.astype(w_ref.dtype), w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bn, d]

    @pl.when(vj == nv - 1)
    def _():
        dx_ref[:] = acc_ref[:].astype(dx_ref.dtype)


def _dw_kernel(w_ref, x_ref, b_ref, label_ref, lse_ref, dw_ref, db_ref,
               acc_ref, db_acc, *, block_v: int, v_valid: int, inv_n: float):
    vj = pl.program_id(1)
    ni = pl.program_id(2)
    nn = pl.num_programs(2)

    @pl.when(ni == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        db_acc[:] = jnp.zeros_like(db_acc)

    s = jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b_ref[:].astype(jnp.float32)  # [bn, bv]
    col = vj * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    p = jnp.exp(s - lse_ref[:])
    if v_valid != block_v * pl.num_programs(1):
        p = jnp.where(col < v_valid, p, 0.0)
    onehot = (col == label_ref[:]) & (col < v_valid)
    dlog = (p - onehot.astype(jnp.float32)) * inv_n
    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], dlog.astype(x_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [d, bv]
    db_acc[:] += jnp.sum(dlog, axis=0, keepdims=True)  # [1, bv]

    @pl.when(ni == nn - 1)
    def _():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)
        db_ref[:] = db_acc[:].astype(db_ref.dtype)


def _fused_backward(x, w, b, labels, lse, g, block_n, block_v, interpret):
    (n, d, v, block_n, block_v, n_pad, v_pad, xf, wf, lf, lsef
     ) = _bwd_prologue(x, w, labels, lse, block_n, block_v)
    # The dW kernel holds a [d, block_v] f32 scratch PLUS double-buffered
    # [d, block_v] in/out W tiles; cap its vocab tile so the working set
    # stays under the ~16 MB scoped-VMEM limit (5 live [d, bv] f32 tiles
    # + x/dlog  ->  bv <= 12 MB / (5 * 4 * d)).
    bv_budget = max(128, (12 * 1024 * 1024) // (5 * 4 * d) // 128 * 128)
    block_v_dw = min(block_v, bv_budget)
    bf = (jnp.pad(b, (0, v_pad - v)) if v_pad != v else b)[None, :]
    dx = pl.pallas_call(
        partial(_dx_kernel, block_v=block_v, v_valid=v, inv_n=1.0 / n),
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        grid=(n_pad // block_n, v_pad // block_v),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((1, block_v), lambda i, j: (0, j)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        interpret=interpret,
    )(xf, wf, bf, lf, lsef)[:n]
    v_pad_dw = _round_up(v, block_v_dw)
    wfd = jnp.pad(w, ((0, 0), (0, v_pad_dw - v))) if v_pad_dw != v else w
    bfd = (jnp.pad(b, (0, v_pad_dw - v)) if v_pad_dw != v else b)[None, :]
    dw, db = pl.pallas_call(
        partial(_dw_kernel, block_v=block_v_dw, v_valid=v, inv_n=1.0 / n),
        out_shape=[
            jax.ShapeDtypeStruct(wfd.shape, w.dtype),
            jax.ShapeDtypeStruct((1, v_pad_dw), jnp.float32),
        ],
        grid=(1, v_pad_dw // block_v_dw, n_pad // block_n),
        in_specs=[
            pl.BlockSpec((d, block_v_dw), lambda _, j, i: (0, j)),
            pl.BlockSpec((block_n, d), lambda _, j, i: (i, 0)),
            pl.BlockSpec((1, block_v_dw), lambda _, j, i: (0, j)),
            pl.BlockSpec((block_n, 1), lambda _, j, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda _, j, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((d, block_v_dw), lambda _, j, i: (0, j)),
            pl.BlockSpec((1, block_v_dw), lambda _, j, i: (0, j)),
        ],
        scratch_shapes=[
            pltpu.VMEM((d, block_v_dw), jnp.float32),
            pltpu.VMEM((1, block_v_dw), jnp.float32),
        ],
        interpret=interpret,
    )(wfd, xf, bfd, lf, lsef)
    return _scale_cotangents(dx, dw[:, :v], db[0, :v], g, x, w, b)


# --------------------------------------------------------------- dispatch


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fused(x, w, b, labels, block_n, block_v, interpret, save_s):
    lse, picked = _fused_forward(x, w, b, labels, block_n, block_v, interpret)
    return jnp.mean(lse - picked)


def _fused_fwd(x, w, b, labels, block_n, block_v, interpret, save_s):
    if save_s:
        lse, picked, s = _fused_forward(
            x, w, b, labels, block_n, block_v, interpret, save_s=True
        )
        return jnp.mean(lse - picked), (x, w, b, labels, lse, s)
    lse, picked = _fused_forward(x, w, b, labels, block_n, block_v, interpret)
    return jnp.mean(lse - picked), (x, w, b, labels, lse, None)


def _fused_bwd(block_n, block_v, interpret, save_s, res, g):
    import numpy as np

    x, w, b, labels, lse, s = res
    if save_s:
        dx, dw, db = _fused_backward_saved(
            x, w, b, labels, lse, s, g, block_n, block_v, interpret
        )
    else:
        dx, dw, db = _fused_backward(
            x, w, b, labels, lse, g, block_n, block_v, interpret
        )
    return dx, dw, db, np.zeros(labels.shape, dtype=jax.dtypes.float0)


_fused.defvjp(_fused_fwd, _fused_bwd)


# save_s auto threshold (round 5, VERDICT r4 item 5): the speed mode's
# f32 score residual is N_pad·V_pad·4 bytes; keep it on by default while
# that stays a modest slice of v5e-class HBM (16 GB) and fall back to the
# O(N) lean mode beyond. 2 GiB covers the flagship (8k×32k = 1 GiB) and
# the chip-filling config (16k×32k = 2 GiB) with room for the model;
# 131k-token long-context regimes (16 GiB of scores) auto-drop to lean —
# exactly the regime the O(N) contract exists for. The speed win is
# measured at kernel granularity by tools/xent_micro.py.
SAVE_S_AUTO_MAX_BYTES = 2 * 1024**3


def _padded_dims(n: int, v: int, block_n: int, block_v: int):
    """The kernel tiling rule, in one place: clamp blocks to the
    rounded-up problem (rows to 8, vocab to 128), pad the problem to a
    block multiple. Every consumer — forward, backward prologue, and
    the save-s auto threshold — must see the SAME (block_n, block_v,
    n_pad, v_pad) or residual-size estimates drift from reality."""
    block_n = min(block_n, _round_up(n, 8))
    block_v = min(block_v, _round_up(v, 128))
    return block_n, block_v, _round_up(n, block_n), _round_up(v, block_v)


def _auto_save_s(n: int, v: int, block_n: int, block_v: int) -> bool:
    """save_s=None resolution: speed mode iff the padded f32 score
    residual fits the auto budget."""
    _, _, n_pad, v_pad = _padded_dims(n, v, block_n, block_v)
    return n_pad * v_pad * 4 <= SAVE_S_AUTO_MAX_BYTES


def _reference_xent(xn, w, b, ln):
    """Differentiable XLA reference with the SAME out-of-range-label
    semantics as the kernel (loss = lse, no pull-up) —
    ``softmax_cross_entropy`` would CLAMP invalid ids to an edge class,
    silently training differently per backend."""
    v = w.shape[-1]
    logits = (xn @ w + b).astype(jnp.float32)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    ids = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    picked = jnp.sum(
        jnp.where(ids == ln[:, None].astype(jnp.int32), logits, 0.0),
        axis=-1,
    )
    valid = (ln >= 0) & (ln < v)
    return jnp.mean(lse - jnp.where(valid, picked, 0.0))


# The unsharded dispatch runs inside a NAMED nested jit so the call
# survives as a recognizably-named pjit equation in any traced step —
# the marker tpudml.analysis rule J107 keys on to flag a full-vocab
# fused-xent call whose W operand is actually vocab-sharded on a mesh
# axis (a partial-vocab softmax that trains wrong silently). The
# sharded wrapper below carries a DIFFERENT name, so the correct
# composition stays silent. XLA inlines inner jits at lowering, so the
# marker costs nothing on the chip.
def _fused_xent_unsharded(x, w, b, labels, block_n, block_v, interpret,
                          save_s):
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _reference_xent(x, w, b, labels)
        interpret = False
    return _fused(x, w, b, labels, block_n, block_v, interpret, save_s)


FUSED_XENT_MARKER = _fused_xent_unsharded.__name__

_fused_xent_unsharded_jit = jax.jit(
    _fused_xent_unsharded, static_argnums=(4, 5, 6, 7)
)


def linear_cross_entropy(
    x: jax.Array,
    w: jax.Array,
    labels: jax.Array,
    bias: jax.Array | None = None,
    *,
    block_n: int = 256,
    block_v: int = 2048,
    interpret: bool | None = None,
    save_s: bool | None = None,
) -> jax.Array:
    """Mean softmax cross-entropy of ``x @ w [+ bias]`` against integer
    ``labels`` without materializing the [N, V] logits (see module
    docstring).

    ``x`` [..., d] flattens to [N, d]; ``labels`` [...] to [N]. Labels
    outside [0, V) contribute loss = lse (no pull-up) — mask such rows
    out beforehand. ``save_s=True`` is the SPEED mode: it keeps the
    [N_pad, V_pad] f32 scores as a backward residual (2 fewer backward
    matmuls — 8.21 → 5.97 ms at [8192,32k] at kernel granularity,
    tools/xent_micro.py; 21.54 → 19.29 ms/step in-situ); the
    default ``save_s=None`` resolves it AUTOMATICALLY: speed mode while
    the score residual fits ``SAVE_S_AUTO_MAX_BYTES``, the O(N) lean
    mode beyond (the long-context regimes the memory contract exists
    for). Pass ``False`` to force the O(N) contract regardless. On
    non-TPU backends dispatches to the XLA reference math unless
    ``interpret=True`` forces the Pallas interpreter.

    ``w`` here is the FULL vocab projection. When the head is
    vocab-sharded over a mesh axis, use
    :func:`sharded_linear_cross_entropy` inside the ``shard_map``
    region instead — feeding a vocab shard to this function computes a
    partial-vocab softmax (rule J107 flags exactly that)."""
    d = x.shape[-1]
    v = w.shape[-1]
    xn = x.reshape(-1, d)
    ln = labels.reshape(-1)
    if xn.shape[0] != ln.shape[0]:
        raise ValueError(f"{x.shape} rows != {labels.shape} labels")
    if save_s is None:
        save_s = _auto_save_s(xn.shape[0], v, block_n, block_v)
    b = jnp.zeros((v,), w.dtype) if bias is None else bias
    return _fused_xent_unsharded_jit(
        xn, w, b, ln, block_n, block_v, interpret, save_s
    )


# ------------------------------------------------- vocab-sharded variant
# The distributed form of the fused head: each shard of a vocab-sharded
# W ([d, V/W] per chip) streams only its local tiles through the SAME
# Pallas kernels above and emits per-shard partial statistics
# (lse_local, picked_local); shards merge with the online log-sum-exp
# combination rule ring attention uses per arriving K/V block
# (tpudml/parallel/cp.py _merge_blocks), here one pmax + one psum over
# the mesh axis (collectives.plogsumexp). Label semantics do the shard
# routing for free: shifting labels by -shard·V_local makes out-of-shard
# labels out-of-range, which the kernel already maps to picked = 0 — so
# psum(picked_local) recovers the one true pick with no gather.
#
# Backward: p = exp(s_local − lse_GLOBAL) is exactly this shard's slice
# of the global softmax, so the existing backward kernels run unchanged
# with the merged lse as input — dW/db stay 1/W shard-local with NO
# extra collective, and dX comes back as a per-shard partial that the
# enclosing shard_map transpose psums once (W's axis is mentioned in
# its in_spec, x's is not: the single dX reduce is derived, not coded).
# The custom_vjp therefore returns dX UN-summed — summing here too
# would double-count by the axis size.


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _fused_sharded(x, w, b, labels, axis_name, block_n, block_v, interpret,
                   save_s):
    loss, _ = _fused_sharded_fwd(
        x, w, b, labels, axis_name, block_n, block_v, interpret, save_s
    )
    return loss


def _fused_sharded_fwd(x, w, b, labels, axis_name, block_n, block_v,
                       interpret, save_s):
    from tpudml.comm.collectives import plogsumexp

    v_local = w.shape[-1]
    shard = jax.lax.axis_index(axis_name)
    ln = labels.astype(jnp.int32) - shard * v_local
    s = None
    if save_s:
        lse_loc, picked_loc, s = _fused_forward(
            x, w, b, ln, block_n, block_v, interpret, save_s=True
        )
    else:
        lse_loc, picked_loc = _fused_forward(
            x, w, b, ln, block_n, block_v, interpret
        )
    lse = plogsumexp(lse_loc, axis_name)
    picked = jax.lax.psum(picked_loc, axis_name)
    return jnp.mean(lse - picked), (x, w, b, ln, lse, s)


def _fused_sharded_bwd(axis_name, block_n, block_v, interpret, save_s,
                       res, g):
    import numpy as np

    x, w, b, ln, lse, s = res
    # shard_map (check_rep=False) transposition convention: the
    # cotangent of an output whose spec does not mention an axis arrives
    # DIVIDED by that axis size, and body psums transpose to psums —
    # that is how the pure-autodiff reference path regains the factor
    # through the merge collectives' transposes. This custom_vjp
    # replaces those transposes, so it must restore the factor itself:
    # psum of the (replicated) cotangent over the merge axis. Verified
    # by the TP/FSDP/FSDP×TP interpret-mode parity tests — dropping
    # this psum deflates every gradient by exactly the axis size.
    g = jax.lax.psum(g, axis_name)
    if save_s:
        dx, dw, db = _fused_backward_saved(
            x, w, b, ln, lse, s, g, block_n, block_v, interpret
        )
    else:
        dx, dw, db = _fused_backward(
            x, w, b, ln, lse, g, block_n, block_v, interpret
        )
    # dx is this shard's PARTIAL over its vocab slice — the shard_map
    # transpose supplies the one cross-shard reduce (see block comment).
    return dx, dw, db, np.zeros(ln.shape, dtype=jax.dtypes.float0)


_fused_sharded.defvjp(_fused_sharded_fwd, _fused_sharded_bwd)


def _sharded_reference(xn, w, b, ln, axis_name):
    """Differentiable sharded XLA reference (non-TPU dispatch): local
    partial-vocab statistics merged with the identical plogsumexp/psum
    rule. Grad-exact vs the unsharded reference by construction —
    autodiff of the merge reproduces p = exp(s − lse_global) per
    shard."""
    from tpudml.comm.collectives import plogsumexp

    v_local = w.shape[-1]
    shard = jax.lax.axis_index(axis_name)
    ln = ln.astype(jnp.int32) - shard * v_local
    logits = (xn @ w + b).astype(jnp.float32)
    m = jnp.max(logits, axis=-1)
    lse_loc = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    lse = plogsumexp(lse_loc, axis_name)
    ids = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    picked_loc = jnp.sum(
        jnp.where(ids == ln[:, None], logits, 0.0), axis=-1
    )
    valid = (ln >= 0) & (ln < v_local)
    picked = jax.lax.psum(jnp.where(valid, picked_loc, 0.0), axis_name)
    return jnp.mean(lse - picked)


# Named marker for the CORRECT sharded composition — distinct from
# FUSED_XENT_MARKER, so J107 stays silent on it.
def _fused_xent_sharded(x, w, b, labels, axis_name, block_n, block_v,
                        interpret, save_s):
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _sharded_reference(x, w, b, labels, axis_name)
        interpret = False
    return _fused_sharded(
        x, w, b, labels, axis_name, block_n, block_v, interpret, save_s
    )


SHARDED_XENT_MARKER = _fused_xent_sharded.__name__

_fused_xent_sharded_jit = jax.jit(
    _fused_xent_sharded, static_argnums=(4, 5, 6, 7, 8)
)


def sharded_linear_cross_entropy(
    x: jax.Array,
    w: jax.Array,
    labels: jax.Array,
    bias: jax.Array | None = None,
    *,
    axis_name: str,
    block_n: int = 256,
    block_v: int = 2048,
    interpret: bool | None = None,
    save_s: bool | None = None,
) -> jax.Array:
    """Vocab-sharded :func:`linear_cross_entropy`: call INSIDE a
    ``shard_map`` region where ``axis_name`` is bound, with ``w`` the
    LOCAL [d, V/W] vocab shard (``bias`` its [V/W] slice) and ``labels``
    GLOBAL ids; every shard must hold the same ``x`` rows. Returns the
    replicated global mean loss — identical to the unsharded call on
    the concatenated W, to float tolerance (pinned by parity tests under
    TP, FSDP, and FSDP×TP meshes).

    ``save_s=None`` auto-resolves against the LOCAL vocab: the f32
    score residual is N_pad·(V/W)_pad·4 bytes PER SHARD — 1/W of the
    unsharded residual — so sharding widens the regime where the speed
    mode fits ``SAVE_S_AUTO_MAX_BYTES``. Gradient contract: dW/db are
    shard-local (1/W per chip, no collective); dX is returned as a
    per-shard partial for the enclosing shard_map transpose to reduce
    once."""
    d = x.shape[-1]
    v_local = w.shape[-1]
    xn = x.reshape(-1, d)
    ln = labels.reshape(-1)
    if xn.shape[0] != ln.shape[0]:
        raise ValueError(f"{x.shape} rows != {labels.shape} labels")
    if save_s is None:
        save_s = _auto_save_s(xn.shape[0], v_local, block_n, block_v)
    b = jnp.zeros((v_local,), w.dtype) if bias is None else bias
    return _fused_xent_sharded_jit(
        xn, w, b, ln, axis_name, block_n, block_v, interpret, save_s
    )
