"""Fused attention kernel (Pallas, TPU) — flash-attention tiling.

Softmax(QKᵀ)V fused into one kernel with BOTH operands blocked: the
[T, T] score matrix never exists, and K/V stream through VMEM one
[block_k, D] tile at a time, folded into an online softmax held in VMEM
scratch (running max m, normalizer l, and an f32 output accumulator —
rescaled by exp(m_prev − m_new) as new tiles arrive). Per-step VMEM is
O(block_q·D + block_k·D), independent of T — the memory shape that makes
very long contexts possible — and HBM traffic for scores drops from
O(T²) to zero.

Grid: (batch×heads, T/block_q, T/block_k) with the K dimension innermost:
each output block is revisited across the K steps, initialized at the
first (``pl.when kj == 0``) and finalized (acc/l) at the last. Scores are
computed on the MXU with f32 accumulation; masking (causal and
sequence-padding) uses global positions so any T works via pad-and-mask.

Backward uses recompute-through-the-reference-math (custom_vjp): exact
gradients, O(T²) transient inside XLA — acceptable because training at
long T runs under ring context parallelism (tpudml.parallel.cp), where
per-shard T is short; a blocked backward kernel is the natural next step.

Validated against the reference math on a real v5e chip (bf16
max-abs-err ~1e-2 vs f32 reference — MXU input precision — and ~5e-3 for
f32 inputs). On non-TPU platforms ``flash_attention`` dispatches to the
reference math (full speed under XLA); the interpreter runs only when
forced (tests).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudml.nn.attention import NEG_INF, dot_product_attention


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                 scale: float, causal: bool, block_q: int, block_k: int,
                 t_valid: int):
    # Grid reads hoisted out of the conditional body: program_id has no
    # lowering inside a cond branch in interpret mode.
    kj = pl.program_id(2)
    qi = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    def fold_block():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]  # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k] on the MXU
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if t_valid != block_k * nk:  # static: nk is a trace-time constant
            # Padded keys (K rounded up to its tile multiple) must get no
            # attention mass; padded Q rows are sliced off outside.
            s = jnp.where(k_pos < t_valid, s, NEG_INF)

        m_prev = m_ref[:]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    if causal:
        # Skip K blocks entirely above the diagonal (the standard causal
        # flash-attention ~2× FLOP saving): block (i, kj) contributes only
        # if its first key position can be attended by its last query row.
        last_q = (qi + 1) * block_q - 1
        pl.when(last_q >= kj * block_k)(fold_block)
    else:
        fold_block()

    @pl.when(kj == nk - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    # Any T works: Q and K/V pad INDEPENDENTLY to their own block
    # multiples (nothing requires equal lengths — masking uses global
    # positions), so neither grid axis inflates past one extra block.
    # Never shrink blocks — small tiles waste the MXU's 8-sublane
    # granularity on odd/prime T.
    block_q = min(block_q, _round_up(t, 8))
    block_k = min(block_k, _round_up(t, 8))
    t_pad_q = _round_up(t, block_q)
    t_pad_k = _round_up(t, block_k)
    # [B, T, H, D] → [B·H, T_pad, D]: one grid row per (batch, head).
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    qf, kf, vf = fold(q), fold(k), fold(v)
    if t_pad_q != t:
        qf = jnp.pad(qf, ((0, 0), (0, t_pad_q - t), (0, 0)))
    if t_pad_k != t:
        pad = ((0, 0), (0, t_pad_k - t), (0, 0))
        kf, vf = jnp.pad(kf, pad), jnp.pad(vf, pad)
    out = pl.pallas_call(
        partial(
            _attn_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, t_valid=t,
        ),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(b * h, t_pad_q // block_q, t_pad_k // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, kj: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, kj: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, kj: (bh, kj, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, kj: (bh, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running normalizer
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out[:, :t].reshape(b, h, t, d).transpose(0, 2, 1, 3)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    # Exact gradients by recomputing the reference math under vjp; XLA
    # fuses the recompute, and the forward's fused kernel is untouched.
    _, vjp = jax.vjp(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused blocked attention over [B, T, H, D]; same semantics as
    ``dot_product_attention``. Dispatch: compiled kernel on TPU; on other
    backends the reference math (full speed under XLA) unless
    ``interpret=True`` forces the Pallas interpreter (tests)."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return dot_product_attention(q, k, v, causal=causal)
        interpret = False
    return _flash(q, k, v, causal, block_q, block_k, interpret)
