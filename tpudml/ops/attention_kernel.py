"""Fused attention kernel (Pallas, TPU).

Softmax(QKᵀ)V fused into one kernel: the [T, T] score matrix never
round-trips to HBM — each grid step holds one Q block and the full K/V for
that (batch, head) in VMEM, computes scores on the MXU in float32, applies
the numerically-stable softmax on the VPU, and writes only the [block_q, D]
output block. Versus the unfused path, HBM traffic for the scores drops
from O(T²) to zero, which is the whole game on bandwidth-bound TPUs.

Grid: (batch×heads, T/block_q). K/V are streamed per (batch, head) —
fine to O(100k) tokens at D=128 within ~16 MB VMEM; K-blocking (full
flash-attention tiling) is the natural extension if sequences outgrow it.
Validated bit-accurate against the reference math on a real v5e chip
(bf16 max-abs-err ~1e-2 vs f32 reference at T=512); at short/moderate T
XLA's own fusion of the unfused math is already competitive, so the
kernel's payoff is the memory ceiling at long T, not small-T latency.

Backward uses recompute-through-the-reference-math (custom_vjp): exact
gradients, O(T²) transient inside XLA — acceptable because training at
long T runs under ring context parallelism (tpudml.parallel.cp), where
per-shard T is short; the kernel's own backward tiling is future work.

On non-TPU platforms the kernel runs in interpret mode (tests) or falls
back to the reference math (``tpudml.nn.attention.dot_product_attention``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpudml.nn.attention import NEG_INF, dot_product_attention


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool,
                 block_q: int, t_valid: int):
    q = q_ref[0]  # [block_q, D]
    k = k_ref[0]  # [T_pad, D]
    v = v_ref[0]  # [T_pad, D]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [block_q, T_pad] on the MXU, f32 accumulation
    k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if causal:
        q_pos = pl.program_id(1) * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    if t_valid != s.shape[-1]:
        # Sequence padded up to the block multiple: padded keys must not
        # receive attention mass (padded Q rows are sliced off outside).
        s = jnp.where(k_pos < t_valid, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = (o / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, interpret: bool):
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    # Any T works: pad the sequence up to a block-multiple and mask the
    # padded keys in-kernel (never shrink the block — a small block would
    # silently waste the MXU's 8-sublane tiles on odd/prime T).
    block_q = min(block_q, _round_up(t, 8))
    t_pad = _round_up(t, block_q)
    # [B, T, H, D] → [B·H, T_pad, D]: one grid row per (batch, head).
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    qf, kf, vf = fold(q), fold(k), fold(v)
    if t_pad != t:
        pad = ((0, 0), (0, t_pad - t), (0, 0))
        qf, kf, vf = (jnp.pad(a, pad) for a in (qf, kf, vf))
    out = pl.pallas_call(
        partial(
            _attn_kernel, scale=scale, causal=causal, block_q=block_q, t_valid=t
        ),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(b * h, t_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, t_pad, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, t_pad, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out[:, :t].reshape(b, h, t, d).transpose(0, 2, 1, 3)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, interpret):
    return _flash_forward(q, k, v, causal, block_q, interpret)


def _flash_fwd(q, k, v, causal, block_q, interpret):
    return _flash_forward(q, k, v, causal, block_q, interpret), (q, k, v)


def _flash_bwd(causal, block_q, interpret, res, g):
    q, k, v = res
    # Exact gradients by recomputing the reference math under vjp; XLA
    # fuses the recompute, and the forward's fused kernel is untouched.
    _, vjp = jax.vjp(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused attention over [B, T, H, D]; same semantics as
    ``dot_product_attention``. Dispatch: compiled kernel on TPU; on other
    backends the reference math (full speed under XLA) unless
    ``interpret=True`` forces the Pallas interpreter (tests)."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return dot_product_attention(q, k, v, causal=causal)
        interpret = False
    return _flash(q, k, v, causal, block_q, interpret)
