"""Fused attention kernels (Pallas, TPU) — flash-attention tiling, both
directions.

Forward: softmax(QKᵀ)V with BOTH operands blocked — the [T, T] score
matrix never exists. K/V stream through VMEM one [block_k, D] tile at a
time into an online softmax held in scratch (running max m, normalizer l,
f32 output accumulator, rescaled by exp(m_prev − m_new) per tile), and the
row log-sum-exp is emitted as a residual. Per-step VMEM is
O(block_q·D + block_k·D), independent of T.

Backward: the flash recipe — no O(T²) transient. With the forward's
output O and lse, and Δ = rowsum(dO ⊙ O):

- dQ kernel (K innermost): recompute the tile's scores, p = exp(s − lse),
  dp = dO·Vᵀ, ds = p ⊙ (dp − Δ); accumulate dQ += scale · ds·K in scratch.
- dK/dV kernel (Q innermost): same recompute per tile; dV += pᵀ·dO,
  dK += scale · dsᵀ·Q.

Causal runs skip tiles entirely off the diagonal in all three kernels
(~2× fewer FLOPs). Q and K pad independently to their own block
multiples; masking uses global positions so any T works. Grid reads are
hoisted out of skip branches (program_id can't lower inside a cond in
interpret mode). ``blocked_backward=False`` falls back to
recompute-through-the-reference-math under vjp (debugging aid).

Validated against the reference math on a real v5e chip; on non-TPU
platforms ``flash_attention`` dispatches to the reference math unless
``interpret=True`` forces the Pallas interpreter (tests).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudml.nn.attention import NEG_INF, dot_product_attention


from tpudml.ops.tiling import round_up as _round_up  # shared tiling helper


def _plan(t: int, block_q: int, block_k: int) -> tuple[int, int, int, int]:
    """(block_q, block_k, t_pad_q, t_pad_k): blocks are capped from above
    at round_up(t, 8) (so tiny T doesn't allocate oversized tiles), never
    raised — callers control the lower bound; Q/K pad independently."""
    block_q = min(block_q, _round_up(t, 8))
    block_k = min(block_k, _round_up(t, 8))
    return block_q, block_k, _round_up(t, block_q), _round_up(t, block_k)


def _fold_pad(arrays, b, h, t, d, t_pad):
    """[B, T, H, D] → [B·H, T_pad, D] per array (shared by fwd/bwd so the
    layouts can never diverge)."""
    out = []
    for x in arrays:
        f = x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        if t_pad != t:
            f = jnp.pad(f, ((0, 0), (0, t_pad - t), (0, 0)))
        out.append(f)
    return out


def _unfold(x, b, h, t, d):
    return x[:, :t].reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _scores(q, k, qi, kj, *, scale, causal, block_q, block_k, t_valid, nk,
            k_shift=0):
    """Recomputable masked score tile [block_q, block_k] in f32.
    ``k_shift`` offsets the causal diagonal (striped ring layout: blocks
    from later-striped devices are visible only STRICTLY below it)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        s = jnp.where(q_pos >= k_pos + k_shift, s, NEG_INF)
    if t_valid != block_k * nk:  # static: nk is a trace-time constant
        # Padded keys (K rounded up to its tile multiple) must get no
        # attention mass; padded Q rows are sliced off outside.
        s = jnp.where(k_pos < t_valid, s, NEG_INF)
    return s


# --------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                t_valid: int, k_shift: int = 0):
    kj = pl.program_id(2)
    qi = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    def fold_block():
        s = _scores(
            q_ref[0], k_ref[0], qi, kj, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, t_valid=t_valid, nk=nk,
            k_shift=k_shift,
        )
        m_prev = m_ref[:]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    if causal:
        # Skip K tiles entirely above the (shifted) diagonal: tile
        # (qi, kj) contributes only if its last query row can attend its
        # first key.
        pl.when((qi + 1) * block_q - 1 >= kj * block_k + k_shift)(fold_block)
    else:
        fold_block()

    @pl.when(kj == nk - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l_ref[:])


def _flash_forward(q, k, v, causal, block_q, block_k, interpret, k_shift=0):
    """Returns (out [B,T,H,D], lse [B·H, t_pad_q, 1] f32)."""
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    block_q, block_k, t_pad_q, t_pad_k = _plan(t, block_q, block_k)
    (qf,) = _fold_pad((q,), b, h, t, d, t_pad_q)
    kf, vf = _fold_pad((k, v), b, h, t, d, t_pad_k)
    out, lse = pl.pallas_call(
        partial(
            _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, t_valid=t, k_shift=k_shift,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            jax.ShapeDtypeStruct((b * h, t_pad_q, 1), jnp.float32),
        ],
        grid=(b * h, t_pad_q // block_q, t_pad_k // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, kj: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, kj: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, kj: (bh, kj, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, kj: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, kj: (bh, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running normalizer
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return _unfold(out, b, h, t, d), lse


# -------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, scale, causal, block_q, block_k, t_valid,
               k_shift: int = 0):
    kj = pl.program_id(2)
    qi = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def fold_block():
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = _scores(
            q_ref[0], k, qi, kj, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, t_valid=t_valid, nk=nk,
            k_shift=k_shift,
        )
        p = jnp.exp(s - lse_ref[0])  # lse_ref[0]: [bq, 1]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0])
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when((qi + 1) * block_q - 1 >= kj * block_k + k_shift)(fold_block)
    else:
        fold_block()

    @pl.when(kj == nk - 1)
    def _():
        dq_ref[0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _dkdv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, block_q,
                 block_k, t_valid, nk, k_shift: int = 0):
    qi = pl.program_id(2)
    kj = pl.program_id(1)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def fold_block():
        q = q_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = _scores(
            q, k_ref[0], qi, kj, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, t_valid=t_valid, nk=nk,
            k_shift=k_shift,
        )
        p = jnp.exp(s - lse_ref[0])  # lse_ref[0]: [bq, 1]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # pᵀ·dO → [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # dsᵀ·Q → [bk, d]

    if causal:
        pl.when((qi + 1) * block_q - 1 >= kj * block_k + k_shift)(fold_block)
    else:
        fold_block()

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k, interpret):
    b, t, h, d = q.shape
    block_q, block_k, t_pad_q, t_pad_k = _plan(t, block_q, block_k)
    qf, dof, of = _fold_pad((q, g, o), b, h, t, d, t_pad_q)
    kf, vf = _fold_pad((k, v), b, h, t, d, t_pad_k)
    # Δ = rowsum(dO ⊙ O): cheap elementwise, computed once outside.
    delta = jnp.sum(
        dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1, keepdims=True
    )  # [B·H, t_pad_q, 1]
    dqf, dkf, dvf = _backward_calls(
        qf, kf, vf, dof, lse, delta, b, h, t, d, causal, block_q, block_k,
        t_pad_q, t_pad_k, interpret,
    )
    return tuple(_unfold(x, b, h, t, d) for x in (dqf, dkf, dvf))


def _backward_calls(qf, kf, vf, dof, lse, delta, b, h, t, d, causal, block_q,
                    block_k, t_pad_q, t_pad_k, interpret, k_shift=0):
    """The two backward pallas_calls on pre-folded [B·H, t_pad, ·] inputs
    (shared by the full backward and the per-block ring entry point)."""
    scale = 1.0 / (d ** 0.5)
    bh = b * h
    nq, nk = t_pad_q // block_q, t_pad_k // block_k
    q_spec = pl.BlockSpec((1, block_q, d), lambda i, j, r: (i, j, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda i, j, r: (i, j, 0))

    dqf = pl.pallas_call(
        partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, t_valid=t, k_shift=k_shift,
        ),
        out_shape=jax.ShapeDtypeStruct(qf.shape, qf.dtype),
        grid=(bh, nq, nk),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, block_k, d), lambda bh, i, kj: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, kj: (bh, kj, 0)),
            q_spec,
            row_spec,
            row_spec,
        ],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    k_spec = pl.BlockSpec((1, block_k, d), lambda bh, kj, i: (bh, kj, 0))
    qrow_spec = pl.BlockSpec((1, block_q, d), lambda bh, kj, i: (bh, i, 0))
    lrow_spec = pl.BlockSpec((1, block_q, 1), lambda bh, kj, i: (bh, i, 0))
    dkf, dvf = pl.pallas_call(
        partial(
            _dkdv_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, t_valid=t, nk=nk, k_shift=k_shift,
        ),
        out_shape=[
            jax.ShapeDtypeStruct(kf.shape, kf.dtype),
            jax.ShapeDtypeStruct(vf.shape, vf.dtype),
        ],
        grid=(bh, nk, nq),
        in_specs=[k_spec, k_spec, qrow_spec, qrow_spec, lrow_spec, lrow_spec],
        out_specs=[k_spec, k_spec],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(kf, vf, qf, dof, lse, delta)

    return dqf, dkf, dvf


# ------------------------------------------------- blockwise entry points
#
# Ring context parallelism (tpudml.parallel.cp) composes attention from
# per-K/V-block partials: each arriving block runs a flash forward that
# also RETURNS its log-sum-exp so blocks merge exactly, and the ring
# backward re-runs the tile kernels per block with the GLOBALLY-merged
# softmax statistics (lse, Δ) — the flash decomposition dq = Σ_b ds_b·K_b,
# dk_b = ds_bᵀ·Q with p_b = exp(s_b − lse_global).


def _fold_rows(x, t_pad):
    """[B, H, T] → [B·H, t_pad, 1] (row-statistic layout of the kernels)."""
    b, h, t = x.shape
    f = x.reshape(b * h, t, 1)
    if t_pad != t:
        f = jnp.pad(f, ((0, 0), (0, t_pad - t), (0, 0)))
    return f


def flash_forward_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    k_shift: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Flash forward that also returns the row log-sum-exp.

    Returns (out [B,T,H,D], lse [B,H,T] f32). ``causal`` here masks by
    LOCAL tile positions — for a ring block pair this is exactly the
    diagonal (same-length, aligned) block; off-diagonal visible blocks
    pass causal=False. ``k_shift=1`` makes the diagonal strict (the
    striped ring layout's later-device blocks).
    """
    b, t, h, d = q.shape
    (default_fwd_bq, _), default_bk = _default_blocks(d)
    out, lse = _flash_forward(
        q, k, v, causal,
        default_fwd_bq if block_q is None else block_q,
        default_bk if block_k is None else block_k,
        interpret, k_shift=k_shift,
    )
    return out, lse[:, :t, 0].reshape(b, h, t)


def flash_block_grads(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    do: jax.Array,
    lse: jax.Array,
    delta: jax.Array,
    *,
    causal: bool = False,
    k_shift: int = 0,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-block flash backward with EXTERNAL softmax statistics.

    ``lse``/``delta`` [B,H,T] come from the globally-merged attention
    (delta = rowsum(dO ⊙ O_final)), so the returned (dq, dk, dv) are this
    block's exact contributions to the global gradients; summing over
    blocks reproduces the full backward.
    """
    b, t, h, d = q.shape
    (_, default_bwd_bq), default_bk = _default_blocks(d)
    if block_q is None:
        block_q = default_bwd_bq
    if block_k is None:
        block_k = default_bk
    block_q, block_k, t_pad_q, t_pad_k = _plan(t, block_q, block_k)
    qf, dof = _fold_pad((q, do), b, h, t, d, t_pad_q)
    kf, vf = _fold_pad((k, v), b, h, t, d, t_pad_k)
    lsef = _fold_rows(lse.astype(jnp.float32), t_pad_q)
    deltaf = _fold_rows(delta.astype(jnp.float32), t_pad_q)
    dqf, dkf, dvf = _backward_calls(
        qf, kf, vf, dof, lsef, deltaf, b, h, t, d, causal, block_q, block_k,
        t_pad_q, t_pad_k, interpret, k_shift=k_shift,
    )
    return tuple(_unfold(x, b, h, t, d) for x in (dqf, dkf, dvf))


# ------------------------------------------------------------- dispatch


# Measured-best default tiles by head dim (v5e, T=1024 sweeps):
# - forward wants the largest Q tile that fits VMEM (fewer grid
#   programs, bigger MXU ops: 0.43 vs 0.71 ms/layer at dh=64 for
#   (512,512) vs (128,512));
# - the backward's dQ/dKdV kernels carry more scratch/live values per
#   program and prefer smaller Q tiles;
# - at dh>=128 (full-lane tiles) larger K blocks win in BOTH directions
#   (fwd 0.067 ms at bk=1024 vs 0.131 at 512; bwd (256,1024) 0.56 ms vs
#   (128,512) 0.90 ms per layer).
def _default_blocks(d: int) -> tuple[tuple[int, int], int]:
    """((fwd_block_q, bwd_block_q), block_k) by head dim."""
    if d >= 128:
        return (512, 256), 1024
    return (512, 128), 512


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, interpret, blocked_backward):
    out, _ = _flash_forward(q, k, v, causal, block_q[0], block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, blocked_backward):
    out, lse = _flash_forward(q, k, v, causal, block_q[0], block_k, interpret)
    res = (q, k, v, out, lse) if blocked_backward else (q, k, v)
    return out, res


def _flash_bwd(causal, block_q, block_k, interpret, blocked_backward, res, g):
    if blocked_backward:
        q, k, v, o, lse = res
        return _flash_backward(
            q, k, v, o, lse, g, causal, block_q[1], block_k, interpret
        )
    q, k, v = res
    # Fallback: exact gradients by recomputing the reference math under
    # vjp (O(T²) transient inside XLA; debugging aid).
    _, vjp = jax.vjp(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal), q, k, v
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int | tuple[int, int] | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    blocked_backward: bool = True,
) -> jax.Array:
    """Fused blocked attention over [B, T, H, D]; same semantics as
    ``dot_product_attention``. Dispatch: compiled kernels on TPU; on other
    backends the reference math (full speed under XLA) unless
    ``interpret=True`` forces the Pallas interpreter (tests).

    ``block_q``: one int for both directions, or a (forward, backward)
    pair; ``block_q``/``block_k`` default (None) to the measured-best
    tiles for the head dim (``_default_blocks``: the forward prefers
    large Q tiles, the backward small; dh>=128 takes bigger K blocks).
    ``_plan`` still caps every block at the padded T."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return dot_product_attention(q, k, v, causal=causal)
        interpret = False
    default_bq, default_bk = _default_blocks(q.shape[-1])
    if block_q is None:
        bq = default_bq
    elif isinstance(block_q, int):
        bq = (block_q, block_q)
    else:
        bq = tuple(block_q)
    if block_k is None:
        block_k = default_bk
    return _flash(q, k, v, causal, bq, block_k, interpret, blocked_backward)
