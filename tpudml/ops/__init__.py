"""Custom TPU kernels (Pallas).

The reference has no custom kernels (its native compute is vendored cuDNN,
SURVEY.md §2.4); here the hot ops the XLA fusion engine can't already
produce optimally are written in Pallas against the TPU memory hierarchy
(HBM→VMEM→MXU; /opt/skills/guides/pallas_guide.md is the playbook).
"""

from tpudml.ops.attention_kernel import (
    flash_attention,
    flash_block_grads,
    flash_forward_lse,
)
from tpudml.ops.decode_head import fused_decode_head, fused_decode_head_int8
from tpudml.ops.junction_kernel import fused_attn_junction
from tpudml.ops.layernorm_kernel import fused_layernorm
from tpudml.ops.xent_kernel import linear_cross_entropy

__all__ = [
    "flash_attention",
    "flash_block_grads",
    "flash_forward_lse",
    "fused_attn_junction",
    "fused_decode_head",
    "fused_decode_head_int8",
    "fused_layernorm",
    "linear_cross_entropy",
]
