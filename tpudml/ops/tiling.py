"""Shared tiling helpers for the Pallas kernels (flash attention, fused
linear-cross-entropy, fused LayerNorm): one definition of the block
rounding and row-padding boilerplate so a tiling/padding fix (e.g. a
different sublane multiple per dtype) lands everywhere at once."""

from __future__ import annotations

import jax.numpy as jnp


def round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def pad_rows(x, n_pad: int):
    """Zero-pad the leading (row) axis of a 2-D array up to ``n_pad``."""
    return (
        jnp.pad(x, ((0, n_pad - x.shape[0]), (0, 0)))
        if n_pad != x.shape[0] else x
    )
