"""Fused attention-block junction: flash attention + out-projection +
residual add + LayerNorm as one differentiable unit.

BASELINE.md round 7 put ~2.2 ms/step of the flagship's non-MXU residual
at the block junctions — the seams where attention output meets the
residual stream and the next norm, which XLA schedules as separate
reduce-broken fusion chains. This module closes the seam by chaining
the two existing Pallas kernels through the out-projection matmul under
ONE named jit:

    a      = flash_attention(q, k, v)          # ops/attention_kernel
    h      = a.reshape(B, T, d) @ Wo + bo      # MXU epilogue
    (s, y) = fused_add_layernorm(r, h, γ, β)   # ops/layernorm_kernel

Both kernels carry full custom_vjp backwards (flash recompute-tiles,
add+LN one-pass with the residual-cotangent merge), so differentiating
the junction runs kernel backwards end to end — no reference-math
recompute anywhere in the chain — while the matmul between them stays
an ordinary MXU op XLA fuses into the surrounding epilogues. The named
jit (``ATTN_JUNCTION_MARKER``) keeps the junction recognizable in any
traced step for the analysis tracer, exactly the marker discipline of
the fused xent and serve decode programs.

Semantics match the unfused block composition
``s = r + (attn(q,k,v) @ Wo + bo); y = LN(s)`` with the sum rounded to
the stream dtype before the f32 statistics (the add+LN kernel's
contract). Dispatch: each sub-kernel compiles on TPU and falls back to
its reference math on other backends unless ``interpret=True`` forces
the Pallas interpreter (tests).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpudml.ops.attention_kernel import flash_attention
from tpudml.ops.layernorm_kernel import fused_add_layernorm


def _attn_junction(q, k, v, r, wo, bo, scale, bias, causal, eps, interpret):
    b, t, h, dh = q.shape
    a = flash_attention(q, k, v, causal=causal, interpret=interpret)
    proj = jax.lax.dot_general(
        a.reshape(b, t, h * dh), wo, (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(r.dtype) + bo.astype(r.dtype)
    return fused_add_layernorm(
        r, proj, scale, bias, eps=eps, interpret=interpret
    )


ATTN_JUNCTION_MARKER = _attn_junction.__name__

_attn_junction_jit = jax.jit(_attn_junction, static_argnums=(8, 9, 10))


def fused_attn_junction(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    r: jax.Array,
    wo: jax.Array,
    bo: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    *,
    causal: bool = True,
    eps: float = 1e-5,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """The attention junction as one fused unit (module docstring).

    ``q``/``k``/``v`` [B, T, H, D] (post-QKV-projection heads), ``r``
    [B, T, d] the incoming residual stream (d = H·D), ``wo`` [d, d] /
    ``bo`` [d] the attention out-projection, ``scale``/``bias`` [d] the
    junction norm's affine. Returns ``(s, y)``: the new residual stream
    ``s = r + proj`` and ``y = LayerNorm(s)`` — the same contract as
    ``fused_add_layernorm``, so the deferred-trunk composition pattern
    applies unchanged. Fully differentiable: the backward chains the
    add+LN and flash kernel vjps through the projection transpose."""
    b, t, h, dh = q.shape
    d = h * dh
    if r.shape != (b, t, d):
        raise ValueError(f"r {r.shape} must be {(b, t, d)}")
    if wo.shape != (d, d):
        raise ValueError(f"wo {wo.shape} must be {(d, d)}")
    return _attn_junction_jit(
        q, k, v, r, wo, bo, scale, bias, causal, eps, interpret
    )


def reference_attn_junction(q, k, v, r, wo, bo, scale, bias, *,
                            causal: bool = True, eps: float = 1e-5):
    """Differentiable unfused reference for the parity tests: the exact
    block-junction math (reference attention, rounded residual sum,
    f32 LN statistics) the fused unit must reproduce grad-exactly."""
    from tpudml.nn.attention import dot_product_attention

    b, t, h, dh = q.shape
    a = dot_product_attention(q, k, v, causal=causal)
    proj = jax.lax.dot_general(
        a.reshape(b, t, h * dh), wo, (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(r.dtype) + bo.astype(r.dtype)
    s = r + proj
    sf = s.astype(jnp.float32)
    m = jnp.mean(sf, axis=-1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(jnp.square(sf), axis=-1, keepdims=True) - jnp.square(m), 0.0
    )
    y = (sf - m) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return s, y.astype(s.dtype)
