"""Fused LayerNorm (Pallas, TPU), forward + backward.

MEASURED OUTCOME (round 3, v5e, [8192, 512] bf16) — read before using:
in ISOLATION XLA's own LN is already near the bandwidth bound (fwd
0.017 ms / fwd+bwd 0.078 ms vs this kernel's ~0.24-0.29 for either —
the two kernel numbers sit within run-to-run jitter of each other), and
swapping this kernel into the flagship LM step made the step SLOWER
(26.1 vs 25.0 ms): the 4.4 ms/step in-situ "LN cost" (BASELINE.md
ablation) is the price of the norm's reductions breaking XLA's
producer/consumer fusion, and an opaque Pallas call is a HARDER fusion
barrier, not a softer one. This kernel therefore stays an unplugged
primitive: the validated, tested base for the actual next lever — an
LN+residual(+matmul-epilogue) fusion kernel that absorbs the neighbors
the XLA norm currently fuses with. Per direction it does ONE pass over
row tiles:

- forward: per [block_n, d] tile compute row mean and rstd in f32, emit
  y = (x − m)·rstd·γ + β plus the (mean, rstd) row statistics as
  residuals — O(N) extra memory, no recompute in the backward.
- backward: the standard LN chain in one kernel —
    g   = dy·γ
    dx  = rstd · (g − mean_row(g) − x̂ · mean_row(g·x̂))
  with dγ = Σ_rows dy·x̂ and dβ = Σ_rows dy accumulated in VMEM scratch
  across row tiles (grid iterates row blocks; the [1, d] partials are
  revisited consecutively and written once at the end).

Exactness: matches the reference LayerNorm (f32 statistics, clamped-var
single-pass moments are irrelevant here — mean/var come from the same
single pass) to float tolerance; pinned by tests against
``tpudml.nn.layers.LayerNorm`` in interpret mode and on the real chip.
Dispatch: compiled kernel on TPU; reference math elsewhere unless
``interpret=True`` (tests).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from tpudml.ops.tiling import round_up as _round_up  # shared tiling helper


def _fwd_body(x_ref, r_ref, g_ref, b_ref, s_ref, y_ref, mean_ref, rstd_ref,
              *, eps: float):
    """Shared forward: optional residual add (r_ref/s_ref None = plain LN),
    then f32 single-pass statistics and the affine normalize."""
    if r_ref is not None:
        sf = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
        s = sf.astype(s_ref.dtype)
        s_ref[:] = s
        # Post-rounding, exactly as the unfused path sees the stream.
        xf = s.astype(jnp.float32)
    else:
        xf = x_ref[:].astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(jnp.square(xf), axis=-1, keepdims=True) - jnp.square(m), 0.0
    )
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (xf - m) * rstd
    y = xhat * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = m
    rstd_ref[:] = rstd


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps: float):
    _fwd_body(x_ref, None, g_ref, b_ref, None, y_ref, mean_ref, rstd_ref,
              eps=eps)


def _bwd_body(x_ref, g_ref, dy_ref, ds_ref, mean_ref, rstd_ref, dx_ref,
              dg_ref, db_ref, dg_acc, db_acc):
    """Shared backward: the LN input-gradient chain with dγ/dβ accumulated
    in VMEM scratch across row tiles; ``ds_ref`` (None = plain LN) is the
    downstream residual cotangent merged into dx in the same pass."""
    ni = pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(ni == 0)
    def _():
        dg_acc[:] = jnp.zeros_like(dg_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    xf = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = (xf - mean_ref[:]) * rstd
    gam = g_ref[:].astype(jnp.float32)

    gy = dy * gam
    mean_gy = jnp.mean(gy, axis=-1, keepdims=True)
    mean_gyx = jnp.mean(gy * xhat, axis=-1, keepdims=True)
    dx = rstd * (gy - mean_gy - xhat * mean_gyx)
    if ds_ref is not None:
        dx = dx + ds_ref[:].astype(jnp.float32)
    dx_ref[:] = dx.astype(dx_ref.dtype)

    dg_acc[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_acc[:] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(ni == nn - 1)
    def _():
        dg_ref[:] = dg_acc[:].astype(dg_ref.dtype)
        db_ref[:] = db_acc[:].astype(db_ref.dtype)


def _bwd_kernel(x_ref, g_ref, dy_ref, mean_ref, rstd_ref, dx_ref, dg_ref,
                db_ref, dg_acc, db_acc):
    _bwd_body(x_ref, g_ref, dy_ref, None, mean_ref, rstd_ref, dx_ref,
              dg_ref, db_ref, dg_acc, db_acc)


from tpudml.ops.tiling import pad_rows as _pad_rows  # shared tiling helper


def _ln_forward(x, g, b, eps, block_n, interpret):
    n, d = x.shape
    block_n = min(block_n, _round_up(n, 8))
    n_pad = _round_up(n, block_n)
    xf = _pad_rows(x, n_pad)
    y, mean, rstd = pl.pallas_call(
        partial(_fwd_kernel, eps=eps),
        out_shape=[
            jax.ShapeDtypeStruct(xf.shape, x.dtype),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        grid=(n_pad // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        interpret=interpret,
    )(xf, g[None, :], b[None, :])
    return y[:n], mean, rstd


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x, g, b, eps, block_n, interpret):
    y, _, _ = _ln_forward(x, g, b, eps, block_n, interpret)
    return y


def _ln_fwd(x, g, b, eps, block_n, interpret):
    y, mean, rstd = _ln_forward(x, g, b, eps, block_n, interpret)
    # b rides along only for its dtype: the bias cotangent must match the
    # PRIMAL bias aval (scale and bias dtypes may differ).
    return y, (x, g, b, mean, rstd)


def _ln_bwd(eps, block_n, interpret, res, dy):
    x, g, b, mean, rstd = res
    n, d = x.shape
    block_n = min(block_n, _round_up(n, 8))
    n_pad = _round_up(n, block_n)
    xf = _pad_rows(x, n_pad)
    dyf = _pad_rows(dy, n_pad)
    # Padded rows: dy rows are zero after padding, mean/rstd already
    # cover n_pad (forward produced them); zero dy -> zero dx/dg/db
    # contributions regardless of the statistics' padded values.
    dx, dg, db = pl.pallas_call(
        _bwd_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(xf.shape, x.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        grid=(1, n_pad // block_n),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda _, i: (i, 0)),
            pl.BlockSpec((1, d), lambda _, i: (0, 0)),
            pl.BlockSpec((block_n, d), lambda _, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda _, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda _, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, d), lambda _, i: (i, 0)),
            pl.BlockSpec((1, d), lambda _, i: (0, 0)),
            pl.BlockSpec((1, d), lambda _, i: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(xf, g[None, :], dyf, mean, rstd)
    return dx[:n], dg[0].astype(g.dtype), db[0].astype(b.dtype)


_ln.defvjp(_ln_fwd, _ln_bwd)


# ------------------------------------------------- fused residual-add + LN
#
# Round-4 lever (VERDICT r3 item 1): the standalone LN kernel above loses
# in-situ because an opaque Pallas call breaks XLA's producer/consumer
# fusion around the norm. This variant absorbs the neighbors instead of
# fighting them: at every residual junction ``s = x + r; y = LN(s)`` the
# forward emits BOTH the new residual stream ``s`` and the normalized
# ``y`` in one pass over the rows, and the backward folds the downstream
# residual cotangent ``ds`` into the LN input-gradient in one pass:
#
#     gy = dy·γ
#     dx = rstd · (gy − mean(gy) − ŝ·mean(gy·ŝ)) + ds      (= dr as well)
#
# so the whole junction — add, f32 casts, norm, and the backward's
# gradient merge — is two kernels per direction instead of XLA's
# reduce-broken fusion chains. Numerics match the reference composition
# ``s = (x + r) in bf16; LayerNorm(s)`` exactly: the sum is rounded to
# the stream dtype BEFORE the f32 statistics, like the unfused model.


def _add_ln_fwd_kernel(x_ref, r_ref, g_ref, b_ref, s_ref, y_ref, mean_ref,
                       rstd_ref, *, eps: float):
    _fwd_body(x_ref, r_ref, g_ref, b_ref, s_ref, y_ref, mean_ref, rstd_ref,
              eps=eps)


def _add_ln_bwd_kernel(s_ref, g_ref, dy_ref, ds_ref, mean_ref, rstd_ref,
                       dx_ref, dg_ref, db_ref, dg_acc, db_acc):
    _bwd_body(s_ref, g_ref, dy_ref, ds_ref, mean_ref, rstd_ref, dx_ref,
              dg_ref, db_ref, dg_acc, db_acc)


def _add_ln_forward(x, r, g, b, eps, block_n, interpret):
    n, d = x.shape
    block_n = min(block_n, _round_up(n, 8))
    n_pad = _round_up(n, block_n)
    xf = _pad_rows(x, n_pad)
    rf = _pad_rows(r, n_pad)
    s, y, mean, rstd = pl.pallas_call(
        partial(_add_ln_fwd_kernel, eps=eps),
        out_shape=[
            jax.ShapeDtypeStruct(xf.shape, x.dtype),
            jax.ShapeDtypeStruct(xf.shape, x.dtype),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        grid=(n_pad // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((block_n, d), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        interpret=interpret,
    )(xf, rf, g[None, :], b[None, :])
    return s[:n], y[:n], mean, rstd


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _add_ln(x, r, g, b, eps, block_n, interpret):
    s, y, _, _ = _add_ln_forward(x, r, g, b, eps, block_n, interpret)
    return s, y


def _add_ln_fwd(x, r, g, b, eps, block_n, interpret):
    s, y, mean, rstd = _add_ln_forward(x, r, g, b, eps, block_n, interpret)
    return (s, y), (s, g, b, mean, rstd)


def _add_ln_bwd(eps, block_n, interpret, res, cts):
    ds, dy = cts
    s, g, b, mean, rstd = res
    n, d = s.shape
    block_n = min(block_n, _round_up(n, 8))
    n_pad = _round_up(n, block_n)
    sf = _pad_rows(s, n_pad)
    dyf = _pad_rows(dy, n_pad)
    dsf = _pad_rows(ds, n_pad)
    # Padded rows: dy and ds rows are zero after padding; mean/rstd cover
    # n_pad from the forward; zero cotangents -> zero dx/dg/db there.
    dx, dg, db = pl.pallas_call(
        _add_ln_bwd_kernel,
        out_shape=[
            jax.ShapeDtypeStruct(sf.shape, s.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        grid=(1, n_pad // block_n),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda _, i: (i, 0)),
            pl.BlockSpec((1, d), lambda _, i: (0, 0)),
            pl.BlockSpec((block_n, d), lambda _, i: (i, 0)),
            pl.BlockSpec((block_n, d), lambda _, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda _, i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda _, i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, d), lambda _, i: (i, 0)),
            pl.BlockSpec((1, d), lambda _, i: (0, 0)),
            pl.BlockSpec((1, d), lambda _, i: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(sf, g[None, :], dyf, dsf, mean, rstd)
    dx = dx[:n]
    # d(x) = d(r) = dx: the junction's sum distributes the cotangent to
    # both addends unchanged; returning the same buffer twice costs no
    # memory.
    return dx, dx, dg[0].astype(g.dtype), db[0].astype(b.dtype)


_add_ln.defvjp(_add_ln_fwd, _add_ln_bwd)


def fused_add_layernorm(
    x: jax.Array,
    r: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    *,
    eps: float = 1e-5,
    block_n: int = 256,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Residual-junction fusion: returns ``(s, y)`` with ``s = x + r``
    (rounded to the stream dtype) and ``y = LayerNorm(s)`` computed in one
    kernel per direction; the backward merges the downstream residual
    cotangent of ``s`` into the LN input gradient (module comment above).
    ``x``/``r`` [..., d]. Dispatches to the reference composition on
    non-TPU backends unless ``interpret=True``."""
    d = x.shape[-1]
    if x.shape != r.shape:
        raise ValueError(f"x {x.shape} != r {r.shape}")
    if scale.shape != (d,) or bias.shape != (d,):
        raise ValueError(
            f"scale/bias {scale.shape}/{bias.shape} must be ({d},)"
        )
    if interpret is None:
        if jax.default_backend() != "tpu":
            s = x + r
            sf = s.astype(jnp.float32)
            m = jnp.mean(sf, axis=-1, keepdims=True)
            var = jnp.maximum(
                jnp.mean(jnp.square(sf), axis=-1, keepdims=True)
                - jnp.square(m),
                0.0,
            )
            y = (sf - m) * jax.lax.rsqrt(var + eps)
            y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
            return s, y.astype(s.dtype)
        interpret = False
    xn = x.reshape(-1, d)
    rn = r.reshape(-1, d)
    s, y = _add_ln(xn, rn, scale, bias, eps, block_n, interpret)
    return s.reshape(x.shape), y.reshape(x.shape)


def fused_layernorm(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    *,
    eps: float = 1e-5,
    block_n: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """LayerNorm over the trailing axis with fused one-pass forward and
    backward kernels (see module docstring). ``x`` [..., d] flattens to
    rows; f32 statistics regardless of dtype; same math as
    ``tpudml.nn.layers.LayerNorm``. Dispatches to the reference formula
    on non-TPU backends unless ``interpret=True``."""
    d = x.shape[-1]
    if scale.shape != (d,) or bias.shape != (d,):
        raise ValueError(
            f"scale/bias {scale.shape}/{bias.shape} must be ({d},)"
        )
    if interpret is None:
        if jax.default_backend() != "tpu":
            xf = x.astype(jnp.float32)
            m = jnp.mean(xf, axis=-1, keepdims=True)
            var = jnp.maximum(
                jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
                - jnp.square(m),
                0.0,
            )
            y = (xf - m) * jax.lax.rsqrt(var + eps)
            y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
            return y.astype(x.dtype)
        interpret = False
    xn = x.reshape(-1, d)
    y = _ln(xn, scale, bias, eps, block_n, interpret)
    return y.reshape(x.shape)
