"""Grouped-dW Pallas kernel for the dropless ragged MoE backward.

``lax.ragged_dot``'s transpose rule materializes BOTH operands as
``[E, P, .]`` range-masked broadcasts and contracts them with a batched
``dot_general`` — an E-scaled masked matmul (E x the dense dW FLOPs plus
an E-fold activation blow-up).  That one equation is the whole reason
``dispatch="ragged"`` trailed gather by 10-16% end-to-end (BASELINE.md
round-5: 1.105 ms fwd+bwd at E=8 vs 0.327 ms dense on the [16k,512] x
[512,2048] probe — 3.4x).

The fix exploits what the ragged layout already guarantees: rows are
argsorted by expert, so expert ``e`` owns the contiguous row slab
``[offsets[e], offsets[e+1])``.  ``grouped_dw`` walks row tiles exactly
once, accumulates ``x_slab^T @ g_slab`` in an f32 VMEM scratch, and
flushes to ``dW[e]`` at each group boundary — cost proportional to total
tokens, independent of E.  The schedule is the MegaBlocks tgmm schedule
(grid = row-tile *visits*; a tile shared by two experts is visited once
per expert with complementary row masks) adapted to a fully static grid:
rows are padded by one extra tile so the ``visits = tiles + E`` bound is
exact and metadata padding lands on an unowned zero tile.

``ragged_ffn`` wraps the two-matmul expert FFN in a ``custom_vjp`` whose
backward uses ``grouped_dw`` for both weight gradients (dx/dh reuse
``lax.ragged_dot`` forward-form, which was never the problem).  On
non-TPU backends the public entry points dispatch to differentiable
reference math (segment one-hot einsum — no masked broadcasts, so the
J109 analyzer rule stays silent on the fixed path); ``interpret=True``
forces the Pallas interpreter for kernel parity tests on CPU.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudml.ops.tiling import round_up

# Default (rows, lhs-cols, rhs-cols) tile. 512 rows amortizes the
# boundary re-visits; tk x tn = 512 x 1024 keeps the f32 accumulator at
# 2 MiB of VMEM while covering d=512 / ffn=2048 in 1 x 2 output tiles.
_DEFAULT_TILING = (512, 512, 1024)


def _grouped_tiling(m: int, k: int, n: int,
                    tiling: Sequence[int] | None) -> tuple[int, int, int]:
    """Clamp the requested tile to the (padded) problem, keeping TPU
    alignment: rows/sublanes a multiple of 8, lanes a multiple of 128."""
    tm, tk, tn = tiling if tiling is not None else _DEFAULT_TILING
    tm = min(round_up(tm, 8), round_up(m, 8))
    tk = min(round_up(tk, 128), round_up(k, 128))
    tn = min(round_up(tn, 128), round_up(n, 128))
    return tm, tk, tn


def _group_metadata(group_sizes, m_pad: int, tm: int, num_groups: int):
    """Static-shape visit schedule for the grouped row walk.

    Returns ``(group_offsets [E+1], group_ids [V], tile_ids [V])`` with
    ``V = m_pad//tm + E`` visits: every row tile once, plus one extra
    visit per group boundary that splits a tile (and one per empty group,
    so its output still gets zeroed).  ``m_pad`` must leave at least one
    fully unowned tail tile (rows >= sum(group_sizes)); schedule padding
    beyond the real visit count resolves to (last group, tail tile)
    pairs whose row masks are empty, so they contribute nothing.
    """
    tiles_m = m_pad // tm
    num_visits = tiles_m + num_groups

    ends = jnp.cumsum(group_sizes)
    group_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), ends.astype(jnp.int32)])
    starts = group_offsets[:-1]

    rounded_starts = starts // tm * tm
    rounded_ends = (ends + tm - 1) // tm * tm
    empty = group_sizes == 0
    group_tiles = jnp.where(
        empty, 1, (rounded_ends - rounded_starts) // tm).astype(jnp.int32)
    group_ids = jnp.repeat(
        jnp.arange(num_groups, dtype=jnp.int32), group_tiles,
        total_repeat_length=num_visits)

    # A group whose start is tile-aligned does not add a visit: its first
    # tile is counted by the plain walk. Unaligned starts (and empty
    # groups, which still need their zeroing visit) add one visit on the
    # tile they share.
    aligned = (starts % tm == 0) & ~empty
    partial_tile_ids = jnp.where(aligned, tiles_m, starts // tm)
    tile_visits = (
        jnp.histogram(partial_tile_ids, bins=tiles_m,
                      range=(0, tiles_m - 1))[0].astype(jnp.int32) + 1)
    tile_ids = jnp.repeat(
        jnp.arange(tiles_m, dtype=jnp.int32), tile_visits,
        total_repeat_length=num_visits)
    return group_offsets, group_ids, tile_ids


def _grouped_dw_kernel(meta, x_ref, g_ref, out_ref, acc_ref, *, tm: int):
    group_offsets, group_ids, tile_ids = meta
    visit = pl.program_id(2)
    num_visits = pl.num_programs(2)
    group = group_ids[visit]
    prev_group = group_ids[jnp.maximum(visit - 1, 0)]
    next_group = group_ids[jnp.minimum(visit + 1, num_visits - 1)]

    @pl.when((visit == 0) | (group != prev_group))
    def _zero_acc():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    row0 = tile_ids[visit] * tm
    rows = row0 + lax.broadcasted_iota(jnp.int32, (tm, 1), 0)
    mask = (group_offsets[group] <= rows) & (rows < group_offsets[group + 1])

    @pl.when(group_offsets[group] < group_offsets[group + 1])
    def _accumulate():
        x_tile = lax.select(
            jnp.broadcast_to(mask, x_ref.shape), x_ref[...],
            jnp.zeros_like(x_ref))
        acc_ref[...] += lax.dot(
            x_tile.swapaxes(0, 1), g_ref[...],
            preferred_element_type=jnp.float32)

    @pl.when((visit == num_visits - 1) | (group != next_group))
    def _store():
        out_ref[...] = acc_ref[...]


def _grouped_dw_pallas(x, g, group_sizes, tiling, interpret: bool):
    m, k = x.shape
    _, n = g.shape
    num_groups = group_sizes.shape[0]
    tm, tk, tn = _grouped_tiling(m, k, n, tiling)
    # One extra row tile guarantees an unowned zero tail tile for the
    # metadata padding to land on.
    m_pad = round_up(m, tm) + tm
    k_pad, n_pad = round_up(k, tk), round_up(n, tn)

    x_p = jnp.pad(x, ((0, m_pad - m), (0, k_pad - k)))
    g_p = jnp.pad(g, ((0, m_pad - m), (0, n_pad - n)))
    meta = _group_metadata(group_sizes, m_pad, tm, num_groups)
    num_visits = m_pad // tm + num_groups

    def x_index(k_i, n_i, visit, meta):
        _, _, tile_ids = meta
        return tile_ids[visit], k_i

    def g_index(k_i, n_i, visit, meta):
        _, _, tile_ids = meta
        return tile_ids[visit], n_i

    def out_index(k_i, n_i, visit, meta):
        _, group_ids, _ = meta
        return group_ids[visit], k_i, n_i

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k_pad // tk, n_pad // tn, num_visits),
        in_specs=[
            pl.BlockSpec((tm, tk), x_index),
            pl.BlockSpec((tm, tn), g_index),
        ],
        out_specs=pl.BlockSpec((None, tk, tn), out_index),
        scratch_shapes=[pltpu.VMEM((tk, tn), jnp.float32)],
    )
    dw = pl.pallas_call(
        functools.partial(_grouped_dw_kernel, tm=tm),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_groups, k_pad, n_pad),
                                       jnp.float32),
        interpret=interpret,
    )(meta, x_p, g_p)
    return dw[:, :k, :n]


def _reference_grouped_dw(x, g, group_sizes):
    """Differentiable XLA reference: segment one-hot einsum over the
    sorted rows. No range-masked ``[E, P, .]`` broadcast is ever built
    (each factor stays rank 2), so this path is J109-silent."""
    num_groups = group_sizes.shape[0]
    ends = jnp.cumsum(group_sizes)
    starts = ends - group_sizes
    rows = jnp.arange(x.shape[0], dtype=group_sizes.dtype)[:, None]
    seg = ((starts[None, :] <= rows) & (rows < ends[None, :]))
    seg = seg.astype(jnp.float32)  # [P, E]
    return jnp.einsum(
        "pe,pk,pn->ekn", seg, x.astype(jnp.float32), g.astype(jnp.float32),
        optimize=True)


def grouped_dw(x, g, group_sizes, *, tiling: Sequence[int] | None = None,
               interpret: bool | None = None):
    """Per-group ``x^T @ g`` over contiguous row slabs.

    ``x [m, k]`` and ``g [m, n]`` hold rows sorted by group;
    ``group_sizes [E]`` (int) gives each group's slab length (cumsum =
    slab boundaries; rows beyond ``sum(group_sizes)`` are ignored).
    Returns ``dW [E, k, n]`` in f32 — one row walk, f32 accumulation,
    cost proportional to ``m`` rather than ``E * m``.

    ``interpret=None`` auto-dispatches: reference math off-TPU, the
    Pallas kernel on TPU. ``interpret=True`` forces the Pallas
    interpreter (CPU parity tests); ``interpret=False`` forces the
    compiled kernel.
    """
    if x.ndim != 2 or g.ndim != 2 or x.shape[0] != g.shape[0]:
        raise ValueError(
            f"grouped_dw wants row-aligned 2-D operands, got {x.shape} "
            f"and {g.shape}")
    if group_sizes.ndim != 1 or not np.issubdtype(group_sizes.dtype,
                                                  np.integer):
        raise ValueError(
            f"group_sizes must be a 1-D integer array, got "
            f"{group_sizes.shape} {group_sizes.dtype}")
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _reference_grouped_dw(x, g, group_sizes)
        interpret = False
    return _grouped_dw_pallas(x, g, group_sizes.astype(jnp.int32), tiling,
                              interpret)


# ---------------------------------------------------------------------------
# ragged_ffn: the two-matmul expert FFN with the grouped-dW backward.
# ---------------------------------------------------------------------------


def _ffn_forward(x, w1, b1, w2, b2, onehot, group_sizes):
    hidden = jax.nn.relu(
        lax.ragged_dot(x, w1, group_sizes) + onehot @ b1)
    out = lax.ragged_dot(hidden, w2, group_sizes) + onehot @ b2
    return out, hidden


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def ragged_ffn(x, w1, b1, w2, b2, onehot, group_sizes,
               tiling: Sequence[int] | None = None,
               interpret: bool | None = None):
    """Expert FFN ``relu(x @ w1[e] + b1[e]) @ w2[e] + b2[e]`` over rows
    sorted by expert, with a hand-written backward: dx/dh via
    ``lax.ragged_dot`` on the swapped weights (forward-form — cheap),
    dW1/dW2 via :func:`grouped_dw` (f32 accumulation), db via
    ``onehot^T @ cotangent``.  ``onehot [P, E]`` is the sorted-row
    expert one-hot (already needed for the biases); its cotangent is
    returned as zeros — it is integer-derived, the stock VJP dies at
    ``one_hot`` anyway.
    """
    out, _ = _ffn_forward(x, w1, b1, w2, b2, onehot, group_sizes)
    return out


def _ragged_ffn_fwd(x, w1, b1, w2, b2, onehot, group_sizes, tiling,
                    interpret):
    out, hidden = _ffn_forward(x, w1, b1, w2, b2, onehot, group_sizes)
    return out, (x, w1, w2, onehot, group_sizes, hidden)


def _ragged_ffn_bwd(tiling, interpret, res, dout):
    x, w1, w2, onehot, group_sizes, hidden = res
    ct = dout.dtype
    dw2 = grouped_dw(hidden, dout, group_sizes, tiling=tiling,
                     interpret=interpret).astype(w2.dtype)
    db2 = lax.dot(onehot.swapaxes(0, 1), dout,
                  preferred_element_type=jnp.float32).astype(ct)
    dh = lax.ragged_dot(dout, w2.swapaxes(1, 2), group_sizes)
    dpre = dh * (hidden > 0).astype(ct)
    dw1 = grouped_dw(x, dpre, group_sizes, tiling=tiling,
                     interpret=interpret).astype(w1.dtype)
    db1 = lax.dot(onehot.swapaxes(0, 1), dpre,
                  preferred_element_type=jnp.float32).astype(ct)
    dx = lax.ragged_dot(dpre, w1.swapaxes(1, 2), group_sizes)
    d_onehot = jnp.zeros_like(onehot)
    d_gs = np.zeros(group_sizes.shape, dtype=jax.dtypes.float0)
    return (dx.astype(x.dtype), dw1, db1.astype(w1.dtype), dw2,
            db2.astype(w2.dtype), d_onehot, d_gs)


ragged_ffn.defvjp(_ragged_ffn_fwd, _ragged_ffn_bwd)
