"""Training engine: jitted step functions + host-side epoch loops.

The reference's per-batch eager hot loop (forward → loss → zero_grad →
backward → step, codes/task1/pytorch/model.py:44-61) becomes ONE jitted XLA
program per step — the MindSpore notebook's sink-mode graph training
(model.ipynb cell 6) is the closest reference analogue of this execution
model (SURVEY.md §3.5). Distributed variants in ``tpudml.parallel`` reuse
the same loss/step structure under shard_map / pjit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Any, Callable

import jax
import jax.numpy as jnp

from tpudml.metrics import MetricsWriter
from tpudml.nn.layers import Module
from tpudml.nn.losses import accuracy, softmax_cross_entropy
from tpudml.optim import Optimizer


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    """Everything that evolves during training, as one pytree."""

    params: Any
    model_state: Any  # e.g. batch-norm running stats
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, model: Module, optimizer: Optimizer, key: jax.Array) -> "TrainState":
        params, model_state = model.init(key)
        return cls(
            params=params,
            model_state=model_state,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )


def collect_aux_losses(state: Any) -> jax.Array:
    """Sum of every ``aux_loss`` leaf in a model-state tree (e.g. the
    Switch load-balancing terms MoE layers record, one per layer)."""
    total = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        last = path[-1] if path else None
        if getattr(last, "key", None) == "aux_loss":
            total = total + leaf
    return total


DEFAULT_MOE_AUX_WEIGHT = 1e-2  # the canonical Switch load-balancing α


def model_has_moe(model: Any) -> bool:
    """Detect MoE layers anywhere in a Module tree (shared walker), so
    engines can default the Switch aux-loss pressure on — a dense-MoE run
    without it lets the top-1 router collapse onto one expert."""
    from tpudml.nn.layers import iter_module_tree
    from tpudml.nn.moe import MoELayer

    return any(
        isinstance(obj, MoELayer) or getattr(obj, "moe_experts", 0)
        for obj in iter_module_tree(model)
    )


def resolve_aux_loss_weight(model: Any, aux_loss_weight: float | None) -> float:
    """None → the canonical α for MoE-bearing models, 0 otherwise."""
    if aux_loss_weight is not None:
        return aux_loss_weight
    return DEFAULT_MOE_AUX_WEIGHT if model_has_moe(model) else 0.0


def make_loss_fn(
    model: Module,
    loss: Callable = softmax_cross_entropy,
    aux_loss_weight: float = 0.0,
) -> Callable:
    """(params, model_state, images, labels[, rng]) -> (loss, (new_model_state,
    logits)). ``aux_loss_weight`` adds α·Σ(aux_loss leaves of the new model
    state) to the objective — the Switch router load-balancing pressure
    (``tpudml.nn.moe``); gradients flow to the router through the recorded
    aux terms."""

    def loss_fn(params, model_state, images, labels, rng=None):
        logits, new_state = model.apply(
            params, model_state, images, train=True, rng=rng
        )
        total = loss(logits, labels)
        if aux_loss_weight:
            total = total + aux_loss_weight * collect_aux_losses(new_state)
        return total, (new_state, logits)

    return loss_fn


def _grads_nonfinite(grads) -> jax.Array:
    """Scalar bool: any non-finite element in any grad leaf."""
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.any(
        jnp.stack([jnp.any(~jnp.isfinite(g)) for g in leaves])
    )


def accumulate_grads(
    loss_fn: Callable,
    params: Any,
    model_state: Any,
    images: jax.Array,
    labels: jax.Array,
    rng: jax.Array | None,
    accum_steps: int,
    taint: bool = False,
):
    """Gradients of ``loss_fn`` over the batch, computed in ``accum_steps``
    sequential micro-batches inside one XLA program (``lax.scan``) —
    activation memory scales with the micro-batch while the optimizer sees
    the full-batch gradient. Returns (grads, model_state, metrics); grads
    and metrics are micro-batch means, model_state threads through the
    chunks (e.g. BN running stats see every micro-batch).

    ``taint=True`` adds ``metrics["bad_micro"]``: the index of the FIRST
    micro-batch whose gradients contain a non-finite value (-1 if none).
    A single poisoned micro-batch makes the accumulated sum non-finite —
    the sentinel then skips the whole step — and the taint pinpoints the
    culprit for the escalation diagnostic instead of letting it average
    in silently.

    ``accum_steps=1`` short-circuits to a single grad call.
    """
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    if accum_steps == 1:
        (loss, (model_state, logits)), grads = grad_fn(
            params, model_state, images, labels, rng
        )
        metrics = {"loss": loss, "accuracy": accuracy(logits, labels)}
        if taint:
            metrics["bad_micro"] = jnp.where(
                _grads_nonfinite(grads), 0, -1
            ).astype(jnp.int32)
        return grads, model_state, metrics

    batch = images.shape[0]
    if batch % accum_steps:
        raise ValueError(
            f"(per-replica) batch {batch} not divisible by accum_steps "
            f"{accum_steps}"
        )
    micro = batch // accum_steps
    mb_images = images.reshape(accum_steps, micro, *images.shape[1:])
    mb_labels = labels.reshape(accum_steps, micro, *labels.shape[1:])

    zero_grads = jax.tree.map(jnp.zeros_like, params)

    def body(carry, mb):
        grads_acc, state, loss_acc, acc_acc, bad_acc = carry
        imgs, lbls, i = mb
        mb_rng = None if rng is None else jax.random.fold_in(rng, i)
        (loss, (state, logits)), grads = grad_fn(params, state, imgs, lbls, mb_rng)
        grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
        if taint:
            bad_acc = jnp.where(
                (bad_acc < 0) & _grads_nonfinite(grads),
                i.astype(jnp.int32),
                bad_acc,
            )
        return (
            grads_acc,
            state,
            loss_acc + loss,
            acc_acc + accuracy(logits, lbls),
            bad_acc,
        ), None

    (grads_sum, model_state, loss_sum, acc_sum, bad_micro), _ = jax.lax.scan(
        body,
        (zero_grads, model_state, jnp.zeros(()), jnp.zeros(()),
         jnp.full((), -1, jnp.int32)),
        (mb_images, mb_labels, jnp.arange(accum_steps)),
    )
    inv = 1.0 / accum_steps
    grads = jax.tree.map(lambda g: g * inv, grads_sum)
    metrics = {"loss": loss_sum * inv, "accuracy": acc_sum * inv}
    if taint:
        metrics["bad_micro"] = bad_micro
    return grads, model_state, metrics


def accumulate_fused_grads(
    loss_fn: Callable,
    params: Any,
    model_state: Any,
    tokens: jax.Array,
    labels: jax.Array,
    rng: jax.Array | None,
    accum_steps: int,
    taint: bool = False,
):
    """:func:`accumulate_grads` for FUSED loss fns — those returning
    ``(loss, new_model_state)`` with no logits aux (the linear-cross-
    entropy head never materializes them), so metrics carry loss only.
    Same micro-batch scan, same per-chunk rng fold, same mean semantics
    (and the same ``taint`` micro-batch tracking): the full-batch
    gradient at micro-batch activation memory."""
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    if accum_steps == 1:
        (loss, model_state), grads = grad_fn(params, model_state, tokens, labels, rng)
        metrics = {"loss": loss}
        if taint:
            metrics["bad_micro"] = jnp.where(
                _grads_nonfinite(grads), 0, -1
            ).astype(jnp.int32)
        return grads, model_state, metrics

    batch = tokens.shape[0]
    if batch % accum_steps:
        raise ValueError(
            f"(per-replica) batch {batch} not divisible by accum_steps "
            f"{accum_steps}"
        )
    micro = batch // accum_steps
    mb_tokens = tokens.reshape(accum_steps, micro, *tokens.shape[1:])
    mb_labels = labels.reshape(accum_steps, micro, *labels.shape[1:])

    zero_grads = jax.tree.map(jnp.zeros_like, params)

    def body(carry, mb):
        grads_acc, state, loss_acc, bad_acc = carry
        toks, lbls, i = mb
        mb_rng = None if rng is None else jax.random.fold_in(rng, i)
        (loss, state), grads = grad_fn(params, state, toks, lbls, mb_rng)
        grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
        if taint:
            bad_acc = jnp.where(
                (bad_acc < 0) & _grads_nonfinite(grads),
                i.astype(jnp.int32),
                bad_acc,
            )
        return (grads_acc, state, loss_acc + loss, bad_acc), None

    (grads_sum, model_state, loss_sum, bad_micro), _ = jax.lax.scan(
        body,
        (zero_grads, model_state, jnp.zeros(()), jnp.full((), -1, jnp.int32)),
        (mb_tokens, mb_labels, jnp.arange(accum_steps)),
    )
    inv = 1.0 / accum_steps
    grads = jax.tree.map(lambda g: g * inv, grads_sum)
    metrics = {"loss": loss_sum * inv}
    if taint:
        metrics["bad_micro"] = bad_micro
    return grads, model_state, metrics


def make_train_step_body(
    model: Module,
    optimizer: Optimizer,
    rng_root: jax.Array | None = None,
    accum_steps: int = 1,
    loss: Callable = softmax_cross_entropy,
    aux_loss_weight: float | None = None,
) -> Callable:
    """Un-jitted (ts, images, labels) -> (new_ts, metrics) step body —
    the traceable core of :func:`make_train_step`, composable under
    ``lax.fori_loop``/``lax.scan`` (bench.py times K of these inside one
    dispatch)."""
    loss_fn = make_loss_fn(model, loss, resolve_aux_loss_weight(model, aux_loss_weight))

    def step(ts: TrainState, images, labels):
        rng = None if rng_root is None else jax.random.fold_in(rng_root, ts.step)
        grads, model_state, metrics = accumulate_grads(
            loss_fn, ts.params, ts.model_state, images, labels, rng, accum_steps
        )
        new_params, new_opt = optimizer.update(grads, ts.opt_state, ts.params)
        new_ts = TrainState(
            params=new_params,
            model_state=model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return new_ts, metrics

    return step


def make_lm_fused_loss_fn(
    model: Module,
    save_scores: bool | None = None,
    aux_loss_weight: float | None = None,
) -> Callable:
    """(params, model_state, tokens, labels[, rng]) -> (loss, new_state)
    through the fused linear-cross-entropy head: ``apply_features`` +
    ``linear_cross_entropy`` — the [B·T, V] logits never exist. The model
    must expose ``apply_features`` and a ``head`` Dense param subtree.
    The kernel is token-parallel, so this loss fn composes under
    ``shard_map`` on a batch/sequence-sharded trunk unchanged (the DP/CP
    engines' ``fused_xent`` mode): each shard's token-mean loss pmean-s
    to the global token mean for equal-size shards, exactly like the
    standard loss path."""
    from tpudml.ops.xent_kernel import linear_cross_entropy

    aux_w = resolve_aux_loss_weight(model, aux_loss_weight)

    def loss_fn(params, model_state, tokens, labels, rng=None):
        feats, new_state = model.apply_features(
            params, model_state, tokens, train=True, rng=rng
        )
        head = model._cast_params(params)["head"]
        loss = linear_cross_entropy(
            feats, head["kernel"], labels, head.get("bias"),
            save_s=save_scores,
        )
        if aux_w:
            loss = loss + aux_w * collect_aux_losses(new_state)
        return loss, new_state

    return loss_fn


def make_lm_fused_sharded_loss_fn(
    model: Module,
    mesh: Any,
    kernel_spec: Any,
    batch_axis: str | None = None,
    save_scores: bool | None = None,
    aux_loss_weight: float | None = None,
) -> Callable:
    """(params, model_state, tokens, labels[, rng]) -> (loss, new_state)
    through the fused head when the head itself is SHARDED — the GSPMD
    engines' (TP / FSDP / FSDP×TP) ``fused_xent`` path.

    The trunk stays GSPMD-auto-partitioned; only the head runs inside an
    explicit ``shard_map`` region (the Pallas kernel is opaque to the
    SPMD partitioner, and the cross-shard lse merge is manual math).
    ``kernel_spec`` is the head kernel's [d, V] PartitionSpec from the
    engine's placement; the region derives everything from it:

    - dim 1 names the VOCAB axis → per-shard partial statistics merged
      by ``sharded_linear_cross_entropy`` (one pmax + two psums); a
      demoted (replicated) dim 1 falls back to the plain kernel call.
    - dim 0 sharded (FSDP×TP puts ``data`` there) → W is all-gathered
      on use, and the gather's transpose delivers dW as the ZeRO
      reduce-scatter — exactly FSDP's gradient layout, derived not coded.
    - vocab axis == batch axis (1-D FSDP: ``data`` does double duty) →
      tokens+labels are all-gathered over the batch axis first, so every
      shard scores ALL tokens against its vocab slice; the gather's
      transpose (psum_scatter) routes the partial dX back to token
      shards with the single reduce the math needs.
    """
    from jax.sharding import PartitionSpec as P

    from tpudml.ops.xent_kernel import (
        linear_cross_entropy,
        sharded_linear_cross_entropy,
    )
    from tpudml.parallel.sharding import shard_map_fn

    aux_w = resolve_aux_loss_weight(model, aux_loss_weight)

    def _axes(entry):
        if entry is None:
            return ()
        return tuple(entry) if isinstance(entry, tuple) else (entry,)

    kspec = tuple(kernel_spec)
    kspec = kspec + (None,) * (2 - len(kspec))  # P drops trailing Nones
    d0_axes, v_axes = _axes(kspec[0]), _axes(kspec[1])
    if len(v_axes) > 1:
        raise ValueError(
            f"head kernel vocab dim sharded over {v_axes}: the partial-"
            "stat merge runs over ONE mesh axis"
        )
    vocab_axis = v_axes[0] if v_axes else None
    # 1-D FSDP shards tokens AND vocab over the same axis; merging
    # partial stats across shards holding DIFFERENT tokens would be
    # wrong, so the batch gathers first (see docstring).
    gather_batch = batch_axis is not None and batch_axis == vocab_axis
    batch_spec = P(batch_axis) if batch_axis else P()

    def head_loss(feats, kernel, bias, labels):
        xn = feats.reshape(-1, feats.shape[-1])
        ln = labels.reshape(-1)
        if gather_batch:
            xn = jax.lax.all_gather(xn, batch_axis, axis=0, tiled=True)
            ln = jax.lax.all_gather(ln, batch_axis, axis=0, tiled=True)
        k = kernel
        for ax in d0_axes:
            k = jax.lax.all_gather(k, ax, axis=0, tiled=True)
        if vocab_axis is not None:
            loss = sharded_linear_cross_entropy(
                xn, k, ln, bias, axis_name=vocab_axis, save_s=save_scores
            )
        else:
            loss = linear_cross_entropy(xn, k, ln, bias, save_s=save_scores)
        if batch_axis and not gather_batch:
            # Per-shard token-mean → global token mean (equal shards).
            loss = jax.lax.pmean(loss, batch_axis)
        return loss

    sharded_head = shard_map_fn(
        head_loss,
        mesh,
        in_specs=(batch_spec, P(*kspec), P(kspec[1]), batch_spec),
        out_specs=P(),
    )

    def loss_fn(params, model_state, tokens, labels, rng=None):
        feats, new_state = model.apply_features(
            params, model_state, tokens, train=True, rng=rng
        )
        head = model._cast_params(params)["head"]
        bias = head.get("bias")
        if bias is None:
            bias = jnp.zeros((head["kernel"].shape[-1],), head["kernel"].dtype)
        loss = sharded_head(feats, head["kernel"], bias, labels)
        if aux_w:
            loss = loss + aux_w * collect_aux_losses(new_state)
        return loss, new_state

    return loss_fn


def make_lm_fused_train_step_body(
    model: Module,
    optimizer: Optimizer,
    rng_root: jax.Array | None = None,
    save_scores: bool | None = None,
) -> Callable:
    """Un-jitted (ts, tokens, labels) -> (new_ts, metrics) body of
    :func:`make_lm_fused_train_step` — composable under ``lax.fori_loop``
    (bench.py times K of these inside one dispatch, like
    :func:`make_train_step_body` for the standard step)."""
    loss_fn = make_lm_fused_loss_fn(model, save_scores)

    def step(ts: TrainState, tokens, labels):
        rng = None if rng_root is None else jax.random.fold_in(rng_root, ts.step)
        (loss, model_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            ts.params, ts.model_state, tokens, labels, rng
        )
        new_params, new_opt = optimizer.update(grads, ts.opt_state, ts.params)
        new_ts = TrainState(
            params=new_params,
            model_state=model_state,
            opt_state=new_opt,
            step=ts.step + 1,
        )
        return new_ts, {"loss": loss}

    return step


def make_lm_fused_train_step(
    model: Module,
    optimizer: Optimizer,
    rng_root: jax.Array | None = None,
    save_scores: bool | None = None,
) -> Callable:
    """Jitted LM train step through the fused linear-cross-entropy kernel
    (``tpudml.ops.xent_kernel``): the [B·T, V] logits are never
    materialized — residual memory for the head drops from O(B·T·V) to
    O(B·T), the enabling trade for very long sequences / large vocabs.
    ``save_scores=True`` trades that memory contract back for speed (the
    kernel keeps an O(B·T·V) f32 score residual and skips both backward
    recompute matmuls — measured 21.6 → 18.0 ms/step at the flagship
    config) — an explicit opt-in for memory-comfortable configs; the
    default keeps the O(B·T) promise.
    The model must expose ``apply_features`` (TransformerLM) and a
    ``head`` Dense param subtree. Metrics carry loss only (no logits ⇒
    no accuracy; use the standard step when accuracy matters). MoE
    models get the Switch aux-loss pressure exactly like the standard
    step (None → α=0.01 when MoE layers are present)."""
    body = make_lm_fused_train_step_body(model, optimizer, rng_root, save_scores)
    return jax.jit(body, donate_argnums=(0,))


def make_train_step(
    model: Module,
    optimizer: Optimizer,
    rng_root: jax.Array | None = None,
    accum_steps: int = 1,
    loss: Callable = softmax_cross_entropy,
    aux_loss_weight: float | None = None,
) -> Callable:
    """Jitted single-device train step: grad + optimizer update fused into
    one XLA program. ``rng_root`` (optional) seeds per-step dropout keys,
    folded with the step counter inside the program; ``accum_steps``
    splits the batch into sequential micro-batches (gradient
    accumulation) to trade step latency for activation memory.
    ``aux_loss_weight`` defaults on (α=0.01) for MoE-bearing models.

    Donated TrainState: in-place parameter/optimizer buffers (halves
    their HBM traffic). The input state is CONSUMED on every backend —
    callers must rebind ts on each step."""
    body = make_train_step_body(
        model, optimizer, rng_root, accum_steps, loss, aux_loss_weight
    )
    return jax.jit(body, donate_argnums=(0,))


@lru_cache(maxsize=64)
def make_eval_step(model: Module) -> Callable:
    """Cached per-model (Modules are frozen dataclasses, hence hashable), so
    repeated ``evaluate`` calls reuse one compiled program instead of
    re-jitting every epoch."""

    @jax.jit
    def step(params, model_state, images, labels):
        logits, _ = model.apply(params, model_state, images, train=False)
        correct = jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.int32))
        return correct

    return step


def evaluate_counts(step: Callable, ts: TrainState, loader) -> float:
    """Accuracy from a compiled (params, model_state, x, labels) →
    (correct, count) step — the shared accumulation loop behind the
    sharded engines' ``evaluate`` methods."""
    correct = total = 0
    for x, labels in loader:
        c, n = step(ts.params, ts.model_state, jnp.asarray(x), jnp.asarray(labels))
        correct += int(c)
        total += int(n)
    return correct / max(total, 1)


def evaluate(model: Module, ts: TrainState, loader) -> float:
    """Top-1 test accuracy, reference ``test()`` parity (codes/task1/
    pytorch/model.py:67-81)."""
    step = make_eval_step(model)
    correct, total = 0, 0
    for images, labels in loader:
        correct += int(step(ts.params, ts.model_state, images, labels))
        total += len(labels)
    return correct / max(total, 1)


def train_loop(
    model: Module,
    optimizer: Optimizer,
    train_loader,
    num_epochs: int,
    key: jax.Array,
    writer: MetricsWriter | None = None,
    log_every: int = 20,
    step_fn: Callable | None = None,
    state: TrainState | None = None,
    hooks: list[Callable] | None = None,
    accum_steps: int = 1,
) -> tuple[TrainState, dict]:
    """Host-side epoch loop with the reference's logging cadence (loss every
    ``log_every`` iters, codes/task1/pytorch/model.py:57-61) and total
    wall-clock accounting (codes/task2/model-mp.py:48,76-78)."""
    ts = state or TrainState.create(model, optimizer, key)
    if step_fn is not None and accum_steps > 1:
        # Engines own their accumulation (e.g. DataParallel(accum_steps=N));
        # silently ignoring the flag here would fake a memory win.
        raise ValueError(
            "accum_steps is handled by the engine that built step_fn; this "
            "engine/entrypoint does not support gradient accumulation"
        )
    # Dropout keys derive from a domain-separated branch of the init key.
    step = step_fn or make_train_step(
        model,
        optimizer,
        rng_root=jax.random.fold_in(key, 0x0D0),
        accum_steps=accum_steps,
    )
    # Resume semantics: ``num_epochs`` is the TOTAL budget. A restored
    # state (step > 0) resumes STEP-GRANULAR: completed epochs are
    # skipped outright, and within the partial epoch the first
    # ``start_step % steps_per_epoch`` batches are fast-forwarded —
    # ``set_epoch`` regenerates the same (seed, epoch) sampler
    # permutation and dropout streams fold ``rng_root`` by ``ts.step``
    # inside the program, so the resumed run replays exactly the batches
    # and rng the uninterrupted run would have seen from that step on
    # (bit-exact params; see docs/RESILIENCE.md). (One host sync here,
    # before the loop — not per step.)
    counter = start_step = int(ts.step)
    steps_per_epoch = len(train_loader) if hasattr(train_loader, "__len__") else 0
    if steps_per_epoch:
        start_epoch = min(start_step // steps_per_epoch, num_epochs)
        skip_batches = start_step - start_epoch * steps_per_epoch
    else:
        start_epoch, skip_batches = 0, 0
    t0 = time.time()
    metrics = None  # device values; materialized to floats only on log/exit
    for epoch in range(start_epoch, num_epochs):
        if hasattr(train_loader, "set_epoch"):
            train_loader.set_epoch(epoch)
        for i, (images, labels) in enumerate(train_loader):
            if epoch == start_epoch and i < skip_batches:
                continue  # fast-forward the sampler to the resume point
            ts, metrics = step(ts, images, labels)
            counter += 1
            if log_every and counter % log_every == 0:
                loss = float(metrics["loss"])
                if writer is not None:
                    writer.add_scalar("Train Loss", loss, counter)
                    stats = metrics.get("step_stats")
                    if stats is not None and hasattr(stats, "to_scalars"):
                        # In-graph telemetry (tpudml.obs): the StepStats
                        # pytree streams as obs/* scalars on the same
                        # cadence as the loss.
                        writer.add_scalars(
                            {
                                f"obs/{k}": float(v)
                                for k, v in stats.to_scalars().items()
                            },
                            counter,
                        )
                print(f"epoch {epoch} iter {counter}: loss {loss:.4f}")
            for h in hooks or ():
                h(epoch=epoch, step=counter, train_state=ts, metrics=metrics)
    jax.block_until_ready(ts.params)
    train_time = time.time() - t0
    print(f"Training time: {train_time:.3f}s")
    if writer is not None:
        writer.add_scalar("Train Time", train_time, counter)
    last_metrics = (
        {
            k: (
                {kk: float(vv) for kk, vv in v.to_scalars().items()}
                if hasattr(v, "to_scalars")  # obs StepStats pytree
                else float(v)
            )
            for k, v in metrics.items()
        }
        if metrics is not None
        else {}
    )
    last_metrics["train_time_s"] = train_time
    last_metrics["steps"] = counter
    return ts, last_metrics
