"""Speculative decoding with exact greedy acceptance-rejection.

A small DRAFT model proposes K tokens autoregressively, then the target
model scores the whole K+1-token window in ONE pass
(``apply_decode_window``): K+1 positions of target logits for one
target-model step instead of K+1. Greedy acceptance keeps the longest
prefix where the draft's argmax equals the target's argmax and emits the
TARGET's token at the first mismatch (or the bonus K+1-th token when all
match) — so the committed token stream is, by construction, EXACTLY what
pure target-greedy would have produced, and the PR 8 parity tests pin
spec mode with the same golden sequences. The throughput lever is
``accepted_len``: every accepted draft token is a target decode step the
engine did not run.

Static shapes throughout: the window is always K+1 tokens for every slot
every step (fixed-K discipline, per the pjit paper's static-shape rule),
and the step returns ``(emitted [B, K+1], n_emit [B], ...)`` — the host
commits the first ``n_emit`` per slot. Rejected draft rows leave stale
K/V at positions >= the commit point in BOTH caches; the next window
starts at the commit point and rewrites every such row before the mask
first exposes it — the same stale-row invariant the dense engine's
eviction path relies on (engine.py module docstring).

The draft is by default a layer-truncated view of the target
(``draft_from_trunk``): block0..n-1 + the shared embedding/head — zero
extra training, decent agreement on repetitive traffic, and exactness
never depends on draft quality.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

# Spec steps are jitted under their own NAME (not SERVE_DECODE_MARKER):
# the verify window's [B, H, K+1, L] softmax would false-fire J110's
# wide-softmax check on a single-token-marked program. J117 keys on this
# marker too (the paged spec step gathers through the table like the
# plain paged step). Mirrored in tpudml/analysis/jaxpr_pass.py.
SPEC_DECODE_MARKER = "_serve_spec_decode_step"


def draft_from_trunk(model, params, num_layers: int):
    """(draft_model, draft_params): the target's first ``num_layers``
    blocks with the shared embedding/ln_f/head. The cheapest possible
    draft — no second set of weights to store or train — and any
    agreement it achieves is pure speedup (exactness is the verify
    step's job, not the draft's)."""
    if not 1 <= num_layers < model.num_layers:
        raise ValueError(
            f"draft num_layers must be in [1, {model.num_layers}), "
            f"got {num_layers}"
        )
    draft = dataclasses.replace(model, num_layers=num_layers)
    keep = {"tok_embed", "ln_f", "head"}
    keep |= {f"block{i}" for i in range(num_layers)}
    if not model.rope:
        keep.add("pos_embed")
    dparams = {k: v for k, v in params.items() if k in keep}
    return draft, dparams


def _verify(window, logits, spec_k):
    """Greedy acceptance over the scored window.

    ``window`` [B, K+1] is [t0, d1..dK]; ``logits`` [B, K+1, V] row j
    predicts position pos+j+1. Returns (emitted [B, K+1], n_emit [B]):
    ``emitted`` is the target's greedy token at every window row — its
    first ``accepted`` entries coincide with the draft's by definition
    of acceptance, entry ``accepted`` is the target's correction at the
    first mismatch (or the bonus token when all K drafts match)."""
    emitted = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
    match = (window[:, 1:] == emitted[:, :spec_k]).astype(jnp.int32)
    accepted = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B]
    return emitted, accepted + 1


def make_spec_decode_step(model, draft_model, spec_k: int, *,
                          paged: bool = False):
    """The one jitted spec-decode program. Dense signature
    ``(params, dparams, caches, dcaches, tokens [B], pos [B])``; paged
    inserts ``table`` [B, max_pages] after ``dcaches`` (the DRAFT cache
    stays dense — it is small by construction, and one paged layout per
    step keeps the program simple). Returns
    ``(emitted [B, K+1], n_emit [B], logits [B, K+1, V], caches,
    dcaches)``. Both caches are donated."""
    if spec_k < 1:
        raise ValueError("spec_k must be >= 1")

    def _draft_window(dparams, dcaches, tokens, pos):
        """K draft decode steps (unrolled — K is small and static):
        [t0, d1..dK] plus the draft cache advanced through every window
        row's K/V. The final call is write-only (its logits would
        propose d_{K+1}, which no verify row scores): on a full accept
        the commit point jumps to pos+K+1, so row pos+K — dK's K/V —
        sits BELOW the next window's first write and would otherwise be
        a permanent hole the draft attends through ever after. On any
        rejection that row is merely stale and the next window rewrites
        it before the mask exposes it."""
        t = tokens
        window = [tokens]
        for j in range(spec_k):
            d_logits, dcaches = draft_model.apply_decode(
                dparams, dcaches, t, pos + j
            )
            t = jnp.argmax(d_logits, axis=-1).astype(jnp.int32)
            window.append(t)
        _, dcaches = draft_model.apply_decode(
            dparams, dcaches, t, pos + spec_k
        )
        return jnp.stack(window, axis=1), dcaches  # [B, K+1]

    if paged:
        def _serve_spec_decode_step(params, dparams, caches, dcaches,
                                    table, tokens, pos):
            window, dcaches = _draft_window(dparams, dcaches, tokens, pos)
            logits, caches = model.apply_decode_paged(
                params, caches, table, window, pos
            )
            emitted, n_emit = _verify(window, logits, spec_k)
            return emitted, n_emit, logits, caches, dcaches
    else:
        def _serve_spec_decode_step(params, dparams, caches, dcaches,
                                    tokens, pos):
            window, dcaches = _draft_window(dparams, dcaches, tokens, pos)
            logits, caches = model.apply_decode_window(
                params, caches, window, pos
            )
            emitted, n_emit = _verify(window, logits, spec_k)
            return emitted, n_emit, logits, caches, dcaches

    inner = jax.jit(_serve_spec_decode_step)

    def step(*args):
        return inner(*args)

    return jax.jit(step, donate_argnums=(2, 3))
