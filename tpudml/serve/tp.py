"""Tensor-parallel serving: the decode/prefill steps under shard_map.

Parameter placement is EXACTLY ``tensor_parallel_rules`` — the serving
graph reuses the training-time TP layout (column-parallel QKV/fc1/head,
row-parallel out/fc2, vocab-sharded embedding), so a TP training
checkpoint serves without resharding. The KV cache shards over its
``kv_heads`` axis with the same placement as the K/V projections that
fill it (``P(None, None, axis, None)``), so cache writes and attention
reads are collective-free; the decode step pays the training stack's two
psums per block (attention-out, fc2) plus one tiled all-gather of the
[B, V/world] logits shards for the greedy argmax.

Unlike GSPMD training (sharding constraints, partitioner inserts the
collectives), serving uses MANUAL shard_map bodies: the decode hot loop
is latency-bound at batch≈slots, and hand-placed collectives keep the
per-step program free of partitioner-inferred resharding. The manual
body reuses ``MultiHeadAttention._project`` with LOCAL head counts —
head-aligned kernel shards make "run the same math on 1/world of the
heads" literally the same code.

Divisibility is REJECTED, not demoted: ``apply_rules`` silently
replicates a non-dividing leaf, which GSPMD tolerates but a manual body
(whose matmul shapes assume local shards) cannot.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudml.parallel.mp import apply_rules, tensor_parallel_rules
from tpudml.parallel.sharding import shard_map_fn
from tpudml.serve.cache import KVCache, read_all, read_slot_prefix, write_chunk, write_token


class TPServing:
    """Sharded decode + prefill programs for one (model, mesh, axis)."""

    def __init__(self, model, mesh, axis_name: str, cfg):
        if getattr(cfg, "cache_layout", "dense") != "dense" or getattr(
            cfg, "spec_k", 0
        ):
            # Defense in depth behind the engine's own guard: the manual
            # shard_map decode body has no page-table or verify-window
            # variant, and running the dense body against a paged/spec
            # engine state would be a silent wrong-answer path.
            from tpudml.capabilities import reject
            from tpudml.serve.engine import ServeCompositionError

            reject("serve_tp_dense_only", exc=ServeCompositionError)
        self.model = model
        self.mesh = mesh
        self.axis = axis_name
        self.cfg = cfg
        self.world = mesh.shape[axis_name]
        d = model.embed_dim
        kv_heads = model.num_kv_heads or model.num_heads
        hidden = model._block().mlp_ratio * d
        for what, n in (
            ("num_heads", model.num_heads),
            ("kv_heads", kv_heads),
            ("vocab_size", model.vocab_size),
            ("mlp hidden dim", hidden),
        ):
            if n % self.world:
                raise ValueError(
                    f"TP serving requires {what} ({n}) divisible by the "
                    f"'{axis_name}' axis size ({self.world}); apply_rules "
                    f"would demote the shard and break the manual decode body"
                )
        self.h_local = model.num_heads // self.world
        self.kv_local = kv_heads // self.world
        self.v_local = model.vocab_size // self.world
        self.param_specs = None  # set by shard_params (needs the real tree)
        self._prefill_cache: dict = {}
        self.decode_step = None

    # ------------------------------------------------------------ placement

    def shard_params(self, params):
        self.param_specs = apply_rules(
            tensor_parallel_rules(self.axis), params, self.mesh
        )
        sharded = jax.device_put(
            params,
            jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                         self.param_specs, is_leaf=lambda x: isinstance(x, P)),
        )
        self.decode_step = self._build_decode()
        return sharded

    def _cache_spec_tree(self):
        kind = self.cfg.cache_kind
        kv = P(None, None, self.axis, None)
        sc = P(None, None, self.axis) if kind == "int8" else P()
        return tuple(
            KVCache(k=kv, v=kv, k_scale=sc, v_scale=sc, kind=kind)
            for _ in range(self.model.num_layers)
        )

    def init_caches(self):
        caches = self.model.init_decode_cache(
            self.cfg.slots, self.cfg.max_len, self.cfg.cache_kind
        )
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._cache_spec_tree(),
            is_leaf=lambda x: isinstance(x, P),
        )
        return jax.device_put(caches, shardings)

    # ------------------------------------------------------------ shared math

    def _embed(self, params, tokens):
        """Vocab-sharded embedding gather: mask tokens outside this
        shard's row range, gather locally, psum the one nonzero
        contribution. [B] → [B, 1, d]."""
        table = params["tok_embed"]  # [V/world, d]
        idx = lax.axis_index(self.axis)
        local = tokens - idx * self.v_local
        ok = (local >= 0) & (local < self.v_local)
        rows = table[jnp.clip(local, 0, self.v_local - 1)]
        rows = rows * ok[:, None].astype(rows.dtype)
        return lax.psum(rows, self.axis)[:, None, :]

    def _block_parts(self):
        return self.model._block()._parts()

    def _tp_block(self, parts, p, h, attend):
        """One pre-LN block on local shards: column-parallel in,
        psum-then-bias on the row-parallel way out."""
        attn = parts["attn"]
        y = parts["ln1"](p["ln1"], h)
        a, cache = attend(attn, p["attn"], y)
        o = lax.psum(a @ p["attn"]["out"]["kernel"], self.axis)
        h = h + o + p["attn"]["out"]["bias"]
        y2 = parts["ln2"](p["ln2"], h)
        f = jax.nn.gelu(y2 @ p["fc1"]["kernel"] + p["fc1"]["bias"])
        h = h + lax.psum(f @ p["fc2"]["kernel"], self.axis) + p["fc2"]["bias"]
        return h, cache

    # --------------------------------------------------------------- decode

    def _build_decode(self):
        model, cfg, axis = self.model, self.cfg, self.axis
        from tpudml.nn.attention import decode_attention, rotary_embedding

        def _serve_decode_step(params, caches, tokens, pos):
            params = model._cast_params(params)
            parts = self._block_parts()
            h = self._embed(params, tokens)
            if not model.rope:
                h = h + params["pos_embed"][pos][:, None, :]
            new_caches = []
            for i, cache in enumerate(caches):
                def attend(attn, p, y, cache=cache):
                    q, k_new, v_new = attn._project(
                        p, y, self.h_local, self.kv_local
                    )
                    if model.rope:
                        q = rotary_embedding(q, pos[:, None], model.rope_base)
                        k_new = rotary_embedding(
                            k_new, pos[:, None], model.rope_base
                        )
                    cache = write_token(cache, k_new, v_new, pos)
                    k, v = read_all(cache, y.dtype)
                    k, v = attn._gqa_repeat(k, v, self.h_local)
                    o = decode_attention(q, k, v, pos)
                    b = y.shape[0]
                    return o.reshape(b, 1, -1), cache

                h, cache = self._tp_block(parts, params[f"block{i}"], h, attend)
                new_caches.append(cache)
            # Head module on LOCAL shards: ln_f params are replicated and
            # the vocab projection is column-parallel, so the stock module
            # emits this shard's [B, 1, V/world] logits slice directly.
            ll = model._head()(
                {k: params[k] for k in ("ln_f", "head")}, h
            )
            logits = lax.all_gather(ll[:, 0, :], axis, axis=-1, tiled=True)
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32), logits,
                    tuple(new_caches))

        inner = jax.jit(_serve_decode_step)

        def body(params, caches, tokens, pos):
            return inner(params, caches, tokens, pos)

        sm = shard_map_fn(
            body, self.mesh,
            in_specs=(self.param_specs, self._cache_spec_tree(), P(), P()),
            out_specs=(P(), P(), self._cache_spec_tree()),
        )
        return jax.jit(sm, donate_argnums=(1,))

    # -------------------------------------------------------------- prefill

    def prefill_at(self, start: int):
        model, axis = self.model, self.axis
        c = self.cfg.prefill_chunk
        from tpudml.nn.attention import (
            _chunk_flash_window, dot_product_attention, rotary_embedding,
        )
        if not model.rope and start + c > model.max_len:
            raise ValueError(
                f"prefill window {start + c} exceeds max_len {model.max_len}"
            )

        def _serve_prefill_chunk(params, caches, chunk, slot):
            params = model._cast_params(params)
            parts = self._block_parts()
            h = self._embed(params, chunk[0])  # [C, 1, d] — re-lay below
            h = h[:, 0, :][None]  # [1, C, d]
            if not model.rope:
                h = h + params["pos_embed"][start:start + c][None]
            new_caches = []
            for i, cache in enumerate(caches):
                def attend(attn, p, y, cache=cache):
                    q, k_new, v_new = attn._project(
                        p, y, self.h_local, self.kv_local
                    )
                    if model.rope:
                        positions = start + jnp.arange(c)
                        q = rotary_embedding(q, positions, model.rope_base)
                        k_new = rotary_embedding(k_new, positions, model.rope_base)
                    cache = write_chunk(cache, k_new, v_new, slot, start)
                    k, v = read_slot_prefix(cache, slot, start + c, y.dtype)
                    k, v = attn._gqa_repeat(k, v, self.h_local)
                    if jax.default_backend() == "tpu":
                        o = _chunk_flash_window(q, k, v, start)
                    else:
                        o = dot_product_attention(
                            q, k, v, causal=True, q_offset=start
                        )
                    return o.reshape(1, c, -1), cache

                h, cache = self._tp_block(parts, params[f"block{i}"], h, attend)
                new_caches.append(cache)
            return tuple(new_caches)

        sm = shard_map_fn(
            _serve_prefill_chunk, self.mesh,
            in_specs=(self.param_specs, self._cache_spec_tree(), P(), P()),
            out_specs=self._cache_spec_tree(),
        )
        return jax.jit(sm, donate_argnums=(1,))
