"""tpudml.serve — prefill–decode LM serving with continuous batching.

Layers: ``cache`` (preallocated per-layer KV caches, f32/bf16/int8),
``engine`` (ONE jitted decode step + chunked prefill + slot scheduler),
``load`` (seeded Poisson request streams), ``tp`` (the same steps under
shard_map on a tensor-parallel mesh). See docs/API.md §Serving.
"""

from tpudml.serve.cache import KVCache, cache_bytes, init_cache
from tpudml.serve.engine import (
    SERVE_DECODE_MARKER,
    RequestStats,
    ServeConfig,
    ServeReport,
    ServingEngine,
    make_cacheless_decode_step,
    make_decode_step,
)
from tpudml.serve.load import Request, poisson_workload

__all__ = [
    "KVCache",
    "Request",
    "RequestStats",
    "SERVE_DECODE_MARKER",
    "ServeConfig",
    "ServeReport",
    "ServingEngine",
    "cache_bytes",
    "init_cache",
    "make_cacheless_decode_step",
    "make_decode_step",
    "poisson_workload",
]
