"""tpudml.serve — multi-tenant prefill–decode LM serving.

Layers: ``cache`` (dense preallocated per-layer KV caches,
f32/bf16/int8), ``paged`` (page-pool cache + slot→page table + prefix
sharing), ``spec`` (speculative decoding with exact greedy
acceptance-rejection), ``sched`` (SLO-aware admission priced on the
static cost model), ``engine`` (ONE jitted decode step + chunked
prefill + slot scheduler composing all of the above), ``load`` (seeded
Poisson request streams), ``tp`` (the dense steps under shard_map on a
tensor-parallel mesh; TP × {paged, spec, weight_quant} raises
ServeCompositionError), ``fleet`` (scale-OUT: multi-replica router with
drain/re-admit membership, disaggregated prefill/decode handoff, int8
weight quantization — imported lazily, see ``tpudml.serve.fleet``).
See docs/API.md §Serving.
"""

from tpudml.serve.cache import KVCache, cache_bytes, init_cache
from tpudml.serve.engine import (
    SERVE_DECODE_MARKER,
    RequestStats,
    ServeCompositionError,
    ServeConfig,
    ServeReport,
    ServingEngine,
    make_cacheless_decode_step,
    make_decode_step,
    make_paged_decode_step,
)
from tpudml.serve.load import Request, poisson_workload
from tpudml.serve.paged import (
    PAGED_DECODE_MARKER,
    PagedKVCache,
    PagePool,
    init_pool,
    pool_bytes,
)
from tpudml.serve.sched import DecodeCostModel, SLOConfig
from tpudml.serve.spec import (
    SPEC_DECODE_MARKER,
    draft_from_trunk,
    make_spec_decode_step,
)

_FLEET_EXPORTS = (
    "FleetConfig", "FleetReport", "FleetRequestStats", "FleetRouter",
    "replay_fleet_fixture",
)


def __getattr__(name):
    # Lazy: the fleet tier pulls in the checkpoint store (disagg handoff)
    # and, for the drill, the elastic controller stack — none of which a
    # plain single-engine import should pay for.
    if name in _FLEET_EXPORTS:
        import tpudml.serve.fleet as fleet

        return getattr(fleet, name)
    raise AttributeError(name)


__all__ = [
    "FleetConfig",
    "FleetReport",
    "FleetRequestStats",
    "FleetRouter",
    "KVCache",
    "PAGED_DECODE_MARKER",
    "PagePool",
    "PagedKVCache",
    "Request",
    "RequestStats",
    "SERVE_DECODE_MARKER",
    "SPEC_DECODE_MARKER",
    "DecodeCostModel",
    "SLOConfig",
    "ServeCompositionError",
    "ServeConfig",
    "ServeReport",
    "ServingEngine",
    "cache_bytes",
    "draft_from_trunk",
    "init_cache",
    "init_pool",
    "make_cacheless_decode_step",
    "make_decode_step",
    "make_paged_decode_step",
    "make_spec_decode_step",
    "poisson_workload",
    "pool_bytes",
    "replay_fleet_fixture",
]
