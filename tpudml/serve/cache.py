"""Preallocated KV caches for incremental decode.

Layout is ``[B, max_len, kv_heads, head_dim]`` per layer — B is the
engine's SLOT count (one row per in-flight sequence, continuous
batching rewrites rows in place), and the head axis is the GQA
``kv_heads`` so the cache shrinks with the KV-group count and shards
over the tensor-parallel axis exactly like the K/V projections
(``P(None, None, "model", None)``).

Kinds:

- ``"f32"`` / ``"bf16"``: plain dtype storage; a read casts back to the
  compute dtype.
- ``"int8"``: per-(token, head) symmetric quantization — ``scale =
  amax(|x|)/127`` over head_dim, stored alongside as f32
  ``[B, max_len, kv_heads]``; the decode read dequantizes in-kernel
  (``q * scale``), so HBM traffic in the cache-bound decode regime drops
  4× vs f32.
- ``"bf16_sim"`` / ``"int8_sim"``: test oracles — write the
  quantize→dequantize ROUNDTRIP into an f32 cache. A real quantized
  cache must produce bitwise the values of its ``_sim`` twin (the
  dequant is deterministic), which is how tests/test_serve.py pins
  "dequant in the decode kernel is exactly the write-side roundtrip"
  without demanding the impossible (lossy int8 matching full-precision
  logits at 1e-6).

Writes happen BEFORE the attention read at a step, so slot positions
beyond a sequence's current token only ever hold zeros-or-stale values
that the causal mask (``k_pos <= pos``) excludes; no masking state is
stored in the cache itself.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax import lax

KINDS = ("f32", "bf16", "int8", "bf16_sim", "int8_sim")

# Floor on the per-(token, head) scale: an all-zero row (unwritten cache
# positions) would otherwise divide 0/0 at dequant time.
_SCALE_EPS = 1e-8


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    """One layer's cache: K/V plus (int8 only) per-(token, head) scales."""

    k: jax.Array  # [B, L, Hkv, Dh] storage dtype
    v: jax.Array
    k_scale: jax.Array  # [B, L, Hkv] f32; zeros-shaped [0] when unused
    v_scale: jax.Array
    kind: str = field(metadata=dict(static=True))

    @property
    def max_len(self) -> int:
        return self.k.shape[1]


def _store_dtype(kind: str):
    return {
        "f32": jnp.float32, "bf16": jnp.bfloat16, "int8": jnp.int8,
        "bf16_sim": jnp.float32, "int8_sim": jnp.float32,
    }[kind]


def init_cache(batch: int, max_len: int, kv_heads: int, head_dim: int,
               kind: str = "f32") -> KVCache:
    if kind not in KINDS:
        raise ValueError(f"unknown cache kind {kind!r}; one of {KINDS}")
    shape = (batch, max_len, kv_heads, head_dim)
    sshape = (batch, max_len, kv_heads) if kind == "int8" else (0,)
    # k/v (and the scales) must be DISTINCT buffers: the engine donates
    # the cache pytree every step, and XLA rejects donating one buffer
    # twice — so no `z = zeros(...); KVCache(k=z, v=z, ...)` aliasing.
    return KVCache(
        k=jnp.zeros(shape, _store_dtype(kind)),
        v=jnp.zeros(shape, _store_dtype(kind)),
        k_scale=jnp.zeros(sshape, jnp.float32),
        v_scale=jnp.zeros(sshape, jnp.float32),
        kind=kind,
    )


def _quant(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """x [..., Dh] f32-ish -> (int8 codes, f32 scale [...])."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), _SCALE_EPS) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _dequant(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale[..., None]


def _encode(x: jax.Array, kind: str) -> tuple[jax.Array, jax.Array | None]:
    """Storage-form (values, scales-or-None) of new K/V rows."""
    if kind == "int8":
        return _quant(x)
    if kind == "int8_sim":
        q, s = _quant(x)
        return _dequant(q, s), None
    if kind == "bf16":
        return x.astype(jnp.bfloat16), None
    if kind == "bf16_sim":
        return x.astype(jnp.bfloat16).astype(jnp.float32), None
    return x.astype(jnp.float32), None


def write_token(cache: KVCache, k_new: jax.Array, v_new: jax.Array,
                pos: jax.Array) -> KVCache:
    """Write one token per slot: k_new/v_new [B, 1, Hkv, Dh] at per-slot
    positions ``pos`` [B] (continuous batching: every slot sits at its
    own depth)."""
    ks, kscale = _encode(k_new, cache.kind)
    vs, vscale = _encode(v_new, cache.kind)

    def one(ck, kn, p):  # ck [L, Hkv, Dh], kn [1, Hkv, Dh]
        return lax.dynamic_update_slice(ck, kn, (p, 0, 0))

    k = jax.vmap(one)(cache.k, ks, pos)
    v = jax.vmap(one)(cache.v, vs, pos)
    k_sc, v_sc = cache.k_scale, cache.v_scale
    if cache.kind == "int8":
        def one_s(cs, sn, p):  # cs [L, Hkv], sn [1, Hkv]
            return lax.dynamic_update_slice(cs, sn, (p, 0))

        k_sc = jax.vmap(one_s)(k_sc, kscale, pos)
        v_sc = jax.vmap(one_s)(v_sc, vscale, pos)
    return KVCache(k=k, v=v, k_scale=k_sc, v_scale=v_sc, kind=cache.kind)


def write_chunk(cache: KVCache, k_new: jax.Array, v_new: jax.Array,
                slot: jax.Array, start: int) -> KVCache:
    """Prefill write: k_new/v_new [1, C, Hkv, Dh] into one slot's rows
    [start, start+C). ``start`` is static (one compiled prefill program
    per chunk index, shared across slots/requests); ``slot`` is a traced
    scalar."""
    ks, kscale = _encode(k_new, cache.kind)
    vs, vscale = _encode(v_new, cache.kind)
    at = (slot, start, 0, 0)
    k = lax.dynamic_update_slice(cache.k, ks, at)
    v = lax.dynamic_update_slice(cache.v, vs, at)
    k_sc, v_sc = cache.k_scale, cache.v_scale
    if cache.kind == "int8":
        k_sc = lax.dynamic_update_slice(k_sc, kscale, (slot, start, 0))
        v_sc = lax.dynamic_update_slice(v_sc, vscale, (slot, start, 0))
    return KVCache(k=k, v=v, k_scale=k_sc, v_scale=v_sc, kind=cache.kind)


def read_all(cache: KVCache, dtype) -> tuple[jax.Array, jax.Array]:
    """Full-cache read for the decode step: [B, L, Hkv, Dh] in the
    compute dtype, dequantized in the int8 case (this IS the "dequant in
    the decode kernel" — the int8 codes live in HBM, the f32 product is
    a register-level transient of the attention computation)."""
    if cache.kind == "int8":
        k = _dequant(cache.k, cache.k_scale)
        v = _dequant(cache.v, cache.v_scale)
        return k.astype(dtype), v.astype(dtype)
    return cache.k.astype(dtype), cache.v.astype(dtype)


def read_slot_prefix(cache: KVCache, slot: jax.Array, length: int,
                     dtype) -> tuple[jax.Array, jax.Array]:
    """One slot's first ``length`` rows (static) for a prefill chunk's
    attention window: [1, length, Hkv, Dh]."""
    b, _, h, d = cache.k.shape
    at = (slot, 0, 0, 0)
    k = lax.dynamic_slice(cache.k, at, (1, length, h, d))
    v = lax.dynamic_slice(cache.v, at, (1, length, h, d))
    if cache.kind == "int8":
        k = _dequant(k, lax.dynamic_slice(cache.k_scale, (slot, 0, 0),
                                          (1, length, h)))
        v = _dequant(v, lax.dynamic_slice(cache.v_scale, (slot, 0, 0),
                                          (1, length, h)))
    return k.astype(dtype), v.astype(dtype)


def cache_bytes(cache: KVCache) -> int:
    """Total storage bytes (K + V + scales) — the number the int8 option
    exists to shrink."""
    return sum(x.size * x.dtype.itemsize
               for x in (cache.k, cache.v, cache.k_scale, cache.v_scale))
