"""SLO-aware admission: price a decode step before occupying a slot.

PR 9's overload guard is purely *queue-shaped* (``max_queue`` bounds the
line, ``deadline_s`` drops the hopeless); it admits whenever a slot is
free, even when the marginal occupant pushes every tenant's per-token
cadence past its latency contract. This module adds the missing price
tag, built on the same roofline inputs as the PR 10 static cost reports:
a decode step streams the weights once plus each active slot's KV window
from HBM, and (under tensor parallelism) moves two activation allreduces
per block over the interconnect, priced with the shared ring model
(``comm.timing.collective_wire_bytes``). The scheduler then admits the
queue head only while

    predicted_step_seconds(active + 1) <= slo.tpot_budget_s

deferring it (event ``("defer", rid, -1, step)``) otherwise — FIFO order
and the (arrival, rid) tie-break are preserved because admission only
ever peeks the head; nobody overtakes. An idle engine always admits, so
a budget that is simply unsatisfiable degrades to slots=1 behaviour
instead of deadlocking the queue.

Honesty note (also in docs/API.md): the engine's compiled step runs ALL
slots every step, so on real hardware the measured step time is nearly
flat in occupancy — the model prices the *work* a step does, which is
what the TPOT contract cares about at production batch sizes, and what
makes admission deterministic on the CPU-dryrun virtual clock.
"""

from __future__ import annotations

from dataclasses import dataclass

from tpudml.comm.timing import collective_wire_bytes

_CACHE_ITEMSIZE = {"f32": 4, "bf16": 2, "int8": 1, "bf16_sim": 4, "int8_sim": 4}

# Stored bytes per PARAMETER element, keyed by ServeConfig.weight_quant.
# Same convention as the cache table above: the "_sim" oracle keeps f32
# storage (it only rounds values), so it prices like f32 — pricing the
# sim as if it saved bytes would be the dishonest-placement bug the
# fleet router's SLO pricing exists to avoid.
_PARAM_ITEMSIZE = {None: 4, "f32": 4, "bf16": 2, "int8": 1, "int8_sim": 4}


@dataclass(frozen=True)
class SLOConfig:
    """Latency contract + machine constants for admission pricing.

    ``tpot_budget_s``: target per-token cadence (time-per-output-token)
    the tier promises every admitted tenant. ``hbm_gbps``/``ici_gbps``:
    memory and interconnect roofline constants, same role as the PR 10
    ``--cost`` report's; defaults are deliberately round CPU-dryrun
    stand-ins — rerun with chip constants for real capacity planning."""

    tpot_budget_s: float
    hbm_gbps: float = 100.0
    ici_gbps: float = 45.0

    def __post_init__(self):
        if self.tpot_budget_s <= 0:
            raise ValueError("tpot_budget_s must be > 0")
        if self.hbm_gbps <= 0 or self.ici_gbps <= 0:
            raise ValueError("hbm_gbps/ici_gbps must be > 0")


class DecodeCostModel:
    """Static per-step cost of the serving engine's decode program.

    bytes(step) = params_read + n_active × (per_slot_window + logits_tail)
                  + spec_draft
    seconds(step) = bytes/hbm + ring_wire_bytes/ici

    The per-slot window is what the cache layout decides: the dense
    engine streams ``max_len`` rows per slot; the paged engine gathers
    exactly the slot's ``max_pages`` table rows (``max_pages ×
    page_size`` positions) — gathering the whole pool instead is the
    J117 anti-pattern and would show up here as a pool-sized window.
    Spec decode adds K draft passes (draft weights re-read per drafted
    token) but amortizes the whole step over ~``1 + accepted`` emitted
    tokens; admission prices the pessimistic 1-token floor."""

    def __init__(self, model, cfg, slo: SLOConfig, *, world: int = 1,
                 draft_model=None):
        self.slo = slo
        self.world = world
        kv_heads = model.num_kv_heads or model.num_heads
        head_dim = model.embed_dim // model.num_heads
        itemsize = _CACHE_ITEMSIZE[cfg.cache_kind]
        if cfg.cache_layout == "paged":
            window_rows = cfg.max_pages * cfg.page_size
        else:
            window_rows = cfg.max_len
        # K + V rows across all layers, once per step per active slot.
        self.per_slot_bytes = (
            2 * window_rows * kv_heads * head_dim * itemsize * model.num_layers
        )
        p_item = _PARAM_ITEMSIZE[getattr(cfg, "weight_quant", None)]
        self.params_bytes = (
            self._params_bytes(model, itemsize=p_item) // max(world, 1)
        )
        self.draft_bytes = 0
        self.spec_k = cfg.spec_k or 0
        if draft_model is not None and self.spec_k:
            self.draft_bytes = (
                self._params_bytes(draft_model, itemsize=p_item)
                // max(world, 1)
            )
        # Decode tail: the unfused step writes each slot's [vocab] logits
        # row to HBM and reads it back for the argmax + stats pass; the
        # fused head (ops/decode_head.py) keeps the row in VMEM tiles, so
        # its tail traffic is zero. Priced per slot so admission sees the
        # fused tail's headroom at production vocab sizes.
        if getattr(cfg, "fused_head", False):
            self.tail_bytes_per_slot = 0
        else:
            self.tail_bytes_per_slot = 2 * model.vocab_size * 4
        # Two activation allreduces per block per step under TP (attn.out
        # + mlp.fc2 — serve/tp.py), priced on the shared ring model.
        act_bytes = model.embed_dim * 4
        self.wire_bytes_per_slot = (
            2 * model.num_layers
            * collective_wire_bytes("psum", act_bytes, world)
        )

    @staticmethod
    def _params_bytes(model, *, itemsize: int = 4) -> int:
        """Stored parameter bytes at ``itemsize`` bytes/element — the ONE
        param-pricing code path for every weight dtype (f32/bf16/int8):
        quantization changes the multiplier, never the element count."""
        d, v, l = model.embed_dim, model.vocab_size, model.num_layers
        kv = model.num_kv_heads or model.num_heads
        head_dim = d // model.num_heads
        mlp = getattr(model, "mlp_ratio", 4) * d
        per_block = d * d * 2 + d * kv * head_dim * 2 + 2 * d * mlp
        return itemsize * (v * d * 2 + l * per_block)  # embed+head+blocks

    def step_seconds(self, n_active: int) -> float:
        hbm = (
            self.params_bytes
            + self.spec_k * self.draft_bytes
            + n_active * (self.per_slot_bytes + self.tail_bytes_per_slot)
        )
        wire = n_active * self.wire_bytes_per_slot
        return (
            hbm / (self.slo.hbm_gbps * 1e9)
            + wire / (self.slo.ici_gbps * 1e9)
        )

    def admit_ok(self, n_active: int) -> bool:
        """May the scheduler add one more tenant? Always yes from idle
        (the budget can defer, never deadlock)."""
        if n_active == 0:
            return True
        return self.step_seconds(n_active + 1) <= self.slo.tpot_budget_s
