"""Disaggregated prefill/decode: KV handoff between specialist replicas.

Prefill and decode want different machines: prefill is compute-bound
(one big attention pass over the prompt), decode is cache-bound (stream
weights + KV per token). The fleet's disaggregated roles split them —
a PREFILL replica runs the prompt once and fills content-hashed pages;
a DECODE replica adopts those pages into its own ``PagePool`` and
serves the tokens without ever touching the prompt's prefill.

The transport rides two existing invariants instead of inventing new
machinery:

- **Pages already have identity.** Prefix sharing keys a page by the
  byte-hash of the prompt head it covers (``PagePool._key``); a page is
  shareable iff it ends strictly before the first decode write, so its
  contents are a pure function of the token prefix. Shipping a page is
  therefore just shipping (tokens-it-covers, K/V tensors) — the decode
  side re-registers it under the SAME content hash and ``match_prefix``
  finds it exactly as if a local tenant had prefilled it.
- **The checkpoint store already does integrity.** The handoff file is
  a checkpoint (``tpudml.checkpoint.store``, format 2): per-leaf
  CRC-32, atomic tmp+rename, and a loud ``CheckpointCorruptError`` on
  truncation/bitflip — so a vandalized handoff is REJECTED at adopt and
  the request transparently falls back to local prefill (no prefix hit,
  same tokens, just slower). ``faults.vandalize`` works on handoff
  directories unmodified, which is exactly how the rollback test
  injects the truncation.

Greedy parity is byte-exact by construction: adopted pages hold
bitwise-identical K/V to what local prefill would have written (same
params, same compiled prefill programs, same positions), so the decode
replica's token stream equals the single-engine stream token-for-token
— pinned in tests/test_fleet_disagg.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tpudml.checkpoint.store import (
    CheckpointCorruptError,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
    _read_manifest,
)
from tpudml.serve.engine import RequestStats, ServeConfig, ServingEngine
from tpudml.serve.load import Request
from tpudml.serve.paged import PagedKVCache

HANDOFF_VERSION = 1


def _require_paged_sharing(cfg: ServeConfig, who: str):
    if cfg.cache_layout != "paged" or not cfg.prefix_sharing:
        raise ValueError(
            f"{who} requires cache_layout='paged' with prefix_sharing=True "
            f"(content-hashed pages are the handoff unit)"
        )


def write_handoff(model, params, cfg: ServeConfig, prompt,
                  directory) -> dict:
    """PREFILL role: run ``prompt``'s prefill on a 1-slot paged engine
    and serialize its shareable pages (the whole-page prompt prefix)
    through the CRC-verified checkpoint format under ``directory``.

    Returns ``{"n_pages", "covered_tokens", "path"}`` — ``n_pages`` may
    be 0 for a sub-page prompt (nothing shareable; adopt is a no-op and
    decode falls back to local prefill)."""
    _require_paged_sharing(cfg, "write_handoff")
    prompt = np.asarray(prompt, np.int32)
    if prompt.ndim != 1 or prompt.size < 1:
        raise ValueError("prompt must be [L>=1]")
    ecfg = ServeConfig(
        slots=1,
        max_len=cfg.max_len,
        prefill_chunk=cfg.prefill_chunk,
        cache_kind=cfg.cache_kind,
        cache_layout="paged",
        page_size=cfg.page_size,
        prefix_sharing=True,
        step_time_s=cfg.step_time_s,
        weight_quant=cfg.weight_quant,
    )
    if prompt.size + 1 > ecfg.max_len:
        raise ValueError(
            f"prompt {prompt.size} + 1 exceeds max_len {ecfg.max_len}"
        )
    eng = ServingEngine(model, params, ecfg)
    st = RequestStats(
        rid=0, prompt_len=prompt.size, max_new_tokens=1, arrival=0.0
    )
    admitted = eng._admit_paged(
        0, Request(rid=0, prompt=prompt, max_new_tokens=1), st
    )
    assert admitted is not None  # a fresh pool cannot be starved
    p = prompt.size - 1  # first decode write position
    pages = eng._slot_pages[0]
    n = sum(1 for j in range(len(pages))
            if (j + 1) * ecfg.page_size <= p)
    pids = np.asarray(pages[:n], np.int32)
    kind = ecfg.cache_kind
    has_scales = kind == "int8"

    def gather(field_name):
        return np.stack([
            np.asarray(jax.device_get(getattr(c, field_name)[pids]))
            for c in eng.caches
        ]) if n else np.zeros((0,), np.float32)

    payload = {
        "prompt_head": prompt[: n * ecfg.page_size],
        "k": gather("k"),
        "v": gather("v"),
        "k_scale": gather("k_scale") if has_scales else np.zeros((0,), np.float32),
        "v_scale": gather("v_scale") if has_scales else np.zeros((0,), np.float32),
    }
    meta = {
        "fleet_handoff": HANDOFF_VERSION,
        "page_size": ecfg.page_size,
        "cache_kind": kind,
        "n_pages": int(n),
        "num_layers": len(eng.caches),
        "covered_tokens": int(n * ecfg.page_size),
    }
    path = save_checkpoint(directory, payload, 0, metadata=meta)
    return {"n_pages": int(n), "covered_tokens": meta["covered_tokens"],
            "path": path}


def adopt_handoff(engine: ServingEngine, directory, *,
                  strict: bool = False) -> int:
    """DECODE role: verify + load a handoff directory and graft its
    pages into ``engine``'s pool under their content hashes; returns
    the number of pages adopted.

    0 means "serve without the handoff": missing/empty handoff, a
    CRC-failed (vandalized) file, or a pool too full to take the pages
    — in every case the next matching request simply finds no prefix
    hit and prefills locally (correctness never depends on adoption;
    only prefill work does). ``strict=True`` re-raises the corruption
    instead, for callers that want the loud version. Config mismatches
    (page size / cache kind / layer count) always raise — that is a
    wiring bug, not a fault."""
    _require_paged_sharing(engine.cfg, "adopt_handoff")
    path = latest_checkpoint(directory)
    if path is None:
        if strict:
            raise CheckpointCorruptError(f"{directory}: no handoff found")
        return 0
    try:
        meta = _read_manifest(path).get("metadata", {})
    except CheckpointCorruptError:
        if strict:
            raise
        return 0
    if meta.get("fleet_handoff") != HANDOFF_VERSION:
        raise ValueError(
            f"handoff version {meta.get('fleet_handoff')!r} != "
            f"{HANDOFF_VERSION}"
        )
    cfg = engine.cfg
    if (meta.get("page_size") != cfg.page_size
            or meta.get("cache_kind") != cfg.cache_kind
            or meta.get("num_layers") != len(engine.caches)):
        raise ValueError(
            f"handoff/engine mismatch: handoff (page_size="
            f"{meta.get('page_size')}, kind={meta.get('cache_kind')}, "
            f"layers={meta.get('num_layers')}) vs engine (page_size="
            f"{cfg.page_size}, kind={cfg.cache_kind}, "
            f"layers={len(engine.caches)})"
        )
    n = int(meta.get("n_pages", 0))
    if n == 0:
        return 0
    layers = len(engine.caches)
    c0 = engine.caches[0]
    _, psz, hkv, dh = c0.k.shape
    has_scales = cfg.cache_kind == "int8"
    target = {
        "prompt_head": np.zeros(n * cfg.page_size, np.int32),
        "k": np.zeros((layers, n, psz, hkv, dh), c0.k.dtype),
        "v": np.zeros((layers, n, psz, hkv, dh), c0.v.dtype),
        "k_scale": (np.zeros((layers, n, psz, hkv), np.float32)
                    if has_scales else np.zeros((0,), np.float32)),
        "v_scale": (np.zeros((layers, n, psz, hkv), np.float32)
                    if has_scales else np.zeros((0,), np.float32)),
    }
    try:
        payload = restore_checkpoint(path, target, verify=True)
    except CheckpointCorruptError:
        if strict:
            raise
        return 0
    pool = engine._pool
    pids = pool.alloc_n(n)
    if pids is None:
        return 0  # pool under pressure; local prefill still works
    idx = jnp.asarray(np.asarray(pids, np.int32))
    caches = []
    for l, c in enumerate(engine.caches):
        k = c.k.at[idx].set(jnp.asarray(payload["k"][l]))
        v = c.v.at[idx].set(jnp.asarray(payload["v"][l]))
        k_sc, v_sc = c.k_scale, c.v_scale
        if has_scales:
            k_sc = k_sc.at[idx].set(jnp.asarray(payload["k_scale"][l]))
            v_sc = v_sc.at[idx].set(jnp.asarray(payload["v_scale"][l]))
        caches.append(
            PagedKVCache(k=k, v=v, k_scale=k_sc, v_scale=v_sc, kind=c.kind)
        )
    engine.caches = caches
    prompt_head = np.asarray(payload["prompt_head"], np.int32)
    for j, pid in enumerate(pids):
        # Publish under the content hash, then release: a keyed page at
        # refcount 0 parks in the retained-LRU — exactly the state a
        # local tenant's shareable pages reach after eviction, so
        # ``match_prefix`` serves it to the next matching prompt.
        pool.register(pid, prompt_head, j)
        pool.release(pid)
    return n
