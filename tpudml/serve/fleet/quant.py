"""int8 weight quantization for the cache-bound decode path.

Decode is memory-bound: every step streams the weights once, so halving
or quartering stored parameter bytes buys cadence directly (the
``DecodeCostModel`` param term — serve/sched.py now prices it by dtype).
This module quantizes the model's matmul kernels to int8 with
per-OUTPUT-channel absmax scales, the weight-side twin of the KV
cache's per-row scheme (serve/cache.py ``_quant``): with the kernel
laid out [in, out], one f32 scale per output column keeps each column's
dynamic range independent, which is what absmax needs — Dense columns
are the unit fan-in-normalized init and training perturb independently.

Eligibility is *name-based and total*: every param-tree leaf named
``"kernel"`` with ndim == 2 (attention q/k/v/out projections, fc1/fc2,
the LM head) is quantized; embeddings (``tok_embed``/``pos_embed``),
LayerNorm scale/bias, and biases stay f32 — they are a rounding error
of the byte budget and disproportionately sensitive to rounding.

The correctness contract mirrors the cache's ``_sim`` oracle pattern:

- :func:`sim_quantize_params` is the oracle — a quantize→dequantize
  round-trip that keeps f32 storage (so it prices like f32, see
  ``serve/sched.py _PARAM_ITEMSIZE``);
- :func:`quantize_params` + :func:`dequantize_params` is the real path
  (int8 storage + f32 scales), and its dequantization must equal the
  oracle BITWISE — same ops in the same order, only the storage differs;
- decode logits under either mode are atol-close to f32 (the parity
  test in tests/test_fleet_quant.py); exact token equality is NOT
  promised — rounded weights may legitimately flip an argmax.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tpudml.serve.cache import _SCALE_EPS


def _quant_kernel(w) -> tuple[jnp.ndarray, jnp.ndarray]:
    """kernel [in, out] f32 -> (int8 codes [in, out], f32 scale [out]).

    Same absmax/127 + ``_SCALE_EPS`` floor + round/clip sequence as the
    cache's ``_quant``, with the reduction over the INPUT axis so each
    output channel owns its scale."""
    wf = jnp.asarray(w, jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=0), _SCALE_EPS) / 127.0
    q = jnp.clip(jnp.round(wf / scale[None, :]), -127, 127)
    return q.astype(jnp.int8), scale


def _dequant_kernel(q, scale) -> jnp.ndarray:
    return q.astype(jnp.float32) * scale[None, :]


def _is_kernel(name: str, leaf) -> bool:
    return name == "kernel" and getattr(leaf, "ndim", 0) == 2


def _walk(tree, fn):
    """Map ``fn(name, leaf)`` over a nested-dict param tree's leaves."""
    if isinstance(tree, dict):
        return {k: _walk_named(k, v, fn) for k, v in tree.items()}
    return fn(None, tree)


def _walk_named(name, node, fn):
    if isinstance(node, dict):
        return {k: _walk_named(k, v, fn) for k, v in node.items()}
    return fn(name, node)


def quantize_params(params: dict) -> tuple[dict, dict]:
    """Real int8 path: returns ``(qparams, scales)`` — the param tree
    with every eligible kernel stored as int8, and a parallel tree
    holding the f32 per-output-channel scales at exactly the quantized
    paths (non-quantized leaves carry None)."""

    def _q(node, name=None):
        if isinstance(node, dict):
            pairs = {k: _q(v, k) for k, v in node.items()}
            return (
                {k: p[0] for k, p in pairs.items()},
                {k: p[1] for k, p in pairs.items()},
            )
        if _is_kernel(name, node):
            return _quant_kernel(node)
        return node, None

    return _q(params)


def dequantize_params(qparams: dict, scales: dict) -> dict:
    """Inverse of :func:`quantize_params` — bitwise-equal to the
    :func:`sim_quantize_params` oracle on the same input params."""

    def _deq(q, s):
        if isinstance(q, dict):
            return {k: _deq(q[k], s[k]) for k in q}
        if s is None:
            return q
        return _dequant_kernel(q, s)

    return _deq(qparams, scales)


def sim_quantize_params(params: dict) -> dict:
    """The ``_sim`` oracle: quantize→dequantize every eligible kernel,
    keeping f32 storage. The real path's dequantization must match this
    bitwise (pinned in tests) — the simulation IS the spec."""

    def _sim(n, w):
        if not _is_kernel(n, w):
            return w
        return _dequant_kernel(*_quant_kernel(w))

    return _walk(params, _sim)


def quantized_param_bytes(qparams: dict, scales: dict) -> int:
    """Actually-stored bytes of the real int8 tree (int8 kernels + f32
    scales + untouched f32 leaves) — what a chip would hold resident,
    for honest accounting next to ``DecodeCostModel._params_bytes``."""

    def _bytes(q, s):
        if isinstance(q, dict):
            return sum(_bytes(q[k], s[k]) for k in q)
        total = int(np.asarray(q).nbytes)
        if s is not None:
            total += int(np.asarray(s).nbytes)
        return total

    return _bytes(qparams, scales)
