"""FleetRouter: one FIFO queue fronting N serving-engine replicas.

PR 11's engine is one process, one decode batch. The fleet tier scales
it OUT: N replicas, each an unmodified :class:`ServingEngine`, behind a
single router that owns the waiting line and places each admit with the
same :class:`DecodeCostModel` the single engine prices admission with —
per replica, so a quantized replica (smaller param-byte term) honestly
prices cheaper and attracts load.

Two execution forms share this module's scheduling core:

- **Deterministic in-process form** (this file): every replica is an
  engine instance driven EXTERNALLY — the router owns the per-slot
  decode state the engine's ``run()`` loop normally keeps in locals,
  and advances all replicas on one global virtual clock
  (``engine.step_time_s`` is mandatory). A run is a pure function of
  (workload, config, kill script): the fleet event log re-serializes
  byte-for-byte, which is what the committed fixtures pin and what
  ``--fixture`` replays in CI without spawning anything.
- **Spawned form** (``fleet/drill.py``): the same replicas as real OS
  processes under :class:`ElasticController`, where SIGKILL is actual
  SIGKILL — the supervised e2e arm.

**Replica death is a membership event.** A kill drains the victim's
in-flight requests back into the queue as *continuations* — prompt =
original prompt + tokens generated so far, budget = what is still owed,
deadline still measured from the ORIGINAL arrival (PR 9 semantics:
partial tokens stay in the ledger) — merged into the line in
(arrival, rid) order, so an old request re-enters ahead of younger
arrivals (FIFO fairness survives the failure). Greedy decode makes the
continuation exact: the re-admitted prefill reconstructs the identical
K/V prefix, so a request's token stream is byte-identical to an
uninterrupted run's. The dead replica re-forms after
``reform_after_steps`` fleet steps with the PR 16 replan path consulted
(duck-typed ``replanner.replan(world, why=...)``, fail-open, receipts
recorded) before it takes traffic again.

Event log: tuples ``(kind, rid, replica, slot, step)`` with kinds
``admit / evict / reject / expire / defer`` (the engine's vocabulary,
plus the replica column) and the membership kinds ``kill / drain /
reform`` (rid/slot −1 where not applicable). ``spec`` never appears:
fleet × spec_k is a capability-table rejection (``serve_fleet_spec``).
"""

from __future__ import annotations

import binascii
import json
import math
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from tpudml.capabilities import reject
from tpudml.serve.engine import (
    RequestStats,
    ServeCompositionError,
    ServeConfig,
    ServingEngine,
)
from tpudml.serve.load import Request


@dataclass(frozen=True)
class FleetConfig:
    """Fleet shape: N replicas of one engine template — or a
    heterogeneous mix.

    ``engine.step_time_s`` is REQUIRED — the fleet advances every
    replica on one global virtual clock (one fleet step = one decode
    step on every live replica), which is what makes a 2×-overload run
    with a mid-run kill a pure function of (workload, config, kill
    script). ``max_queue`` bounds the router's single waiting line
    (the engine template's own ``max_queue`` is ignored: replicas never
    see a queue). ``reform_after_steps`` re-forms a killed replica that
    many fleet steps later (None: it stays dead).

    ``replica_engines`` makes the fleet heterogeneous: one
    :class:`ServeConfig` per replica (e.g. one ``weight_quant="int8"``
    replica among f32 ones). The template stays the ROUTER policy —
    clock (``step_time_s``), ``deadline_s``, ``eos_token`` — so every
    per-replica config must agree with it on ``step_time_s`` (one
    virtual clock) and each is priced by ITS OWN cost model: an int8
    replica's smaller param-byte term makes it honestly cheaper under
    cache-bound load, and the router's cheapest-feasible placement
    routes traffic there without any special-casing."""

    engine: ServeConfig
    replicas: int = 2
    max_queue: int | None = None
    reform_after_steps: int | None = None
    replica_engines: tuple | None = None

    def __post_init__(self):
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.engine.step_time_s is None:
            raise ValueError(
                "FleetConfig requires engine.step_time_s (the fleet "
                "schedules on the virtual clock; wall-clock replicas "
                "cannot replay deterministically)"
            )
        if self.engine.spec_k:
            reject("serve_fleet_spec", exc=ServeCompositionError)
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")
        if self.reform_after_steps is not None and self.reform_after_steps < 1:
            raise ValueError("reform_after_steps must be >= 1 (or None)")
        if self.replica_engines is not None:
            object.__setattr__(
                self, "replica_engines", tuple(self.replica_engines)
            )
            if len(self.replica_engines) != self.replicas:
                raise ValueError(
                    f"replica_engines has {len(self.replica_engines)} "
                    f"entries for {self.replicas} replicas"
                )
            for i, e in enumerate(self.replica_engines):
                if e.step_time_s != self.engine.step_time_s:
                    raise ValueError(
                        f"replica {i}: step_time_s {e.step_time_s} != "
                        f"template {self.engine.step_time_s} — the fleet "
                        "runs one virtual clock"
                    )
                if e.spec_k:
                    reject("serve_fleet_spec", exc=ServeCompositionError)

    def engine_for(self, i: int) -> ServeConfig:
        """Replica ``i``'s engine config (the template when the fleet is
        homogeneous)."""
        if self.replica_engines is not None:
            return self.replica_engines[i]
        return self.engine


@dataclass
class FleetRequestStats(RequestStats):
    """Per-request ledger across the whole fleet: the engine's fields
    plus which replicas served it. ``tokens``/``token_times`` span
    drains — partial tokens from a killed replica stay, continuation
    tokens append after re-admission."""

    replica: int | None = None  # last replica that held the request
    readmits: int = 0  # times drained off a killed replica and re-placed
    replicas_visited: list = field(default_factory=list)


class _Replica:
    """One engine instance plus the per-slot decode state the engine's
    ``run()`` keeps in locals — externalized so the router can stop,
    drain, and re-form the replica between any two steps."""

    def __init__(self, idx: int, model, params, ecfg: ServeConfig):
        self.idx = idx
        self.model = model
        self.eng = ServingEngine(model, params, ecfg)
        self.alive = True
        self.killed_at: int | None = None
        self.reformed_at: int | None = None
        self.decode_steps = 0
        self.busy_slot_steps = 0
        self._reset_slots()

    def _reset_slots(self):
        b = self.eng.cfg.slots
        self.last = np.zeros(b, np.int32)
        self.pos = np.zeros(b, np.int32)
        self.remaining = np.zeros(b, np.int64)
        self.slot_rid = np.full(b, -1, np.int64)
        self.slot_deadline = np.full(b, np.inf)
        self.active = np.zeros(b, bool)
        self.slot_req: list[Request | None] = [None] * b

    # ------------------------------------------------------------ state

    def n_active(self) -> int:
        return int(self.active.sum())

    def free_slot(self) -> int | None:
        for i in range(self.eng.cfg.slots):
            if not self.active[i]:
                return i
        return None

    def admit_price(self) -> float:
        """Predicted step seconds with one more tenant (0.0 without an
        SLO cost model — placement falls back to least-loaded)."""
        cost = self.eng._cost
        if cost is None:
            return float(self.n_active())
        return cost.step_seconds(self.n_active() + 1)

    def admit_ok(self) -> bool:
        cost = self.eng._cost
        return cost is None or cost.admit_ok(self.n_active())

    # ---------------------------------------------------------- actions

    def admit(self, slot: int, req: Request, st: FleetRequestStats,
              deadline_s: float | None) -> bool:
        """Prefill ``req`` into ``slot``; False iff the paged pool is
        starved (all-or-nothing: pool untouched, request stays queued)."""
        if self.eng._paged:
            admitted = self.eng._admit_paged(slot, req, st)
            if admitted is None:
                return False
        else:
            admitted = self.eng._admit(slot, req)
        self.pos[slot], self.last[slot] = admitted
        self.remaining[slot] = req.max_new_tokens
        self.slot_rid[slot] = req.rid
        self.slot_deadline[slot] = (
            req.arrival_time + deadline_s
            if deadline_s is not None else np.inf
        )
        self.active[slot] = True
        self.slot_req[slot] = req
        return True

    def decode(self) -> np.ndarray:
        """One jitted decode step over ALL slots; returns the emitted
        token per slot (inactive slots emit garbage — masked by the
        caller exactly as the engine's run loop does)."""
        eng = self.eng
        last_j, pos_j = jnp.asarray(self.last), jnp.asarray(self.pos)
        if eng._paged:
            next_t, _, eng.caches = eng._decode(
                eng.params, eng.caches, jnp.asarray(eng._table),
                last_j, pos_j,
            )
        else:
            next_t, _, eng.caches = eng._decode(
                eng.params, eng.caches, last_j, pos_j
            )
        self.decode_steps += 1
        self.busy_slot_steps += self.n_active()
        return np.asarray(jax.device_get(next_t))

    def release(self, slot: int):
        self.eng._release_slot(slot)
        self.slot_rid[slot] = -1
        self.active[slot] = False
        self.slot_req[slot] = None

    def kill(self, step: int) -> list[Request]:
        """SIGKILL semantics: mark dead and hand back the in-flight
        requests for the router to drain. Cache contents are garbage
        from here until :meth:`reform` reinitializes them."""
        self.alive = False
        self.killed_at = step
        victims = [r for r in self.slot_req if r is not None]
        self._reset_slots()
        return victims

    def reform(self, step: int):
        """Re-form in place: fresh caches + allocator, SAME compiled
        programs (re-jitting per reform would recompile for nothing —
        the weights never changed)."""
        eng, cfg = self.eng, self.eng.cfg
        if eng._paged:
            eng.caches = self.model.init_paged_cache(
                cfg.total_pages, cfg.page_size, cfg.cache_kind
            )
            from tpudml.serve.paged import PagePool

            eng._pool = PagePool(
                cfg.total_pages, cfg.page_size, cfg.prefix_sharing
            )
            eng._table = np.zeros((cfg.slots, cfg.max_pages), np.int32)
            eng._slot_pages = [[] for _ in range(cfg.slots)]
        else:
            eng.caches = self.model.init_decode_cache(
                cfg.slots, cfg.max_len, cfg.cache_kind
            )
        self._reset_slots()
        self.alive = True
        self.reformed_at = step


@dataclass
class FleetReport:
    """One fleet run's outcome: the per-request ledger, the
    byte-deterministic event log, and per-replica aggregates."""

    requests: dict
    events: list  # (kind, rid, replica, slot, step)
    steps: int
    wall_time: float
    replicas: int
    peak_queue_depth: int = 0
    queue_depth: list = field(default_factory=list)  # (step, depth) samples
    per_replica: list = field(default_factory=list)
    replans: list = field(default_factory=list)

    @property
    def generated_tokens(self) -> int:
        return sum(len(s.tokens) for s in self.requests.values())

    @property
    def rejected(self) -> int:
        return sum(1 for s in self.requests.values() if s.rejected is not None)

    @property
    def expired(self) -> int:
        return sum(1 for s in self.requests.values() if s.expired is not None)

    @property
    def finished(self) -> int:
        return sum(1 for s in self.requests.values() if s.finished is not None)

    @property
    def drains(self) -> int:
        return sum(1 for e in self.events if e[0] == "drain")

    @property
    def kills(self) -> int:
        return sum(1 for e in self.events if e[0] == "kill")

    @property
    def tokens_per_sec(self) -> float:
        return self.generated_tokens / max(self.wall_time, 1e-9)

    def canonical_events(self) -> str:
        """The determinism contract: the event log as sorted canonical
        JSON (same serialization rules as ``obs.tracer.dump_trace``) —
        two runs of the same (workload, config, kill script) must
        produce this string byte-for-byte, and the committed fixtures
        pin its CRC."""
        doc = {"fleet_events": [list(e) for e in self.events]}
        return json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n"

    def events_crc32(self) -> int:
        return binascii.crc32(self.canonical_events().encode()) & 0xFFFFFFFF

    def latency_summary(self) -> dict:
        """p50/p99 over FINISHED requests: ttft (arrival → first token),
        per-token cadence (consecutive token-timestamp gaps WITHIN a
        request — the admission gap is excluded because a drained
        request's re-admission would otherwise produce a negative
        seed gap), end-to-end latency."""
        gaps, e2e, ttft = [], [], []
        for s in self.requests.values():
            if s.finished is None:
                continue
            ts = s.token_times
            gaps += [b - a for a, b in zip(ts, ts[1:])]
            e2e.append(s.finished - s.arrival)
            ttft.append(s.first_token - s.arrival)

        def pct(xs, q):
            return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")

        return {
            "per_token_p50_s": pct(gaps, 50),
            "per_token_p99_s": pct(gaps, 99),
            "e2e_p50_s": pct(e2e, 50),
            "e2e_p99_s": pct(e2e, 99),
            "ttft_p50_s": pct(ttft, 50),
            "ttft_p99_s": pct(ttft, 99),
        }

    def to_trace_docs(self, step_time_s: float | None = None) -> list[dict]:
        """Per-replica Chrome trace documents (pid = replica index, the
        engine's slot/queue tracks via ``obs.convert``) plus a router
        document (pid = ``replicas``) carrying queue-depth samples and
        the membership instants (kill/drain/reform) — ready for
        ``merge_chrome_traces``."""
        from tpudml.obs.convert import serve_trace_events
        from tpudml.obs.tracer import chrome_trace_doc

        engine_kinds = ("admit", "evict", "reject", "expire", "defer")
        docs = []
        for r in range(self.replicas):
            ev = [
                (k, rid, slot, step)
                for (k, rid, rep, slot, step) in self.events
                if rep == r and k in engine_kinds
            ]
            docs.append(
                chrome_trace_doc(
                    serve_trace_events(ev, step_time_s=step_time_s), pid=r
                )
            )

        def ts_us(step):
            if step_time_s is None:
                return int(step)
            return int(round(step * step_time_s * 1e6))

        router_events = []
        for step, depth in self.queue_depth:
            router_events.append({
                "name": "queue_depth", "cat": "fleet", "ph": "i",
                "ts": ts_us(step), "tid": 0, "s": "t",
                "args": {"depth": depth, "step": step},
            })
        for kind, rid, rep, slot, step in self.events:
            if kind in engine_kinds and kind != "defer":
                continue  # replica-track events; defer is router-side too
            router_events.append({
                "name": kind, "cat": "fleet", "ph": "i",
                "ts": ts_us(step), "tid": 1, "s": "t",
                "args": {"rid": rid, "replica": rep, "step": step},
            })
        docs.append(chrome_trace_doc(router_events, pid=self.replicas))
        return docs

    def to_dict(self) -> dict:
        """JSON-ready summary (``fleet.json`` — what ``tools/
        obs_report.py``'s fleet section reads)."""
        return {
            "replicas": self.replicas,
            "steps": self.steps,
            "wall_time_s": self.wall_time,
            "generated_tokens": self.generated_tokens,
            "tokens_per_sec": self.tokens_per_sec,
            "finished": self.finished,
            "rejected": self.rejected,
            "expired": self.expired,
            "kills": self.kills,
            "drains": self.drains,
            "readmits": sum(s.readmits for s in self.requests.values()),
            "peak_queue_depth": self.peak_queue_depth,
            "events_crc32": self.events_crc32(),
            "latency": self.latency_summary(),
            "per_replica": self.per_replica,
            "replans": self.replans,
        }


class FleetRouter:
    """The deterministic in-process fleet: N externally-driven engine
    replicas behind one FIFO line — see the module docstring.

    ``replanner`` is the PR 16 duck-typed hook: on every re-form the
    router calls ``replanner.replan(live_world, why=...)`` and records
    the decision (fail-open — a raising replanner never blocks the
    re-form, mirroring ``ElasticController``)."""

    def __init__(self, model, params, cfg: FleetConfig, *, replanner=None):
        self.cfg = cfg
        self.model = model
        self.replanner = replanner
        self.replicas = [
            _Replica(i, model, params, cfg.engine_for(i))
            for i in range(cfg.replicas)
        ]

    # ------------------------------------------------------------- run

    def run(self, requests: list[Request],
            kills: list[tuple[int, int]] | None = None) -> FleetReport:
        """Serve ``requests`` to completion across the fleet.

        ``kills`` is the scripted failure injection: ``(step, replica)``
        pairs — at the START of fleet step ``step`` the replica is
        killed (drain → re-queue → eventual re-form). Every request ends
        in exactly one terminal state (finished / rejected / expired),
        with Σ tokens conserved across any number of drains — the
        exact-accounting invariant the fleet tests audit.
        """
        cfg = self.cfg
        ecfg = cfg.engine
        step_time = ecfg.step_time_s
        kill_script: dict[int, list[int]] = {}
        for step, rep in kills or ():
            if not 0 <= rep < cfg.replicas:
                raise ValueError(f"kill targets unknown replica {rep}")
            kill_script.setdefault(int(step), []).append(int(rep))
        arrivals = deque(
            sorted(requests, key=lambda r: (r.arrival_time, r.rid))
        )
        queue: deque[Request] = deque()
        stats = {
            r.rid: FleetRequestStats(
                rid=r.rid, prompt_len=len(r.prompt),
                max_new_tokens=r.max_new_tokens, arrival=r.arrival_time,
            )
            for r in requests
        }
        if len(stats) != len(requests):
            raise ValueError("duplicate request ids")
        base_prompt = {r.rid: np.asarray(r.prompt, np.int32) for r in requests}

        events: list = []
        replans: list = []
        queue_depth: list = []
        steps = 0
        peak_queue = 0
        v_extra = 0.0
        deferred_logged: set[int] = set()
        pending_reforms: list[tuple[int, int]] = []  # (due_step, replica)

        def now():
            return steps * step_time + v_extra

        def any_active():
            return any(r.alive and r.active.any() for r in self.replicas)

        def live():
            return [r for r in self.replicas if r.alive]

        while arrivals or queue or any_active() or pending_reforms:
            t = now()
            # --- membership: scripted kills fire at the step boundary.
            for rep_idx in kill_script.pop(steps, ()):
                rep = self.replicas[rep_idx]
                if not rep.alive:
                    continue
                victims = rep.kill(steps)
                events.append(("kill", -1, rep_idx, -1, steps))
                drained: list[Request] = []
                for req in victims:
                    st = stats[req.rid]
                    slot = st.slot if st.slot is not None else -1
                    events.append(("drain", req.rid, rep_idx, slot, steps))
                    owed = st.max_new_tokens - len(st.tokens)
                    if owed <= 0:
                        # The kill landed exactly on the finish boundary;
                        # nothing left to serve.
                        st.finished = t
                        continue
                    cont = Request(
                        rid=req.rid,
                        prompt=np.concatenate([
                            base_prompt[req.rid],
                            np.asarray(st.tokens, np.int32),
                        ]),
                        max_new_tokens=owed,
                        arrival_time=st.arrival,
                    )
                    st.readmits += 1
                    st.slot = None
                    drained.append(cont)
                if drained:
                    # Merge by (arrival, rid): drained requests are the
                    # oldest admits, so they re-enter at the line's head
                    # — FIFO fairness survives the failure.
                    queue = deque(sorted(
                        drained + list(queue),
                        key=lambda r: (r.arrival_time, r.rid),
                    ))
                if cfg.reform_after_steps is not None:
                    pending_reforms.append(
                        (steps + cfg.reform_after_steps, rep_idx)
                    )
            # --- membership: due re-forms rejoin before admission.
            still_pending = []
            for due, rep_idx in pending_reforms:
                if steps < due:
                    still_pending.append((due, rep_idx))
                    continue
                rep = self.replicas[rep_idx]
                rep.reform(steps)
                events.append(("reform", -1, rep_idx, -1, steps))
                world = len(live())
                if self.replanner is not None:
                    receipt = {
                        "step": steps, "replica": rep_idx, "world": world,
                        "why": f"fleet-reform replica {rep_idx}",
                    }
                    try:
                        decision = self.replanner.replan(
                            world, why=receipt["why"]
                        )
                        if hasattr(decision, "to_dict"):
                            receipt["decision"] = decision.to_dict()
                        elif isinstance(decision, dict):
                            receipt["decision"] = decision
                        else:
                            receipt["decision"] = repr(decision)
                    except Exception as e:  # fail-open, like the controller
                        receipt["error"] = f"{type(e).__name__}: {e}"
                    replans.append(receipt)
            pending_reforms = still_pending
            # --- stage arrivals; a full fleet line rejects at the door.
            while arrivals and arrivals[0].arrival_time <= t:
                req = arrivals.popleft()
                if cfg.max_queue is not None and len(queue) >= cfg.max_queue:
                    stats[req.rid].rejected = t
                    events.append(("reject", req.rid, -1, -1, steps))
                else:
                    queue.append(req)
            peak_queue = max(peak_queue, len(queue))
            # --- expire queued requests strictly past their deadline.
            if ecfg.deadline_s is not None:
                kept: deque[Request] = deque()
                while queue:
                    req = queue.popleft()
                    if t > req.arrival_time + ecfg.deadline_s:
                        stats[req.rid].expired = t
                        events.append(("expire", req.rid, -1, -1, steps))
                    else:
                        kept.append(req)
                queue = kept
            # --- placement: head-of-line only (FIFO — nothing behind the
            # head may overtake). Each candidate replica is priced with
            # ITS cost model; cheapest feasible wins, index tie-break.
            while queue:
                req = queue[0]
                candidates = []
                for rep in live():
                    slot = rep.free_slot()
                    if slot is None:
                        continue
                    if not rep.admit_ok():
                        continue
                    candidates.append((rep.admit_price(), rep.idx, rep, slot))
                if not candidates:
                    if (
                        any(rep.free_slot() is not None for rep in live())
                        and req.rid not in deferred_logged
                    ):
                        # Free capacity exists but every priced replica
                        # defers — the SLO is the binding constraint.
                        deferred_logged.add(req.rid)
                        events.append(("defer", req.rid, -1, -1, steps))
                    break
                candidates.sort(key=lambda c: (c[0], c[1]))
                st = stats[req.rid]
                st.admit_start = now()
                placed = False
                starved = 0
                for price, rep_idx, rep, slot in candidates:
                    if rep.admit(slot, req, st, ecfg.deadline_s):
                        placed = True
                        break
                    starved += 1
                if not placed:
                    if starved == len(candidates) and not any_active():
                        raise ValueError(
                            f"request {req.rid} needs more pages than any "
                            f"replica's pool can ever supply"
                        )
                    if req.rid not in deferred_logged:
                        deferred_logged.add(req.rid)
                        events.append(("defer", req.rid, -1, -1, steps))
                    break
                queue.popleft()
                st.admitted = now()
                st.slot = slot
                st.replica = rep.idx
                st.replicas_visited.append(rep.idx)
                events.append(("admit", req.rid, rep.idx, slot, steps))
            queue_depth.append((steps, len(queue)))
            if not any_active():
                if not arrivals and not queue:
                    # Everything is served (a still-pending re-form
                    # nobody needs is not worth spinning for) — the
                    # loop condition exits.
                    pending_reforms = []
                    continue
                if pending_reforms:
                    # Idle but a re-form is due in a known number of
                    # steps: burn virtual steps toward it (queued work
                    # can expire on the way — deadlines keep ticking).
                    steps += 1
                    continue
                if arrivals:
                    gap = arrivals[0].arrival_time - now()
                    v_extra += max(gap, 0.0)
                    continue
                # Queue non-empty, fleet idle, nothing coming: with any
                # live replica the head must have been admissible (SLO
                # admits from idle; total pool starvation raised above).
                raise ValueError(
                    "fleet has queued work but no live replica and no "
                    "re-form scheduled (kill script killed everything "
                    "with reform_after_steps=None)"
                )
            # --- one decode step on every live replica with tenants.
            t_step = (steps + 1) * step_time + v_extra
            for rep in live():
                if not rep.active.any():
                    continue
                emitted = rep.decode()
                for i in range(rep.eng.cfg.slots):
                    if not rep.active[i]:
                        continue
                    st = stats[rep.slot_rid[i]]
                    tok = int(emitted[i])
                    st.tokens.append(tok)
                    st.token_times.append(t_step)
                    if st.first_token is None:
                        st.first_token = t_step
                    rep.pos[i] += 1
                    rep.last[i] = tok
                    rep.remaining[i] -= 1
                    done = rep.remaining[i] <= 0 or (
                        ecfg.eos_token is not None and tok == ecfg.eos_token
                    )
                    if done:
                        st.finished = t_step
                        events.append(
                            ("evict", int(rep.slot_rid[i]), rep.idx, i,
                             steps + 1)
                        )
                        rep.release(i)
                    elif t_step > rep.slot_deadline[i]:
                        st.expired = t_step
                        events.append(
                            ("expire", int(rep.slot_rid[i]), rep.idx, i,
                             steps + 1)
                        )
                        rep.release(i)
            steps += 1

        per_replica = []
        for rep in self.replicas:
            row = {
                "replica": rep.idx,
                "decode_steps": rep.decode_steps,
                "busy_slot_steps": rep.busy_slot_steps,
                "slots": rep.eng.cfg.slots,
                "killed_at": rep.killed_at,
                "reformed_at": rep.reformed_at,
            }
            if rep.eng._pool is not None:
                row["pool"] = {
                    "prefix_hits": rep.eng._pool.prefix_hits,
                    "pages_reused": rep.eng._pool.pages_reused,
                }
            per_replica.append(row)
        return FleetReport(
            requests=stats, events=events, steps=steps, wall_time=now(),
            replicas=cfg.replicas, peak_queue_depth=peak_queue,
            queue_depth=queue_depth, per_replica=per_replica,
            replans=replans,
        )


# --------------------------------------------------------------- fixtures

FLEET_FIXTURE_VERSION = 1


def replay_fleet_fixture(fixture: dict, sink=None) -> dict:
    """Meshless CI replay (the fleet twin of ``tpudml.elastic``'s
    ``replay_fixture``): rebuild the fleet from the fixture's config,
    run the recorded workload + kill script on the virtual clock — no
    processes spawned — and verify the event log's CRC and the token
    accounting against the fixture's expectations.

    Fixture schema (version 1)::

        {"version": 1,
         "model":    {"vocab_size": ..., "embed_dim": ..., ...},
         "workload": {"n": ..., "qps": ..., "seed": ...,
                      "prompt_len": [lo, hi], "new_tokens": [lo, hi]},
         "fleet":    {"replicas": ..., "max_queue": ...,
                      "reform_after_steps": ...,
                      "engine": {ServeConfig kwargs}},
         "kills":    [[step, replica], ...],
         "expect":   {"events_crc32": ..., "generated_tokens": ...,
                      "finished": ..., "drains": ...}}

    The expectations are platform-portable on purpose: the event log and
    token COUNTS depend only on prompt lengths, budgets, and the
    scheduler (host arithmetic) — never on model weights — so the same
    fixture passes on CPU and TPU alike.
    """
    if fixture.get("version") != FLEET_FIXTURE_VERSION:
        raise ValueError(
            f"fixture version {fixture.get('version')!r} != "
            f"{FLEET_FIXTURE_VERSION}"
        )

    def log(msg):
        if sink is not None:
            print(msg, file=sink)

    from tpudml.models.transformer import TransformerLM
    from tpudml.serve.load import poisson_workload

    mspec = dict(fixture["model"])
    model = TransformerLM(**mspec)
    params = model.init(jax.random.PRNGKey(int(fixture.get("seed", 0))))[0]
    w = dict(fixture["workload"])
    requests, _ = poisson_workload(
        w["n"], w["qps"], w.get("seed", 0),
        vocab_size=mspec.get("vocab_size", 64),
        prompt_len=tuple(w.get("prompt_len", (4, 8))),
        new_tokens=tuple(w.get("new_tokens", (4, 8))),
    )
    f = dict(fixture["fleet"])
    cfg = FleetConfig(
        engine=ServeConfig(**f.get("engine", {})),
        replicas=f.get("replicas", 2),
        max_queue=f.get("max_queue"),
        reform_after_steps=f.get("reform_after_steps"),
    )
    kills = [tuple(k) for k in fixture.get("kills", ())]
    log(f"[fleet-fixture] replicas={cfg.replicas} requests={len(requests)} "
        f"kills={kills}")
    report = FleetRouter(model, params, cfg).run(requests, kills=kills)
    expect = fixture.get("expect", {})
    got = {
        "events_crc32": report.events_crc32(),
        "generated_tokens": report.generated_tokens,
        "finished": report.finished,
        "drains": report.drains,
    }
    mismatches = {
        k: {"expected": expect[k], "got": got[k]}
        for k in expect
        if k in got and got[k] != expect[k]
    }
    # Accounting invariants hold in every fixture, expected or not:
    # a finished request got EXACTLY its owed tokens, however many
    # drains interrupted it (fixtures never set eos_token).
    conserved = all(
        st.finished is None or len(st.tokens) == st.max_new_tokens
        for st in report.requests.values()
    )
    terminal = all(
        sum(x is not None for x in (st.finished, st.rejected, st.expired)) == 1
        or (st.finished is None and st.rejected is None
            and st.expired is None and not st.tokens)
        for st in report.requests.values()
    )
    ok = not mismatches and conserved and terminal
    for k, m in mismatches.items():
        log(f"[fleet-fixture] MISMATCH {k}: expected {m['expected']}, "
            f"got {m['got']}")
    return {
        "ok": ok,
        "mismatches": mismatches,
        "kills": len(kills),
        "replicas": cfg.replicas,
        **got,
    }
