"""Serving fleet: multi-replica routing, disaggregated prefill/decode,
and int8 weight quantization under elastic supervision.

The scale-out layer over ``tpudml.serve``'s single engine (ROADMAP
item 3): a :class:`FleetRouter` fronts N replicas behind one FIFO line
with per-replica SLO pricing, treats replica death as a membership
event (drain → re-admit with partial tokens kept → supervised re-form
with the replan path consulted), hands KV pages between prefill- and
decode-specialist replicas through the CRC-verified checkpoint format
(``fleet.disagg``), and quantizes decode weights to int8 with the
cache's ``_sim`` oracle discipline (``fleet.quant``).

Two execution forms: the deterministic in-process router (fixture-
replayable in CI: ``python -m tpudml.serve.fleet --fixture``) and the
spawned drill under :class:`ElasticController`
(``python -m tpudml.serve.fleet --drill`` — the ``slow``-marked e2e).
"""

from tpudml.serve.fleet.disagg import adopt_handoff, write_handoff
from tpudml.serve.fleet.quant import (
    dequantize_params,
    quantize_params,
    quantized_param_bytes,
    sim_quantize_params,
)
from tpudml.serve.fleet.router import (
    FLEET_FIXTURE_VERSION,
    FleetConfig,
    FleetReport,
    FleetRequestStats,
    FleetRouter,
    replay_fleet_fixture,
)


def __getattr__(name):
    # Lazy: the drill imports the controller/launcher stack, which the
    # router-only (and child) paths never need.
    if name == "run_fleet_drill":
        from tpudml.serve.fleet.drill import run_fleet_drill

        return run_fleet_drill
    raise AttributeError(name)


__all__ = [
    "FLEET_FIXTURE_VERSION",
    "FleetConfig",
    "FleetReport",
    "FleetRequestStats",
    "FleetRouter",
    "adopt_handoff",
    "dequantize_params",
    "quantize_params",
    "quantized_param_bytes",
    "replay_fleet_fixture",
    "run_fleet_drill",
    "sim_quantize_params",
    "write_handoff",
]
