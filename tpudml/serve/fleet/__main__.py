"""CLI: ``python -m tpudml.serve.fleet`` — fleet drills + fixture replay.

Modes (exit 0 iff the verdict holds, mirroring ``tpudml.elastic``):

- fixture replay (meshless CI mode: no processes spawned — the
  deterministic router re-runs the recorded workload + kill script and
  checks the event-log CRC and token accounting)::

    JAX_PLATFORMS=cpu python -m tpudml.serve.fleet \
        --fixture tests/fleet_fixtures/kill_drain.json

- spawned fleet drill (replica children under ElasticController, one
  SIGKILLed mid-serve; tokens must match an uninterrupted reference)::

    JAX_PLATFORMS=cpu python -m tpudml.serve.fleet --drill

- replica child (spawned by the controller, not by hand)::

    python -m tpudml.serve.fleet --child --dir D --rank R --world W ...
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpudml.serve.fleet")
    p.add_argument("--fixture", type=str, default=None,
                   help="replay a committed fleet fixture through the "
                        "deterministic router (no processes, no mesh)")
    p.add_argument("--drill", action="store_true",
                   help="spawned fleet drill: replica children under "
                        "ElasticController, one SIGKILLed mid-serve")
    p.add_argument("--child", action="store_true",
                   help=argparse.SUPPRESS)  # controller-spawned only
    p.add_argument("--dir", type=str, default=None)
    p.add_argument("--rank", type=int, default=0)
    p.add_argument("--world", type=int, default=2)
    p.add_argument("--kill_rank", type=int, default=1)
    p.add_argument("--requests", type=int, default=10)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout_s", type=float, default=300.0)
    args = p.parse_args(argv)

    if args.fixture:
        from tpudml.serve.fleet.router import replay_fleet_fixture

        with open(args.fixture) as f:
            fixture = json.load(f)
        report = replay_fleet_fixture(fixture, sink=sys.stderr)
        print(json.dumps(report, sort_keys=True))
        return 0 if report["ok"] else 1

    if args.child:
        if args.dir is None:
            p.error("--child requires --dir")
        from tpudml.serve.fleet.drill import child_main

        return child_main(args)

    if args.drill:
        from tpudml.serve.fleet.drill import run_fleet_drill

        base = args.dir or tempfile.mkdtemp(prefix="tpudml_fleet_")
        report = run_fleet_drill(
            base, world=args.world, requests=args.requests,
            kill_rank=args.kill_rank, seed=args.seed,
            timeout_s=args.timeout_s, sink=sys.stderr,
        )
        print(json.dumps(report, sort_keys=True))
        return 0 if report["ok"] else 1

    p.error("pick a mode: --fixture FILE.json | --drill | --child")


if __name__ == "__main__":
    sys.exit(main())
