"""The fleet's spawned e2e: serving replicas as real OS processes under
ElasticController, one of them SIGKILLed mid-serve.

The deterministic router (fleet/router.py) proves the *scheduling*
story in-process; this drill proves the *supervision* story with real
processes: N replica children each serve a fixed workload shard, the
designated victim SIGKILLs itself after serving half its shard (marker-
file gated, so it dies exactly once), the controller contains the round
and re-forms, and the re-formed incarnation serves the full shard. The
verdict is the fleet analogue of the elastic drill's bit-exactness
gate: every replica's final token CRC must equal an uninterrupted
in-process reference run of the same shard — decode is a pure function
of (seed, shard, config), so SIGKILL-grade death must be invisible in
the tokens.

Artifacts land in the drill dir for ``tools/obs_report.py``:
``result_r{rank}.json`` (per-replica verdict inputs), ``trace_r{rank}.
json`` (per-replica serve trace, pid = rank), ``trace_fleet.json``
(the ``merge_chrome_traces`` union — one pid track per replica),
``fleet.json`` (the drill report), ``elastic.json`` (the controller's
reform history).

Run via ``python -m tpudml.serve.fleet --drill`` or the ``slow``-marked
test; the child entrypoint is ``python -m tpudml.serve.fleet --child``
(spawned by the controller, never by hand).
"""

from __future__ import annotations

import binascii
import json
import os
import signal
import sys
import tempfile
from pathlib import Path

import jax
import numpy as np

# One model/workload shape shared by children, parent reference, and the
# bench fleet smoke — small enough that each child compiles in seconds.
MODEL_KW = dict(vocab_size=48, embed_dim=32, num_heads=4,
                num_kv_heads=2, num_layers=2, max_len=64)
SERVE_KW = dict(slots=2, max_len=64, prefill_chunk=8, step_time_s=0.01)


def _model_and_params(seed: int):
    from tpudml.models.transformer import TransformerLM

    model = TransformerLM(**MODEL_KW)
    params = model.init(jax.random.PRNGKey(seed))[0]
    return model, params


def _workload(n: int, seed: int):
    from tpudml.serve.load import poisson_workload

    requests, _ = poisson_workload(
        n, 200.0, seed, vocab_size=MODEL_KW["vocab_size"],
        prompt_len=(4, 10), new_tokens=(4, 8),
    )
    return requests


def _shard(requests, rank: int, world: int):
    return [r for r in requests if r.rid % world == rank]


def _tokens_crc(report) -> int:
    doc = {
        str(rid): list(st.tokens)
        for rid, st in sorted(report.requests.items())
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return binascii.crc32(blob.encode()) & 0xFFFFFFFF


def _serve_shard(rank: int, world: int, n_requests: int, seed: int,
                 requests=None):
    from tpudml.serve.engine import ServeConfig, ServingEngine

    model, params = _model_and_params(seed)
    if requests is None:
        requests = _workload(n_requests, seed)
    shard = _shard(requests, rank, world)
    eng = ServingEngine(model, params, ServeConfig(**SERVE_KW))
    return eng.run(shard), shard


def child_main(args) -> int:
    """Replica child body (``python -m tpudml.serve.fleet --child``).

    Serves its rid-modulo shard and writes ``result_r{rank}.json`` +
    ``trace_r{rank}.json`` atomically. The victim rank SIGKILLs itself
    after a half-shard warmup run the first time through (the marker
    file is the "already died once" latch — written BEFORE the kill, so
    the re-formed incarnation runs to completion)."""
    base = Path(args.dir)
    rank, world = args.rank, args.world
    marker = base / "killed.marker"
    if rank == args.kill_rank and not marker.exists():
        # Mid-run death: serve half the shard so real decode state is
        # live when the SIGKILL lands, then die without cleanup.
        requests = _workload(args.requests, args.seed)
        shard = _shard(requests, rank, world)
        half = shard[: max(1, len(shard) // 2)]
        from tpudml.serve.engine import ServeConfig, ServingEngine

        model, params = _model_and_params(args.seed)
        ServingEngine(model, params, ServeConfig(**SERVE_KW)).run(half)
        marker.write_text(f"rank {rank} died once\n")
        os.kill(os.getpid(), signal.SIGKILL)
    report, shard = _serve_shard(rank, world, args.requests, args.seed)
    result = {
        "rank": rank,
        "world": world,
        "round": os.environ.get("TPUDML_ELASTIC_ROUND"),
        "requests": len(shard),
        "generated_tokens": report.generated_tokens,
        "tokens_crc": _tokens_crc(report),
        "decode_steps": report.decode_steps,
    }
    from tpudml.obs.convert import write_serve_trace

    write_serve_trace(
        report, base / f"trace_r{rank}.json",
        step_time_s=SERVE_KW["step_time_s"], pid=rank,
    )
    tmp = base / f".result_r{rank}.tmp"
    tmp.write_text(json.dumps(result, sort_keys=True))
    os.replace(tmp, base / f"result_r{rank}.json")
    print(f"[fleet-child] rank {rank}/{world} requests={len(shard)} "
          f"tokens_crc={result['tokens_crc']:08x}", file=sys.stderr)
    return 0


def run_fleet_drill(base_dir=None, *, world: int = 2, requests: int = 10,
                    kill_rank: int = 1, seed: int = 0,
                    timeout_s: float = 300.0, backoff_s: float = 0.25,
                    sink=None) -> dict:
    """Spawn the replica fleet under ElasticController, let the victim
    die, verify the re-formed fleet's tokens against an uninterrupted
    in-process reference, and merge the per-replica traces."""
    from tpudml.elastic.controller import ElasticController
    from tpudml.launch.cluster import ClusterSpec
    from tpudml.obs.tracer import dump_trace, merge_chrome_traces

    base = Path(base_dir or tempfile.mkdtemp(prefix="tpudml_fleet_"))
    base.mkdir(parents=True, exist_ok=True)
    cmd = [
        sys.executable, "-m", "tpudml.serve.fleet", "--child",
        "--dir", str(base), "--rank", "{rank}", "--world", "{world}",
        "--kill_rank", str(kill_rank), "--requests", str(requests),
        "--seed", str(seed),
    ]
    spec = ClusterSpec(
        num_processes=world, platform="cpu", timeout_s=timeout_s,
        restart_backoff_s=backoff_s, restart_backoff_seed=seed,
    )
    ctrl = ElasticController(
        cmd, spec, policy="restart", max_reforms=2, sink=sink,
    )
    res = ctrl.run()
    (base / "elastic.json").write_text(
        json.dumps(res.to_dict(), sort_keys=True, indent=2)
    )
    # Uninterrupted reference, in-process: per-rank expected token CRCs.
    reference = _workload(requests, seed)
    expected = {}
    for r in range(world):
        ref_report, _ = _serve_shard(r, world, requests, seed,
                                     requests=reference)
        expected[r] = _tokens_crc(ref_report)
    ranks = {}
    crc_ok = True
    for r in range(world):
        path = base / f"result_r{r}.json"
        if not path.is_file():
            ranks[r] = {"error": "missing result"}
            crc_ok = False
            continue
        row = json.loads(path.read_text())
        row["expected_crc"] = expected[r]
        row["match"] = row.get("tokens_crc") == expected[r]
        crc_ok = crc_ok and row["match"]
        ranks[r] = row
    # Merged fleet trace: one pid track per replica (latest incarnation
    # wins — each child overwrites its own trace file).
    docs = []
    for r in range(world):
        tpath = base / f"trace_r{r}.json"
        if tpath.is_file():
            docs.append(json.loads(tpath.read_text()))
    merged_path = None
    if docs:
        merged = merge_chrome_traces(docs)
        merged_path = base / "trace_fleet.json"
        merged_path.write_text(dump_trace(merged))
    report = {
        "ok": bool(res.success and crc_ok and res.reforms >= 1),
        "world": world,
        "reforms": res.reforms,
        "stop_reason": res.stop_reason,
        "crc_ok": crc_ok,
        "ranks": {str(r): ranks[r] for r in ranks},
        "merged_trace": str(merged_path) if merged_path else None,
        "dir": str(base),
    }
    (base / "fleet.json").write_text(
        json.dumps(report, sort_keys=True, indent=2)
    )
    return report
