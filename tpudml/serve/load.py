"""Seeded synthetic request streams for the serving engine.

A Poisson process (exponential inter-arrival gaps at a given QPS) with
per-request prompt/output lengths drawn uniformly from closed ranges —
the standard open-loop serving-benchmark shape: arrival times are fixed
by the seed BEFORE the run, so a slow engine accumulates queue depth
instead of back-pressuring the generator (that is what makes p99 honest).

Everything is ``numpy.random.default_rng(seed)``-driven — the same seed
reproduces the same workload bit-for-bit, and the returned ledger
records what every request is owed so tests can audit the engine's
per-request token accounting against it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np


@dataclass
class Request:
    """One serving request: a prompt and a generation budget."""

    rid: int
    prompt: np.ndarray  # [L] int32 token ids, L >= 1
    max_new_tokens: int
    arrival_time: float = 0.0  # seconds from stream start


def poisson_workload(
    n_requests: int,
    qps: float,
    seed: int,
    *,
    vocab_size: int,
    prompt_len: tuple[int, int] = (4, 16),
    new_tokens: tuple[int, int] = (4, 16),
) -> tuple[list[Request], dict[int, dict]]:
    """Build ``n_requests`` requests arriving as a Poisson process at
    ``qps`` (``math.inf`` → everything arrives at t=0, the deterministic
    scheduler-test regime). Lengths are uniform over the inclusive
    ranges. Returns ``(requests, ledger)`` where ``ledger[rid]`` records
    the exact prompt length and owed token count."""
    if n_requests < 1:
        raise ValueError("n_requests must be >= 1")
    if prompt_len[0] < 1:
        raise ValueError("prompts must have at least 1 token")
    if new_tokens[0] < 1:
        raise ValueError("each request must generate at least 1 token")
    rng = np.random.default_rng(seed)
    if math.isinf(qps):
        arrivals = np.zeros(n_requests)
    else:
        arrivals = np.cumsum(rng.exponential(1.0 / qps, n_requests))
    plens = rng.integers(prompt_len[0], prompt_len[1] + 1, n_requests)
    olens = rng.integers(new_tokens[0], new_tokens[1] + 1, n_requests)
    requests, ledger = [], {}
    for i in range(n_requests):
        prompt = rng.integers(0, vocab_size, int(plens[i])).astype(np.int32)
        requests.append(Request(
            rid=i,
            prompt=prompt,
            max_new_tokens=int(olens[i]),
            arrival_time=float(arrivals[i]),
        ))
        ledger[i] = {
            "prompt_len": int(plens[i]),
            "max_new_tokens": int(olens[i]),
            "arrival_time": float(arrivals[i]),
            # Per-request latency outcomes, filled by
            # ServeReport.annotate_ledger after a run (None = the
            # request never reached that milestone). Previously these
            # were derivable only by replaying the event log.
            "ttft_s": None,
            "tpot_s": None,
        }
    return requests, ledger
