"""Paged KV cache: a fixed page pool + slot→page table, with prefix
sharing.

The dense cache (``serve.cache``) reserves ``max_len`` rows per slot, so
one long request strands HBM that could hold many short ones. Here the
HBM is a single pool of ``[num_pages, page_size, kv_heads, head_dim]``
pages per layer, and each slot maps at most ``max_pages`` of them
through a static-shape ``[slots, max_pages]`` int32 page table that is
an ordinary traced argument of the ONE jitted decode step:

- **read**: gather the slot's table rows from the pool
  (``pool[table] → [slots, max_pages, page_size, ...]``), flatten to a
  ``[slots, max_pages·page_size]`` key window, and mask by the flat
  position exactly like the dense path (``k_pos <= pos``). Attention
  cost scales with per-slot capacity, never with pool size — a decode
  step that instead materializes the whole pool per token is what
  analysis rule J117 flags.
- **write**: scatter the step's new K/V rows to
  ``(table[b, pos//P], pos % P)``. Page 0 is a reserved garbage sink:
  inactive slots carry an all-zero table row, so their don't-care writes
  land there and can never corrupt a live request's pages.
- **alloc/free** is host-side scheduler bookkeeping between steps
  (``PagePool``), so the compiled program never changes shape with
  occupancy, and the pool tensors are donated every step like the dense
  cache.

**Prefix sharing** (copy-on-write at page granularity): at admit time
the scheduler hashes the prompt head page-by-page (the key for page j is
the first ``(j+1)·page_size`` prompt tokens — K/V at a position depend
only on the tokens up to it, so equal heads mean bitwise-equal pages)
and maps any already-resident pages into the new slot's table with a
refcount bump instead of re-prefilling them. Only pages that end
strictly before the first decode-write position are ever registered, so
a shared page is written exactly once in its life — the "copy" of
copy-on-write is the fresh prefill of the first divergent page, and no
device-side copy primitive is needed. Pages whose refcount drops to
zero but that still carry a prefix key are RETAINED (not freed) in LRU
order, so identical system prompts hit across requests over time; the
allocator evicts retained pages deterministically (oldest release
first) only under pool pressure.
"""

from __future__ import annotations

import heapq
from collections import OrderedDict
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from tpudml.serve.cache import KINDS, _dequant, _encode

# The paged decode step is jitted under this NAME (serve/engine.py) so
# analysis rule J117 can key on it — mirrored as a literal in
# tpudml/analysis/jaxpr_pass.py (pinned by test_serve_paged).
PAGED_DECODE_MARKER = "_serve_paged_decode_step"

#: Page 0 is never allocated: it is the scatter sink for inactive slots'
#: don't-care writes (their table rows are all zeros).
GARBAGE_PAGE = 0


@jax.tree_util.register_dataclass
@dataclass
class PagedKVCache:
    """One layer's page pool: K/V pages plus (int8 only) per-(page, row,
    head) scales. Distinct buffers per field — the engine donates the
    pool pytree every step and XLA rejects double-donation."""

    k: jax.Array  # [N, P, Hkv, Dh] storage dtype
    v: jax.Array
    k_scale: jax.Array  # [N, P, Hkv] f32; zeros-shaped [0] when unused
    v_scale: jax.Array
    kind: str = field(metadata=dict(static=True))

    @property
    def num_pages(self) -> int:
        return self.k.shape[0]

    @property
    def page_size(self) -> int:
        return self.k.shape[1]


def _store_dtype(kind: str):
    return {
        "f32": jnp.float32, "bf16": jnp.bfloat16, "int8": jnp.int8,
        "bf16_sim": jnp.float32, "int8_sim": jnp.float32,
    }[kind]


def init_pool(num_pages: int, page_size: int, kv_heads: int, head_dim: int,
              kind: str = "f32") -> PagedKVCache:
    if kind not in KINDS:
        raise ValueError(f"unknown cache kind {kind!r}; one of {KINDS}")
    if num_pages < 2:
        raise ValueError("num_pages must be >= 2 (page 0 is the garbage sink)")
    shape = (num_pages, page_size, kv_heads, head_dim)
    sshape = (num_pages, page_size, kv_heads) if kind == "int8" else (0,)
    return PagedKVCache(
        k=jnp.zeros(shape, _store_dtype(kind)),
        v=jnp.zeros(shape, _store_dtype(kind)),
        k_scale=jnp.zeros(sshape, jnp.float32),
        v_scale=jnp.zeros(sshape, jnp.float32),
        kind=kind,
    )


def _addr(table: jax.Array, positions: jax.Array, page_size: int):
    """(pool page ids, in-page offsets) for flat ``positions`` [B, Q]
    through ``table`` [B, max_pages]. Out-of-table positions (inactive
    slots at stale depths) clamp to the last table column — which, for
    an inactive slot's all-zero row, is the garbage page."""
    max_pages = table.shape[1]
    page_idx = jnp.clip(positions // page_size, 0, max_pages - 1)
    pages = jnp.take_along_axis(table, page_idx, axis=1)
    offs = positions % page_size
    return pages, offs


def write_tokens(pool: PagedKVCache, k_new: jax.Array, v_new: jax.Array,
                 table: jax.Array, pos: jax.Array) -> PagedKVCache:
    """Scatter ``k_new``/``v_new`` [B, Q, Hkv, Dh] — Q consecutive
    tokens per slot starting at per-slot positions ``pos`` [B] — into
    the pages the table maps for those positions. Active slots' target
    pages are exclusively owned by construction (shared pages end before
    the first decode-write position), so the scatter never races a
    reader."""
    ks, kscale = _encode(k_new, pool.kind)
    vs, vscale = _encode(v_new, pool.kind)
    q = k_new.shape[1]
    positions = pos[:, None] + jnp.arange(q)[None, :]  # [B, Q]
    pages, offs = _addr(table, positions, pool.page_size)
    k = pool.k.at[pages, offs].set(ks)
    v = pool.v.at[pages, offs].set(vs)
    k_sc, v_sc = pool.k_scale, pool.v_scale
    if pool.kind == "int8":
        k_sc = k_sc.at[pages, offs].set(kscale)
        v_sc = v_sc.at[pages, offs].set(vscale)
    return PagedKVCache(k=k, v=v, k_scale=k_sc, v_scale=v_sc, kind=pool.kind)


def write_chunk(pool: PagedKVCache, k_new: jax.Array, v_new: jax.Array,
                table_row: jax.Array, start: int) -> PagedKVCache:
    """Prefill write: ``k_new``/``v_new`` [1, C, Hkv, Dh] at flat
    positions [start, start+C) of the one slot owning ``table_row``
    [max_pages]. ``start`` is static (one compiled prefill program per
    chunk index, shared across slots/requests — the in-page offsets are
    compile-time constants, only the page ids are traced)."""
    ks, kscale = _encode(k_new, pool.kind)
    vs, vscale = _encode(v_new, pool.kind)
    c = k_new.shape[1]
    p = pool.page_size
    flat = start + np.arange(c)
    pages = table_row[np.clip(flat // p, 0, table_row.shape[0] - 1)]  # [C]
    offs = jnp.asarray(flat % p, jnp.int32)
    k = pool.k.at[pages, offs].set(ks[0])
    v = pool.v.at[pages, offs].set(vs[0])
    k_sc, v_sc = pool.k_scale, pool.v_scale
    if pool.kind == "int8":
        k_sc = k_sc.at[pages, offs].set(kscale[0])
        v_sc = v_sc.at[pages, offs].set(vscale[0])
    return PagedKVCache(k=k, v=v, k_scale=k_sc, v_scale=v_sc, kind=pool.kind)


def read_table(pool: PagedKVCache, table: jax.Array,
               dtype) -> tuple[jax.Array, jax.Array]:
    """The J117-silent read: gather each slot's table rows from the pool
    and flatten to a [B, max_pages·page_size, Hkv, Dh] key window whose
    flat index IS the token position (row r, offset o → position
    r·page_size + o). Unallocated table tail entries point at page 0 but
    sit at flat positions beyond the slot's length, which the decode
    mask (``k_pos <= pos``) excludes."""
    b, m = table.shape
    p, h, d = pool.k.shape[1:]
    k = pool.k[table]  # [B, M, P, Hkv, Dh]
    v = pool.v[table]
    if pool.kind == "int8":
        k = _dequant(k, pool.k_scale[table])
        v = _dequant(v, pool.v_scale[table])
    return (k.reshape(b, m * p, h, d).astype(dtype),
            v.reshape(b, m * p, h, d).astype(dtype))


def read_row_prefix(pool: PagedKVCache, table_row: jax.Array, length: int,
                    dtype) -> tuple[jax.Array, jax.Array]:
    """One slot's first ``length`` flat positions (static) for a prefill
    chunk's attention window: [1, length, Hkv, Dh]."""
    p, h, d = pool.k.shape[1:]
    m = table_row.shape[0]
    k = pool.k[table_row].reshape(m * p, h, d)
    v = pool.v[table_row].reshape(m * p, h, d)
    if pool.kind == "int8":
        ks = pool.k_scale[table_row].reshape(m * p, h)
        vs = pool.v_scale[table_row].reshape(m * p, h)
        k = _dequant(k, ks)
        v = _dequant(v, vs)
    return k[None, :length].astype(dtype), v[None, :length].astype(dtype)


def pool_bytes(pool: PagedKVCache) -> int:
    """Total pool storage bytes (K + V + scales) — the equal-HBM axis of
    the paged-vs-dense bench comparison."""
    return sum(x.size * x.dtype.itemsize
               for x in (pool.k, pool.v, pool.k_scale, pool.v_scale))


class PagePool:
    """Host-side page allocator + prefix index. Purely between-steps
    bookkeeping: nothing here is traced, and every structure iterates in
    a deterministic order (min-heap free list, insertion-ordered LRU),
    so the scheduler's event log stays a pure function of (workload
    seed, config).

    Page lifecycle: free → allocated (refcount ≥ 1) → on last release,
    either back to free (unregistered pages) or RETAINED (pages carrying
    a prefix key — still matchable by future admits, evicted oldest-
    first only when the free heap runs dry). Page 0 never enters the
    allocator."""

    def __init__(self, num_pages: int, page_size: int,
                 prefix_sharing: bool = False):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2")
        self.num_pages = num_pages
        self.page_size = page_size
        self.prefix_sharing = prefix_sharing
        self._free: list[int] = list(range(1, num_pages))
        heapq.heapify(self._free)
        self.refcount = [0] * num_pages
        self._retained: OrderedDict[int, None] = OrderedDict()
        self._key_to_page: dict[bytes, int] = {}
        self._page_key: dict[int, bytes] = {}
        # Observability counters (bench/report): prefix hits = admits
        # that reused >= 1 page; reused pages = prefill work avoided.
        self.prefix_hits = 0
        self.pages_reused = 0
        self.retained_evictions = 0

    @property
    def available(self) -> int:
        """Pages an alloc could obtain right now (free + evictable)."""
        return len(self._free) + len(self._retained)

    @property
    def allocated(self) -> int:
        return (self.num_pages - 1) - self.available

    # ------------------------------------------------------------ sharing

    def _key(self, prompt: np.ndarray, j: int) -> bytes:
        return prompt[: (j + 1) * self.page_size].tobytes()

    def match_prefix(self, prompt: np.ndarray) -> list[int]:
        """Longest run of resident shared pages covering the prompt head.
        Page j is matchable only if it ends strictly before the first
        decode-write position ``len(prompt) - 1`` — so a matched page is
        never written by the new request either. Side-effect-free: the
        prefix_hits/pages_reused counters are bumped by the caller once
        admission actually succeeds, so a page-starved admit retried
        every scheduler pass doesn't re-count the same hit."""
        if not self.prefix_sharing:
            return []
        p = int(prompt.size) - 1  # prefilled positions are [0, p)
        pages: list[int] = []
        j = 0
        while (j + 1) * self.page_size <= p:
            pid = self._key_to_page.get(self._key(prompt, j))
            if pid is None:
                break
            pages.append(pid)
            j += 1
        return pages

    def register(self, pid: int, prompt: np.ndarray, j: int) -> None:
        """Publish page ``pid`` as holding prompt head page ``j``. First
        resident writer wins — a key already mapping to a live page is
        left alone (the new admit would have matched it instead)."""
        key = self._key(prompt, j)
        if self._key_to_page.get(key, pid) != pid:
            return
        self._key_to_page[key] = pid
        self._page_key[pid] = key

    def _unregister(self, pid: int) -> None:
        key = self._page_key.pop(pid, None)
        if key is not None and self._key_to_page.get(key) == pid:
            del self._key_to_page[key]

    # ---------------------------------------------------------- lifecycle

    def acquire(self, pid: int) -> None:
        """Take a reference on an already-resident (shared) page."""
        if self.refcount[pid] == 0:
            self._retained.pop(pid, None)
        self.refcount[pid] += 1

    def alloc_n(self, n: int) -> list[int] | None:
        """n fresh pages, all-or-nothing (None leaves the pool exactly as
        it was — the admit stays queued). Fresh pages come from the free
        heap lowest-id-first, then from retained prefix pages oldest-
        release-first (their keys are unregistered on eviction). On
        failure, retained pages evicted mid-attempt get their keys,
        retained status, and LRU positions back — a deferred admit must
        not cost the prefix cache anything."""
        got: list[int] = []
        evicted: list[tuple[int, bytes]] = []  # (pid, key) in pop order
        for _ in range(n):
            if self._free:
                pid = heapq.heappop(self._free)
            elif self._retained:
                pid, _ = self._retained.popitem(last=False)
                evicted.append((pid, self._page_key[pid]))
                self._unregister(pid)
                self.retained_evictions += 1
            else:
                evicted_ids = {e for e, _ in evicted}
                for g in got:
                    self.refcount[g] = 0
                    if g not in evicted_ids:
                        heapq.heappush(self._free, g)
                # Re-insert at the LRU head in reverse pop order so the
                # original oldest-release-first order is restored.
                for pid, key in reversed(evicted):
                    self._page_key[pid] = key
                    self._key_to_page[key] = pid
                    self._retained[pid] = None
                    self._retained.move_to_end(pid, last=False)
                self.retained_evictions -= len(evicted)
                return None
            self.refcount[pid] = 1
            got.append(pid)
        return got

    def release(self, pid: int) -> None:
        rc = self.refcount[pid] - 1
        if rc < 0:
            raise RuntimeError(f"page {pid} released more times than acquired")
        self.refcount[pid] = rc
        if rc == 0:
            if pid in self._page_key:
                self._retained[pid] = None  # newest retention at LRU tail
            else:
                heapq.heappush(self._free, pid)
