"""Prefill–decode serving engine with continuous batching.

The execution model, in one sentence: a fixed decode batch of
``slots`` cache rows runs ONE jitted single-token decode step forever,
and the host-side scheduler rewrites rows — evicting finished sequences
and prefilling queued ones into the freed rows — between steps, so
request churn never triggers a recompile.

- **Decode** is ``TransformerLM.apply_decode`` under a donated jit: all
  slots advance one token per step at their OWN positions (``pos`` [B]),
  greedy argmax picks the next token. The jit is wrapped in a NAMED
  inner jit (``SERVE_DECODE_MARKER``) so analysis rule J110 can prove
  the program attends O(cache) per token — a decode-marked program that
  recomputes full-sequence attention per emitted token is exactly what
  the rule flags.
- **Prefill** fills a slot's cache in fixed-size chunks
  (``prefill_chunk`` tokens per program) via ``apply_prefill``: one
  compiled program per chunk INDEX, shared by every request and slot
  (the slot id is a traced scalar), so a max_len-M cache needs at most
  M/C prefill programs ever. The prompt's last token is NOT prefilled —
  it feeds the first decode step, which emits the first generated token.
- **Scheduling** is FIFO by arrival time with slot-index tie-breaking:
  deterministic under a fixed workload seed (the scheduler unit tests
  pin eviction/refill order), and starvation-free — an admitted request
  runs to completion, and the queue head is always the oldest
  unadmitted arrival.

Stale cache rows need no zeroing on eviction: a slot's attention mask is
``k_pos <= pos``, and every position is written before it is first
unmasked, so a new occupant can never read its predecessor's K/V.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from tpudml.serve.cache import KINDS
from tpudml.serve.load import Request

# Decode programs are jitted under this NAME so the call survives as a
# recognizably-named pjit equation in any traced program — the marker
# analysis rule J110 keys on. Mirrored as a string literal in
# tpudml/analysis/jaxpr_pass.py (pinned by test_analysis); XLA inlines
# inner jits at lowering, so the marker costs nothing on the chip.
SERVE_DECODE_MARKER = "_serve_decode_step"


def make_decode_step(model):
    """The one jitted decode program: (params, caches, tokens [B],
    pos [B]) → (next greedy tokens [B], logits [B, V], updated caches).
    Caches are donated — the engine rebinds them every step. The run
    loop only ever pulls the tokens to host; the logits output exists
    for the parity tests (and stays device-side, costing nothing)."""

    def _serve_decode_step(params, caches, tokens, pos):
        logits, caches = model.apply_decode(params, caches, tokens, pos)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits, caches

    inner = jax.jit(_serve_decode_step)

    def step(params, caches, tokens, pos):
        return inner(params, caches, tokens, pos)

    return jax.jit(step, donate_argnums=(1,))


def make_cacheless_decode_step(model):
    """The decode strategy the KV cache exists to kill: re-run the full
    forward over the whole history and keep the last logits row. Kept as
    the A/B baseline for ``bench.py --serve`` (the ≥5× acceptance
    criterion) and as the living firing fixture for analysis rule J110 —
    it carries the decode marker, and the [T, T] softmax inside it is
    precisely what the rule reports. One compile per history length, too
    (tokens [B, T] is shape-polymorphic in T) — recompile churn the
    slot engine never pays."""

    def _serve_decode_step(params, tokens):
        logits, _ = model.apply(params, {}, tokens)
        return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    inner = jax.jit(_serve_decode_step)
    return jax.jit(lambda params, tokens: inner(params, tokens))


@dataclass(frozen=True)
class ServeConfig:
    """Engine shape knobs (all static — they size the compiled programs)."""

    slots: int = 4  # fixed decode batch: concurrent in-flight sequences
    max_len: int = 256  # cache rows per slot (prompt + generation bound)
    prefill_chunk: int = 32
    cache_kind: str = "f32"  # f32 | bf16 | int8 (serve.cache)
    eos_token: int | None = None  # early-stop token id (None: run budget out)
    # Overload guard. ``max_queue`` bounds the waiting line: an arrival
    # finding it full is REJECTED at admission control (event
    # ``("reject", rid, -1, step)``) instead of growing an unbounded
    # backlog whose tail latencies are all ruined together. ``deadline_s``
    # is a per-request TTL from its arrival: a queued request strictly
    # past its deadline is dropped before admission, an in-flight one is
    # evicted at the next decode-step boundary (both logged as
    # ``("expire", rid, slot, step)`` with slot=-1 for queued) — its
    # ``finished`` stays None, so it never pollutes the latency
    # percentiles of requests that met their contract.
    max_queue: int | None = None  # None: unbounded (pre-guard behaviour)
    deadline_s: float | None = None  # None: requests never expire
    # Virtual clock: with ``step_time_s`` set, "now" is
    # ``decode_steps × step_time_s`` (+ idle skips to the next arrival)
    # instead of the wall clock, so queue depth, rejections, and expiries
    # become a pure function of (workload seed, config) — the regime the
    # overload tests pin bit-for-bit.
    step_time_s: float | None = None

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.cache_kind not in KINDS:
            raise ValueError(f"cache_kind must be one of {KINDS}")
        if self.prefill_chunk < 1 or self.max_len % self.prefill_chunk:
            raise ValueError(
                f"prefill_chunk {self.prefill_chunk} must divide "
                f"max_len {self.max_len} (padded tail chunks stay in-bounds)"
            )
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None for unbounded)")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0 (or None)")
        if self.step_time_s is not None and self.step_time_s <= 0:
            raise ValueError("step_time_s must be > 0 (or None)")


@dataclass
class RequestStats:
    """Per-request outcome + timing ledger (all times are seconds from
    run start; latency aggregation happens in ServeReport)."""

    rid: int
    prompt_len: int
    max_new_tokens: int
    arrival: float
    admitted: float | None = None  # prefill finished, slot occupied
    first_token: float | None = None
    finished: float | None = None
    rejected: float | None = None  # bounced at admission control (full queue)
    expired: float | None = None  # deadline passed (queued or mid-flight)
    slot: int | None = None
    tokens: list = field(default_factory=list)
    token_times: list = field(default_factory=list)


@dataclass
class ServeReport:
    """One run's outcome: per-request stats, the scheduler event log
    (admit/evict tuples — the determinism contract), and aggregates."""

    requests: dict
    events: list  # ("admit"|"evict"|"reject"|"expire", rid, slot, step)
    decode_steps: int
    wall_time: float
    peak_queue_depth: int = 0  # max waiting-line length ever observed

    @property
    def generated_tokens(self) -> int:
        return sum(len(s.tokens) for s in self.requests.values())

    @property
    def rejected(self) -> int:
        return sum(1 for s in self.requests.values() if s.rejected is not None)

    @property
    def expired(self) -> int:
        return sum(1 for s in self.requests.values() if s.expired is not None)

    @property
    def tokens_per_sec(self) -> float:
        return self.generated_tokens / max(self.wall_time, 1e-9)

    def latency_summary(self) -> dict:
        """p50/p99 of per-token gaps (decode cadence: consecutive token
        timestamps within a request, seeded by the admit time) and of
        end-to-end request latency (arrival → last token), plus
        time-to-first-token (arrival → first token: queueing + prefill
        + one decode step)."""
        gaps, e2e, ttft = [], [], []
        for s in self.requests.values():
            if s.finished is None:
                continue
            prev = s.admitted
            for t in s.token_times:
                gaps.append(t - prev)
                prev = t
            e2e.append(s.finished - s.arrival)
            ttft.append(s.first_token - s.arrival)

        def pct(xs, q):
            return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")

        return {
            "per_token_p50_s": pct(gaps, 50),
            "per_token_p99_s": pct(gaps, 99),
            "e2e_p50_s": pct(e2e, 50),
            "e2e_p99_s": pct(e2e, 99),
            "ttft_p50_s": pct(ttft, 50),
            "ttft_p99_s": pct(ttft, 99),
        }


class ServingEngine:
    """Continuous-batching prefill/decode over a ``TransformerLM``.

    Single-device by default; pass ``mesh`` (+ ``axis_name``) to shard
    params, cache heads, and the decode step over a tensor-parallel axis
    (``tpudml.serve.tp`` — reuses ``tensor_parallel_rules``).
    """

    def __init__(self, model, params, config: ServeConfig | None = None,
                 *, mesh=None, axis_name: str = "model"):
        self.model = model
        self.cfg = config or ServeConfig()
        if not model.rope and self.cfg.max_len > model.max_len:
            raise ValueError(
                f"cache max_len {self.cfg.max_len} exceeds the position "
                f"table ({model.max_len}); only RoPE models extrapolate"
            )
        self._tp = None
        if mesh is not None:
            from tpudml.serve.tp import TPServing

            self._tp = TPServing(model, mesh, axis_name, self.cfg)
            self.params = self._tp.shard_params(params)
            self.caches = self._tp.init_caches()
            self._decode = self._tp.decode_step
            self._prefill_cache = self._tp._prefill_cache
            self._prefill_builder = self._tp.prefill_at
        else:
            self.params = params
            self.caches = model.init_decode_cache(
                self.cfg.slots, self.cfg.max_len, self.cfg.cache_kind
            )
            self._decode = make_decode_step(model)
            self._prefill_cache = {}
            self._prefill_builder = self._build_prefill

    # ------------------------------------------------------------ prefill

    def _build_prefill(self, start: int):
        model = self.model

        def _serve_prefill_chunk(params, caches, chunk, slot):
            return model.apply_prefill(params, caches, chunk, slot, start)

        return jax.jit(_serve_prefill_chunk, donate_argnums=(1,))

    def _prefill_at(self, start: int):
        fn = self._prefill_cache.get(start)
        if fn is None:
            fn = self._prefill_cache[start] = self._prefill_builder(start)
        return fn

    def _admit(self, slot: int, req: Request) -> tuple[int, int]:
        """Prefill ``req``'s prompt (all but the last token) into a
        slot's cache rows; returns (pos, last_token) for the decode
        state. Chunk tails are padded — padded rows land at positions
        the mask excludes until decode overwrites them."""
        prompt = np.asarray(req.prompt, np.int32)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(f"request {req.rid}: prompt must be [L>=1]")
        total = prompt.size + req.max_new_tokens
        if total > self.cfg.max_len:
            raise ValueError(
                f"request {req.rid}: prompt {prompt.size} + "
                f"max_new_tokens {req.max_new_tokens} exceeds cache "
                f"max_len {self.cfg.max_len}"
            )
        p = prompt.size - 1
        c = self.cfg.prefill_chunk
        slot_j = jnp.asarray(slot, jnp.int32)
        for s0 in range(0, p, c):
            chunk = np.zeros((1, c), np.int32)
            n = min(c, p - s0)
            chunk[0, :n] = prompt[s0:s0 + n]
            self.caches = self._prefill_at(s0)(
                self.params, self.caches, jnp.asarray(chunk), slot_j
            )
        return p, int(prompt[-1])

    # ---------------------------------------------------------------- run

    def run(self, requests: list[Request]) -> ServeReport:
        """Serve a request stream to completion. Arrival times are
        honored open-loop (a request only becomes admissible once the
        clock passes its arrival), decode advances every occupied slot
        one token per step, finished slots are refilled mid-flight from
        the waiting queue. Every request ends in EXACTLY ONE terminal
        state: finished, rejected (bounded queue full at arrival), or
        expired (deadline passed while queued or in flight) — the
        ledger-accounting invariant the overload tests audit.
        """
        cfg = self.cfg
        b = cfg.slots
        arrivals = deque(sorted(requests, key=lambda r: (r.arrival_time, r.rid)))
        queue: deque[Request] = deque()  # arrived, not yet admitted
        stats = {
            r.rid: RequestStats(
                rid=r.rid, prompt_len=len(r.prompt),
                max_new_tokens=r.max_new_tokens, arrival=r.arrival_time,
            )
            for r in requests
        }
        if len(stats) != len(requests):
            raise ValueError("duplicate request ids")

        last = np.zeros(b, np.int32)
        pos = np.zeros(b, np.int32)
        remaining = np.zeros(b, np.int64)
        slot_rid = np.full(b, -1, np.int64)
        slot_deadline = np.full(b, np.inf)
        active = np.zeros(b, bool)
        events: list = []
        steps = 0
        peak_queue = 0
        # Clock: wall time by default; virtual (decode-step-derived) when
        # cfg.step_time_s is set — see ServeConfig.
        t0 = time.perf_counter()
        v_extra = 0.0  # virtual-clock idle skips (accumulated)
        if cfg.step_time_s is not None:
            now = lambda: steps * cfg.step_time_s + v_extra  # noqa: E731
        else:
            now = lambda: time.perf_counter() - t0  # noqa: E731

        while arrivals or queue or active.any():
            t = now()
            # Stage arrivals into the waiting queue; a full bounded queue
            # rejects at the door (slot -1 in the event tuple).
            while arrivals and arrivals[0].arrival_time <= t:
                req = arrivals.popleft()
                if cfg.max_queue is not None and len(queue) >= cfg.max_queue:
                    stats[req.rid].rejected = t
                    events.append(("reject", req.rid, -1, steps))
                else:
                    queue.append(req)
            peak_queue = max(peak_queue, len(queue))
            # Expire queued requests strictly past arrival + deadline
            # BEFORE admission — never spend prefill on a dead request.
            if cfg.deadline_s is not None:
                kept: deque[Request] = deque()
                while queue:
                    req = queue.popleft()
                    if t > req.arrival_time + cfg.deadline_s:
                        stats[req.rid].expired = t
                        events.append(("expire", req.rid, -1, steps))
                    else:
                        kept.append(req)
                queue = kept
            # Admit: free slots in index order, queue in arrival order.
            for i in range(b):
                if active[i] or not queue:
                    continue
                req = queue.popleft()
                pos[i], last[i] = self._admit(i, req)
                remaining[i] = req.max_new_tokens
                slot_rid[i] = req.rid
                slot_deadline[i] = (
                    req.arrival_time + cfg.deadline_s
                    if cfg.deadline_s is not None
                    else np.inf
                )
                active[i] = True
                st = stats[req.rid]
                st.admitted = now()
                st.slot = i
                events.append(("admit", req.rid, i, steps))
            if not active.any():
                if not arrivals:
                    continue  # queue drained by expiry; loop re-checks
                # Idle: nothing in flight, queue head hasn't arrived yet.
                gap = arrivals[0].arrival_time - now()
                if cfg.step_time_s is not None:
                    v_extra += max(gap, 0.0)  # skip virtual time forward
                elif gap > 0:
                    time.sleep(min(gap, 0.05))
                continue
            # One decode step for ALL slots. Inactive slots run garbage
            # tokens at stale positions — harmless by the mask argument
            # in the module docstring — so the compiled shape never
            # changes with occupancy.
            next_t, _, self.caches = self._decode(
                self.params, self.caches, jnp.asarray(last), jnp.asarray(pos)
            )
            next_np = np.asarray(jax.device_get(next_t))
            steps += 1
            t_step = now()
            for i in range(b):
                if not active[i]:
                    continue
                tok = int(next_np[i])
                st = stats[slot_rid[i]]
                st.tokens.append(tok)
                st.token_times.append(t_step)
                if st.first_token is None:
                    st.first_token = t_step
                pos[i] += 1
                last[i] = tok
                remaining[i] -= 1
                if remaining[i] <= 0 or (
                    cfg.eos_token is not None and tok == cfg.eos_token
                ):
                    st.finished = t_step
                    active[i] = False
                    events.append(("evict", int(slot_rid[i]), i, steps))
                    slot_rid[i] = -1
                elif t_step > slot_deadline[i]:
                    # Mid-flight deadline eviction at the step boundary:
                    # the slot frees for the queue head, the partial
                    # tokens stay in the ledger, finished stays None.
                    st.expired = t_step
                    active[i] = False
                    events.append(("expire", int(slot_rid[i]), i, steps))
                    slot_rid[i] = -1
        return ServeReport(
            requests=stats, events=events, decode_steps=steps,
            wall_time=now(), peak_queue_depth=peak_queue,
        )
