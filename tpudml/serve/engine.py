"""Prefill–decode serving engine with continuous batching.

The execution model, in one sentence: a fixed decode batch of
``slots`` cache rows runs ONE jitted single-token decode step forever,
and the host-side scheduler rewrites rows — evicting finished sequences
and prefilling queued ones into the freed rows — between steps, so
request churn never triggers a recompile.

- **Decode** is ``TransformerLM.apply_decode`` under a donated jit: all
  slots advance one token per step at their OWN positions (``pos`` [B]),
  greedy argmax picks the next token. The jit is wrapped in a NAMED
  inner jit (``SERVE_DECODE_MARKER``) so analysis rule J110 can prove
  the program attends O(cache) per token — a decode-marked program that
  recomputes full-sequence attention per emitted token is exactly what
  the rule flags.
- **Prefill** fills a slot's cache in fixed-size chunks
  (``prefill_chunk`` tokens per program) via ``apply_prefill``: one
  compiled program per chunk INDEX, shared by every request and slot
  (the slot id is a traced scalar), so a max_len-M cache needs at most
  M/C prefill programs ever. The prompt's last token is NOT prefilled —
  it feeds the first decode step, which emits the first generated token.
- **Scheduling** is FIFO by arrival time with slot-index tie-breaking:
  deterministic under a fixed workload seed (the scheduler unit tests
  pin eviction/refill order), and starvation-free — an admitted request
  runs to completion, and the queue head is always the oldest
  unadmitted arrival.

Stale cache rows need no zeroing on eviction: a slot's attention mask is
``k_pos <= pos``, and every position is written before it is first
unmasked, so a new occupant can never read its predecessor's K/V.

Three multi-tenant levers compose on top, each flag-gated in
``ServeConfig`` and each greedy-parity-exact against the dense path:
``cache_layout="paged"`` (+ ``prefix_sharing``) swaps the cache for a
page pool behind a slot→page table (serve/paged.py), ``spec_k>0`` swaps
the decode step for draft-then-verify speculative decoding
(serve/spec.py), and ``slo`` prices admission with the static cost model
(serve/sched.py). TP × {paged, spec} raises ServeCompositionError.
"""

from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from tpudml.capabilities import CompositionError, reject
from tpudml.ops.decode_head import fused_decode_head, fused_decode_head_int8
from tpudml.serve.cache import KINDS
from tpudml.serve.load import Request
from tpudml.serve.paged import PAGED_DECODE_MARKER, PagePool
from tpudml.serve.sched import DecodeCostModel, SLOConfig
from tpudml.serve.spec import draft_from_trunk, make_spec_decode_step


class ServeCompositionError(CompositionError):
    """Raised when serving levers are combined in a regime this tier has
    no correct compiled path for (today: tensor parallelism × paged
    cache, and tensor parallelism × speculative decoding). Loud by
    contract — the alternative is a silently wrong answer path."""

# Decode programs are jitted under this NAME so the call survives as a
# recognizably-named pjit equation in any traced program — the marker
# analysis rule J110 keys on. Mirrored as a string literal in
# tpudml/analysis/jaxpr_pass.py (pinned by test_analysis); XLA inlines
# inner jits at lowering, so the marker costs nothing on the chip.
SERVE_DECODE_MARKER = "_serve_decode_step"


def make_decode_step(model):
    """The one jitted decode program: (params, caches, tokens [B],
    pos [B]) → (next greedy tokens [B], logits [B, V], updated caches).
    Caches are donated — the engine rebinds them every step. The run
    loop only ever pulls the tokens to host; the logits output exists
    for the parity tests (and stays device-side, costing nothing)."""

    def _serve_decode_step(params, caches, tokens, pos):
        logits, caches = model.apply_decode(params, caches, tokens, pos)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits, caches

    inner = jax.jit(_serve_decode_step)

    def step(params, caches, tokens, pos):
        return inner(params, caches, tokens, pos)

    return jax.jit(step, donate_argnums=(1,))


def make_fused_decode_step(model, head_q=None, head_scale=None):
    """The fused-tail twin of :func:`make_decode_step`: the trunk runs to
    post-``ln_f`` features (``apply_decode_features``) and the head
    matmul, greedy pick, and step stats fold into ONE vocab-tiled Pallas
    program (ops/decode_head.py) — the [slots, vocab] logits row never
    round-trips HBM. Returns (next tokens [B], {"max_logit": [B],
    "lse": [B]}, caches): same arity as the unfused step (the run loop
    pulls tokens only), with the in-graph stats replacing the logits
    output as the step's observable. With ``head_q``/``head_scale`` set
    (int8 mode), the kernel consumes the int8 codes + scales directly,
    dequantizing per vocab tile in the oracle's exact op order — the
    dequantized f32 head never exists in HBM either."""

    def _serve_decode_step(params, caches, tokens, pos):
        h, caches = model.apply_decode_features(params, caches, tokens, pos)
        bias = params["head"].get("bias")
        if head_q is not None:
            tok, mx, lse = fused_decode_head_int8(h, head_q, head_scale, bias)
        else:
            tok, mx, lse = fused_decode_head(h, params["head"]["kernel"], bias)
        return tok, {"max_logit": mx, "lse": lse}, caches

    assert _serve_decode_step.__name__ == SERVE_DECODE_MARKER
    inner = jax.jit(_serve_decode_step)

    def step(params, caches, tokens, pos):
        return inner(params, caches, tokens, pos)

    return jax.jit(step, donate_argnums=(1,))


def make_cacheless_decode_step(model):
    """The decode strategy the KV cache exists to kill: re-run the full
    forward over the whole history and keep the last logits row. Kept as
    the A/B baseline for ``bench.py --serve`` (the ≥5× acceptance
    criterion) and as the living firing fixture for analysis rule J110 —
    it carries the decode marker, and the [T, T] softmax inside it is
    precisely what the rule reports. One compile per history length, too
    (tokens [B, T] is shape-polymorphic in T) — recompile churn the
    slot engine never pays."""

    def _serve_decode_step(params, tokens):
        logits, _ = model.apply(params, {}, tokens)
        return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    inner = jax.jit(_serve_decode_step)
    return jax.jit(lambda params, tokens: inner(params, tokens))


def make_paged_decode_step(model):
    """The paged twin of :func:`make_decode_step`: (params, pools,
    table [B, max_pages], tokens [B], pos [B]) → (next tokens [B],
    logits [B, V], updated pools). The table is an ordinary traced
    argument — page alloc/free between steps never recompiles — and the
    pools are donated. Jitted under its OWN marker name so analysis
    rule J117 (full-pool gather per token) can key on exactly the
    programs that read through a page table."""

    def _serve_paged_decode_step(params, caches, table, tokens, pos):
        logits, caches = model.apply_decode_paged(
            params, caches, table, tokens[:, None], pos
        )
        logits = logits[:, 0, :]
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits, caches

    assert _serve_paged_decode_step.__name__ == PAGED_DECODE_MARKER
    inner = jax.jit(_serve_paged_decode_step)

    def step(params, caches, table, tokens, pos):
        return inner(params, caches, table, tokens, pos)

    return jax.jit(step, donate_argnums=(1,))


@dataclass(frozen=True)
class ServeConfig:
    """Engine shape knobs (all static — they size the compiled programs)."""

    slots: int = 4  # fixed decode batch: concurrent in-flight sequences
    max_len: int = 256  # cache rows per slot (prompt + generation bound)
    prefill_chunk: int = 32
    cache_kind: str = "f32"  # f32 | bf16 | int8 (serve.cache)
    eos_token: int | None = None  # early-stop token id (None: run budget out)
    # Overload guard. ``max_queue`` bounds the waiting line: an arrival
    # finding it full is REJECTED at admission control (event
    # ``("reject", rid, -1, step)``) instead of growing an unbounded
    # backlog whose tail latencies are all ruined together. ``deadline_s``
    # is a per-request TTL from its arrival: a queued request strictly
    # past its deadline is dropped before admission, an in-flight one is
    # evicted at the next decode-step boundary (both logged as
    # ``("expire", rid, slot, step)`` with slot=-1 for queued) — its
    # ``finished`` stays None, so it never pollutes the latency
    # percentiles of requests that met their contract.
    max_queue: int | None = None  # None: unbounded (pre-guard behaviour)
    deadline_s: float | None = None  # None: requests never expire
    # Virtual clock: with ``step_time_s`` set, "now" is
    # ``decode_steps × step_time_s`` (+ idle skips to the next arrival)
    # instead of the wall clock, so queue depth, rejections, and expiries
    # become a pure function of (workload seed, config) — the regime the
    # overload tests pin bit-for-bit.
    step_time_s: float | None = None
    # Cache layout. "dense" is the PR 8 [slots, max_len] block; "paged"
    # stores K/V in a fixed pool of [num_pages, page_size, ...] pages
    # addressed through a [slots, max_pages] table (serve/paged.py) —
    # max_len still bounds prompt + generation per request, but HBM is
    # sized by ``num_pages``, so short requests stop stranding long
    # requests' headroom. ``num_pages=None`` sizes the pool to dense
    # capacity + the garbage page (a pure-layout A/B at equal HBM).
    cache_layout: str = "dense"
    page_size: int = 16
    num_pages: int | None = None
    # Prefix sharing (paged only): admit-time page reuse for equal
    # prompt heads, refcounted, copy-on-write at the first divergent
    # page. Requires page_size % prefill_chunk == 0 so a shared head
    # always ends on a prefill-chunk boundary.
    prefix_sharing: bool = False
    # Speculative decoding: draft spec_k tokens per target step, exact
    # greedy acceptance-rejection (serve/spec.py). 0 disables. Admission
    # reserves spec_k rows of headroom per slot (the verify window
    # writes up to spec_k rows past the commit point).
    spec_k: int = 0
    # SLO-aware admission: with an SLOConfig set, the queue head is
    # admitted only while the priced decode step (serve/sched.py) fits
    # the per-token budget; otherwise it waits (event
    # ``("defer", rid, -1, step)`` on first deferral).
    slo: SLOConfig | None = None
    # Weight quantization for the cache-bound decode path
    # (serve/fleet/quant.py): "int8" stores per-output-channel absmax
    # int8 kernels + f32 scales and computes on their dequantization;
    # "int8_sim" is the f32-storage oracle (quantize→dequantize
    # round-trip) the real path must match bitwise. None: f32 weights.
    weight_quant: str | None = None
    # Fused decode tail (ops/decode_head.py): fold the head matmul,
    # greedy pick, and step stats into one vocab-tiled Pallas program —
    # the [slots, vocab] logits row never materializes in HBM. Dense
    # single-device layout only (capability row ``serve_fused_head_dense``
    # rejects paged / speculative / TP composition at engine init).
    # Composes with weight_quant: "int8" feeds the kernel the int8 codes
    # + scales directly, "int8_sim" runs the f32 kernel on the oracle's
    # round-tripped params.
    fused_head: bool = False

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.cache_kind not in KINDS:
            raise ValueError(f"cache_kind must be one of {KINDS}")
        if self.prefill_chunk < 1 or self.max_len % self.prefill_chunk:
            raise ValueError(
                f"prefill_chunk {self.prefill_chunk} must divide "
                f"max_len {self.max_len} (padded tail chunks stay in-bounds)"
            )
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None for unbounded)")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0 (or None)")
        if self.step_time_s is not None and self.step_time_s <= 0:
            raise ValueError("step_time_s must be > 0 (or None)")
        if self.cache_layout not in ("dense", "paged"):
            raise ValueError(
                f"cache_layout must be 'dense' or 'paged', "
                f"got {self.cache_layout!r}"
            )
        if self.cache_layout == "paged":
            if self.page_size < 1:
                raise ValueError("page_size must be >= 1")
            if self.num_pages is not None and self.num_pages < 2:
                raise ValueError(
                    "num_pages must be >= 2 (page 0 is the garbage sink)"
                )
            if self.prefix_sharing and self.page_size % self.prefill_chunk:
                raise ValueError(
                    f"prefix_sharing requires page_size "
                    f"{self.page_size} to be a multiple of prefill_chunk "
                    f"{self.prefill_chunk} (a shared head must end on a "
                    f"chunk boundary so fresh prefill never rewrites a "
                    f"shared page)"
                )
        elif self.prefix_sharing:
            raise ValueError("prefix_sharing requires cache_layout='paged'")
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        if self.weight_quant not in (None, "int8", "int8_sim"):
            raise ValueError(
                f"weight_quant must be None, 'int8' or 'int8_sim', "
                f"got {self.weight_quant!r}"
            )

    @property
    def max_pages(self) -> int:
        """Page-table width: pages covering one slot's max_len rows."""
        return math.ceil(self.max_len / self.page_size)

    @property
    def total_pages(self) -> int:
        """Pool size: ``num_pages``, defaulting to dense-equivalent
        capacity (slots × max_pages) plus the reserved garbage page."""
        if self.num_pages is not None:
            return self.num_pages
        return self.slots * self.max_pages + 1


@dataclass
class RequestStats:
    """Per-request outcome + timing ledger (all times are seconds from
    run start; latency aggregation happens in ServeReport)."""

    rid: int
    prompt_len: int
    max_new_tokens: int
    arrival: float
    admit_start: float | None = None  # admission began (prefill starts)
    admitted: float | None = None  # prefill finished, slot occupied
    first_token: float | None = None
    finished: float | None = None
    rejected: float | None = None  # bounced at admission control (full queue)
    expired: float | None = None  # deadline passed (queued or mid-flight)
    slot: int | None = None
    tokens: list = field(default_factory=list)
    token_times: list = field(default_factory=list)
    shared_pages: int = 0  # prefix-cache pages reused at admit (paged)

    @property
    def ttft_s(self) -> float | None:
        """Time-to-first-token: arrival → first generated token."""
        if self.first_token is None:
            return None
        return self.first_token - self.arrival

    @property
    def tpot_s(self) -> float | None:
        """Mean time-per-output-token AFTER the first (decode cadence);
        None until a request has at least two tokens."""
        if len(self.token_times) < 2:
            return None
        return (self.token_times[-1] - self.token_times[0]) / (
            len(self.token_times) - 1
        )


@dataclass
class ServeReport:
    """One run's outcome: per-request stats, the scheduler event log
    (admit/evict tuples — the determinism contract), and aggregates."""

    requests: dict
    # ("admit"|"evict"|"reject"|"expire"|"defer", rid, slot, step) plus
    # ("spec", rid, slot, step, accepted_len) when spec decoding is on.
    events: list
    decode_steps: int
    wall_time: float
    peak_queue_depth: int = 0  # max waiting-line length ever observed
    busy_slot_steps: int = 0  # Σ over steps of active-slot count
    slots: int = 0  # engine slot count (occupancy denominator)
    pool_stats: dict | None = None  # paged only: prefix hits/evictions

    @property
    def generated_tokens(self) -> int:
        return sum(len(s.tokens) for s in self.requests.values())

    @property
    def occupancy(self) -> float:
        """Mean fraction of decode-slot-steps doing useful work — the
        number a paged layout raises on mixed short/long traffic (dense
        strands capacity as queued work waits for whole max_len rows)."""
        denom = self.decode_steps * max(self.slots, 1)
        return self.busy_slot_steps / denom if denom else 0.0

    @property
    def mean_accepted_len(self) -> float:
        """Mean accepted draft tokens COMMITTED per spec step (0.0
        without spec events; windows truncated by EOS/budget count only
        what landed); tokens-per-target-step is ``1 + mean_accepted_len``
        and Σ(accepted_len + 1) equals the generated token count."""
        ls = [e[4] for e in self.events if e[0] == "spec"]
        return float(np.mean(ls)) if ls else 0.0

    def to_trace_events(self, step_time_s: float | None = None) -> list[dict]:
        """This run's event log as Chrome trace events (pure conversion —
        see :mod:`tpudml.obs.convert`); pass the run's
        ``ServeConfig.step_time_s`` for virtual-clock timestamps."""
        from tpudml.obs.convert import serve_trace_events

        return serve_trace_events(self.events, step_time_s=step_time_s)

    def annotate_ledger(self, ledger: dict[int, dict]) -> dict[int, dict]:
        """Fill the workload ledger's per-request ``ttft_s``/``tpot_s``
        fields (serve/load.py creates them as None) from this run's
        stats, in place."""
        for rid, row in ledger.items():
            st = self.requests.get(rid)
            if st is not None:
                row["ttft_s"] = st.ttft_s
                row["tpot_s"] = st.tpot_s
        return ledger

    @property
    def rejected(self) -> int:
        return sum(1 for s in self.requests.values() if s.rejected is not None)

    @property
    def expired(self) -> int:
        return sum(1 for s in self.requests.values() if s.expired is not None)

    @property
    def tokens_per_sec(self) -> float:
        return self.generated_tokens / max(self.wall_time, 1e-9)

    def latency_summary(self) -> dict:
        """p50/p99 of per-token gaps (decode cadence: consecutive token
        timestamps within a request, seeded by the admit time) and of
        end-to-end request latency (arrival → last token), plus
        time-to-first-token (arrival → first token: queueing + prefill
        + one decode step)."""
        gaps, e2e, ttft = [], [], []
        for s in self.requests.values():
            if s.finished is None:
                continue
            prev = s.admitted
            for t in s.token_times:
                gaps.append(t - prev)
                prev = t
            e2e.append(s.finished - s.arrival)
            ttft.append(s.first_token - s.arrival)

        def pct(xs, q):
            return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")

        return {
            "per_token_p50_s": pct(gaps, 50),
            "per_token_p99_s": pct(gaps, 99),
            "e2e_p50_s": pct(e2e, 50),
            "e2e_p99_s": pct(e2e, 99),
            "ttft_p50_s": pct(ttft, 50),
            "ttft_p99_s": pct(ttft, 99),
        }


class ServingEngine:
    """Continuous-batching prefill/decode over a ``TransformerLM``.

    Single-device by default; pass ``mesh`` (+ ``axis_name``) to shard
    params, cache heads, and the decode step over a tensor-parallel axis
    (``tpudml.serve.tp`` — reuses ``tensor_parallel_rules``).
    """

    def __init__(self, model, params, config: ServeConfig | None = None,
                 *, mesh=None, axis_name: str = "model",
                 draft_model=None, draft_params=None,
                 draft_layers: int | None = None):
        self.model = model
        self.cfg = config or ServeConfig()
        cfg = self.cfg
        if not model.rope and cfg.max_len > model.max_len:
            raise ValueError(
                f"cache max_len {cfg.max_len} exceeds the position "
                f"table ({model.max_len}); only RoPE models extrapolate"
            )
        self._paged = cfg.cache_layout == "paged"
        if mesh is not None and (self._paged or cfg.spec_k):
            # The TP decode step shards cache heads through a shard_map
            # body that knows nothing of page tables or verify windows.
            # Until those bodies exist, composing would silently run the
            # unsharded math on sharded params — reject instead.
            reject("serve_tp_paged_spec", exc=ServeCompositionError)
        if mesh is not None and cfg.weight_quant is not None:
            # shard_params knows nothing of int8 kernels + scale trees;
            # sharding the dequantized params would silently price (and
            # store) f32 while claiming int8 — reject instead.
            reject("serve_tp_weight_quant", exc=ServeCompositionError)
        if cfg.fused_head and (mesh is not None or self._paged or cfg.spec_k):
            # The fused tail consumes the dense step's post-ln features
            # and the unsharded [d, V] head; paged/spec steps consume
            # full logits windows and TP shards the head — run those
            # unfused rather than silently falling back.
            reject("serve_fused_head_dense", exc=ServeCompositionError)
        # Weight quantization happens ONCE at init: decode compute runs
        # on the dequantized params (bitwise identical to the int8_sim
        # oracle — quant.py's contract), while the "int8" mode keeps the
        # int8 kernels + scales as the params of record so storage
        # accounting (quantized_param_bytes) reflects what a chip would
        # actually hold resident.
        self.quantized_params = None
        self.quant_scales = None
        if cfg.weight_quant is not None:
            from tpudml.serve.fleet.quant import (
                dequantize_params,
                quantize_params,
                sim_quantize_params,
            )

            if cfg.weight_quant == "int8":
                self.quantized_params, self.quant_scales = quantize_params(
                    params
                )
                params = dequantize_params(
                    self.quantized_params, self.quant_scales
                )
            else:  # int8_sim: the f32-storage oracle
                params = sim_quantize_params(params)
        self._tp = None
        if mesh is not None:
            from tpudml.serve.tp import TPServing

            self._tp = TPServing(model, mesh, axis_name, self.cfg)
            self.params = self._tp.shard_params(params)
            self.caches = self._tp.init_caches()
            self._decode = self._tp.decode_step
            self._prefill_cache = self._tp._prefill_cache
            self._prefill_builder = self._tp.prefill_at
        else:
            self.params = params
            if self._paged:
                self.caches = model.init_paged_cache(
                    cfg.total_pages, cfg.page_size, cfg.cache_kind
                )
                self._decode = make_paged_decode_step(model)
                self._prefill_builder = self._build_prefill_paged
            else:
                self.caches = model.init_decode_cache(
                    cfg.slots, cfg.max_len, cfg.cache_kind
                )
                if cfg.fused_head:
                    hq = hs = None
                    if self.quantized_params is not None:
                        hq = self.quantized_params["head"]["kernel"]
                        hs = self.quant_scales["head"]["kernel"]
                    self._decode = make_fused_decode_step(
                        model, head_q=hq, head_scale=hs
                    )
                else:
                    self._decode = make_decode_step(model)
                self._prefill_builder = self._build_prefill
            self._prefill_cache = {}
        # Paged bookkeeping: the host-side allocator plus the
        # [slots, max_pages] table the decode step reads through.
        self._pool = None
        self._table = None
        self._slot_pages: list[list[int]] = [[] for _ in range(cfg.slots)]
        if self._paged:
            self._pool = PagePool(
                cfg.total_pages, cfg.page_size, cfg.prefix_sharing
            )
            self._table = np.zeros((cfg.slots, cfg.max_pages), np.int32)
        # Speculative decoding: default draft is the target's lower
        # trunk (zero extra weights); exactness never depends on it.
        self._spec = None
        self.draft_model = None
        if cfg.spec_k:
            if draft_model is None:
                n = draft_layers or max(1, model.num_layers // 2)
                draft_model, draft_params = draft_from_trunk(model, params, n)
            elif draft_params is None:
                raise ValueError("draft_model requires draft_params")
            self.draft_model = draft_model
            self._dparams = draft_params
            # The draft cache stays dense in every mode — it is small by
            # construction and only ever single-token-stepped.
            self._dcaches = draft_model.init_decode_cache(
                cfg.slots, cfg.max_len, cfg.cache_kind
            )
            self._dprefill_cache = {}
            self._spec = make_spec_decode_step(
                model, draft_model, cfg.spec_k, paged=self._paged
            )
        # SLO admission pricing (deterministic, host-side).
        self._cost = None
        if cfg.slo is not None:
            self._cost = DecodeCostModel(
                model, cfg, cfg.slo,
                world=self._tp.world if self._tp is not None else 1,
                draft_model=self.draft_model,
            )

    # ------------------------------------------------------------ prefill

    def _build_prefill(self, start: int):
        model = self.model

        def _serve_prefill_chunk(params, caches, chunk, slot):
            return model.apply_prefill(params, caches, chunk, slot, start)

        return jax.jit(_serve_prefill_chunk, donate_argnums=(1,))

    def _build_prefill_paged(self, start: int):
        model = self.model

        def _serve_prefill_chunk(params, caches, chunk, table_row):
            return model.apply_prefill_paged(params, caches, table_row,
                                             chunk, start)

        return jax.jit(_serve_prefill_chunk, donate_argnums=(1,))

    def _prefill_at(self, start: int):
        fn = self._prefill_cache.get(start)
        if fn is None:
            fn = self._prefill_cache[start] = self._prefill_builder(start)
        return fn

    def _build_prefill_draft(self, start: int):
        draft = self.draft_model

        def _serve_prefill_chunk(dparams, dcaches, chunk, slot):
            return draft.apply_prefill(dparams, dcaches, chunk, slot, start)

        return jax.jit(_serve_prefill_chunk, donate_argnums=(1,))

    def _prefill_draft(self, slot: int, prompt: np.ndarray) -> None:
        """Spec only: the DRAFT cache needs the prompt too — a draft
        proposing from an unprefilled history is pure noise, zeroing
        acceptance (exactness never cared, throughput very much did).
        It is per-slot dense and never shares prefix pages, so the whole
        head is prefilled even when the target's pages were shared."""
        if self._spec is None:
            return
        p = prompt.size - 1
        c = self.cfg.prefill_chunk
        slot_j = jnp.asarray(slot, jnp.int32)
        for s0 in range(0, p, c):
            chunk = np.zeros((1, c), np.int32)
            n = min(c, p - s0)
            chunk[0, :n] = prompt[s0:s0 + n]
            fn = self._dprefill_cache.get(s0)
            if fn is None:
                fn = self._dprefill_cache[s0] = self._build_prefill_draft(s0)
            self._dcaches = fn(
                self._dparams, self._dcaches, jnp.asarray(chunk), slot_j
            )

    def _spec_headroom(self) -> int:
        return self.cfg.spec_k if self._spec is not None else 0

    def _validate_request(self, req: Request) -> np.ndarray:
        prompt = np.asarray(req.prompt, np.int32)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(f"request {req.rid}: prompt must be [L>=1]")
        total = prompt.size + req.max_new_tokens + self._spec_headroom()
        if total > self.cfg.max_len:
            extra = (
                f" (+ spec_k {self.cfg.spec_k} verify headroom)"
                if self._spec_headroom() else ""
            )
            raise ValueError(
                f"request {req.rid}: prompt {prompt.size} + "
                f"max_new_tokens {req.max_new_tokens}{extra} exceeds "
                f"cache max_len {self.cfg.max_len}"
            )
        return prompt

    def _admit(self, slot: int, req: Request) -> tuple[int, int]:
        """Prefill ``req``'s prompt (all but the last token) into a
        slot's cache rows; returns (pos, last_token) for the decode
        state. Chunk tails are padded — padded rows land at positions
        the mask excludes until decode overwrites them."""
        prompt = self._validate_request(req)
        p = prompt.size - 1
        c = self.cfg.prefill_chunk
        slot_j = jnp.asarray(slot, jnp.int32)
        for s0 in range(0, p, c):
            chunk = np.zeros((1, c), np.int32)
            n = min(c, p - s0)
            chunk[0, :n] = prompt[s0:s0 + n]
            self.caches = self._prefill_at(s0)(
                self.params, self.caches, jnp.asarray(chunk), slot_j
            )
        self._prefill_draft(slot, prompt)
        return p, int(prompt[-1])

    def _admit_paged(self, slot: int, req: Request,
                     stats: RequestStats) -> tuple[int, int] | None:
        """Paged admission: map pages into the slot's table row — prefix
        hits first (refcounted, skipping their prefill entirely), fresh
        pages for the rest — then prefill from the first unshared
        position. Returns None (leaving the pool untouched and the
        request queued) when the pool cannot supply the fresh pages; the
        caller defers FIFO-preservingly."""
        cfg = self.cfg
        prompt = self._validate_request(req)
        total = prompt.size + req.max_new_tokens + self._spec_headroom()
        p = prompt.size - 1
        pool = self._pool
        needed = math.ceil(total / cfg.page_size)
        shared = pool.match_prefix(prompt)  # only pages ending before p
        # Acquire the matched pages BEFORE allocating fresh ones: taking
        # a reference pulls a retained page out of the eviction LRU, so
        # a pressured alloc_n can never evict a page we are about to map
        # as this slot's prefix (which would alias the same pool page at
        # two table rows and let decode writes corrupt the prompt K/V).
        for pid in shared:
            pool.acquire(pid)
        fresh = pool.alloc_n(needed - len(shared))
        if fresh is None:
            for pid in shared:
                pool.release(pid)
            return None
        if shared:
            pool.prefix_hits += 1
            pool.pages_reused += len(shared)
        pages = shared + fresh
        row = np.zeros(cfg.max_pages, np.int32)
        row[: len(pages)] = pages
        self._table[slot] = row
        self._slot_pages[slot] = pages
        stats.shared_pages = len(shared)
        # Prefill [n_shared·P, p) — a chunk-aligned start by the
        # page_size % prefill_chunk == 0 config rule, so a fresh chunk
        # never writes into a shared page.
        c = cfg.prefill_chunk
        row_j = jnp.asarray(row)
        for s0 in range(len(shared) * cfg.page_size, p, c):
            chunk = np.zeros((1, c), np.int32)
            n = min(c, p - s0)
            chunk[0, :n] = prompt[s0:s0 + n]
            self.caches = self._prefill_at(s0)(
                self.params, self.caches, jnp.asarray(chunk), row_j
            )
        if pool.prefix_sharing:
            # Publish this request's fully-prefilled fresh pages: page j
            # is shareable iff it ends strictly before the first decode
            # write at p, so no future occupant ever writes it.
            for j in range(len(shared), len(pages)):
                if (j + 1) * cfg.page_size <= p:
                    pool.register(pages[j], prompt, j)
        self._prefill_draft(slot, prompt)
        return p, int(prompt[-1])

    def _release_slot(self, slot: int) -> None:
        """Return a finished/expired slot's pages to the allocator and
        zero its table row (pointing future don't-care writes at the
        garbage page)."""
        if self._pool is None:
            return
        for pid in self._slot_pages[slot]:
            self._pool.release(pid)
        self._slot_pages[slot] = []
        self._table[slot] = 0

    # ---------------------------------------------------------------- run

    def run(self, requests: list[Request]) -> ServeReport:
        """Serve a request stream to completion. Arrival times are
        honored open-loop (a request only becomes admissible once the
        clock passes its arrival), decode advances every occupied slot
        one token per step, finished slots are refilled mid-flight from
        the waiting queue. Every request ends in EXACTLY ONE terminal
        state: finished, rejected (bounded queue full at arrival), or
        expired (deadline passed while queued or in flight) — the
        ledger-accounting invariant the overload tests audit.
        """
        cfg = self.cfg
        b = cfg.slots
        arrivals = deque(sorted(requests, key=lambda r: (r.arrival_time, r.rid)))
        queue: deque[Request] = deque()  # arrived, not yet admitted
        stats = {
            r.rid: RequestStats(
                rid=r.rid, prompt_len=len(r.prompt),
                max_new_tokens=r.max_new_tokens, arrival=r.arrival_time,
            )
            for r in requests
        }
        if len(stats) != len(requests):
            raise ValueError("duplicate request ids")

        last = np.zeros(b, np.int32)
        pos = np.zeros(b, np.int32)
        remaining = np.zeros(b, np.int64)
        slot_rid = np.full(b, -1, np.int64)
        slot_deadline = np.full(b, np.inf)
        active = np.zeros(b, bool)
        events: list = []
        steps = 0
        peak_queue = 0
        busy_slot_steps = 0
        deferred_logged: set[int] = set()  # one "defer" event per rid
        # Clock: wall time by default; virtual (decode-step-derived) when
        # cfg.step_time_s is set — see ServeConfig.
        t0 = time.perf_counter()
        v_extra = 0.0  # virtual-clock idle skips (accumulated)
        if cfg.step_time_s is not None:
            now = lambda: steps * cfg.step_time_s + v_extra  # noqa: E731
        else:
            now = lambda: time.perf_counter() - t0  # noqa: E731

        while arrivals or queue or active.any():
            t = now()
            # Stage arrivals into the waiting queue; a full bounded queue
            # rejects at the door (slot -1 in the event tuple).
            while arrivals and arrivals[0].arrival_time <= t:
                req = arrivals.popleft()
                if cfg.max_queue is not None and len(queue) >= cfg.max_queue:
                    stats[req.rid].rejected = t
                    events.append(("reject", req.rid, -1, steps))
                else:
                    queue.append(req)
            peak_queue = max(peak_queue, len(queue))
            # Expire queued requests strictly past arrival + deadline
            # BEFORE admission — never spend prefill on a dead request.
            if cfg.deadline_s is not None:
                kept: deque[Request] = deque()
                while queue:
                    req = queue.popleft()
                    if t > req.arrival_time + cfg.deadline_s:
                        stats[req.rid].expired = t
                        events.append(("expire", req.rid, -1, steps))
                    else:
                        kept.append(req)
                queue = kept
            # Admit: free slots in index order, queue in arrival order.
            # The head is only ever PEEKED until admission succeeds —
            # an SLO deferral or a page-starved pool leaves it queued,
            # and nothing behind it may overtake (FIFO + (arrival, rid)
            # order is the determinism contract).
            for i in range(b):
                if active[i] or not queue:
                    continue
                req = queue[0]
                if self._cost is not None and not self._cost.admit_ok(
                    int(active.sum())
                ):
                    if req.rid not in deferred_logged:
                        deferred_logged.add(req.rid)
                        events.append(("defer", req.rid, -1, steps))
                    break
                st = stats[req.rid]
                st.admit_start = now()
                if self._paged:
                    admitted = self._admit_paged(i, req, st)
                    if admitted is None:
                        if not active.any():
                            raise ValueError(
                                f"request {req.rid} needs more pages "
                                f"than the pool can ever supply "
                                f"({cfg.total_pages} pages incl. the "
                                f"garbage page)"
                            )
                        if req.rid not in deferred_logged:
                            deferred_logged.add(req.rid)
                            events.append(("defer", req.rid, -1, steps))
                        break
                else:
                    admitted = self._admit(i, req)
                queue.popleft()
                pos[i], last[i] = admitted
                remaining[i] = req.max_new_tokens
                slot_rid[i] = req.rid
                slot_deadline[i] = (
                    req.arrival_time + cfg.deadline_s
                    if cfg.deadline_s is not None
                    else np.inf
                )
                active[i] = True
                st.admitted = now()
                st.slot = i
                events.append(("admit", req.rid, i, steps))
            if not active.any():
                if not arrivals:
                    continue  # queue drained by expiry; loop re-checks
                # Idle: nothing in flight, queue head hasn't arrived yet.
                gap = arrivals[0].arrival_time - now()
                if cfg.step_time_s is not None:
                    v_extra += max(gap, 0.0)  # skip virtual time forward
                elif gap > 0:
                    time.sleep(min(gap, 0.05))
                continue
            # One decode step for ALL slots. Inactive slots run garbage
            # tokens at stale positions — harmless by the mask argument
            # in the module docstring (paged: their zero table rows point
            # every write at the garbage page) — so the compiled shape
            # never changes with occupancy. Spec steps return a K+1-wide
            # window + per-slot commit counts; plain steps reduce to the
            # same contract at width 1.
            busy_slot_steps += int(active.sum())
            last_j, pos_j = jnp.asarray(last), jnp.asarray(pos)
            if self._spec is not None:
                if self._paged:
                    emitted, n_emit, _, self.caches, self._dcaches = (
                        self._spec(self.params, self._dparams, self.caches,
                                   self._dcaches, jnp.asarray(self._table),
                                   last_j, pos_j)
                    )
                else:
                    emitted, n_emit, _, self.caches, self._dcaches = (
                        self._spec(self.params, self._dparams, self.caches,
                                   self._dcaches, last_j, pos_j)
                    )
                emitted_np = np.asarray(jax.device_get(emitted))
                n_emit_np = np.asarray(jax.device_get(n_emit))
            else:
                if self._paged:
                    next_t, _, self.caches = self._decode(
                        self.params, self.caches, jnp.asarray(self._table),
                        last_j, pos_j,
                    )
                else:
                    next_t, _, self.caches = self._decode(
                        self.params, self.caches, last_j, pos_j
                    )
                emitted_np = np.asarray(jax.device_get(next_t))[:, None]
                n_emit_np = np.ones(b, np.int64)
            steps += 1
            t_step = now()
            for i in range(b):
                if not active[i]:
                    continue
                st = stats[slot_rid[i]]
                done = False
                committed = 0
                for tok in emitted_np[i, : int(n_emit_np[i])]:
                    tok = int(tok)
                    st.tokens.append(tok)
                    st.token_times.append(t_step)
                    committed += 1
                    if st.first_token is None:
                        st.first_token = t_step
                    pos[i] += 1
                    last[i] = tok
                    remaining[i] -= 1
                    if remaining[i] <= 0 or (
                        cfg.eos_token is not None and tok == cfg.eos_token
                    ):
                        done = True
                        break
                if self._spec is not None:
                    # accepted_len counts draft tokens actually COMMITTED
                    # (committed - 1: the last commit is the target's
                    # bonus/correction token) — a window truncated by EOS
                    # or the max_new_tokens budget logs only what landed
                    # in the ledger, so mean_accepted_len stays an exact
                    # tokens-per-target-step accounting.
                    events.append(("spec", int(slot_rid[i]), i, steps,
                                   committed - 1))
                if done:
                    st.finished = t_step
                    active[i] = False
                    events.append(("evict", int(slot_rid[i]), i, steps))
                    slot_rid[i] = -1
                    self._release_slot(i)
                elif t_step > slot_deadline[i]:
                    # Mid-flight deadline eviction at the step boundary:
                    # the slot frees for the queue head, the partial
                    # tokens stay in the ledger, finished stays None.
                    st.expired = t_step
                    active[i] = False
                    events.append(("expire", int(slot_rid[i]), i, steps))
                    slot_rid[i] = -1
                    self._release_slot(i)
        pool_stats = None
        if self._pool is not None:
            pool_stats = {
                "prefix_hits": self._pool.prefix_hits,
                "pages_reused": self._pool.pages_reused,
                "retained_evictions": self._pool.retained_evictions,
            }
        return ServeReport(
            requests=stats, events=events, decode_steps=steps,
            wall_time=now(), peak_queue_depth=peak_queue,
            busy_slot_steps=busy_slot_steps, slots=b, pool_stats=pool_stats,
        )
