"""Collective primitives over a named mesh axis.

Design: the reference aggregates gradients with one collective call PER
PARAMETER TENSOR per step (codes/task2/dist_utils.py:39-49 — 8 tensors ⇒ 8
NCCL calls, SURVEY.md §3.2). Here every wrapper takes a whole pytree and
lowers to XLA collectives inside one jitted program, so XLA fuses/schedules
them over ICI; the per-parameter-loop overhead class disappears.

All functions must be called inside a ``shard_map``/``pmap`` context where
``axis_name`` is bound. Primitive coverage mirrors and extends what the
reference exercises (broadcast / all_reduce / all_gather, dist_utils.py:
33-49) plus the concepts its spec names (Reduce/Gather/Scatter,
sections/task2.tex:11) and the ring/all-to-all primitives that keep the door
open for sequence parallelism (SURVEY.md §5.7): psum, pmean, all_gather,
psum_scatter (= ReduceScatter), ppermute (ring shift), all_to_all.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def axis_size(axis_name: str) -> int:
    """Static size of a bound mesh axis.

    ``lax.axis_size`` is newer than the pinned jax (0.4.37 raises
    AttributeError — tpudml.analysis rule J100 caught this breaking every
    ring/CP path); ``psum`` of the literal 1 is the long-standing static
    equivalent and constant-folds to a Python int at trace time.
    """
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def pmax_tree(tree: PyTree, axis_name: str) -> PyTree:
    """AllReduce-MAX over every leaf — the merge collective for online
    statistics (running softmax maxima, lse merges)."""
    return jax.tree.map(lambda x: lax.pmax(x, axis_name), tree)


def plogsumexp(x: jax.Array, axis_name: str) -> jax.Array:
    """Cross-shard log-sum-exp merge: each shard holds a partial
    ``lse_local = log Σ_local exp(s)`` over its slice of a reduced axis;
    the global lse is their logsumexp over the mesh axis. This is the
    SAME online combination rule the ring-attention fold uses per
    arriving block (tpudml/parallel/cp.py ``_merge_blocks``), expressed
    as one pmax + one psum — the shift makes the psum overflow-safe, and
    lse's shift-invariance makes ``stop_gradient`` on the shift exact:
    d lse/d lse_local = exp(lse_local − lse), the correct softmax slice
    weight, flows entirely through the psum term. Differentiable; used
    by the vocab-sharded fused cross-entropy head to merge per-shard
    partial-vocab statistics."""
    # stop_gradient on the INPUT, not the result: pmax has no JVP rule
    # on the pinned jax, and with a symbolic-zero tangent the primitive
    # is never differentiated at all.
    m = lax.pmax(lax.stop_gradient(x), axis_name)
    return m + jnp.log(lax.psum(jnp.exp(x - m), axis_name))


def psum_tree(tree: PyTree, axis_name: str) -> PyTree:
    """AllReduce-SUM over every leaf of a pytree (one traced program)."""
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


def pmean_tree(tree: PyTree, axis_name: str) -> PyTree:
    """AllReduce-MEAN over every leaf."""
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def allreduce_average_gradients(grads: PyTree, axis_name: str = "data") -> PyTree:
    """Gradient aggregation, AllReduce strategy.

    Parity: reference ``allreduce_average_gradients`` — per-param
    ``all_reduce(SUM)`` then ``/world_size`` (codes/task2/dist_utils.py:
    39-42); here a single pmean over the grad pytree.
    """
    return pmean_tree(grads, axis_name)


def allgather_average_gradients(grads: PyTree, axis_name: str = "data") -> PyTree:
    """Gradient aggregation, AllGather strategy: gather every replica's
    gradient then average locally.

    Parity: reference ``allgather_average_gradients`` (codes/task2/
    dist_utils.py:44-49) — whose list-construction bug (``[zeros]*2``
    hardcodes world=2 and aliases one tensor) is deliberately NOT
    reproduced; SURVEY.md §2.1 calls for a *correct* allgather-mean.
    Mathematically equal to allreduce-mean; communication volume is
    world× larger — the comparison task2 asks students to measure
    (sections/checking.tex:20-21).
    """

    def gather_mean(g):
        stacked = lax.all_gather(g, axis_name)  # [world, ...]
        return jnp.mean(stacked, axis=0)

    return jax.tree.map(gather_mean, grads)


def reduce_scatter_average_gradients(grads: PyTree, axis_name: str = "data") -> PyTree:
    """Gradient aggregation, ReduceScatter(+AllGather) strategy.

    The bandwidth-optimal decomposition of AllReduce (what ring-allreduce
    does internally): psum_scatter leaves each replica with a distinct
    averaged shard, all_gather reassembles. Exposed as a third measurable
    strategy beyond the reference's two (sections/task2.tex:18 asks for ≥2
    collective primitives; this adds the Scatter/Reduce concepts named at
    task2.tex:11). Leading dim of each leaf must divide the axis size; falls
    back to pmean for leaves where it doesn't.
    """
    world = axis_size(axis_name)

    def rs_ag(g):
        if g.ndim >= 1 and g.shape[0] % world == 0:
            shard = lax.psum_scatter(g, axis_name, scatter_dimension=0, tiled=True)
            return lax.all_gather(shard, axis_name, axis=0, tiled=True) / world
        return lax.pmean(g, axis_name)

    return jax.tree.map(rs_ag, grads)


def all_gather_tree(tree: PyTree, axis_name: str, axis: int = 0, tiled: bool = False) -> PyTree:
    """AllGather every leaf along ``axis``."""
    return jax.tree.map(lambda x: lax.all_gather(x, axis_name, axis=axis, tiled=tiled), tree)


def psum_scatter_tree(tree: PyTree, axis_name: str, axis: int = 0) -> PyTree:
    """ReduceScatter every leaf along ``axis`` (tiled)."""
    return jax.tree.map(
        lambda x: lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True), tree
    )


def broadcast_from(tree: PyTree, axis_name: str, root: int = 0) -> PyTree:
    """Broadcast every leaf from replica ``root`` to all replicas.

    Parity: reference ``init_parameters`` — per-param ``dist.broadcast(p, 0)``
    (codes/task2/dist_utils.py:33-37). Implemented as select-root + psum,
    which XLA lowers to an efficient one-to-all over ICI. In idiomatic JAX
    this is rarely needed (replicated init from a shared PRNG seed gives
    bitwise-identical params on every replica for free — the design the DP
    engine uses by default); provided for explicit-broadcast parity and for
    resume-from-checkpoint flows (SURVEY.md §5.4).
    """

    def bcast(x):
        idx = lax.axis_index(axis_name)
        masked = jnp.where(idx == root, x, jnp.zeros_like(x))
        return lax.psum(masked, axis_name)

    return jax.tree.map(bcast, tree)


def ppermute_ring(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Ring shift: replica i's value goes to replica (i+shift) mod world.

    The primitive under ring-allreduce and ring attention (SURVEY.md §5.7
    scope note: exposed so the SP door stays open).
    """
    world = axis_size(axis_name)
    perm = [(i, (i + shift) % world) for i in range(world)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x: jax.Array, axis_name: str, split_axis: int, concat_axis: int) -> jax.Array:
    """All-to-all: transpose a sharded axis with a local axis (the Ulysses
    sequence-parallel primitive; SURVEY.md §5.7)."""
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)


AGGREGATORS = {
    "allreduce": allreduce_average_gradients,
    "allgather": allgather_average_gradients,
    "reducescatter": reduce_scatter_average_gradients,
}


def get_aggregator(name: str):
    """Factory keyed by the config's ``aggregation`` field (task2's ≥2
    collective-primitive contract, sections/task2.tex:18)."""
    try:
        return AGGREGATORS[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown aggregation {name!r}; options: {sorted(AGGREGATORS)}"
        ) from None
