"""Collective aggregation micro-benchmark: ``python -m tpudml.comm.bench``.

The task2 deliverable — "implement ≥2 collective aggregation strategies
and compare their communication time" (sections/task2.tex:18,
sections/checking.tex:20-21) — as a standalone tool: times each gradient
aggregation strategy (allreduce / allgather / reducescatter) over
configurable payload sizes on the current mesh and prints a comparison
table plus one JSON line per (strategy, size).

Methodology: the collective runs alone inside one jitted shard_map
program (mirroring the engines' ``measure_comm`` split-step mode), timed
host-side around ``block_until_ready`` — the reference's comm-span
accounting (codes/task2/model-mp.py:61-66) without the training loop
around it.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpudml.comm.collectives import AGGREGATORS, get_aggregator
from tpudml.core.config import MeshConfig
from tpudml.core.dist import distributed_init, make_mesh
from tpudml.parallel.sharding import shard_map_fn


def bench_strategy(name: str, mesh, size: int, iters: int) -> dict:
    agg = get_aggregator(name)
    axis = mesh.axis_names[0]
    fn = jax.jit(
        shard_map_fn(
            lambda t: agg(t, axis), mesh, in_specs=P(), out_specs=P()
        )
    )
    payload = {"grad": jnp.ones((size,), jnp.float32)}
    out = fn(payload)  # compile + warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(payload)
    jax.block_until_ready(out)
    mean_s = (time.perf_counter() - t0) / iters
    return {
        "strategy": name,
        "elements": size,
        "bytes": size * 4,
        "world": mesh.devices.size,
        "mean_ms": mean_s * 1e3,
    }


def main(argv=None) -> list[dict]:
    p = argparse.ArgumentParser(prog="tpudml.comm.bench")
    p.add_argument(
        "--strategies", nargs="+", default=sorted(AGGREGATORS),
        choices=sorted(AGGREGATORS),
    )
    p.add_argument(
        "--sizes", nargs="+", type=int,
        default=[1 << 14, 1 << 18, 1 << 22],
        help="payload element counts (float32)",
    )
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--n_devices", type=int, default=None)
    args = p.parse_args(argv)

    distributed_init()
    devices = jax.devices()
    if args.n_devices:
        devices = devices[: args.n_devices]
    mesh = make_mesh(MeshConfig({"data": len(devices)}), devices)

    results = []
    for size in args.sizes:
        for name in args.strategies:
            rec = bench_strategy(name, mesh, size, args.iters)
            results.append(rec)
            print(json.dumps(rec))
    # Human-readable comparison (the lab's analysis table).
    print(f"\n{'elements':>10} | " + " | ".join(f"{n:>13}" for n in args.strategies))
    for size in args.sizes:
        row = [r for r in results if r["elements"] == size]
        cells = {r["strategy"]: r["mean_ms"] for r in row}
        print(
            f"{size:>10} | "
            + " | ".join(f"{cells[n]:>11.3f}ms" for n in args.strategies)
        )
    return results


if __name__ == "__main__":
    main()
